"""RGWRados role: bucket/object layout over librados.

Re-expresses the reference's src/rgw/rgw_rados.cc storage model at the
fidelity the S3 surface needs:

- bucket registry: a directory object ("buckets") in the meta pool,
  maintained by the rgw object class (atomic server-side updates —
  reference cls_rgw + the RGWRados bucket metadata handlers)
- per-bucket index: hash-sharded directory objects in the meta pool
  (reference bucket index shards, cls_rgw).  Routing, layout and the
  merge-sorted listing cursor live in rgw/bucket_index.py; online
  dynamic resharding in rgw/reshard.py.  Buckets created without a
  shard count keep the legacy single object ("index.<bucket>").
- object data: one rados object per S3 object in the data pool, named
  with a length-prefixed bucket separator so keys may contain any
  character (reference rgw_obj raw-object naming)
- multipart uploads (reference rgw_op.h:1716-1754 RGWInitMultipart /
  RGWListMultipart / RGWCompleteMultipart / RGWAbortMultipart and the
  RGWUploadPartInfo manifest model): each part is its own RADOS object
  in the data pool; the completed S3 object's index entry carries a
  parts manifest instead of data, and GET stitches the parts —
  completing a 5 TB upload moves no data, exactly like the reference's
  manifest-based RGWObjManifest.

The data pool may be erasure-coded (pass an EC profile); the meta pool
is replicated, matching the reference's constraint that index pools be
replicated.
"""

from __future__ import annotations

import errno
import hashlib
import json
import time

from ..common.options import SCHEMA
from ..rados.client import RadosError
from .bucket_index import BucketIndex
from .reshard import Resharder

META_POOL = ".rgw.meta"
DATA_POOL = ".rgw.data"
BUCKETS_OBJ = "buckets"
MODLOG_OBJ = "rgw_modlog"


class RGWError(Exception):
    def __init__(self, status: int, code: str, msg: str = ""):
        super().__init__(f"{code}: {msg}")
        self.status = status
        self.code = code


def _data_oid(bucket: str, key: str) -> str:
    return f"{len(bucket)}_{bucket}_{key}"


def _part_oid(bucket: str, upload_id: str, part_num: int) -> str:
    # distinct namespace from _data_oid (which always starts with a
    # digit): a user key can never collide with a part object
    # (reference uses the __multipart_ shadow-object namespace)
    return f"mp_{len(bucket)}_{bucket}_{upload_id}.{part_num}"


def _version_oid(bucket: str, version_id: str, key: str) -> str:
    # archived version payloads (non-colliding namespace, see above)
    return f"vr_{len(bucket)}_{bucket}_{version_id}_{key}"


class RGWStore:
    def __init__(self, client, ec_profile: str | None = None,
                 pg_num: int = 8, modlog: bool = False,
                 usage_log: bool = False):
        self.client = client
        self._ensure_pools(ec_profile, pg_num)
        self.meta = client.open_ioctx(META_POOL)
        self.data = client.open_ioctx(DATA_POOL)
        self._cls(self.meta, BUCKETS_OBJ, "dir_init")
        # zone mod-log: one journal object recording WHAT changed
        # (reference rgw_datalog/bilog, the feed of rgw_data_sync.cc);
        # the sync agent (rgw/sync.py) reconciles current state per
        # entry, so replay is idempotent.  OPT-IN (multisite zones
        # only): a standalone zone must not pay a journal append per
        # mutation; enabling sync on an existing zone starts with
        # ZoneReplayer.full_sync() to cover the pre-log history.
        self.modlog_enabled = modlog
        if modlog:
            self.meta.execute(MODLOG_OBJ, "journal", "create", b"")
        # usage/ops log (reference rgw_enable_usage_log, default off):
        # one cls_log append per mutation when enabled
        self.usage_log_enabled = usage_log
        # bucket notifications (rgw/notify.py), opt-in
        self.notify = None
        # bucket-meta rows are read-modify-written whole (versioning/
        # acl/lifecycle share one row); concurrent HTTP handler threads
        # must not interleave their RMWs or the second write silently
        # drops the first's field
        import threading as _threading
        self._bmeta_lock = _threading.Lock()
        # every index/versions plane access routes through the shard
        # layer (shard selection, dual-write during reshard, merged
        # listing); quota admission is a cls_user reservation — no
        # process-local pending pot survives here (see _quota_gate)
        self.index = BucketIndex(self)
        self.resharder = Resharder(self)
        # continuation-cursor cache: a paginated listing re-entered
        # via its resume token continues the live merged cursor
        # (buffered shard pages intact) instead of re-seeking every
        # shard — without it each page pays one dir_list per shard,
        # so page latency grows with shard count.  Keyed by the full
        # request shape + token; invalidated on any index mutation
        # through this store and on layout (reshard) change, so a
        # reused cursor can never show state older than this
        # gateway's own acked writes.
        from collections import OrderedDict as _OD
        self._cursor_cache: dict = _OD()
        self._cursor_mu = _threading.Lock()

    def _ensure_pools(self, ec_profile, pg_num) -> None:
        for name, kind in ((META_POOL, "replicated"),
                           (DATA_POOL,
                            "erasure" if ec_profile else "replicated")):
            try:
                kw = {"pg_num": pg_num}
                if kind == "erasure":
                    kw["erasure_code_profile"] = ec_profile
                else:
                    kw["size"] = 2
                self.client.create_pool(name, kind, **kw)
            except RadosError as e:
                if e.errno != errno.EEXIST:
                    raise

    def _stash_cursor(self, key: tuple, lay, mcur) -> None:
        with self._cursor_mu:
            self._cursor_cache[key] = ((lay.shards, lay.gen), mcur)
            self._cursor_cache.move_to_end(key)
            while len(self._cursor_cache) > 32:
                self._cursor_cache.popitem(last=False)

    def _take_cursor(self, key: tuple, lay):
        """Pop a stashed cursor if its layout still matches (a reshard
        cutover between pages orphans old-gen cursors)."""
        with self._cursor_mu:
            ent = self._cursor_cache.pop(key, None)
        if ent is not None and ent[0] == (lay.shards, lay.gen):
            return ent[1]
        return None

    def _drop_cursors(self, bucket: str) -> None:
        with self._cursor_mu:
            for k in [k for k in self._cursor_cache if k[0] == bucket]:
                del self._cursor_cache[k]

    def _cls(self, io, oid: str, method: str, payload: dict | None = None
             ) -> bytes:
        inp = json.dumps(payload).encode() if payload is not None else b""
        return io.execute(oid, "rgw", method, inp)

    def _modlog(self, op: str, bucket: str,
                key: str | None = None) -> None:
        """Mutations log TWICE: once after validation/before mutating
        (write-ahead: a crash between log and mutation reconciles to a
        no-op, while mutate-then-crash-before-log would diverge the
        zones forever) and once after success (a replayer that consumed
        the write-ahead entry BEFORE the mutation landed would
        otherwise commit past it and never see the final state).
        Failed ops log nothing.  The replayer coalesces duplicates."""
        if not self.modlog_enabled:
            return
        entry = {"op": op, "bucket": bucket, "ts": time.time()}
        if key is not None:
            entry["key"] = key
        self.meta.execute(MODLOG_OBJ, "journal", "append",
                          json.dumps({"entry": entry}).encode())

    # -- user accounting + quotas (cls_user; reference rgw_quota.cc +
    #    cls_user bucket stats) + usage log (cls_log; rgw_usage.cc) ---------

    @staticmethod
    def _user_oid(user: str) -> str:
        return f"user.{user}"

    def _user_stats(self, user: str | None, bucket: str,
                    d_objects: int, d_bytes: int) -> None:
        """Server-side stats delta on the owner's account object.
        Accounting tracks the CURRENT index view (archived version
        rows and version surgery are not separately charged — noted
        deviation from the reference's full-olh accounting)."""
        if not user or (d_objects == 0 and d_bytes == 0):
            return
        self.meta.execute(self._user_oid(user), "user", "add_stats",
                          json.dumps({"bucket": bucket,
                                      "objects": d_objects,
                                      "bytes": d_bytes}).encode())

    def _account_overwrite(self, bucket: str, key: str | None,
                           cur: dict | None, cur_owner: str | None,
                           new_owner: str | None,
                           new_bytes: int) -> None:
        """Post-success accounting for a write that displaced `cur`:
        release the OLD owner's charge and charge the NEW owner — a
        cross-owner overwrite must not leave the previous owner paying
        for bytes that no longer exist (and the clamp in cls_user must
        never eat the new owner's charge)."""
        if cur is not None and cur_owner == new_owner:
            self._user_stats(new_owner, bucket, 0,
                             new_bytes - cur.get("size", 0))
        else:
            if cur is not None:
                self._user_stats(cur_owner, bucket, -1,
                                 -cur.get("size", 0))
            self._user_stats(new_owner, bucket, 1, new_bytes)
        self._usage(new_owner, "put_obj", bucket, key, new_bytes)

    def get_user_header(self, user: str) -> dict:
        raw = self.meta.execute(self._user_oid(user), "user",
                                "get_header", b"")
        return json.loads(raw.decode())

    def set_user_quota(self, user: str, max_objects: int = -1,
                       max_bytes: int = -1) -> None:
        self.meta.execute(self._user_oid(user), "user", "set_quota",
                          json.dumps({"max_objects": max_objects,
                                      "max_bytes": max_bytes}).encode())

    def _quota_gate(self, user: str | None, add_objects: int,
                    add_bytes: int) -> str | None:
        """Admit-or-403 a write against the owner's quota AND reserve
        its growth (reference RGWQuotaHandler::check_quota before
        every put).  Check and reservation are ONE atomic cls_user
        call on the user object — the OSD serializes class calls per
        object, so racing writers from ANY process or host see each
        other's live reservations and cannot jointly overshoot
        max_bytes/max_objects (this closes the process-local pending
        pot's documented cross-process window).  Returns a reservation
        token; every successful gate must be paired with a
        `_quota_release(user, token)` once the op's accounting has
        landed (or the op failed).  A writer that dies in between
        stops counting against the quota after
        rgw_quota_reservation_ttl_s.

        Residual boundary effect: between `_user_stats` landing and
        the release, growth is briefly counted twice (reservation +
        totals), which can only falsely DENY at the boundary, never
        falsely admit."""
        if not user:
            return None
        try:
            raw = self.meta.execute(
                self._user_oid(user), "user", "reserve",
                json.dumps({
                    "objects": add_objects, "bytes": add_bytes,
                    "ttl": SCHEMA["rgw_quota_reservation_ttl_s"
                                  ].default}).encode())
        except RadosError as e:
            if e.errno == errno.EDQUOT:
                raise RGWError(403, "QuotaExceeded",
                               f"user {user}: {e}") from e
            raise
        return json.loads(raw.decode())["token"]

    def _quota_release(self, user: str | None,
                       token: str | None) -> None:
        """Return a gate's reservation (accounting landed or op died)."""
        if not user or not token:
            return
        self.meta.execute(self._user_oid(user), "user", "release",
                          json.dumps({"token": token}).encode())

    def _usage(self, user: str | None, op: str, bucket: str,
               key: str | None, nbytes: int) -> None:
        if not self.usage_log_enabled:
            return
        entry = {"user": user or "anonymous", "op": op,
                 "bucket": bucket, "bytes": nbytes}
        if key is not None:
            entry["key"] = key
        self.meta.execute("rgw_usagelog", "log", "add", json.dumps(
            {"ts": time.time(), "entry": entry}).encode())

    def get_usage(self, from_ts: float = 0.0, to_ts: float = 1e18,
                  marker: str = "", max_entries: int = 256) -> dict:
        raw = self.meta.execute("rgw_usagelog", "log", "list",
                                json.dumps({"from_ts": from_ts,
                                            "to_ts": to_ts,
                                            "marker": marker,
                                            "max": max_entries}
                                           ).encode())
        return json.loads(raw.decode())

    def trim_usage(self, to_ts: float) -> None:
        self.meta.execute("rgw_usagelog", "log", "trim",
                          json.dumps({"to_ts": to_ts}).encode())

    def enable_notifications(self, push_interval: float = 0.25):
        """Attach the notification manager (reference rgw_notify);
        returns it for topic/binding admin."""
        from .notify import NotificationManager
        if self.notify is None:
            self.notify = NotificationManager(self, push_interval)
        return self.notify

    def _publish(self, bucket: str, key: str, event: str,
                 size: int = 0, bmeta: dict | None = None) -> None:
        if self.notify is not None:
            self.notify.publish(bucket, key, event, size, bmeta=bmeta)

    # -- buckets -------------------------------------------------------------

    def create_bucket(self, bucket: str, owner: str | None = None,
                      acl: str = "private",
                      shards: int | None = None) -> None:
        """`shards` picks the index shard count (None = the
        rgw_bucket_index_shards default).  shards == 1 keeps the
        legacy single-object layout; > 1 creates a hash-sharded index
        at generation 1 (generation 0 is the legacy spelling)."""
        if not bucket or "/" in bucket:
            raise RGWError(400, "InvalidBucketName", bucket)
        if shards is None:
            shards = SCHEMA["rgw_bucket_index_shards"].default
        shards = int(shards)
        if shards < 1:
            raise RGWError(400, "InvalidArgument",
                           f"shard count {shards}")
        meta: dict = {"created": time.time()}
        if owner is not None:
            meta["owner"] = owner
        if acl != "private":
            meta["acl"] = acl
        if shards > 1:
            meta["index"] = {"shards": shards, "gen": 1}
        self._modlog("sync_bucket", bucket)
        self._cls(self.meta, BUCKETS_OBJ, "dir_add", {
            "key": bucket, "meta": meta})
        self.index.init(bucket, shards, 1 if shards > 1 else 0)
        self._modlog("sync_bucket", bucket)     # post-success

    def set_bucket_acl(self, bucket: str, acl: str) -> None:
        with self._bmeta_lock:
            meta = self._bucket_meta(bucket)
            if meta is None:
                raise RGWError(404, "NoSuchBucket", bucket)
            meta["acl"] = acl               # RMW: keep created/owner etc.
            self._modlog("sync_bucket", bucket)
            self._cls(self.meta, BUCKETS_OBJ, "dir_add", {
                "key": bucket, "meta": meta})
            self._modlog("sync_bucket", bucket)  # post-success

    def set_bucket_policy(self, bucket: str, policy: dict | None) -> None:
        """Attach (or with None, detach) a validated policy document to
        the bucket meta (reference: RGW_ATTR_IAM_POLICY xattr on the
        bucket instance, src/rgw/rgw_iam_policy.cc consumers)."""
        with self._bmeta_lock:
            meta = self._bucket_meta(bucket)
            if meta is None:
                raise RGWError(404, "NoSuchBucket", bucket)
            if policy is None:
                meta.pop("policy", None)
            else:
                meta["policy"] = policy
            self._modlog("sync_bucket", bucket)
            self._cls(self.meta, BUCKETS_OBJ, "dir_add", {
                "key": bucket, "meta": meta})
            self._modlog("sync_bucket", bucket)  # post-success

    def get_bucket_policy(self, bucket: str) -> dict | None:
        meta = self._bucket_meta(bucket)
        if meta is None:
            raise RGWError(404, "NoSuchBucket", bucket)
        return meta.get("policy")

    def set_object_acl(self, bucket: str, key: str, acl: str) -> None:
        cur = self._current_meta(bucket, key)
        if cur is None:
            raise RGWError(404, "NoSuchKey", key)
        cur["acl"] = acl
        self._modlog("sync", bucket, key)
        self.index.add(bucket, "index", key, cur)
        self._modlog("sync", bucket, key)       # post-success

    # -- lifecycle (reference rgw_lc.h: per-bucket rules evaluated by
    #    a background worker) ----------------------------------------------

    def set_lifecycle(self, bucket: str, rules: list[dict]) -> None:
        """rules: [{id, prefix, days?, expired_obj_delete_marker?,
        abort_mpu_days?}, ...] — the Expiration(Days) /
        ExpiredObjectDeleteMarker / AbortIncompleteMultipartUpload
        subset of the reference's LC rule grammar."""
        with self._bmeta_lock:
            meta = self._bucket_meta(bucket)
            if meta is None:
                raise RGWError(404, "NoSuchBucket", bucket)
            for r in rules:
                if not (r.get("days") or r.get("abort_mpu_days") or
                        r.get("expired_obj_delete_marker")):
                    raise RGWError(400, "MalformedXML",
                                   f"rule {r.get('id', '?')} has no action")
            meta["lifecycle"] = rules
            self._modlog("sync_bucket", bucket)
            self._cls(self.meta, BUCKETS_OBJ, "dir_add", {
                "key": bucket, "meta": meta})
            self._modlog("sync_bucket", bucket)  # post-success

    def get_lifecycle(self, bucket: str) -> list[dict]:
        meta = self._bucket_meta(bucket)
        if meta is None:
            raise RGWError(404, "NoSuchBucket", bucket)
        return meta.get("lifecycle", [])

    def delete_lifecycle(self, bucket: str) -> None:
        with self._bmeta_lock:
            meta = self._bucket_meta(bucket)
            if meta is None:
                raise RGWError(404, "NoSuchBucket", bucket)
            meta.pop("lifecycle", None)
            self._modlog("sync_bucket", bucket)
            self._cls(self.meta, BUCKETS_OBJ, "dir_add", {
                "key": bucket, "meta": meta})
            self._modlog("sync_bucket", bucket)  # post-success

    def lifecycle_sweep(self, now: float | None = None) -> dict:
        """One pass over every bucket with lifecycle rules (the
        reference's RGWLC::process).  Returns counters for
        observability/tests.  `now` is injectable for time-mocked
        tests."""
        now = time.time() if now is None else now
        stats = {"expired": 0, "markers_removed": 0, "mpu_aborted": 0}
        for bucket, bmeta in self.list_buckets():
            rules = bmeta.get("lifecycle")
            if not rules:
                continue
            for rule in rules:
                prefix = rule.get("prefix", "")
                days = rule.get("days")
                if days:
                    cutoff = now - days * 86400
                    marker = ""
                    while True:
                        entries, _cps, trunc, nm = self.list_objects(
                            bucket, prefix=prefix, marker=marker,
                            max_keys=1000)
                        for k, m in entries:
                            if m.get("mtime", now) <= cutoff:
                                try:
                                    self.delete_object(bucket, k)
                                    stats["expired"] += 1
                                except RGWError:
                                    pass
                        if not trunc or not entries:
                            break
                        marker = entries[-1][0]
                if rule.get("expired_obj_delete_marker"):
                    # a delete marker whose key has NO other versions
                    # is dead weight: remove it (S3
                    # ExpiredObjectDeleteMarker)
                    by_key: dict[str, list] = {}
                    for row in self.list_versions(
                            bucket, prefix=prefix, max_keys=100000):
                        by_key.setdefault(row["key"], []).append(row)
                    for k, rows in by_key.items():
                        if len(rows) == 1 and \
                                rows[0].get("delete_marker"):
                            try:
                                self.delete_object_version(
                                    bucket, k, rows[0]["version_id"])
                                stats["markers_removed"] += 1
                            except RGWError:
                                pass
                mpu_days = rule.get("abort_mpu_days")
                if mpu_days:
                    cutoff = now - mpu_days * 86400
                    for k, upload_id, m in \
                            self.list_multipart_uploads(bucket):
                        if not k.startswith(prefix):
                            continue
                        if m.get("initiated", now) <= cutoff:
                            try:
                                self.abort_multipart(bucket, k,
                                                     upload_id)
                                stats["mpu_aborted"] += 1
                            except RGWError:
                                pass
        return stats

    @staticmethod
    def _not_found(e: RadosError) -> bool:
        """Only ENOENT means absence; anything else is a cluster fault
        that must surface as a 5xx, not a phantom 404 (a sync client
        treating EIO as 'gone' would re-upload or diverge)."""
        if e.errno == errno.ENOENT:
            return True
        raise RGWError(503, "ServiceUnavailable", str(e))

    def bucket_exists(self, bucket: str) -> bool:
        try:
            self._cls(self.meta, BUCKETS_OBJ, "dir_get", {"key": bucket})
            return True
        except RadosError as e:
            return not self._not_found(e)

    def delete_bucket(self, bucket: str) -> None:
        self._require_bucket(bucket)
        count = self.index.count(bucket)
        if count:
            raise RGWError(409, "BucketNotEmpty", bucket)
        # in-flight multipart uploads also block deletion (S3
        # semantics); otherwise their parts leak in the data pool and
        # the upload record resurrects on bucket recreation
        if self.list_multipart_uploads(bucket):
            raise RGWError(409, "BucketNotEmpty",
                           f"{bucket}: multipart uploads in progress")
        # surviving versions (incl. delete markers) hold data: block
        for row in self.list_versions(bucket, max_keys=1):
            raise RGWError(409, "BucketNotEmpty",
                           f"{bucket}: object versions remain")
        self._modlog("sync_bucket", bucket)
        bmeta = self._bucket_meta(bucket) or {}
        owner = bmeta.get("owner")
        if owner:
            self.meta.execute(self._user_oid(owner), "user",
                              "rm_bucket",
                              json.dumps({"bucket": bucket}).encode())
        self._cls(self.meta, BUCKETS_OBJ, "dir_rm", {"key": bucket})
        self.index.remove_all(bucket, bmeta=bmeta)
        try:
            self.meta.remove(f"uploads.{bucket}")
        except RadosError:
            pass
        self._modlog("sync_bucket", bucket)     # post-success

    def list_buckets(self) -> list[tuple[str, dict]]:
        out = json.loads(self._cls(self.meta, BUCKETS_OBJ, "dir_list",
                                   {"max": 10000}).decode())
        return [(k, m) for k, m in out["entries"]]

    def _require_bucket(self, bucket: str) -> None:
        if not self.bucket_exists(bucket):
            raise RGWError(404, "NoSuchBucket", bucket)

    # -- objects -------------------------------------------------------------

    # -- versioning (reference rgw bucket versioning + RGWListBucketV
    #    / delete markers) --------------------------------------------------

    def _bucket_meta(self, bucket: str) -> dict | None:
        """One round-trip for existence + metadata (the object hot
        path must not probe the bucket directory three times)."""
        try:
            raw = self._cls(self.meta, BUCKETS_OBJ, "dir_get",
                            {"key": bucket})
        except RadosError as e:
            self._not_found(e)
            return None
        return json.loads(raw.decode())

    def set_versioning(self, bucket: str, status: str) -> None:
        if status not in ("Enabled", "Suspended"):
            raise RGWError(400, "IllegalVersioningConfiguration",
                           status)
        with self._bmeta_lock:
            meta = self._bucket_meta(bucket)
            if meta is None:
                raise RGWError(404, "NoSuchBucket", bucket)
            meta["versioning"] = status       # RMW: keep created etc.
            self._modlog("sync_bucket", bucket)
            self._cls(self.meta, BUCKETS_OBJ, "dir_add", {
                "key": bucket, "meta": meta})
            self._modlog("sync_bucket", bucket)  # post-success

    def get_versioning(self, bucket: str) -> str:
        meta = self._bucket_meta(bucket)
        if meta is None:
            raise RGWError(404, "NoSuchBucket", bucket)
        return meta.get("versioning", "")

    @staticmethod
    def _new_version_id() -> str:
        # time-prefixed so lexical DESC order of the version dir rows
        # is newest-first (reference uses instance ids w/ an index
        # sort key); inverted timestamp keeps newest first
        import os
        inv = (1 << 63) - time.time_ns()
        return f"{inv:016x}.{os.urandom(6).hex()}"

    def _archive_version(self, bucket: str, key: str, meta: dict,
                         version_id: str,
                         bmeta: dict | None = None) -> None:
        """Record one immutable version row (newest sorts first).
        Version rows shard by PARENT key (all versions of a key
        colocate), so per-key order survives sharding."""
        self.index.add(bucket, "versions", f"{key}\x00{version_id}",
                       {**meta, "version_id": version_id},
                       route=key, bmeta=bmeta)

    def list_versions(self, bucket: str, prefix: str = "",
                      max_keys: int = 1000) -> list[dict]:
        """Version rows up to max_keys, newest-first per key; the
        newest row of each key is marked latest.  The merged cursor
        PAGINATES every underlying shard — a truncated page silently
        presented as complete would let version deletion drop live
        index entries — and yields rows in global row-key order
        (= key asc, newest version first within a key, because the
        inverted-timestamp version ids sort newest-first and a key's
        rows all live in one shard)."""
        self._require_bucket(bucket)
        cur = self.index.cursor(bucket, "versions", prefix=prefix,
                                page=min(max_keys, 1000) + 1)
        rows: list[dict] = []
        latest_seen: set[str] = set()
        while len(rows) < max_keys:
            ent = cur.next()
            if ent is None:
                break
            k, m = ent
            key = k.split("\x00", 1)[0]
            rows.append({"key": key, **m,
                         "is_latest": key not in latest_seen})
            latest_seen.add(key)
        return rows

    def _versions_of_key(self, bucket: str, key: str) -> list[dict]:
        # exact-key prefix: 'key' alone would also match 'keysuffix'
        return self.list_versions(bucket, prefix=f"{key}\x00",
                                  max_keys=100000)

    def _current_meta(self, bucket: str, key: str,
                      bmeta: dict | None = None) -> dict | None:
        try:
            raw = self.index.get(bucket, "index", key, bmeta=bmeta)
        except RadosError as e:
            self._not_found(e)
            return None
        return json.loads(raw.decode())

    def _archive_null_version(self, bucket: str, key: str,
                              bmeta: dict | None = None) -> None:
        """An object written BEFORE versioning was enabled has no
        version row; S3 makes it the "null" version.  Archive its
        existing meta (data stays at _data_oid / its multipart parts —
        the row records where) so enabling versioning never orphans or
        destroys pre-existing data."""
        cur = self._current_meta(bucket, key, bmeta=bmeta)
        if cur is None or cur.get("version_id"):
            return              # absent, or already versioned
        self._archive_version(bucket, key,
                              {**cur, "null_data": True}, "null",
                              bmeta=bmeta)

    def put_object(self, bucket: str, key: str, body: bytes,
                   extra: dict | None = None) -> str:
        """Returns the ETag (md5 hex, S3 semantics).  On a versioned
        bucket every PUT archives a new immutable version; the current
        pointer rides the bucket index like before.  `extra` merges
        additional rows into the object meta (owner/acl stamps from
        the gateway's auth layer)."""
        bmeta = self._bucket_meta(bucket)
        if bmeta is None:
            raise RGWError(404, "NoSuchBucket", bucket)
        owner = (extra or {}).get("owner") or bmeta.get("owner")
        cur = self._current_meta(bucket, key, bmeta=bmeta)
        cur_owner = (cur or {}).get("owner") or bmeta.get("owner")
        same = (cur is None or cur_owner == owner)
        # quota admits the NEW owner's growth; a same-owner overwrite
        # only pays the size delta
        q_obj = (0 if cur else 1) if same else 1
        q_bytes = (len(body) - (cur or {}).get("size", 0)) \
            if same else len(body)
        token = self._quota_gate(owner, q_obj, q_bytes)
        try:
            etag = hashlib.md5(body).hexdigest()
            self._modlog("sync", bucket, key)
            if bmeta.get("versioning") == "Enabled":
                self._archive_null_version(bucket, key)
                vid = self._new_version_id()
                meta = {"size": len(body), "etag": etag,
                        "mtime": time.time(), **(extra or {})}
                self.data.write_full(_version_oid(bucket, vid, key),
                                     body)
                self._archive_version(bucket, key, meta, vid,
                                      bmeta=bmeta)
                self.index.add(bucket, "index", key,
                               {**meta, "version_id": vid},
                               bmeta=bmeta)
                self._account_overwrite(bucket, key, cur, cur_owner,
                                        owner, len(body))
                self._publish(bucket, key, "s3:ObjectCreated:Put",
                              len(body), bmeta=bmeta)
                self._modlog("sync", bucket, key)   # post-success
                return etag
            suspended = bool(bmeta.get("versioning"))  # "" = never
            reap = self._displaced_manifests(bucket, key, suspended,
                                             cur=cur)
            meta = {"size": len(body), "etag": etag,
                    "mtime": time.time(), **(extra or {})}
            self.data.write_full(_data_oid(bucket, key), body)
            self.index.add(bucket, "index", key, meta, bmeta=bmeta)
            if suspended:
                # Suspended bucket: S3 says the PUT replaces the null
                # version — (re)write the null row to match the bytes
                self._archive_version(bucket, key,
                                      {**meta, "null_data": True},
                                      "null", bmeta=bmeta)
            for m in reap:
                self._reap_manifest(bucket, m)
            self._account_overwrite(bucket, key, cur, cur_owner, owner,
                                    len(body))
            self._publish(bucket, key, "s3:ObjectCreated:Put",
                          len(body), bmeta=bmeta)
            self._modlog("sync", bucket, key)       # post-success
            return etag
        finally:
            # accounting has landed (or the op died): the reservation
            # hands back to the shared totals
            self._quota_release(owner, token)

    def get_object_version(self, bucket: str, key: str,
                           version_id: str) -> tuple[bytes, dict]:
        self._require_bucket(bucket)
        try:
            raw = self.index.get(bucket, "versions",
                                 f"{key}\x00{version_id}", route=key)
        except RadosError as e:
            self._not_found(e)
            raise RGWError(404, "NoSuchVersion", version_id) from e
        meta = json.loads(raw.decode())
        if meta.get("delete_marker"):
            raise RGWError(405, "MethodNotAllowed",
                           "this version is a delete marker")
        manifest = meta.get("multipart")
        if manifest:
            # multipart versions (null or minted) read their parts in
            # place — each complete has a unique upload_id, so part
            # objects never collide across versions
            body = b"".join(
                bytes(self.data.read(_part_oid(
                    bucket, manifest["upload_id"], num), size))
                for num, size in manifest["parts"])
            return body, meta
        if meta.get("null_data"):
            body = self.data.read(_data_oid(bucket, key), meta["size"])
        else:
            body = self.data.read(
                _version_oid(bucket, version_id, key), meta["size"])
        return bytes(body), meta

    def delete_object_version(self, bucket: str, key: str,
                              version_id: str) -> None:
        """Permanent removal of ONE version (S3 semantics: the only
        way to truly destroy data on a versioned bucket).  Removing
        the current version promotes the next-newest."""
        self._require_bucket(bucket)
        vmeta = self._version_row(bucket, key, version_id)
        if vmeta is None:
            raise RGWError(404, "NoSuchVersion", version_id)
        bmeta = self._bucket_meta(bucket) or {}
        pre_cur = self._current_meta(bucket, key, bmeta=bmeta)
        self._modlog("sync", bucket, key)
        try:
            self.index.rm(bucket, "versions",
                          f"{key}\x00{version_id}", route=key,
                          bmeta=bmeta)
        except RadosError as e:
            self._not_found(e)
            raise RGWError(404, "NoSuchVersion", version_id) from e
        if vmeta.get("multipart"):
            # a multipart version owns its parts (unique upload_id)
            self._reap_manifest(bucket, vmeta["multipart"])
        elif version_id == "null":
            # the null version's payload lives at the unversioned
            # location; reap it
            try:
                self.data.remove(_data_oid(bucket, key))
            except RadosError:
                pass
        else:
            try:
                self.data.remove(_version_oid(bucket, version_id, key))
            except RadosError:
                pass
        cur = self._current_meta(bucket, key, bmeta=bmeta)
        cur_vid = cur.get("version_id") if cur is not None else None
        null_is_current = (cur is not None and cur_vid is None and
                           version_id == "null")
        if (cur is not None and cur_vid == version_id) or \
                null_is_current:
            # promote the next-newest remaining REAL version; a delete
            # marker on top means the key stays absent, never becomes
            # a phantom zero-byte object
            remaining = self._versions_of_key(bucket, key)
            nxt = remaining[0] if remaining else None
            if nxt is not None and not nxt.get("delete_marker"):
                drop = {"key", "is_latest"}
                if nxt.get("null_data"):
                    # restoring the null version restores the plain
                    # unversioned entry (data at _data_oid / manifest)
                    drop |= {"version_id", "null_data"}
                self.index.add(bucket, "index", key,
                               {k: v for k, v in nxt.items()
                                if k not in drop}, bmeta=bmeta)
            else:
                try:
                    self.index.rm(bucket, "index", key, bmeta=bmeta)
                except RadosError as e:
                    self._not_found(e)
        # CURRENT-view accounting: deleting the current version (or
        # promoting a different-size predecessor) changes the index
        # view the user stats track — without this, version surgery
        # permanently leaks quota
        post_cur = self._current_meta(bucket, key, bmeta=bmeta)
        if (pre_cur is None) != (post_cur is None) or (
                pre_cur is not None and post_cur is not None and
                (pre_cur.get("size"), pre_cur.get("owner")) !=
                (post_cur.get("size"), post_cur.get("owner"))):
            default_owner = bmeta.get("owner")
            if pre_cur is not None:
                self._user_stats(
                    pre_cur.get("owner") or default_owner, bucket,
                    -1, -pre_cur.get("size", 0))
            if post_cur is not None:
                self._user_stats(
                    post_cur.get("owner") or default_owner, bucket,
                    1, post_cur.get("size", 0))
        self._publish(bucket, key, "s3:ObjectRemoved:Delete",
                      bmeta=bmeta)
        self._modlog("sync", bucket, key)       # post-success

    def _version_row(self, bucket: str, key: str,
                     version_id: str) -> dict | None:
        try:
            raw = self.index.get(bucket, "versions",
                                 f"{key}\x00{version_id}", route=key)
        except RadosError as e:
            self._not_found(e)
            return None
        return json.loads(raw.decode())

    def _displaced_manifests(self, bucket: str, key: str,
                             suspended: bool,
                             cur: dict | None = None) -> list[dict]:
        """Manifests whose LAST reference disappears when a
        non-versioned write/delete displaces the current object: the
        current index row's manifest (unless its own version row
        still references it), plus — on a Suspended bucket, where S3
        says the write REPLACES the null version — the existing null
        row's manifest.  Reaping anything else would destroy an
        archived version's data; reaping less leaks parts forever."""
        out: dict[str, dict] = {}
        if cur is None:
            cur = self._current_meta(bucket, key)
        if cur and cur.get("multipart") and not cur.get("version_id"):
            out[cur["multipart"]["upload_id"]] = cur["multipart"]
        if suspended:
            row = self._version_row(bucket, key, "null")
            if row and row.get("multipart"):
                out[row["multipart"]["upload_id"]] = row["multipart"]
        return list(out.values())

    def _reap_manifest(self, bucket: str, manifest: dict | None) -> None:
        """Remove the part objects an overwritten/deleted manifest
        referenced (reference RGWRados gc of multipart parts)."""
        if not manifest:
            return
        for num, _size in manifest["parts"]:
            try:
                self.data.remove(
                    _part_oid(bucket, manifest["upload_id"], num))
            except RadosError:
                pass

    def head_object(self, bucket: str, key: str) -> dict:
        self._require_bucket(bucket)
        try:
            raw = self.index.get(bucket, "index", key)
        except RadosError as e:
            self._not_found(e)
            raise RGWError(404, "NoSuchKey", key) from e
        return json.loads(raw.decode())

    def get_object(self, bucket: str, key: str,
                   meta: dict | None = None) -> tuple[bytes, dict]:
        """`meta` short-circuits the index lookup when the caller
        already fetched the row (the gateway's ACL check) — the
        hottest read path must not pay two identical dir_gets."""
        if meta is None:
            meta = self.head_object(bucket, key)
        manifest = meta.get("multipart")
        if manifest:
            # stitch parts in part-number order (reference RGWGetObj
            # iterating the RGWObjManifest)
            body = b"".join(
                bytes(self.data.read(
                    _part_oid(bucket, manifest["upload_id"], num), size))
                for num, size in manifest["parts"])
            return body, meta
        if meta.get("version_id"):
            body = self.data.read(
                _version_oid(bucket, meta["version_id"], key),
                meta["size"])
        else:
            body = self.data.read(_data_oid(bucket, key), meta["size"])
        return body, meta

    def delete_object(self, bucket: str, key: str) -> None:
        bmeta = self._bucket_meta(bucket)
        if bmeta is None:
            raise RGWError(404, "NoSuchBucket", bucket)
        cur = self._current_meta(bucket, key, bmeta=bmeta)
        if cur is None and bmeta.get("versioning") != "Enabled":
            # validate BEFORE logging (both plain and Suspended paths
            # 404 on an absent key): a failed op must not feed the
            # mod-log or the usage/stats ledgers
            raise RGWError(404, "NoSuchKey", key)
        owner = (cur or {}).get("owner") or bmeta.get("owner")
        self._modlog("sync", bucket, key)
        if bmeta.get("versioning") == "Enabled":
            # versioned delete = insert a delete marker as the new
            # current; nothing is destroyed (reference delete markers)
            self._archive_null_version(bucket, key, bmeta=bmeta)
            vid = self._new_version_id()
            meta = {"size": 0, "etag": "", "mtime": time.time(),
                    "delete_marker": True}
            self._archive_version(bucket, key, meta, vid, bmeta=bmeta)
            try:
                self.index.rm(bucket, "index", key, bmeta=bmeta)
            except RadosError as e:
                self._not_found(e)
            if cur is not None:
                self._user_stats(owner, bucket, -1,
                                 -cur.get("size", 0))
            self._usage(owner, "delete_obj", bucket, key,
                        (cur or {}).get("size", 0))
            self._publish(bucket, key,
                          "s3:ObjectRemoved:DeleteMarkerCreated",
                          bmeta=bmeta)
            self._modlog("sync", bucket, key)   # post-success
            return
        suspended = bool(bmeta.get("versioning"))
        reap = self._displaced_manifests(bucket, key, suspended,
                                         cur=cur)
        try:
            self.index.rm(bucket, "index", key, bmeta=bmeta)
        except RadosError as e:
            self._not_found(e)
            raise RGWError(404, "NoSuchKey", key) from e
        if cur is not None:
            self._user_stats(owner, bucket, -1, -cur.get("size", 0))
        self._usage(owner, "delete_obj", bucket, key,
                    (cur or {}).get("size", 0))
        self._publish(bucket, key, "s3:ObjectRemoved:Delete",
                      bmeta=bmeta)
        if suspended:
            # S3: DELETE on a Suspended bucket replaces the null
            # version with a null DELETE MARKER (the displaced null
            # data is destroyed; version_id'd rows survive untouched)
            self._archive_version(bucket, key, {
                "size": 0, "etag": "", "mtime": time.time(),
                "delete_marker": True}, "null", bmeta=bmeta)
        for m in reap:
            self._reap_manifest(bucket, m)
        try:
            self.data.remove(_data_oid(bucket, key))
        except RadosError:
            pass
        self._modlog("sync", bucket, key)       # post-success

    def copy_object(self, src_bucket: str, src_key: str,
                    dst_bucket: str, dst_key: str,
                    extra: dict | None = None) -> dict:
        """Server-side copy (reference RGWCopyObj, rgw_op.h:1500s):
        the client never sees the bytes.  A multipart source is
        materialized into a plain destination object (the reference
        copies manifests tail-first; one data object is the honest
        equivalent at this scale)."""
        body, _meta = self.get_object(src_bucket, src_key)
        etag = self.put_object(dst_bucket, dst_key, bytes(body),
                               extra=extra)
        return {"etag": etag, "mtime": time.time()}

    # -- multipart uploads (reference rgw_op.h:1716-1754) -------------------

    def init_multipart(self, bucket: str, key: str) -> str:
        self._require_bucket(bucket)
        import os
        upload_id = os.urandom(16).hex()
        self._cls(self.meta, f"uploads.{bucket}", "dir_add", {
            "key": f"{key}\x00{upload_id}",
            "meta": {"key": key, "initiated": time.time()}})
        self._cls(self.meta, f"parts.{bucket}.{upload_id}", "dir_init")
        return upload_id

    def _require_upload(self, bucket: str, key: str,
                        upload_id: str) -> None:
        try:
            self._cls(self.meta, f"uploads.{bucket}", "dir_get",
                      {"key": f"{key}\x00{upload_id}"})
        except RadosError as e:
            self._not_found(e)
            raise RGWError(404, "NoSuchUpload", upload_id) from e

    def upload_part(self, bucket: str, key: str, upload_id: str,
                    part_num: int, body: bytes) -> str:
        if not 1 <= part_num <= 10000:
            raise RGWError(400, "InvalidArgument",
                           f"partNumber {part_num} not in 1..10000")
        self._require_upload(bucket, key, upload_id)
        etag = hashlib.md5(body).hexdigest()
        self.data.write_full(_part_oid(bucket, upload_id, part_num), body)
        self._cls(self.meta, f"parts.{bucket}.{upload_id}", "dir_add", {
            "key": f"{part_num:05d}",
            "meta": {"size": len(body), "etag": etag,
                     "mtime": time.time()}})
        return etag

    def list_parts(self, bucket: str, key: str, upload_id: str
                   ) -> list[tuple[int, dict]]:
        self._require_upload(bucket, key, upload_id)
        out = json.loads(self._cls(
            self.meta, f"parts.{bucket}.{upload_id}", "dir_list",
            {"max": 10000}).decode())
        return [(int(k), m) for k, m in out["entries"]]

    def list_multipart_uploads(self, bucket: str
                               ) -> list[tuple[str, str, dict]]:
        self._require_bucket(bucket)
        try:
            out = json.loads(self._cls(
                self.meta, f"uploads.{bucket}", "dir_list",
                {"max": 10000}).decode())
        except RadosError as e:
            self._not_found(e)
            return []
        rows = []
        for k, m in out["entries"]:
            key, _, upload_id = k.rpartition("\x00")
            rows.append((key, upload_id, m))
        return rows

    def complete_multipart(self, bucket: str, key: str, upload_id: str,
                           parts: list[tuple[int, str]],
                           extra: dict | None = None) -> str:
        """parts = [(part_num, etag), ...] from the client's
        CompleteMultipartUpload body.  Validates against what was
        uploaded (reference RGWCompleteMultipart::execute), writes the
        manifest index entry, reaps the upload bookkeeping.  The
        combined ETag is md5-of-binary-part-md5s + "-N" (S3
        convention)."""
        self._require_upload(bucket, key, upload_id)
        if not parts:
            raise RGWError(400, "MalformedXML", "no parts listed")
        have = dict(self.list_parts(bucket, key, upload_id))
        last = 0
        md5cat = b""
        manifest = []
        total = 0
        for num, etag in parts:
            if num <= last:
                raise RGWError(400, "InvalidPartOrder",
                               f"part {num} after {last}")
            last = num
            meta = have.get(num)
            if meta is None or meta["etag"] != etag.strip('"'):
                raise RGWError(400, "InvalidPart",
                               f"part {num} not uploaded or etag "
                               f"mismatch")
            md5cat += bytes.fromhex(meta["etag"])
            manifest.append([num, meta["size"]])
            total += meta["size"]
        bmeta = self._bucket_meta(bucket) or {}
        owner = (extra or {}).get("owner") or bmeta.get("owner")
        cur = self._current_meta(bucket, key, bmeta=bmeta)
        cur_owner = (cur or {}).get("owner") or bmeta.get("owner")
        same = (cur is None or cur_owner == owner)
        q_obj = (0 if cur else 1) if same else 1
        q_bytes = (total - (cur or {}).get("size", 0)) if same else total
        token = self._quota_gate(owner, q_obj, q_bytes)
        try:
            self._modlog("sync", bucket, key)   # validated: will mutate
            etag = f"{hashlib.md5(md5cat).hexdigest()}-{len(parts)}"
            obj_meta = {"size": total, "etag": etag,
                        "mtime": time.time(),
                        "multipart": {"upload_id": upload_id,
                                      "parts": manifest},
                        **(extra or {})}
            if bmeta.get("versioning") == "Enabled":
                # S3: CompleteMultipartUpload on a versioned bucket
                # mints a new object version like any PUT; the
                # overwritten current survives as a version row (its
                # manifest stays referenced by that row — never reaped
                # here)
                self._archive_null_version(bucket, key, bmeta=bmeta)
                vid = self._new_version_id()
                self._archive_version(bucket, key, obj_meta, vid,
                                      bmeta=bmeta)
                self.index.add(bucket, "index", key,
                               {**obj_meta, "version_id": vid},
                               bmeta=bmeta)
            else:
                suspended = bool(bmeta.get("versioning"))
                reap = self._displaced_manifests(bucket, key, suspended)
                self.index.add(bucket, "index", key, obj_meta,
                               bmeta=bmeta)
                if suspended:
                    # like put_object: the complete replaces the null
                    # version on a Suspended bucket
                    self._archive_version(
                        bucket, key, {**obj_meta, "null_data": True},
                        "null", bmeta=bmeta)
                for m in reap:
                    self._reap_manifest(bucket, m)
            # unreferenced parts (uploaded but not listed)
            listed = {num for num, _ in parts}
            for num in have:
                if num not in listed:
                    try:
                        self.data.remove(
                            _part_oid(bucket, upload_id, num))
                    except RadosError:
                        pass
            self._rm_upload_bookkeeping(bucket, key, upload_id)
            self._account_overwrite(bucket, key, cur, cur_owner, owner,
                                    total)
            self._publish(bucket, key,
                          "s3:ObjectCreated:CompleteMultipartUpload",
                          total, bmeta=bmeta)
            self._modlog("sync", bucket, key)   # post-success
            return etag
        finally:
            self._quota_release(owner, token)

    def abort_multipart(self, bucket: str, key: str,
                        upload_id: str) -> None:
        self._require_upload(bucket, key, upload_id)
        for num, _meta in self.list_parts(bucket, key, upload_id):
            try:
                self.data.remove(_part_oid(bucket, upload_id, num))
            except RadosError:
                pass
        self._rm_upload_bookkeeping(bucket, key, upload_id)

    def _rm_upload_bookkeeping(self, bucket: str, key: str,
                               upload_id: str) -> None:
        try:
            self._cls(self.meta, f"uploads.{bucket}", "dir_rm",
                      {"key": f"{key}\x00{upload_id}"})
        except RadosError:
            pass
        try:
            self.meta.remove(f"parts.{bucket}.{upload_id}")
        except RadosError:
            pass

    @staticmethod
    def _prefix_successor(p: str) -> str | None:
        """Smallest string ordering AFTER every string prefixed by p
        (None when no such string exists)."""
        while p and p[-1] == "\U0010ffff":
            p = p[:-1]
        if not p:
            return None
        return p[:-1] + chr(ord(p[-1]) + 1)

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "", max_keys: int = 1000,
                     delimiter: str = "", resume: str = ""
                     ) -> tuple[list, list[str], bool, str]:
        """(contents, common_prefixes, truncated, resume_point).  With
        a delimiter, keys sharing prefix+...+delimiter roll up into one
        CommonPrefixes entry (reference RGWListBucket delimiter
        handling — what `aws s3 ls` folder listings are made of).
        `marker` (StartAfter) is exclusive; `resume` (continuation
        token) is an INCLUSIVE lower bound and takes precedence.  The
        returned resume_point feeds the next request's `resume`:
        key+"\\0" past an emitted key, or the prefix successor past a
        rolled-up folder — so folders cost one index probe each (not a
        walk of every key underneath) and progress is guaranteed for
        ANY legal key bytes (no sentinel-collision livelock).

        Sharded buckets list through the merged cursor: one bounded
        page per shard in flight, entries in global key order — the
        truncation invariant (never present a truncated page as
        complete) holds per shard and merged, because `truncated` is
        literally "the cursor still holds an entry".  A truncated
        page stashes its live cursor under the returned resume token;
        the follow-up request continues it (buffered shard pages
        intact) instead of paying one re-seek dir_list per shard."""
        self._require_bucket(bucket)
        page = min(max_keys, 1000) + 1
        lay = self.index.read_layout(bucket)
        ckey = (bucket, prefix, marker, delimiter, page)
        mcur = self._take_cursor((*ckey, resume), lay) if resume \
            else None
        if mcur is None:
            mcur = self.index.cursor(bucket, "index", prefix=prefix,
                                     marker=marker, resume=resume,
                                     page=page, lay=lay)
        if not delimiter:
            entries: list[tuple[str, dict]] = []
            while len(entries) < max_keys:
                ent = mcur.next()
                if ent is None:
                    break
                entries.append((ent[0], ent[1]))
            nm = entries[-1][0] + "\x00" if entries else ""
            trunc = mcur.peek() is not None
            if trunc and nm:
                self._stash_cursor((*ckey, nm), lay, mcur)
            return entries, [], trunc, nm
        contents: list[tuple[str, dict]] = []
        prefixes: list[str] = []
        cur = resume
        while True:
            if len(contents) + len(prefixes) >= max_keys:
                # page budget reached: truncated iff anything remains
                # at/after the resume point (the old max:1 probe is
                # now just a peek at the merged stream)
                trunc = mcur.peek() is not None
                if trunc and cur:
                    self._stash_cursor((*ckey, cur), lay, mcur)
                return contents, prefixes, trunc, cur
            ent = mcur.next()
            if ent is None:
                break
            k, m = ent
            rest = k[len(prefix):]
            d = rest.find(delimiter)
            if d >= 0:
                cp = prefix + rest[: d + len(delimiter)]
                prefixes.append(cp)
                succ = self._prefix_successor(cp)
                if succ is None:
                    break          # nothing can sort after the folder
                cur = succ
                # skip the whole folder in one hop on every shard
                mcur.seek(succ)
            else:
                contents.append((k, m))
                cur = k + "\x00"
        return contents, prefixes, False, cur

    # -- index shard admin (reference radosgw-admin bucket reshard /
    #    bucket limit check; rgw/reshard.py does the heavy lifting) --------

    def reshard_bucket(self, bucket: str, shards: int) -> dict:
        """Manual online reshard to `shards` (start dual-write, copy,
        cut over); returns the post-cutover status."""
        return self.resharder.reshard(bucket, shards)

    def reshard_status(self, bucket: str) -> dict:
        return self.resharder.status(bucket)

    def reshard_sweep(self) -> dict:
        """One autoscale/resume pass (mgr tick, gateway maintenance
        loop, or tests)."""
        return self.resharder.sweep()

    def bucket_stats(self, bucket: str) -> dict:
        """Shard layout + per-shard entry counts + live reshard
        marker + in-process per-shard op counters."""
        bmeta = self._bucket_meta(bucket)
        if bmeta is None:
            raise RGWError(404, "NoSuchBucket", bucket)
        lay = self.index.read_layout(bucket, bmeta)
        fill = self.index.shard_counts(bucket, bmeta=bmeta)
        return {"bucket": bucket, "shards": lay.shards,
                "gen": lay.gen, "objects": sum(fill.values()),
                "shard_fill": fill,
                "reshard": bmeta.get("reshard"),
                "perf": self.index.perf_dump(bucket)}

    def bucket_limit_check(self) -> list[dict]:
        """Per-bucket shard-fill report (reference `radosgw-admin
        bucket limit check`): objects per shard vs
        rgw_max_objs_per_shard, with OK / WARN (>50% of the reshard
        threshold) / OVER status."""
        max_objs = SCHEMA["rgw_max_objs_per_shard"].default
        out = []
        for bucket, bmeta in self.list_buckets():
            lay = self.index.read_layout(bucket, bmeta)
            count = self.index.count(bucket, bmeta=bmeta)
            per_shard = count / max(1, lay.shards)
            fill = per_shard / max_objs
            status = ("OVER" if per_shard > max_objs else
                      "WARN" if fill > 0.5 else "OK")
            out.append({"bucket": bucket, "shards": lay.shards,
                        "objects": count,
                        "objects_per_shard": round(per_shard, 1),
                        "fill_ratio": round(fill, 4),
                        "status": status})
        return out
