"""RGWRados role: bucket/object layout over librados.

Re-expresses the reference's src/rgw/rgw_rados.cc storage model at the
fidelity the S3 surface needs:

- bucket registry: a directory object ("buckets") in the meta pool,
  maintained by the rgw object class (atomic server-side updates —
  reference cls_rgw + the RGWRados bucket metadata handlers)
- per-bucket index: one directory object ("index.<bucket>") in the
  meta pool (reference bucket index shards; one shard here)
- object data: one rados object per S3 object in the data pool, named
  with a length-prefixed bucket separator so keys may contain any
  character (reference rgw_obj raw-object naming)

The data pool may be erasure-coded (pass an EC profile); the meta pool
is replicated, matching the reference's constraint that index pools be
replicated.
"""

from __future__ import annotations

import errno
import hashlib
import json
import time

from ..rados.client import RadosError

META_POOL = ".rgw.meta"
DATA_POOL = ".rgw.data"
BUCKETS_OBJ = "buckets"


class RGWError(Exception):
    def __init__(self, status: int, code: str, msg: str = ""):
        super().__init__(f"{code}: {msg}")
        self.status = status
        self.code = code


def _data_oid(bucket: str, key: str) -> str:
    return f"{len(bucket)}_{bucket}_{key}"


class RGWStore:
    def __init__(self, client, ec_profile: str | None = None,
                 pg_num: int = 8):
        self.client = client
        self._ensure_pools(ec_profile, pg_num)
        self.meta = client.open_ioctx(META_POOL)
        self.data = client.open_ioctx(DATA_POOL)
        self._cls(self.meta, BUCKETS_OBJ, "dir_init")

    def _ensure_pools(self, ec_profile, pg_num) -> None:
        for name, kind in ((META_POOL, "replicated"),
                           (DATA_POOL,
                            "erasure" if ec_profile else "replicated")):
            try:
                kw = {"pg_num": pg_num}
                if kind == "erasure":
                    kw["erasure_code_profile"] = ec_profile
                else:
                    kw["size"] = 2
                self.client.create_pool(name, kind, **kw)
            except RadosError as e:
                if e.errno != errno.EEXIST:
                    raise

    def _cls(self, io, oid: str, method: str, payload: dict | None = None
             ) -> bytes:
        inp = json.dumps(payload).encode() if payload is not None else b""
        return io.execute(oid, "rgw", method, inp)

    # -- buckets -------------------------------------------------------------

    def create_bucket(self, bucket: str) -> None:
        if not bucket or "/" in bucket:
            raise RGWError(400, "InvalidBucketName", bucket)
        self._cls(self.meta, BUCKETS_OBJ, "dir_add", {
            "key": bucket,
            "meta": {"created": time.time()}})
        self._cls(self.meta, f"index.{bucket}", "dir_init")

    @staticmethod
    def _not_found(e: RadosError) -> bool:
        """Only ENOENT means absence; anything else is a cluster fault
        that must surface as a 5xx, not a phantom 404 (a sync client
        treating EIO as 'gone' would re-upload or diverge)."""
        if e.errno == errno.ENOENT:
            return True
        raise RGWError(503, "ServiceUnavailable", str(e))

    def bucket_exists(self, bucket: str) -> bool:
        try:
            self._cls(self.meta, BUCKETS_OBJ, "dir_get", {"key": bucket})
            return True
        except RadosError as e:
            return not self._not_found(e)

    def delete_bucket(self, bucket: str) -> None:
        self._require_bucket(bucket)
        count = int(self._cls(self.meta, f"index.{bucket}", "dir_count"))
        if count:
            raise RGWError(409, "BucketNotEmpty", bucket)
        self._cls(self.meta, BUCKETS_OBJ, "dir_rm", {"key": bucket})
        try:
            self.meta.remove(f"index.{bucket}")
        except RadosError:
            pass

    def list_buckets(self) -> list[tuple[str, dict]]:
        out = json.loads(self._cls(self.meta, BUCKETS_OBJ, "dir_list",
                                   {"max": 10000}).decode())
        return [(k, m) for k, m in out["entries"]]

    def _require_bucket(self, bucket: str) -> None:
        if not self.bucket_exists(bucket):
            raise RGWError(404, "NoSuchBucket", bucket)

    # -- objects -------------------------------------------------------------

    def put_object(self, bucket: str, key: str, body: bytes) -> str:
        """Returns the ETag (md5 hex, S3 semantics)."""
        self._require_bucket(bucket)
        etag = hashlib.md5(body).hexdigest()
        self.data.write_full(_data_oid(bucket, key), body)
        self._cls(self.meta, f"index.{bucket}", "dir_add", {
            "key": key, "meta": {"size": len(body), "etag": etag,
                                 "mtime": time.time()}})
        return etag

    def head_object(self, bucket: str, key: str) -> dict:
        self._require_bucket(bucket)
        try:
            raw = self._cls(self.meta, f"index.{bucket}", "dir_get",
                            {"key": key})
        except RadosError as e:
            self._not_found(e)
            raise RGWError(404, "NoSuchKey", key) from e
        return json.loads(raw.decode())

    def get_object(self, bucket: str, key: str) -> tuple[bytes, dict]:
        meta = self.head_object(bucket, key)
        body = self.data.read(_data_oid(bucket, key), meta["size"])
        return body, meta

    def delete_object(self, bucket: str, key: str) -> None:
        self._require_bucket(bucket)
        try:
            self._cls(self.meta, f"index.{bucket}", "dir_rm",
                      {"key": key})
        except RadosError as e:
            self._not_found(e)
            raise RGWError(404, "NoSuchKey", key) from e
        try:
            self.data.remove(_data_oid(bucket, key))
        except RadosError:
            pass

    def list_objects(self, bucket: str, prefix: str = "",
                     marker: str = "", max_keys: int = 1000
                     ) -> tuple[list[tuple[str, dict]], bool]:
        self._require_bucket(bucket)
        out = json.loads(self._cls(
            self.meta, f"index.{bucket}", "dir_list",
            {"prefix": prefix, "marker": marker,
             "max": max_keys}).decode())
        return [(k, m) for k, m in out["entries"]], out["truncated"]
