"""Canned-ACL decision shared by BOTH REST dialects (reference
rgw_acl.h RGWAccessControlPolicy::verify_permission — one policy
evaluator behind rgw_rest_s3 and rgw_rest_swift alike).

One predicate, one truth: the S3 gateway and the Swift frontend must
never drift on what a canned ACL grants.
"""

from __future__ import annotations

CANNED_ACLS = ("private", "public-read", "public-read-write",
               "authenticated-read")


def canned_allows(identity: str | None, owner: str | None,
                  canned: str, perm: str) -> bool:
    """identity None = anonymous.  perm is 'READ' or 'WRITE'; any
    other perm string (ACP ops, OWNER-only admin) is owner-only by
    construction — no canned grant names it.  Ownerless (legacy)
    resources are open to any authenticated caller."""
    if identity is not None and (owner is None or identity == owner):
        return True
    if canned == "public-read-write":
        return perm in ("READ", "WRITE")
    if canned == "public-read":
        return perm == "READ"
    if canned == "authenticated-read":
        return perm == "READ" and identity is not None
    return False        # private
