"""Online bucket resharding (reference RGWReshard, rgw_reshard.cc).

Protocol — three durable states, all riding the bucket-meta row (the
reshard marker is the reference's cls_rgw_bucket_instance_entry
RESHARD_IN_PROGRESS state on the bucket instance):

1. **dual** — `start()` stamps {"reshard": {"shards": M, "gen": G+1,
   "state": "dual", "progress": ...}} into the bucket meta.  From the
   moment a writer reads that meta, every index mutation lands on the
   OLD shard set (still authoritative; all reads come from it) AND
   the NEW one; deletes tombstone on the new side (cls_rgw dir_rm
   tombstone mode).  A grace dwell (rgw_reshard_grace_s) lets writers
   holding a pre-marker bucket meta finish their single-layout writes
   before any copying starts — their entries are then on the old
   shards, where the copier will find them.
2. **copy** — `run()` pages each old shard (dir_list) and applies the
   pages to the new layout with dir_merge if_absent: an entry the
   dual-writers already placed (newer) or tombstoned (deleted) is
   never overwritten or resurrected.  Progress (old shards fully
   copied, per plane) persists in the marker after every shard, so a
   killed daemon resumes where it stopped — and re-copying a
   half-copied shard is idempotent by the same if_absent rule.
3. **cutover** — one bucket-meta RMW under the store's meta lock
   flips "index" to the new layout and drops the marker.  Writers
   pick up the new meta on their next read; old shards are reaped and
   the new shards' tombstone rows cleaned (dir_reshard_clean).

The autoscaler (`sweep()`, driven by the mgr rgw_reshard module and
the gateway's maintenance loop) doubles the shard count to the next
power of two whenever entries/shard exceeds rgw_max_objs_per_shard —
the reference's dynamic resharding — and resumes any reshard left in
the dual state by a dead daemon.
"""

from __future__ import annotations

import json
import threading
import time

from ..common.options import SCHEMA
from ..common.util import next_pow2
from ..rados.client import RadosError
from .bucket_index import _Layout, shard_of

BUCKETS_OBJ = "buckets"


def _opt(name: str):
    return SCHEMA[name].default


class Resharder:
    def __init__(self, store):
        self.store = store
        self._mu = threading.Lock()     # one sweep/run at a time

    # -- admin surface ----------------------------------------------

    def status(self, bucket: str) -> dict:
        st = self.store
        bmeta = st._bucket_meta(bucket)
        if bmeta is None:
            from .store import RGWError
            raise RGWError(404, "NoSuchBucket", bucket)
        lay = _Layout.from_bmeta(bucket, bmeta)
        return {"bucket": bucket, "shards": lay.shards,
                "gen": lay.gen,
                "objects": st.index.count(bucket, bmeta=bmeta),
                "reshard": bmeta.get("reshard")}

    def start(self, bucket: str, shards: int) -> dict:
        """Enter the dual-write state (durable marker + new shard
        objects initialized).  Copy/cutover happen in run()."""
        from .store import RGWError
        st = self.store
        shards = int(shards)
        if shards < 1:
            raise RGWError(400, "InvalidArgument",
                           f"shard count {shards}")
        with st._bmeta_lock:
            bmeta = st._bucket_meta(bucket)
            if bmeta is None:
                raise RGWError(404, "NoSuchBucket", bucket)
            if bmeta.get("reshard"):
                raise RGWError(409, "OperationAborted",
                               f"{bucket}: reshard already in progress")
            old = _Layout.from_bmeta(bucket, bmeta)
            if shards == old.shards:
                raise RGWError(400, "InvalidArgument",
                               f"{bucket} already has {shards} shards")
            marker = {"shards": shards, "gen": old.gen + 1,
                      "state": "dual", "started": time.time(),
                      "progress": {"index": 0, "versions": 0}}
            bmeta["reshard"] = marker
            st._cls(st.meta, BUCKETS_OBJ, "dir_add",
                    {"key": bucket, "meta": bmeta})
        new = _Layout(bucket, shards, old.gen + 1)
        for plane in ("index", "versions"):
            for oid in new.oids(plane):
                st._cls(st.meta, oid, "dir_init")
        return marker

    def reshard(self, bucket: str, shards: int) -> dict:
        """Manual `bucket reshard`: start + run to completion."""
        self.start(bucket, shards)
        return self.run(bucket)

    # -- copy + cutover ----------------------------------------------

    def _progress(self, bucket: str, gen: int, plane: str,
                  done: int) -> None:
        """Durably record `done` old shards fully copied for `plane`
        (the resume point a revived daemon starts from)."""
        st = self.store
        with st._bmeta_lock:
            bmeta = st._bucket_meta(bucket)
            rs = (bmeta or {}).get("reshard")
            if not rs or rs.get("gen") != gen:
                return          # cut over or superseded meanwhile
            rs["progress"][plane] = done
            st._cls(st.meta, BUCKETS_OBJ, "dir_add",
                    {"key": bucket, "meta": bmeta})

    def _copy_shard(self, old_oid: str, bucket: str, new: _Layout,
                    plane: str, batch: int) -> int:
        """Page one old shard into the new layout.  Version rows
        route by PARENT key (everything left of the \\x00 separator)
        so a key's versions stay colocated."""
        st = self.store
        frm = ""
        copied = 0
        while True:
            try:
                out = json.loads(st._cls(
                    st.meta, old_oid, "dir_list",
                    {"from": frm, "max": batch}).decode())
            except RadosError as e:
                st._not_found(e)
                return copied   # legacy plane object never created
            entries = out["entries"]
            if not entries:
                return copied
            groups: dict[str, list] = {}
            for k, m in entries:
                route = k.split("\x00", 1)[0] if plane == "versions" \
                    else k
                oid = new.oid(plane, shard_of(route, new.shards))
                groups.setdefault(oid, []).append([k, m])
            for oid, ents in groups.items():
                copied += int(st._cls(
                    st.meta, oid, "dir_merge",
                    {"entries": ents, "if_absent": True}))
            frm = entries[-1][0] + "\x00"
            if not out["truncated"]:
                return copied

    def run(self, bucket: str) -> dict:
        """Copy + cutover for an in-progress (dual) reshard; safe to
        call again after a crash — progress resumes from the durable
        marker and re-copies are idempotent."""
        st = self.store
        bmeta = st._bucket_meta(bucket)
        rs = (bmeta or {}).get("reshard")
        if not rs or rs.get("state") != "dual":
            return self.status(bucket)
        gen = rs["gen"]
        old = _Layout.from_bmeta(bucket, bmeta)
        new = _Layout(bucket, rs["shards"], gen)
        # grace: writers that fetched bucket meta just before the
        # marker landed must drain before the copy snapshots old shards
        dwell = _opt("rgw_reshard_grace_s") - (
            time.time() - rs.get("started", 0.0))
        if dwell > 0:
            time.sleep(dwell)
        batch = _opt("rgw_reshard_batch")
        copied = 0
        for plane in ("index", "versions"):
            start_at = int(rs["progress"].get(plane, 0))
            oids = old.oids(plane)
            for i in range(start_at, len(oids)):
                copied += self._copy_shard(oids[i], bucket, new,
                                           plane, batch)
                self._progress(bucket, gen, plane, i + 1)
        # cutover: one meta RMW makes the new layout authoritative
        with st._bmeta_lock:
            bmeta = st._bucket_meta(bucket)
            rs2 = (bmeta or {}).get("reshard")
            if not rs2 or rs2.get("gen") != gen:
                return self.status(bucket)      # superseded
            bmeta["index"] = {"shards": new.shards, "gen": gen}
            del bmeta["reshard"]
            st._cls(st.meta, BUCKETS_OBJ, "dir_add",
                    {"key": bucket, "meta": bmeta})
        for plane in ("index", "versions"):
            for oid in new.oids(plane):
                try:
                    st._cls(st.meta, oid, "dir_reshard_clean")
                except RadosError as e:
                    st._not_found(e)
            for oid in old.oids(plane):
                try:
                    st.meta.remove(oid)
                except RadosError:
                    pass
        out = self.status(bucket)
        out["copied"] = copied
        return out

    # -- dynamic autoscaling ------------------------------------------

    def sweep(self) -> dict:
        """One maintenance pass (mgr tick / gateway loop): resume any
        interrupted reshard, then autoscale buckets whose per-shard
        entry count exceeds rgw_max_objs_per_shard.  Per-bucket
        RadosError is swallowed — a degraded cluster retries on the
        next tick from the durable marker."""
        if not self._mu.acquire(blocking=False):
            return {"skipped": "sweep already running"}
        try:
            stats = {"resumed": 0, "started": 0, "errors": 0}
            max_objs = _opt("rgw_max_objs_per_shard")
            cap = _opt("rgw_reshard_max_shards")
            for bucket, bmeta in self.store.list_buckets():
                try:
                    if (bmeta.get("reshard") or {}).get("state") \
                            == "dual":
                        self.run(bucket)
                        stats["resumed"] += 1
                        continue
                    lay = _Layout.from_bmeta(bucket, bmeta)
                    count = self.store.index.count(bucket, bmeta=bmeta)
                    if count <= lay.shards * max_objs:
                        continue
                    target = min(cap, next_pow2(
                        -(-count // max_objs)))
                    if target > lay.shards:
                        self.start(bucket, target)
                        self.run(bucket)
                        stats["started"] += 1
                except RadosError:
                    stats["errors"] += 1
            return stats
        finally:
            self._mu.release()
