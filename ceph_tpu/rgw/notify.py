"""RGW bucket notifications (reference src/rgw/rgw_notify.cc +
rgw_pubsub.cc, reduced to the http-push core).

Model (the reference's shape):
  topic      named push destination (here: an http endpoint — the
             reference also speaks amqp/kafka)
  binding    per-bucket notification config: topic + event filter
             (s3:ObjectCreated:*, s3:ObjectRemoved:*) + optional key
             prefix
  delivery   events publish into a per-topic cls_journal queue and a
             background pusher POSTs them to the endpoint with
             at-least-once semantics (the queue position only advances
             after a 2xx), mirroring the reference's persistent-topic
             reservation/commit flow

Event payload follows the S3 event-record shape (eventName,
s3.bucket.name, s3.object.key/size) so receivers written for S3 can
parse it.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request

from .store import RGWError, RGWStore

TOPICS_OBJ = "rgw_topics"


class NotificationManager:
    """Owns topics + bucket bindings + the delivery pusher for one
    zone.  Attach with RGWStore.enable_notifications()."""

    def __init__(self, store: RGWStore, push_interval: float = 0.25):
        self.store = store
        self.meta = store.meta
        self.meta.execute(TOPICS_OBJ, "rgw", "dir_init", b"")
        self._stop = threading.Event()
        self._pusher = threading.Thread(
            target=self._push_loop, daemon=True, name="rgw-notify")
        self.push_interval = push_interval
        self.delivered = 0            # observability/tests
        self._topics_cache: tuple[float, dict] | None = None
        self._draining: set[str] = set()   # per-topic isolation
        self._drain_lock = threading.Lock()
        self._pusher.start()

    def shutdown(self) -> None:
        self._stop.set()
        self._pusher.join(5)

    # -- topics (reference rgw_pubsub topics) -------------------------------

    def create_topic(self, name: str, endpoint: str) -> None:
        self.store._cls(self.meta, TOPICS_OBJ, "dir_add", {
            "key": name, "meta": {"endpoint": endpoint}})
        self.meta.execute(f"topic.{name}", "journal", "create", b"")
        self.meta.execute(
            f"topic.{name}", "journal", "client_register",
            json.dumps({"id": "pusher", "pos": -1}).encode())
        self._topics_cache = None

    def topics(self, max_age: float = 1.0) -> dict[str, dict]:
        now = time.time()
        if self._topics_cache is not None and \
                now - self._topics_cache[0] < max_age:
            return self._topics_cache[1]
        raw = self.store._cls(self.meta, TOPICS_OBJ, "dir_list",
                              {"max": 10000})
        out = {k: m for k, m in json.loads(raw.decode())["entries"]}
        self._topics_cache = (now, out)
        return out

    def delete_topic(self, name: str) -> None:
        try:
            self.store._cls(self.meta, TOPICS_OBJ, "dir_rm",
                            {"key": name})
        except Exception:  # noqa: BLE001 - absent already
            pass
        # the queue dies with the topic: stale bucket bindings keep
        # matching but publish() filters them against topics(), so
        # nothing appends to (or leaks in) an orphan journal
        try:
            self.meta.remove(f"topic.{name}")
        except Exception:  # noqa: BLE001
            pass
        self._topics_cache = None

    # -- bucket bindings (reference bucket notification conf) ---------------

    def put_bucket_notification(self, bucket: str,
                                configs: list[dict]) -> None:
        """configs: [{"id", "topic", "events": [...], "prefix": ""}].
        Stored on the bucket meta row like acl/policy/lifecycle."""
        known = self.topics()
        for c in configs:
            if c.get("topic") not in known:
                raise RGWError(400, "InvalidArgument",
                               f"unknown topic {c.get('topic')!r}")
            for ev in c.get("events", []):
                if not ev.startswith("s3:Object"):
                    raise RGWError(400, "InvalidArgument",
                                   f"unsupported event {ev!r}")
        with self.store._bmeta_lock:
            meta = self.store._bucket_meta(bucket)
            if meta is None:
                raise RGWError(404, "NoSuchBucket", bucket)
            if configs:
                meta["notifications"] = configs
            else:
                meta.pop("notifications", None)
            from .store import BUCKETS_OBJ
            self.store._cls(self.meta, BUCKETS_OBJ, "dir_add", {
                "key": bucket, "meta": meta})

    def get_bucket_notification(self, bucket: str) -> list[dict]:
        meta = self.store._bucket_meta(bucket)
        if meta is None:
            raise RGWError(404, "NoSuchBucket", bucket)
        return meta.get("notifications", [])

    # -- event publication (store hooks call this) --------------------------

    @staticmethod
    def _matches(cfg: dict, event: str, key: str) -> bool:
        if key and not key.startswith(cfg.get("prefix", "")):
            return False
        wanted = cfg.get("events") or ["s3:Object*"]
        return any(event == w or
                   (w.endswith("*") and event.startswith(w[:-1]))
                   for w in wanted)

    def publish(self, bucket: str, key: str, event: str,
                size: int = 0, bmeta: dict | None = None) -> None:
        meta = bmeta if bmeta is not None \
            else self.store._bucket_meta(bucket)
        if not meta or not meta.get("notifications"):
            return
        import datetime
        iso = datetime.datetime.now(datetime.timezone.utc).strftime(
            "%Y-%m-%dT%H:%M:%S.%fZ")     # S3 carries ISO8601, not epoch
        record = {
            "eventVersion": "2.2", "eventSource": "ceph_tpu:rgw",
            "eventTime": iso, "eventName": event,
            "s3": {"bucket": {"name": bucket},
                   "object": {"key": key, "size": size}},
        }
        live = self.topics()
        for cfg in meta["notifications"]:
            if cfg["topic"] in live and \
                    self._matches(cfg, event, key):
                self.meta.execute(
                    f"topic.{cfg['topic']}", "journal", "append",
                    json.dumps({"entry": {"cfg_id": cfg.get("id"),
                                          "record": record}}).encode())

    # -- delivery (reference persistent-topic push with commit) -------------

    def _push_loop(self) -> None:
        while not self._stop.wait(self.push_interval):
            try:
                # one drain thread per topic: a hung endpoint must not
                # stall every other topic's delivery for its timeout
                for name, tmeta in self.topics().items():
                    with self._drain_lock:
                        if name in self._draining:
                            continue
                        self._draining.add(name)
                    threading.Thread(
                        target=self._drain_guarded,
                        args=(name, tmeta["endpoint"]), daemon=True,
                        name=f"rgw-notify-{name}").start()
            except Exception:  # noqa: BLE001 - zone shutting down etc.
                continue

    def _drain_guarded(self, name: str, endpoint: str) -> None:
        try:
            self._drain_topic(name, endpoint)
        except Exception:  # noqa: BLE001 - topic deleted mid-drain
            pass
        finally:
            with self._drain_lock:
                self._draining.discard(name)

    def _drain_topic(self, name: str, endpoint: str,
                     batch: int = 64) -> None:
        oid = f"topic.{name}"
        raw = self.meta.execute(oid, "journal", "client_get",
                                json.dumps({"id": "pusher"}).encode())
        pos = int(json.loads(raw.decode())["pos"])
        raw = self.meta.execute(
            oid, "journal", "list",
            json.dumps({"after_seq": pos, "max": batch}).encode())
        entries = json.loads(raw.decode())["entries"]
        last_ok = None
        for seq, entry in entries:
            body = json.dumps({"Records": [entry["record"]]}).encode()
            req = urllib.request.Request(
                endpoint, data=body, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                # non-2xx raises HTTPError, landing in the except arm
                with urllib.request.urlopen(req, timeout=10):
                    pass
            except Exception:  # noqa: BLE001 - receiver down/erroring:
                break                     # at-least-once, retry later
            last_ok = seq
            self.delivered += 1
        if last_ok is not None:
            # ONE commit + trim per drained batch (position only moves
            # past what actually got a 2xx — commit-after-push)
            self.meta.execute(
                oid, "journal", "client_update",
                json.dumps({"id": "pusher", "pos": last_ok}).encode())
            self.meta.execute(oid, "journal", "trim",
                              json.dumps({"to_seq": last_ok}).encode())
