"""ceph_tpu — a TPU-native distributed storage-compute framework.

A from-scratch reimplementation of the capabilities of Ceph (reference:
javacruft/ceph, Octopus 15.1.0) designed TPU-first: the erasure-code data
plane runs as bit-sliced GF(2^8) matmuls on the MXU (JAX/Pallas), stripes
are batched into tensors, shardings over a `jax.sharding.Mesh` replace
NCCL-style collectives, and the host-side control plane (plugin registry,
OSD pipeline, CRUSH placement, messenger, monitor) keeps Ceph's contracts
without porting its C++.

Layer map (mirrors reference SURVEY.md section 1):
  common/   foundations: bufferlist, crc32c, config, logging, perf counters
  ec/       erasure-code subsystem (interface, registry, plugins)
  ops/      JAX/Pallas kernels: GF(2^8) bit-sliced matmul, crc32c, bitpack
  osd/      EC write/read/recovery pipeline (ECUtil, ECBackend, PGLog)
  crush/    deterministic placement (straw2, rjenkins hash)
  msg/      async messenger (framed, crc-protected protocol)
  mon/      monitor: cluster-map authority
  osdc/     objecter (client-side op engine)
  rados/    librados-like public client API
  store/    ObjectStore contract + MemStore / FileStore-lite
  parallel/ device-mesh sharding of the stripe-batch data plane
  tools/    benchmark + CLI tools
"""

__version__ = "0.1.0"

# Mirrors CEPH_RELEASE / ceph_release ("15 octopus rc") versioning role:
# plugins embed this and the registry refuses mismatches (reference:
# src/erasure-code/ErasureCodePlugin.cc:142).
PLUGIN_ABI_VERSION = "ceph-tpu-plugin-1"
