"""Tile/variant sweep for the w32 encode kernel on real TPU hardware.

Times gf_bitmatmul_pallas_w32 across per-chunk tile sizes for both the
all-planes kernel (stream=False) and the streaming-accumulation kernel
(stream=True), with the same chained-fori_loop slope method bench.py
uses (defeats dispatch elision over the axon tunnel; see bench.py
docstring).  Verifies bit-exactness of every variant against the XLA
oracle before timing it.  Prints one JSON line per configuration.

Usage: python -m ceph_tpu.tools.w32_sweep [--quick]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

K, M = 8, 3
PER_CHUNK = 4 << 20           # resident bytes per chunk (divides all tiles)
TILES = [1 << 16, 1 << 17, 1 << 18, 1 << 19, 1 << 20, 1 << 21, 1 << 22]


def slope_time(step, x0, rows, iters_lo=50, iters_hi=150, passes=3):
    """bench.py-style chained slope timing; returns sec/iteration."""
    import jax
    from jax import lax

    def make(iters):
        @jax.jit
        def f(x):
            def body(i, x):
                r = step(x)
                return x.at[:rows, :].set(x[:rows, :] ^ r)
            return lax.fori_loop(0, iters, body, x)
        return f

    f_lo, f_hi = make(iters_lo), make(iters_hi)
    reps = 3
    variants = [jax.block_until_ready(x0 ^ (i + 1)) for i in range(reps)]
    jax.block_until_ready(f_lo(x0))
    jax.block_until_ready(f_hi(x0))
    dts = []
    for _ in range(passes + 2):
        lo, hi = [], []
        for i in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(f_lo(variants[i]))
            lo.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            jax.block_until_ready(f_hi(variants[i]))
            hi.append(time.perf_counter() - t0)
        dt = (min(hi) - min(lo)) / (iters_hi - iters_lo)
        if dt > 0:
            dts.append(dt)
            if len(dts) >= passes:
                break
        variants = [jax.block_until_ready(v ^ 0x5A) for v in variants]
    if not dts:
        raise RuntimeError("non-positive slope (tunnel noise)")
    dts.sort()
    return dts[len(dts) // 2]


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="fewer iterations (smoke)")
    ap.add_argument("--tiles", default=None,
                    help="comma-separated per-chunk tile bytes")
    ap.add_argument("--variants", default="0,1",
                    help="comma list: 0=all-planes, 1=streaming")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp

    from ..ec import gf
    from ..ops import bitsliced as bs

    backend = jax.default_backend()
    print(f"# backend: {backend}", file=sys.stderr)
    on_tpu = backend != "cpu"

    mat = gf.cauchy_rs_matrix(K, M)[K:]
    bitmat32 = jnp.asarray(bs._w32_bitmat(mat), dtype=jnp.int8)
    bitmat8 = jnp.asarray(bs.interleave_bitmatrix(mat), dtype=jnp.int8)

    rng = np.random.default_rng(7)
    per_chunk = PER_CHUNK if on_tpu and not args.quick else 1 << 18
    flat = rng.integers(0, 256, (K, per_chunk), dtype=np.uint8)
    words = jnp.asarray(flat.view("<u4").view(np.int32))
    total_bytes = K * per_chunk

    # oracle (small slice, byte path)
    small = flat[:, : 1 << 16]
    want = np.asarray(bs.gf_bitmatmul_xla(
        bitmat8, jnp.asarray(small), M))
    small_words = jnp.asarray(small.view("<u4").view(np.int32))

    tiles = ([int(t) for t in args.tiles.split(",")]
             if args.tiles else TILES)
    variants = [bool(int(v)) for v in args.variants.split(",")]
    iters = (10, 30) if args.quick else (30, 90)
    for stream in variants:
        # bit-exactness on hardware before any timing
        try:
            got = np.asarray(bs.gf_bitmatmul_pallas_w32(
                bitmat32, small_words, M, tile=1 << 15,
                interpret=not on_tpu, stream=stream))
            got_bytes = got.view("<u4").view(np.uint8).reshape(M, -1)
            exact = bool((got_bytes == want).all())
        except Exception as e:  # noqa: BLE001 - variant unsupported
            print(json.dumps({"stream": stream,
                              "error": str(e)[:200]}), flush=True)
            continue
        for tile in tiles:
            if tile > per_chunk:
                continue
            rec = {"stream": stream, "tile": tile, "exact": exact}
            try:
                def step(x, _t=tile, _s=stream):
                    return bs.gf_bitmatmul_pallas_w32(
                        bitmat32, x, M, tile=_t,
                        interpret=not on_tpu, stream=_s)
                dt = slope_time(step, words, M,
                                iters_lo=iters[0], iters_hi=iters[1])
                rec["gbps"] = round(total_bytes / dt / 1e9, 1)
            except Exception as e:  # noqa: BLE001 - report and continue
                rec["error"] = str(e)[:200]
            print(json.dumps(rec), flush=True)


if __name__ == "__main__":
    main()
