"""CLI tools (reference src/tools/, src/test/erasure-code/ benchmark)."""
