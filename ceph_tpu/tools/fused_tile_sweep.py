"""Sweep CLI for the fused parity+crc kernel's operating point.

This used to be a hand-run script whose winners were frozen into
bitsliced.FUSED_TILE_HIER / FUSED_WB; the machinery now lives in
ops/autotune.py, which the jax plugin consults at init (validated,
measured, cached per device).  This CLI drives the same sweep
explicitly, prints the per-candidate table, and refreshes the cache —
use it to inspect WHY the plugin picked its point, or to re-tune after
a runtime/hardware change.

Usage: python -m ceph_tpu.tools.fused_tile_sweep [--keep-cache] [tiles...]

By default the sweep is forced (the cache entry is refreshed); pass
--keep-cache to only print the cached point without re-measuring.
Candidates that fail the bit-exactness validation (e.g. the packed
extraction on a Mosaic generation without strided sublane slices)
print as INVALID.
"""
import sys

import numpy as np

from ..ec.registry import ErasureCodePluginRegistry
from ..ops import autotune

K, M = 8, 3


def main():
    known = {"--keep-cache"}
    unknown = [a for a in sys.argv[1:]
               if a.startswith("-") and a not in known]
    if unknown:
        print(f"unknown option(s): {' '.join(unknown)} — this tool now "
              "drives ops/autotune (the old --flat mode is gone; the "
              "flat 2 KiB kernel is not a tuning candidate).  "
              "Usage: fused_tile_sweep [--keep-cache] [tiles...]")
        raise SystemExit(2)
    tiles = [int(t) for t in sys.argv[1:]
             if not t.startswith("-")] or None
    reg = ErasureCodePluginRegistry.instance()
    codec = reg.factory("jax", {"k": str(K), "m": str(M),
                                "technique": "cauchy"})
    import jax
    if jax.default_backend() == "cpu":
        print("backend is cpu: the fused w32 kernel is TPU-only; "
              f"static default point = {autotune.default_point()}")
        return
    if "--keep-cache" in sys.argv:
        print(f"cached/current point: {codec.fused_point()}")
        print(f"cache file: {autotune._cache_path()}")
        return
    report: list = []
    best = autotune.fused_operating_point(
        K, M, mat=codec.matrix[K:], bitmat32=codec._enc_bitmat32,
        tiles=tiles, force=True, report=report)
    for cand, rate in report:
        tag = (f"tile={cand['tile']:6d} wb={cand['wb']:5d} "
               f"packed={int(cand['packed'])}")
        if rate is None:
            print(f"{tag}  INVALID (failed compile or bit-exactness)")
        else:
            print(f"{tag}  {rate / 1e9:7.2f} GB/s")
    print(f"best: {best}")
    print(f"cache file: {autotune._cache_path()}")


if __name__ == "__main__":
    main()
