"""Sweep the fused parity+crc w32 kernel's tile size on real hardware.

The fused kernel (ops/bitsliced.py gf_encode_with_crc_pallas_w32) had
never been tuned at the headline kernel's operating point: FUSED_TILE
was 2048 bytes while the bare-encode W32_TILE is 131072.  The fused
kernel's crc L-matrix (cmat32, one 32-bit row per input BIT of the
tile) costs 1 KiB of VMEM per byte of tile, so the tile cannot simply
be raised to W32_TILE — this sweep finds the knee.

Usage: python -m ceph_tpu.tools.fused_tile_sweep [tiles...]
"""
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..ec.registry import ErasureCodePluginRegistry
from ..ops import bitsliced as bs
from ..ops import crc32c_linear as cl

K, M, SIZE, BATCH = 8, 3, 1 << 20, 32


def slope_rate(step, x0, iters_lo=20, iters_hi=60):
    """bench.py-style chained fori_loop slope timing (crc feeds the
    chain so neither output can be dead-code-eliminated)."""
    def make(iters):
        @jax.jit
        def f(x):
            def body(i, x):
                r = step(x)
                return x.at[:M, :].set(x[:M, :] ^ r)
            return lax.fori_loop(0, iters, body, x)
        return f

    f_lo, f_hi = make(iters_lo), make(iters_hi)
    jax.block_until_ready(f_lo(x0))
    jax.block_until_ready(f_hi(x0))
    best = []
    for rep in range(3):
        v = jax.block_until_ready(x0 ^ (rep + 1))
        t0 = time.perf_counter()
        jax.block_until_ready(f_lo(v))
        lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        jax.block_until_ready(f_hi(v))
        hi = time.perf_counter() - t0
        dt = (hi - lo) / (iters_hi - iters_lo)
        # same roofline elision gate as bench.py: an above-1TB/s slope
        # is a silently-elided pass, not a fast kernel
        if dt > 0 and BATCH * SIZE / dt < 1e12:
            best.append(BATCH * SIZE / dt)
    best.sort()
    return best[len(best) // 2] if best else 0.0


def main():
    tiles = [int(t) for t in sys.argv[1:]
             if not t.startswith("-")] or [2048, 4096, 8192, 16384]
    reg = ErasureCodePluginRegistry.instance()
    codec = reg.factory("jax", {"k": str(K), "m": str(M),
                                "technique": "cauchy"})
    rng = np.random.default_rng(0)
    flat = rng.integers(0, 256, (K, BATCH * SIZE // K), dtype=np.uint8)
    words = jnp.asarray(flat.view(np.int32))
    codec.encode_words(words)            # build bitmats
    bitmat32 = codec._enc_bitmat32

    flat_mode = "--flat" in sys.argv
    for tile in tiles:
        wt = tile // 4
        if flat_mode:
            try:
                cmat32 = jnp.asarray(cl.crc_tile_matrix_w32(wt))

                def step(x, cmat32=cmat32, tile=tile):
                    par, crc = bs.gf_encode_with_crc_pallas_w32(
                        bitmat32, cmat32, x, M, tile=tile)
                    return par ^ jnp.sum(crc)   # crc feeds chain: no DCE

                rate = slope_rate(step, words)
                print(f"flat tile={tile:6d}  {rate / 1e9:7.2f} GB/s  "
                      f"(cmat {wt * 32 * 32 * 4 / 2**20:.1f} MiB)")
            except Exception as e:  # noqa: BLE001
                print(f"flat tile={tile:6d}  FAILED: {type(e).__name__}: "
                      f"{str(e)[:200]}")
            continue
        for wb in (256, 512, 1024):
            if wt % wb:
                continue
            try:
                cmat_sub = jnp.asarray(cl.crc_tile_matrix_w32(wb))
                combine = jnp.asarray(
                    cl.crc_combine_matrix(wt // wb, 4 * wb))

                def step(x, cs=cmat_sub, cb=combine, tile=tile, wb=wb):
                    par, crc = bs.gf_encode_with_crc_pallas_w32_hier(
                        bitmat32, cs, cb, x, M, tile=tile, wb=wb)
                    return par ^ jnp.sum(crc)   # crc feeds chain: no DCE

                rate = slope_rate(step, words)
                print(f"hier tile={tile:6d} wb={wb:5d}  "
                      f"{rate / 1e9:7.2f} GB/s")
            except Exception as e:  # noqa: BLE001
                print(f"hier tile={tile:6d} wb={wb:5d}  FAILED: "
                      f"{type(e).__name__}: {str(e)[:200]}")


if __name__ == "__main__":
    main()
