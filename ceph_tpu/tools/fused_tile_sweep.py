"""Sweep CLI for the fused parity+crc kernel's operating point.

This used to be a hand-run script whose winners were frozen into
bitsliced.FUSED_TILE_HIER / FUSED_WB; the machinery now lives in
ops/autotune.py, which the jax plugin consults at init (validated,
measured, cached per device).  This CLI drives the same sweep
explicitly, prints the per-candidate table, and refreshes the cache —
use it to inspect WHY the plugin picked its point, or to re-tune after
a runtime/hardware change.

Usage: python -m ceph_tpu.tools.fused_tile_sweep
           [--keep-cache | --validate-only] [tiles...]

By default the sweep is forced (the cache entry is refreshed); pass
--keep-cache to only print the cached point without re-measuring.
Candidates that fail the bit-exactness validation (e.g. the packed or
wide extraction on a Mosaic generation without strided sublane slices,
or the accumulator kernel's scalar-prefetch grid) print as INVALID.

--validate-only runs ONLY the bit-exactness gate over every kernel
variant (no measurement, no cache writes), through the Pallas
interpreter when the backend is CPU — the tier-1 hook
(scripts/tier1.sh): a structural regression in any shipped variant
fails the gate instead of silently falling back at plugin init.
Exits nonzero on any invalid candidate.  Defaults to one small tile
(the variant grid is what matters); pass tiles to widen.  Budget-
capped by CEPH_TPU_AUTOTUNE_BUDGET_S like the init sweep.
"""
import os
import sys
import time

from ..ec.registry import ErasureCodePluginRegistry
from ..ops import autotune

K, M = 8, 3
VALIDATE_TILES = (32768,)


def _cand_tag(cand: dict) -> str:
    return (f"tile={cand['tile']:6d} wb={cand['wb']:5d} "
            f"extract={cand['extract']:6s} combine={cand['combine']:6s}")


def validate_only(codec, tiles) -> int:
    import jax
    import jax.numpy as jnp

    from ..ops import bitsliced as bs
    interpret = jax.default_backend() == "cpu"
    # the CPU plugin skips the w32 matrix build (no w32 kernel runs in
    # production there) — the interpret gate needs it regardless
    bitmat32 = codec._enc_bitmat32
    if bitmat32 is None:
        bitmat32 = jnp.asarray(bs._w32_bitmat(codec.matrix[K:]),
                               dtype=jnp.int8)
    budget = float(os.environ.get("CEPH_TPU_AUTOTUNE_BUDGET_S", "75"))
    mode = "interpret" if interpret else "compiled"
    print(f"# validate-only ({mode}, budget {budget:.0f}s): every "
          f"kernel variant must stay bit-exact vs gf_matvec + host "
          f"crc32c")
    t0 = time.perf_counter()
    bad, checked, skipped = [], 0, 0
    # variant-diverse order: one candidate of EVERY (extract, combine)
    # kernel variant before any repeats at other (tile, wb) shapes —
    # a budget-capped run on a loaded box must still have checked each
    # variant once (the autotuner's best-guess order would leave the
    # accumulator variants, the likeliest to regress, for last)
    cands = autotune.candidates(K, M, tiles=tiles or VALIDATE_TILES)
    seen_variants: dict = {}
    for c in cands:
        seen_variants.setdefault((c["extract"], c["combine"]),
                                 []).append(c)
    rounds = max(len(v) for v in seen_variants.values())
    ordered = [v[i] for i in range(rounds)
               for v in seen_variants.values() if i < len(v)]
    # round 0 (the first candidate of every variant class) is exempt
    # from the budget: the gate's guarantee is that NO shipped kernel
    # variant goes unvalidated, so budget pressure may only drop
    # repeats at other (tile, wb) shapes, never a whole variant class
    for i, cand in enumerate(ordered):
        if i >= len(seen_variants) and \
                time.perf_counter() - t0 > budget:
            skipped += 1
            continue
        checked += 1
        ok = autotune._validate(codec.matrix[K:], bitmat32,
                                cand, interpret=interpret)
        print(f"{_cand_tag(cand)}  "
              f"{'ok' if ok else 'INVALID (failed bit-exactness)'}")
        if not ok:
            bad.append(cand)
    if skipped:
        print(f"# budget exhausted: {skipped} candidate(s) unchecked")
    if bad:
        print(f"# {len(bad)}/{checked} variants INVALID")
        return 1
    print(f"# all {checked} checked variants bit-exact")
    return 0


def main():
    known = {"--keep-cache", "--validate-only"}
    unknown = [a for a in sys.argv[1:]
               if a.startswith("-") and a not in known]
    if unknown:
        print(f"unknown option(s): {' '.join(unknown)} — this tool now "
              "drives ops/autotune (the old --flat mode is gone; the "
              "flat 2 KiB kernel is not a tuning candidate).  Usage: "
              "fused_tile_sweep [--keep-cache | --validate-only] "
              "[tiles...]")
        raise SystemExit(2)
    tiles = [int(t) for t in sys.argv[1:]
             if not t.startswith("-")] or None
    reg = ErasureCodePluginRegistry.instance()
    codec = reg.factory("jax", {"k": str(K), "m": str(M),
                                "technique": "cauchy"})
    import jax
    if "--validate-only" in sys.argv:
        raise SystemExit(validate_only(codec, tiles))
    if jax.default_backend() == "cpu":
        print("backend is cpu: the fused w32 kernel is TPU-only; "
              f"static default point = {autotune.default_point()} "
              "(use --validate-only for the interpret-mode "
              "bit-exactness gate)")
        return
    if "--keep-cache" in sys.argv:
        print(f"cached/current point: {codec.fused_point()}")
        print(f"cache file: {autotune._cache_path()}")
        return
    report: list = []
    best = autotune.fused_operating_point(
        K, M, mat=codec.matrix[K:], bitmat32=codec._enc_bitmat32,
        tiles=tiles, force=True, report=report)
    for cand, rate in report:
        if rate is None:
            print(f"{_cand_tag(cand)}  INVALID (failed compile or "
                  f"bit-exactness)")
        else:
            print(f"{_cand_tag(cand)}  {rate / 1e9:7.2f} GB/s")
    print(f"best: {best}")
    print(f"cache file: {autotune._cache_path()}")


if __name__ == "__main__":
    main()
