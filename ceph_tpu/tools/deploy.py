"""cephadm-role cluster deployer: spec file -> running daemons.

Re-expresses the reference's deployment story (src/cephadm/cephadm:
declarative service specs, per-daemon unit files, `cephadm ls/rm-
cluster`) at this build's scale — containers are out of scope, so a
"unit" is a supervised OS process (daemon_main) whose command line,
pid, and log land under the cluster directory, restartable
individually:

    ceph-tpu-deploy apply spec.json --dir /var/lib/ceph-tpu
    ceph-tpu-deploy ls     --dir /var/lib/ceph-tpu
    ceph-tpu-deploy stop   --dir /var/lib/ceph-tpu [--name osd.2]
    ceph-tpu-deploy start  --dir /var/lib/ceph-tpu --name osd.2
    ceph-tpu-deploy rm-cluster --dir /var/lib/ceph-tpu

Spec (JSON, the service-spec role):

    {
      "mons": 3,
      "osds": 4,
      "objectstore": "filestore",
      "mds": ["a"],
      "rgw": 1,
      "conf": {"osd_max_backfills": "2"}
    }

Each daemon gets <dir>/<name>/ (data) and <dir>/units/<name>.json
recording argv + addr + pid — the unit-file role: `start` re-execs
exactly what `apply` wrote, surviving deployer restarts.
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path


def _unit_dir(root: Path) -> Path:
    d = root / "units"
    d.mkdir(parents=True, exist_ok=True)
    return d


def _write_unit(root: Path, name: str, argv: list[str],
                pid: int, addr: str) -> None:
    (_unit_dir(root) / f"{name}.json").write_text(json.dumps(
        {"name": name, "argv": argv, "pid": pid, "addr": addr,
         "started": time.time()}, indent=2))


def _load_units(root: Path) -> dict[str, dict]:
    out = {}
    for p in sorted(_unit_dir(root).glob("*.json")):
        out[p.stem] = json.loads(p.read_text())
    return out


def _alive(pid: int) -> bool:
    try:
        os.kill(pid, 0)
        return True
    except OSError:
        return False


def _spawn(root: Path, name: str, argv: list[str]) -> str:
    """Start one daemon process, wait for READY (shared handshake
    reader, proc_cluster.wait_ready), record the unit."""
    from .proc_cluster import wait_ready
    log = open(root / f"{name}.log", "ab")
    proc = subprocess.Popen(
        [sys.executable, "-m", "ceph_tpu.tools.daemon_main", *argv],
        stdout=subprocess.PIPE, stderr=log)
    try:
        addr = wait_ready(proc, name)
    except RuntimeError as e:
        proc.kill()
        raise RuntimeError(f"{e} (see {root / (name + '.log')})") from e
    _write_unit(root, name, argv, proc.pid, addr)
    return addr


def cmd_apply(args) -> int:
    root = Path(args.dir)
    root.mkdir(parents=True, exist_ok=True)
    existing = _load_units(root)
    if existing:
        # a second apply would overwrite the unit records and orphan
        # the running daemons beyond stop/rm-cluster's reach
        print(f"cluster dir {root} already has "
              f"{len(existing)} unit(s); run rm-cluster first",
              file=sys.stderr)
        return 1
    spec = json.loads(Path(args.spec).read_text())
    (root / "spec.json").write_text(json.dumps(spec, indent=2))
    n_mons = int(spec.get("mons", 1))
    # fixed mon ports recorded in the cluster dir (the monmap role)
    from .proc_cluster import _free_ports
    ports = _free_ports(n_mons)
    mon_addrs = ",".join(f"127.0.0.1:{p}" for p in ports)
    (root / "monmap.json").write_text(json.dumps(
        {"mons": mon_addrs.split(",")}))
    for rank in range(n_mons):
        _spawn(root, f"mon.{rank}", [
            "mon", "--rank", str(rank), "--addrs", mon_addrs,
            "--data-dir", str(root / f"mon.{rank}")])
    conf_args = []
    for k, v in (spec.get("conf") or {}).items():
        conf_args += ["--conf", f"{k}={v}"]
    for i in range(int(spec.get("osds", 0))):
        _spawn(root, f"osd.{i}", [
            "osd", "--id", str(i), "--mon", mon_addrs,
            "--objectstore", spec.get("objectstore", "filestore"),
            "--data-dir", str(root / f"osd.{i}"), *conf_args])
    for name in spec.get("mds", []):
        _spawn(root, f"mds.{name}", [
            "mds", "--name", name, "--mon", mon_addrs])
    for i in range(int(spec.get("rgw", 0))):
        addr = _spawn(root, f"rgw.{i}", ["rgw", "--mon", mon_addrs])
        print(f"rgw.{i} serving at http://{addr}")
    print(f"cluster up: mons at {mon_addrs}")
    return 0


def cmd_ls(args) -> int:
    units = _load_units(Path(args.dir))
    for name, u in units.items():
        state = "running" if _alive(u["pid"]) else "dead"
        print(json.dumps({"name": name, "state": state,
                          "pid": u["pid"], "addr": u["addr"]}))
    return 0


def _stop_one(root: Path, name: str, u: dict) -> None:
    if _alive(u["pid"]):
        os.kill(u["pid"], signal.SIGTERM)
        for _ in range(50):
            if not _alive(u["pid"]):
                break
            time.sleep(0.1)
        if _alive(u["pid"]):
            os.kill(u["pid"], signal.SIGKILL)


def cmd_stop(args) -> int:
    root = Path(args.dir)
    units = _load_units(root)
    targets = [args.name] if args.name else list(units)
    for name in targets:
        if name not in units:
            print(f"no such daemon {name}", file=sys.stderr)
            return 1
        _stop_one(root, name, units[name])
        print(f"stopped {name}")
    return 0


def cmd_start(args) -> int:
    root = Path(args.dir)
    units = _load_units(root)
    u = units.get(args.name)
    if u is None:
        print(f"no such daemon {args.name}", file=sys.stderr)
        return 1
    if _alive(u["pid"]):
        print(f"{args.name} already running (pid {u['pid']})")
        return 0
    addr = _spawn(root, args.name, u["argv"])
    print(f"started {args.name} at {addr}")
    return 0


def cmd_rm_cluster(args) -> int:
    root = Path(args.dir)
    units = _load_units(root)
    for name, u in units.items():
        _stop_one(root, name, u)
    import shutil
    shutil.rmtree(root, ignore_errors=True)
    print(f"removed cluster at {root}")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="ceph-tpu-deploy")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("apply")
    p.add_argument("spec")
    p.add_argument("--dir", required=True)
    p.set_defaults(fn=cmd_apply)
    p = sub.add_parser("ls")
    p.add_argument("--dir", required=True)
    p.set_defaults(fn=cmd_ls)
    p = sub.add_parser("stop")
    p.add_argument("--dir", required=True)
    p.add_argument("--name")
    p.set_defaults(fn=cmd_stop)
    p = sub.add_parser("start")
    p.add_argument("--dir", required=True)
    p.add_argument("--name", required=True)
    p.set_defaults(fn=cmd_start)
    p = sub.add_parser("rm-cluster")
    p.add_argument("--dir", required=True)
    p.set_defaults(fn=cmd_rm_cluster)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
