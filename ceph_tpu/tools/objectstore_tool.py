"""ceph-objectstore-tool analog: offline surgery on an OSD's store.

Re-expresses the reference's src/tools/ceph_objectstore_tool.cc surface
this framework needs: with the daemon stopped, open its FileStore and
  --op list-pgs                 collections present
  --op list --pgid P            objects of one PG shard
  --op dump --pgid P OBJ        object size/attrs/omap (hinfo decoded)
  --op export --pgid P --file F export a PG shard's objects
  --op import --file F          re-import into (possibly another) store
  --op remove --pgid P OBJ      surgical removal

Export format: one JSON header line then length-prefixed JSON records —
versioned, so exports survive tool upgrades.

Usage: python -m ceph_tpu.tools.objectstore_tool --data-path DIR --op ...
"""

from __future__ import annotations

import argparse
import json
import sys

import numpy as np


def parse_pgid(s: str):
    from ..osd.types import pg_t, spg_t
    # "1.2s3" or "1.2"
    shard = -1
    if "s" in s:
        s, shard_s = s.split("s")
        shard = int(shard_s)
    pool, seed = s.split(".")
    return spg_t(pg_t(int(pool), int(seed, 16)), shard)


def fmt_pgid(cid) -> str:
    return str(cid)


def main(argv=None) -> int:
    from ..osd.ec_util import HINFO_KEY, HashInfo
    from ..store.file_store import FileStore

    ap = argparse.ArgumentParser(prog="objectstore-tool")
    ap.add_argument("--data-path", required=True)
    ap.add_argument("--op", required=True,
                    choices=("list-pgs", "list", "dump", "export",
                             "import", "remove"))
    ap.add_argument("--pgid")
    ap.add_argument("--file")
    ap.add_argument("object", nargs="?")
    args = ap.parse_args(argv)

    store = FileStore(args.data_path)
    store.mount()
    try:
        if args.op == "list-pgs":
            for cid in store.list_collections():
                print(fmt_pgid(cid))
            return 0
        if args.op == "import":
            return do_import(store, args.file)
        cid = parse_pgid(args.pgid)
        if args.op == "list":
            for g in store.list_objects(cid):
                print(g.hobj.name)
            return 0
        if args.op == "dump":
            g = next((g for g in store.list_objects(cid)
                      if g.hobj.name == args.object), None)
            if g is None:
                print(f"no object {args.object}", file=sys.stderr)
                return 1
            attrs = store.getattrs(cid, g)
            out = {
                "oid": g.hobj.name,
                "size": store.stat(cid, g),
                "attrs": {k: v.hex() for k, v in attrs.items()},
                "omap": {k.hex(): v.hex()
                         for k, v in store.omap_get(cid, g).items()},
            }
            if HINFO_KEY in attrs:
                h = HashInfo.decode(attrs[HINFO_KEY])
                out["hinfo"] = {
                    "total_chunk_size": h.total_chunk_size,
                    "logical_size": h.logical_size,
                    "shard_crcs": [hex(c)
                                   for c in h.cumulative_shard_hashes],
                }
            print(json.dumps(out, indent=2))
            return 0
        if args.op == "export":
            return do_export(store, cid, args.file)
        if args.op == "remove":
            from ..store.object_store import Transaction
            g = next((g for g in store.list_objects(cid)
                      if g.hobj.name == args.object), None)
            if g is None:
                print(f"no object {args.object}", file=sys.stderr)
                return 1
            t = Transaction()
            t.remove(g)
            store.queue_transactions(cid, [t])
            print(f"removed {args.object}")
            return 0
        return 2
    finally:
        store.umount()


def do_export(store, cid, path: str) -> int:
    with open(path, "w") as f:
        f.write(json.dumps({"version": 1,
                            "pgid": [cid.pgid.pool, cid.pgid.seed,
                                     cid.shard]}) + "\n")
        count = 0
        for g in store.list_objects(cid):
            rec = {
                "oid": [g.hobj.pool, g.hobj.name, g.hobj.key,
                        g.hobj.snap, g.hobj.hash],
                "gen": g.generation, "shard": g.shard,
                "data": store.read(cid, g).tobytes().hex(),
                "attrs": {k: v.hex()
                          for k, v in store.getattrs(cid, g).items()},
                "omap": {k.hex(): v.hex()
                         for k, v in store.omap_get(cid, g).items()},
            }
            f.write(json.dumps(rec) + "\n")
            count += 1
    print(f"exported {count} objects from {fmt_pgid(cid)} to {path}")
    return 0


def do_import(store, path: str) -> int:
    from ..osd.types import ghobject_t, hobject_t, pg_t, spg_t
    from ..store.object_store import Transaction
    with open(path) as f:
        header = json.loads(f.readline())
        assert header["version"] == 1
        pool, seed, shard = header["pgid"]
        cid = spg_t(pg_t(pool, seed), shard)
        store.create_collection(cid)
        count = 0
        for line in f:
            rec = json.loads(line)
            h = hobject_t(*rec["oid"])
            g = ghobject_t(h, rec["gen"], rec["shard"])
            t = Transaction()
            t.write(g, 0, np.frombuffer(
                bytes.fromhex(rec["data"]), dtype=np.uint8))
            if rec["attrs"]:
                t.setattrs(g, {k: bytes.fromhex(v)
                               for k, v in rec["attrs"].items()})
            if rec["omap"]:
                t.omap_setkeys(g, {bytes.fromhex(k): bytes.fromhex(v)
                                   for k, v in rec["omap"].items()})
            store.queue_transactions(cid, [t])
            count += 1
    print(f"imported {count} objects into {fmt_pgid(cid)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
