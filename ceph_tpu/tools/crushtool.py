"""crushtool-role CLI: compile, decompile, and test crush map text
(reference src/tools/crushtool.cc over CrushCompiler/CrushTester).

    python -m ceph_tpu.tools.crushtool --compile map.txt
    python -m ceph_tpu.tools.crushtool --decompile map.txt  # round-trip
    python -m ceph_tpu.tools.crushtool --test map.txt --rule 0 \\
        --num-rep 3 [--inputs 1024]
"""

from __future__ import annotations

import argparse
import json
import sys

from ..crush.compiler import (CrushCompileError, compile_text,
                              decompile, test_rule)


def main(argv=None) -> int:
    # `crushtool ... | head` must not traceback on the closed pipe
    import signal
    try:
        signal.signal(signal.SIGPIPE, signal.SIG_DFL)
    except (AttributeError, ValueError):
        pass
    ap = argparse.ArgumentParser(prog="crushtool")
    g = ap.add_mutually_exclusive_group(required=True)
    g.add_argument("--compile", metavar="FILE",
                   help="parse + validate; prints a summary")
    g.add_argument("--decompile", metavar="FILE",
                   help="parse then re-emit canonical text")
    g.add_argument("--test", metavar="FILE",
                   help="run placement checks on a rule")
    ap.add_argument("--rule", type=int, default=0)
    ap.add_argument("--num-rep", type=int, default=3)
    ap.add_argument("--inputs", type=int, default=1024)
    args = ap.parse_args(argv)

    path = args.compile or args.decompile or args.test
    try:
        with open(path) as f:
            compiled = compile_text(f.read())
    except CrushCompileError as e:
        print(f"crushtool: {e}", file=sys.stderr)
        return 1
    except OSError as e:
        print(f"crushtool: {e}", file=sys.stderr)
        return 1

    if args.decompile:
        sys.stdout.write(decompile(compiled))
        return 0
    if args.test:
        if args.rule not in compiled.map.rules:
            print(f"crushtool: no rule id {args.rule}",
                  file=sys.stderr)
            return 1
        res = test_rule(compiled.map, args.rule, args.num_rep,
                        args.inputs)
        print(json.dumps({
            "ok": res["ok"],
            "problems": res["problems"][:8],
            "utilization": {f"osd.{d}": c
                            for d, c in sorted(
                                res["utilization"].items())},
        }, indent=2))
        return 0 if res["ok"] else 1
    cm = compiled.map
    print(f"ok: {len(cm.devices)} devices, {len(cm.buckets)} buckets, "
          f"{len(cm.rules)} rules")
    return 0


if __name__ == "__main__":
    sys.exit(main())
