"""Erasure-code benchmark CLI.

Flag- and output-compatible reimplementation of the reference's
`ceph_erasure_code_benchmark` (src/test/erasure-code/
ceph_erasure_code_benchmark.cc:40-144 options, :184/:315 output):

  -p/--plugin NAME        codec plugin (jerasure|isa|jax|example|...)
  -P/--parameter K=V      profile entries, repeatable (k=8, m=3, ...)
  -S/--size BYTES         object size to encode per iteration
  -i/--iterations N       iterations
  -w/--workload encode|decode
  -e/--erasures N         chunks to erase in decode workload
  -N/--erased I           specific chunk index to erase, repeatable
  -E/--erasures-generation random|exhaustive
  -v/--verbose

Output contract preserved: "<elapsed_seconds>\t<iterations*(size/1024)>"
(seconds TAB total KiB processed).  Extra conveniences (not in the
reference): --gbps appends a human-readable GB/s line to stderr, and
--batch B folds B stripes per launch for the jax plugin, the knob the
OSD pipeline turns (reference analog: stripe loop in ECUtil.cc:130).

Exhaustive-erasure decode verifies content equality on every combination
like the reference's decode_erasures recursion (:202-231).
"""

from __future__ import annotations

import argparse
import itertools
import sys
import time

import numpy as np


def parse_args(argv=None):
    ap = argparse.ArgumentParser(prog="ec_benchmark")
    ap.add_argument("-p", "--plugin", default="jerasure")
    ap.add_argument("-P", "--parameter", action="append", default=[],
                    metavar="K=V")
    ap.add_argument("-S", "--size", type=int, default=1 << 20)
    ap.add_argument("-i", "--iterations", type=int, default=1)
    ap.add_argument("-w", "--workload", choices=("encode", "decode"),
                    default="encode")
    ap.add_argument("-e", "--erasures", type=int, default=1)
    ap.add_argument("-N", "--erased", action="append", type=int, default=[])
    ap.add_argument("-E", "--erasures-generation", dest="erasures_generation",
                    choices=("random", "exhaustive"), default="random")
    ap.add_argument("-v", "--verbose", action="store_true")
    ap.add_argument("--batch", type=int, default=1)
    ap.add_argument("--gbps", action="store_true")
    ap.add_argument("--ab", action="store_true",
                    help="symmetric A/B: time CPU-best and jax plugins "
                         "under the IDENTICAL synchronous host-buffer "
                         "loop, per-call and batched; JSON row each")
    return ap.parse_args(argv)


def make_codec(plugin: str, parameters: list[str]):
    from ..ec import ErasureCodePluginRegistry
    profile = {}
    for p in parameters:
        if "=" not in p:
            raise SystemExit(f"--parameter {p!r} is not K=V")
        k, v = p.split("=", 1)
        profile[k] = v
    return ErasureCodePluginRegistry.instance().factory(plugin, profile)


def _device_encode_loop(codec, chunks_np, iterations, batch):
    """Steady-state device-resident encode timing for the jax plugin."""
    import jax
    import jax.numpy as jnp
    k, cs = chunks_np.shape
    if batch > 1:
        stripes = jnp.asarray(
            np.broadcast_to(chunks_np, (batch, k, cs)).copy())
        fn = codec.encode_stripes
        arg = stripes
    else:
        fn = codec.encode_chunks_device
        arg = jnp.asarray(chunks_np)
    fn(arg).block_until_ready()  # compile
    t0 = time.perf_counter()
    out = None
    for _ in range(max(1, iterations // batch)):
        out = fn(arg)
    jax.block_until_ready(out)
    iters_done = max(1, iterations // batch) * batch
    return time.perf_counter() - t0, iters_done


def run_encode(codec, args) -> tuple[float, int]:
    rng = np.random.default_rng(55)
    payload = rng.integers(0, 256, args.size, dtype=np.uint8).tobytes()
    chunks = codec.encode_prepare(payload)
    if hasattr(codec, "encode_chunks_device"):
        return _device_encode_loop(codec, chunks, args.iterations, args.batch)
    codec.encode_chunks(chunks)  # warm LUTs
    t0 = time.perf_counter()
    for _ in range(args.iterations):
        codec.encode_chunks(chunks)
    return time.perf_counter() - t0, args.iterations


def run_decode(codec, args) -> tuple[float, int]:
    n = codec.get_chunk_count()
    rng = np.random.default_rng(56)
    payload = rng.integers(0, 256, args.size, dtype=np.uint8).tobytes()
    encoded = codec.encode(set(range(n)), payload)
    cs = len(encoded[0])

    if args.erasures_generation == "exhaustive":
        combos = list(itertools.combinations(range(n), args.erasures))
    elif args.erased:
        combos = [tuple(args.erased)]
    else:
        combos = [tuple(sorted(rng.choice(n, args.erasures, replace=False)
                               .tolist()))]
    # warm decode-plan caches
    for erased in combos:
        avail = {i: encoded[i] for i in range(n) if i not in erased}
        dec = codec.decode(set(range(n)), avail, cs)
        for i in range(n):
            np.testing.assert_array_equal(dec[i], encoded[i])

    t0 = time.perf_counter()
    done = 0
    for it in range(args.iterations):
        erased = combos[it % len(combos)]
        avail = {i: encoded[i] for i in range(n) if i not in erased}
        codec.decode(set(range(n)), avail, cs)
        done += 1
    return time.perf_counter() - t0, done


# -- symmetric A/B (VERDICT r2 weak #1: one harness, one accounting) --------

def _time_sync_encode(codec, bufs, min_iters=5, min_time=2.0):
    """Synchronous per-call encode timing over host buffers.  The SAME
    loop runs for every side: each iteration is one encode_chunks call
    on a distinct host-resident input (distinct buffers defeat the
    tunnel's repeat-call elision; host residency charges the jax side
    its real transfer cost exactly where the CPU side pays its memory
    traffic).  Mirrors the reference benchmark loop
    (ceph_erasure_code_benchmark.cc:146-186: N synchronous encode()
    calls over an in-memory buffer)."""
    codec.encode_chunks(bufs[0])          # warm LUTs / compile
    t0 = time.perf_counter()
    iters = 0
    while iters < min_iters or time.perf_counter() - t0 < min_time:
        codec.encode_chunks(bufs[iters % len(bufs)])
        iters += 1
    return iters, time.perf_counter() - t0


def ab_rows(k: int, m: int, size: int, batch: int = 32,
            min_time: float = 2.0) -> list[dict]:
    """Symmetric A/B matrix: {cpu-best, jax} x {per_call, batched}.

    per_call: one `size`-byte object per iteration (reference loop
    shape).  batched: one (k, batch*chunk) call per iteration — the
    batch rides the byte axis for BOTH sides (the CPU plugins encode a
    wide stripe the same way), so loop shape and accounting stay
    identical and only the payload width changes.  Throughput is input
    bytes/sec; ratios computed same-mode only."""
    from ..ec import ErasureCodePluginRegistry
    reg = ErasureCodePluginRegistry.instance()
    prof = {"k": str(k), "m": str(m)}
    cpu_best = None
    for plugin, p in (("isa", dict(prof)),
                      ("jerasure", dict(prof, technique="cauchy_good"))):
        try:
            cpu_best = (plugin, reg.factory(plugin, p))
            break
        except Exception:  # noqa: BLE001 - plugin unavailable
            continue
    if cpu_best is None:
        raise RuntimeError("no CPU plugin available for the A/B "
                           "denominator (isa and jerasure both failed)")
    jax_codec = reg.factory("jax", dict(prof))

    rng = np.random.default_rng(77)
    chunk = size // k
    nbufs = 4
    rows = []
    for mode, width in (("per_call", chunk), ("batched", batch * chunk)):
        bufs = [rng.integers(0, 256, (k, width), dtype=np.uint8)
                for _ in range(nbufs)]
        for name, codec in ((cpu_best[0], cpu_best[1]),
                            ("jax", jax_codec)):
            iters, dt = _time_sync_encode(codec, bufs,
                                          min_time=min_time)
            rows.append({
                "side": name, "mode": mode,
                "bytes_per_iter": k * width, "iters": iters,
                "gbps": round(iters * k * width / dt / 1e9, 3),
            })
    by = {(r["side"], r["mode"]): r["gbps"] for r in rows}
    cpu_name = cpu_best[0]
    for mode in ("per_call", "batched"):
        rows.append({
            "ratio_mode": mode,
            "jax_over_cpu": round(by[("jax", mode)] /
                                  by[(cpu_name, mode)], 3),
        })
    return rows


def run_ab(args) -> int:
    import json
    prof = dict(p.split("=", 1) for p in args.parameter if "=" in p)
    for row in ab_rows(int(prof.get("k", 8)), int(prof.get("m", 3)),
                       args.size, batch=max(args.batch, 2)):
        print(json.dumps(row))
    return 0


def main(argv=None) -> int:
    args = parse_args(argv)
    from ..ec import ErasureCodeError
    if args.plugin == "jax" or args.ab:
        # Pin a working backend first: the codec's init touches the device,
        # and this image's TPU tunnel may stall (see utils/platform.py).
        from ..utils.platform import ensure_usable_backend
        backend = ensure_usable_backend()
        if args.verbose:
            print(f"backend={backend}", file=sys.stderr)
    if args.ab:
        return run_ab(args)
    try:
        codec = make_codec(args.plugin, args.parameter)
    except ErasureCodeError as e:
        print(f"ec_benchmark: {e}", file=sys.stderr)
        return 1
    if args.verbose:
        print(f"plugin={args.plugin} k={codec.get_data_chunk_count()} "
              f"m={codec.get_coding_chunk_count()} size={args.size} "
              f"iterations={args.iterations}", file=sys.stderr)
    try:
        if args.workload == "encode":
            elapsed, iters = run_encode(codec, args)
        else:
            elapsed, iters = run_decode(codec, args)
    except ErasureCodeError as e:
        print(f"ec_benchmark: {e}", file=sys.stderr)
        return 1
    total_kib = iters * (args.size // 1024)
    print(f"{elapsed:.6f}\t{total_kib}")
    if args.gbps:
        gbs = iters * args.size / elapsed / 1e9 if elapsed > 0 else float("inf")
        print(f"# {gbs:.3f} GB/s", file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
