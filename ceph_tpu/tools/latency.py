"""Per-op latency recording + workload shaping for the load tools.

The pieces `cluster_bench.py` (throughput rows) and `load_harness.py`
(tail-latency rows) share: a thread-safe per-op latency/error recorder
whose JSON summary carries exact percentiles, a Zipf hot-object
sampler, and burst arrival schedules (reference `rados bench` records
per-op latencies the same way; Zipf + bursts are the standard shape of
production object traffic).
"""

from __future__ import annotations

import threading

import numpy as np

from ..common.perf_counters import percentiles_from_samples


class LatencyRecorder:
    """Per-op end-to-end latency samples + errors bucketed by exception
    type.  record()/error() are one lock + one append — cheap enough
    for every op of a load run; summary() reports exact (nearest-rank)
    percentiles over the raw samples, not bucket estimates."""

    def __init__(self, name: str = ""):
        self.name = name
        self._lock = threading.Lock()
        self._samples: list[float] = []
        self._errors: dict[str, int] = {}

    def record(self, seconds: float) -> None:
        with self._lock:
            self._samples.append(seconds)

    def error(self, exc: BaseException) -> None:
        key = type(exc).__name__
        with self._lock:
            self._errors[key] = self._errors.get(key, 0) + 1

    def merge(self, other: "LatencyRecorder") -> None:
        with other._lock:
            samples = list(other._samples)
            errors = dict(other._errors)
        with self._lock:
            self._samples.extend(samples)
            for k, v in errors.items():
                self._errors[k] = self._errors.get(k, 0) + v

    @property
    def count(self) -> int:
        with self._lock:
            return len(self._samples)

    @property
    def error_count(self) -> int:
        with self._lock:
            return sum(self._errors.values())

    def samples(self) -> list[float]:
        with self._lock:
            return list(self._samples)

    def summary(self, unit_ms: bool = True) -> dict:
        """{ops, errors, errors_by_type, p50/p95/p99/p999[, mean, max]}
        — the JSON-row payload.  unit_ms publishes milliseconds (the
        readable unit for op latency); percentiles are exact over the
        recorded samples."""
        with self._lock:
            samples = list(self._samples)
            errors = dict(self._errors)
        scale = 1e3 if unit_ms else 1.0
        suffix = "_ms" if unit_ms else "_s"
        out = {"ops": len(samples),
               "errors": sum(errors.values()),
               "errors_by_type": errors}
        if samples:
            for label, v in percentiles_from_samples(samples).items():
                out[f"{label}{suffix}"] = round(v * scale, 4)
            out[f"mean{suffix}"] = round(
                sum(samples) / len(samples) * scale, 4)
            out[f"max{suffix}"] = round(max(samples) * scale, 4)
        return out


class ZipfSampler:
    """Zipf-skewed object index draw: P(i) ~ 1/(i+1)^alpha over
    n_objects, so a small hot set takes most of the traffic (the
    skew every production object store sees).  alpha=0 degenerates
    to uniform.  Draws are cheap: precomputed CDF + searchsorted."""

    def __init__(self, n_objects: int, alpha: float = 1.1,
                 seed: int = 0):
        if n_objects < 1:
            raise ValueError("n_objects must be >= 1")
        self.n_objects = n_objects
        ranks = np.arange(1, n_objects + 1, dtype=np.float64)
        weights = ranks ** -float(alpha)
        self._cdf = np.cumsum(weights) / weights.sum()
        self._rng = np.random.default_rng(seed)
        self._lock = threading.Lock()

    def draw(self) -> int:
        with self._lock:
            u = self._rng.random()
        return int(np.searchsorted(self._cdf, u))

    def spawn(self, seed: int) -> "ZipfSampler":
        """A per-worker sampler sharing the CDF but not the rng lock."""
        child = object.__new__(ZipfSampler)
        child.n_objects = self.n_objects
        child._cdf = self._cdf
        child._rng = np.random.default_rng(seed)
        child._lock = threading.Lock()
        return child


def burst_gaps(rate: float, n: int, burst_factor: float = 1.0,
               burst_every: int = 0, burst_len: int = 0,
               seed: int = 0):
    """Inter-arrival gaps (seconds) for an open-loop schedule of `n`
    ops at `rate` ops/sec per worker: exponential (Poisson) gaps, with
    every `burst_every`-th stretch of `burst_len` ops arriving at
    burst_factor * rate — the on/off burst shape that makes queues
    (and p99s) honest.  burst_factor=1 or burst_every=0 is a plain
    Poisson process; rate<=0 yields zero gaps (closed loop)."""
    if rate <= 0:
        for _ in range(n):
            yield 0.0
        return
    rng = np.random.default_rng(seed)
    for i in range(n):
        r = rate
        if burst_every > 0 and burst_len > 0 and \
                (i % burst_every) < burst_len:
            r = rate * max(burst_factor, 1e-9)
        yield float(rng.exponential(1.0 / r))
