"""`ceph` CLI: JSON admin-command dispatch to the mon.

Re-expresses the reference's src/ceph.in command surface for the
commands this build's mon implements:

  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT status
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT health     # SLOW_OPS etc.
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT osd tree
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT osd pool ls
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT osd pool create NAME \
      [--type erasure --profile NAME --pg-num N --size N]
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT osd pool set NAME \
      {pg_num N | pg_autoscale_mode on|warn}  # pg_num up = split, down = merge
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT pg stat      # recovery counts
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT progress     # mgr progress
      # events (recovery/backfill/reshard completion fractions)
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT osd reweight ID W
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT osd drain ID  # weight -> 0
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT osd ok-to-stop ID
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT osd safe-to-destroy ID
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT osd rm ID    # guarded remove
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT osd pool get NAME [VAR]
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT osd erasure-code-profile \
      set NAME k=4 m=2 plugin=jax
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT osd erasure-code-profile \
      {get NAME | ls}
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT osd mclock profile get
  python -m ceph_tpu.tools.ceph_cli -m HOST:PORT osd mclock profile \
      set PROFILE [CLASS:RES,WGT,LIM;...]   # rides central config to OSDs
  python -m ceph_tpu.tools.ceph_cli daemon /path/to/osd.N.asok \
      {dump_latencies | dump_mclock | perf dump | mesh status |
       repair status | launch profile | compile ledger | ...}
      # local asok, no mon needed (reference `ceph daemon`);
      # `mesh status` = the multichip plane state (docs/MULTICHIP.md);
      # `repair status` = recovery backlog/throttle + per-PG repair
      # ledger (docs/REPAIR.md);
      # `launch profile` = the device-plane flight recorder's launch
      # ledger, `compile ledger` = per-host jit-bucket compile
      # attribution (docs/TRACING.md "Device plane");
      # `pg ledger` = the control-plane flight recorder: per-PG
      # state-machine transitions, stage timings, degraded windows
      # (docs/TRACING.md "Control plane");
      # `messenger status` = the wire-plane flight recorder: reactor
      # loop lag, dispatch-queue depth/wait, wire totals; `conn
      # profile` = per-peer msgs/bytes by type, reconnects, replay
      # (docs/TRACING.md "Wire plane")
  python -m ceph_tpu.tools.ceph_cli daemon /path/to/mon.0.asok \
      osdmap status
      # mon map-distribution ledger: full/incremental/keepalive sends,
      # bytes shipped vs the full-publish equivalent, incremental ring
      # span, batched mutations (docs/ARCHITECTURE.md "Map
      # distribution")
"""

from __future__ import annotations

import argparse
import json
import sys


def parse_addr(s: str) -> tuple[str, int]:
    host, port = s.rsplit(":", 1)
    return host, int(port)


def daemon_command(argv: list[str]) -> int:
    """`ceph daemon PATH CMD [KEY VALUE ...]` — straight to a local
    admin socket, mon not required (reference src/ceph.in daemon
    mode).  The tail-latency commands live here: `dump_latencies`
    (percentile summary of every latency histogram) and `dump_mclock`
    (per-class QoS state)."""
    if len(argv) < 2:
        print("ceph daemon: usage: daemon ASOK_PATH COMMAND "
              "[KEY VALUE ...]", file=sys.stderr)
        return 22
    from ..common.admin_socket import admin_command
    path, prefix = argv[0], argv[1]
    extra = argv[2:]
    # multi-word prefixes ride unquoted (`daemon ASOK mesh status`,
    # `daemon ASOK perf dump`, `daemon ASOK launch queue status`):
    # fold words into the prefix while it is still a known INCOMPLETE
    # command head — so an arg typo elsewhere (`config set debug_osd`
    # missing its value) still fails fast instead of becoming a bogus
    # prefix.  Parity-based folding alone cannot reach the three-word
    # `launch queue status`, hence the head-driven loop.
    heads = ("perf", "config", "log", "mesh", "launch", "launch queue",
             "repair", "osdmap", "compile", "prewarm", "bucket",
             "bucket reshard", "bucket limit", "pg", "messenger",
             "conn")
    while extra and prefix in heads:
        prefix = f"{prefix} {extra[0]}"
        extra = extra[1:]
    if len(extra) % 2:
        print("ceph daemon: trailing KEY without VALUE",
              file=sys.stderr)
        return 22
    cmd = {"prefix": prefix}
    for k, v in zip(extra[::2], extra[1::2]):
        cmd[k] = v
    out = admin_command(path, cmd)
    print(json.dumps(out, indent=2, sort_keys=True))
    return 1 if isinstance(out, dict) and "error" in out else 0


def main(argv=None) -> int:
    raw = list(sys.argv[1:] if argv is None else argv)
    if raw and raw[0] == "daemon":
        return daemon_command(raw[1:])
    ap = argparse.ArgumentParser(prog="ceph")
    ap.add_argument("-m", "--mon", required=True)
    ap.add_argument("--type", default="replicated")
    ap.add_argument("--profile", default="default")
    ap.add_argument("--pg-num", type=int, default=8)
    ap.add_argument("--size", type=int, default=3)
    ap.add_argument("words", nargs="+")
    from .rados_cli import add_auth_args, cli_auth
    add_auth_args(ap)
    args = ap.parse_args(raw)
    words = args.words

    from ..osdc import Objecter

    auth, secure = cli_auth(args)
    obj = Objecter(parse_addr(args.mon), "ceph-cli", auth=auth,
                   secure=secure)
    try:
        obj.start()
        cmd = None
        if words == ["status"]:
            cmd = {"prefix": "status"}
        elif words == ["health"]:
            cmd = {"prefix": "health"}
        elif words == ["osd", "tree"]:
            cmd = {"prefix": "osd tree"}
        elif words == ["osd", "pool", "ls"]:
            cmd = {"prefix": "osd pool ls"}
        elif words[:3] == ["osd", "pool", "create"] and len(words) == 4:
            cmd = {"prefix": "osd pool create", "name": words[3],
                   "type": args.type, "pg_num": args.pg_num,
                   "size": args.size,
                   "erasure_code_profile": args.profile}
        elif words[:3] == ["osd", "pool", "set"] and len(words) == 6:
            cmd = {"prefix": "osd pool set", "pool": words[3],
                   "var": words[4], "val": words[5]}
        elif words[:3] == ["osd", "pool", "get"] and len(words) in (4, 5):
            cmd = {"prefix": "osd pool get", "pool": words[3]}
            if len(words) == 5:
                cmd["var"] = words[4]
        elif words[:3] == ["osd", "erasure-code-profile", "set"] \
                and len(words) >= 4:
            name = words[3]
            prof = dict(w.split("=", 1) for w in words[4:] if "=" in w)
            cmd = {"prefix": "osd erasure-code-profile set", "name": name,
                   "profile": prof}
        elif words[:3] == ["osd", "erasure-code-profile", "get"] \
                and len(words) >= 4:
            cmd = {"prefix": "osd erasure-code-profile get",
                   "name": words[3]}
        elif words[:3] == ["osd", "erasure-code-profile", "ls"]:
            cmd = {"prefix": "osd erasure-code-profile ls"}
        elif words == ["mon", "stat"]:
            cmd = {"prefix": "mon stat"}
        elif words == ["pg", "stat"]:
            cmd = {"prefix": "pg stat"}
        elif words == ["progress"]:
            cmd = {"prefix": "progress"}
        elif words[:4] == ["osd", "mclock", "profile", "get"]:
            cmd = {"prefix": "osd mclock profile get"}
        elif words[:4] == ["osd", "mclock", "profile", "set"] \
                and len(words) in (5, 6):
            cmd = {"prefix": "osd mclock profile set",
                   "profile": words[4]}
            if len(words) == 6:
                cmd["custom"] = words[5]
        elif words[:2] == ["osd", "reweight"] and len(words) == 4:
            cmd = {"prefix": "osd reweight", "id": int(words[2]),
                   "weight": float(words[3])}
        elif words[:2] in (["osd", "out"], ["osd", "in"],
                           ["osd", "down"], ["osd", "drain"],
                           ["osd", "ok-to-stop"],
                           ["osd", "safe-to-destroy"],
                           ["osd", "rm"]) and len(words) == 3:
            cmd = {"prefix": f"osd {words[1]}", "id": int(words[2])}
        elif words[:2] == ["auth", "get-or-create"] and len(words) >= 3:
            cmd = {"prefix": "auth get-or-create", "entity": words[2],
                   "caps": " ".join(words[3:]) or "allow *"}
        elif words[:2] == ["auth", "get"] and len(words) == 3:
            cmd = {"prefix": "auth get", "entity": words[2]}
        elif words == ["auth", "ls"]:
            cmd = {"prefix": "auth ls"}
        elif words[:2] == ["auth", "rm"] and len(words) == 3:
            cmd = {"prefix": "auth rm", "entity": words[2]}
        elif words[:2] == ["config", "set"] and len(words) == 5:
            cmd = {"prefix": "config set", "section": words[2],
                   "name": words[3], "value": words[4]}
        elif words[:2] == ["config", "get"] and len(words) in (3, 4):
            cmd = {"prefix": "config get", "section": words[2]}
            if len(words) == 4:
                cmd["name"] = words[3]
        elif words[:2] == ["config", "rm"] and len(words) == 4:
            cmd = {"prefix": "config rm", "section": words[2],
                   "name": words[3]}
        elif words == ["config", "dump"]:
            cmd = {"prefix": "config dump"}
        elif words[:2] == ["fs", "new"] and len(words) == 5:
            cmd = {"prefix": "fs new", "name": words[2],
                   "metadata_pool": words[3], "data_pool": words[4]}
        elif words[:2] == ["fs", "rm"] and len(words) == 3:
            cmd = {"prefix": "fs rm", "name": words[2]}
        elif words == ["fs", "ls"]:
            cmd = {"prefix": "fs ls"}
        elif words == ["fs", "dump"]:
            cmd = {"prefix": "fs dump"}
        elif words == ["mgr", "dump"]:
            cmd = {"prefix": "mgr dump"}
        elif words == ["mgr", "fail"]:
            cmd = {"prefix": "mgr fail"}
        if cmd is None:
            print(f"ceph: unknown command {' '.join(words)!r}",
                  file=sys.stderr)
            return 22
        result, out = obj.mon_command(cmd)
        print(json.dumps(out, indent=2, sort_keys=True))
        return 0 if result == 0 else 1
    finally:
        obj.shutdown()


if __name__ == "__main__":
    sys.exit(main())
