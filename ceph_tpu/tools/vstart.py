"""Dev cluster launcher (reference src/vstart.sh + qa/standalone/
ceph-helpers.sh run_mon/run_osd): start a mon and N OSDs on localhost
loopback — in-process threads by default (standalone-test style: many
daemons, one host, real messenger over loopback).

Library use:
    with Cluster(n_osds=6) as c:
        client = c.client()
        ...

CLI use:
    python -m ceph_tpu.tools.vstart --osds 6     # runs until Ctrl-C
"""

from __future__ import annotations

import argparse
import sys
import time

from ..mon import Monitor
from ..osd.daemon import OSDDaemon
from ..rados import RadosClient


class Cluster:
    def __init__(self, n_osds: int = 6, heartbeat_interval: float = 0.0,
                 failure_quorum: int = 2, asok_dir: str | None = None,
                 objectstore: str = "memstore",
                 data_dir: str | None = None):
        self.mon = Monitor(failure_quorum=failure_quorum)
        self.osds: list[OSDDaemon] = []
        self.n_osds = n_osds
        self.heartbeat_interval = heartbeat_interval
        self.asok_dir = asok_dir
        self.objectstore = objectstore
        self.data_dir = data_dir
        self._clients: list[RadosClient] = []

    def start(self) -> "Cluster":
        from ..store import create_store
        for i in range(self.n_osds):
            asok = (f"{self.asok_dir}/osd.{i}.asok"
                    if self.asok_dir else None)
            store = create_store(
                self.objectstore,
                f"{self.data_dir}/osd.{i}" if self.data_dir else None)
            osd = OSDDaemon(i, self.mon.addr, store=store,
                            heartbeat_interval=self.heartbeat_interval,
                            asok_path=asok)
            self.osds.append(osd)
        for osd in self.osds:
            osd.boot()
        return self

    def client(self) -> RadosClient:
        c = RadosClient(self.mon.addr).connect()
        self._clients.append(c)
        return c

    def kill_osd(self, osd_id: int) -> None:
        """Hard-kill an OSD (thrasher-style, reference
        qa/tasks/ceph_manager.py kill_osd)."""
        osd = self.osds[osd_id]
        osd.shutdown()

    def mark_osd_down(self, osd_id: int) -> None:
        """Administratively mark down (what failure detection would do)."""
        with self.mon.lock:
            self.mon.osdmap.set_osd_down(osd_id)
            self.mon.osdmap.bump_epoch()
            self.mon._publish()

    def stop(self) -> None:
        for c in self._clients:
            c.shutdown()
        for osd in self.osds:
            osd.shutdown()
        self.mon.shutdown()

    def __enter__(self) -> "Cluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="vstart")
    ap.add_argument("--osds", type=int, default=6)
    ap.add_argument("--heartbeat", type=float, default=1.0)
    ap.add_argument("--objectstore", choices=("memstore", "filestore"),
                    default="memstore")
    ap.add_argument("--data-dir", default=None,
                    help="store root (filestore)")
    ap.add_argument("--asok-dir", default=None)
    args = ap.parse_args(argv)
    cluster = Cluster(args.osds, heartbeat_interval=args.heartbeat,
                      asok_dir=args.asok_dir,
                      objectstore=args.objectstore,
                      data_dir=args.data_dir).start()
    print(f"mon at {cluster.mon.addr}; {args.osds} osds up; Ctrl-C to stop",
          flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        cluster.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
