"""Dev cluster launcher (reference src/vstart.sh + qa/standalone/
ceph-helpers.sh run_mon/run_osd): start a mon and N OSDs on localhost
loopback — in-process threads by default (standalone-test style: many
daemons, one host, real messenger over loopback).  For the
multi-PROCESS topology (real SIGKILL, no shared GIL/memory) use
tools/proc_cluster.ProcCluster, same surface.

Library use:
    with Cluster(n_osds=6) as c:
        client = c.client()
        ...

CLI use:
    python -m ceph_tpu.tools.vstart --osds 6     # runs until Ctrl-C
"""

from __future__ import annotations

import argparse
import sys
import time

from ..mon import Monitor
from ..osd.daemon import OSDDaemon
from ..rados import RadosClient


class Cluster:
    def __init__(self, n_osds: int = 6, heartbeat_interval: float = 0.0,
                 failure_quorum: int = 2, asok_dir: str | None = None,
                 objectstore: str = "memstore",
                 data_dir: str | None = None, n_mons: int = 1,
                 auth: str = "none", secure: bool = False,
                 conf: dict | None = None,
                 mesh_devices: str | None = None,
                 boot_parallel: bool = False,
                 prewarm: bool = False,
                 compile_cache_dir: str | None = None):
        self.conf = dict(conf or {})   # applied to every OSD pre-boot
        # compile lifecycle (docs/PIPELINE.md): prewarm=True boots
        # every OSD with the jit-bucket prewarm pass (the first
        # in-process booter warms for the host); compile_cache_dir
        # points the persistent compile cache at a private directory
        # (hermetic CI: a tmpdir instead of ~/.cache/ceph_tpu/xla)
        if prewarm:
            self.conf.setdefault("osd_ec_prewarm", True)
        if compile_cache_dir is not None:
            self.conf.setdefault("osd_ec_compile_cache_dir",
                                 str(compile_cache_dir))
        # multichip deployment mode (docs/MULTICHIP.md): every OSD in
        # this (one-host) cluster shares the process-wide MeshService,
        # so EC PGs drain and repair on the device mesh.  '' = all
        # visible devices, 'SxD' pins the shape; None = single-chip.
        if mesh_devices is not None:
            self.conf.setdefault("osd_ec_use_mesh", True)
            self.conf.setdefault("mesh_devices", mesh_devices)
        # per-OSD conf overrides that SURVIVE revive: a revived daemon
        # gets a fresh CephContext, so anything set only via
        # cct.conf.set (chaos knobs like ms_inject_socket_failures)
        # would silently reset — set through set_osd_conf instead
        self.osd_conf: dict[int, dict] = {}
        # cephx deployment: one cluster service key shared by daemons,
        # a keyring of client entities on the mon (reference
        # vstart.sh's keyring bootstrap + ceph auth get-or-create)
        self.auth_mode = auth
        self.secure = secure
        self.keyring = None
        self.service_key = None
        # reactor pool sizing (ms_async_op_threads, startup-only): the
        # class-level pool is created by the FIRST messenger in this
        # process — the mon's — so the knob must land before Monitor
        # construction to take effect for the whole cluster
        if self.conf.get("ms_async_op_threads"):
            from ..msg.messenger import Messenger
            Messenger.configure_pool(
                int(self.conf["ms_async_op_threads"]))
        mon_auths = [None] * n_mons
        if auth == "cephx":
            import os as _os
            from ..auth import CephxAuth, Keyring
            self.keyring = Keyring()
            self.service_key = _os.urandom(16)
            self.keyring.gen_key("client.admin", "allow *")
            mon_auths = [CephxAuth("mon", service_key=self.service_key,
                                   keyring=self.keyring)
                         for _ in range(n_mons)]
        self.mons = [Monitor(failure_quorum=failure_quorum,
                             auth=mon_auths[i], secure=secure,
                             data_dir=(f"{data_dir}/mon.{i}"
                                       if data_dir else None),
                             asok_path=(f"{asok_dir}/mon.{i}.asok"
                                        if asok_dir else None))
                     for i in range(n_mons)]
        self.mon_addrs = [m.addr for m in self.mons]
        if n_mons > 1:
            for i, m in enumerate(self.mons):
                m.join(self.mon_addrs, i)
        self.mon = self.mons[0]   # convenience alias (rank 0)
        self.osds: list[OSDDaemon] = []
        self.n_osds = n_osds
        # concurrent boots (the scale topology): all MOSDBoots land in
        # the mon's batch window and commit as a couple of epochs
        # instead of one epoch + full publish round per OSD — the
        # difference between O(N) and O(N^2) cold-start control-plane
        # work.  Sequential remains the default (tests that reason
        # about per-boot epochs keep their semantics).
        self.boot_parallel = boot_parallel
        self.heartbeat_interval = heartbeat_interval
        self.asok_dir = asok_dir
        self.objectstore = objectstore
        self.data_dir = data_dir
        self._clients: list[RadosClient] = []

    def wait_for_leader(self, timeout: float = 10.0):
        deadline = time.time() + timeout
        while time.time() < deadline:
            for m in self.mons:
                if m.is_leader:
                    return m
            time.sleep(0.05)
        raise RuntimeError("no mon leader elected")

    def start(self) -> "Cluster":
        from ..store import create_store
        self.wait_for_leader()
        for i in range(self.n_osds):
            asok = (f"{self.asok_dir}/osd.{i}.asok"
                    if self.asok_dir else None)
            store = create_store(
                self.objectstore,
                f"{self.data_dir}/osd.{i}" if self.data_dir else None)
            osd = OSDDaemon(i, self.mon_addrs, store=store,
                            heartbeat_interval=self.heartbeat_interval,
                            asok_path=asok, auth=self._daemon_auth(i),
                            secure=self.secure,
                            conf={**self.conf,
                                  **self.osd_conf.get(i, {})})
            self.osds.append(osd)
        if self.boot_parallel:
            import threading
            ts = [threading.Thread(target=osd.boot, daemon=True)
                  for osd in self.osds]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
        else:
            for osd in self.osds:
                osd.boot()
        return self

    def set_osd_conf(self, osd_id: int, key: str, value) -> None:
        """Set a conf override that sticks across kill/revive (the
        thrasher's chaos knobs must survive restarts; reference
        ceph.conf [osd.N] sections persist the same way).  Applied
        live when the daemon is running."""
        self.osd_conf.setdefault(osd_id, {})[key] = value
        osd = self.osds[osd_id] if osd_id < len(self.osds) else None
        if osd is not None:
            try:
                osd.cct.conf.set(key, value)
            except Exception:  # noqa: BLE001 - daemon mid-shutdown
                pass

    def _daemon_auth(self, osd_id: int):
        if self.auth_mode != "cephx":
            return None
        from ..auth import CephxAuth
        return CephxAuth(f"osd.{osd_id}", service_key=self.service_key)

    def _client_auth(self):
        if self.auth_mode != "cephx":
            return None
        from ..auth import CephxAuth
        return CephxAuth("client.admin",
                         key=self.keyring.get("client.admin"))

    def client(self) -> RadosClient:
        c = RadosClient(self.mon_addrs, auth=self._client_auth(),
                        secure=self.secure).connect()
        self._clients.append(c)
        return c

    def kill_osd(self, osd_id: int) -> None:
        """Hard-kill an OSD (thrasher-style, reference
        qa/tasks/ceph_manager.py kill_osd)."""
        osd = self.osds[osd_id]
        osd.shutdown()

    def revive_osd(self, osd_id: int) -> None:
        """Restart a killed OSD on its surviving store (reference
        qa/tasks/ceph_manager.py revive_osd): FileStore replays its
        WAL on mount; MemStore data survives in-process.  Cluster and
        per-OSD conf overrides re-apply to the fresh CephContext —
        chaos settings (fault injection) survive the restart."""
        old = self.osds[osd_id]
        asok = (f"{self.asok_dir}/osd.{osd_id}.asok"
                if self.asok_dir else None)
        osd = OSDDaemon(osd_id, self.mon_addrs, store=old.store,
                        heartbeat_interval=self.heartbeat_interval,
                        asok_path=asok, auth=self._daemon_auth(osd_id),
                        secure=self.secure,
                        conf={**self.conf,
                              **self.osd_conf.get(osd_id, {})})
        self.osds[osd_id] = osd
        osd.boot()

    def remove_osd(self, osd_id: int) -> None:
        """Decommission an OSD for good: shut the daemon down and drop
        it from the roster so quiescence checks stop expecting it (the
        map-side removal is `osd rm` — run drain/safe-to-destroy
        first)."""
        osd = self.osds[osd_id]
        if osd is not None:
            osd.shutdown()
        self.osds[osd_id] = None
        self.osd_conf.pop(osd_id, None)

    def kill_mon(self, rank: int) -> None:
        """Hard-kill a monitor (quorum must re-elect)."""
        self.mons[rank].shutdown()

    def mark_osd_down(self, osd_id: int) -> None:
        """Administratively mark down (what failure detection would do)."""
        r, _ = self.admin().mon_command(
            {"prefix": "osd down", "id": osd_id})
        assert r == 0, f"osd down failed: {r}"

    def admin(self) -> RadosClient:
        if not self._clients:
            return self.client()
        return self._clients[0]

    # -- quiescence (the "all PGs active+clean" gate; reference
    #    qa/tasks/ceph_manager.wait_for_clean) -----------------------------

    def _active_clean_once(self) -> tuple[bool, str]:
        """One clean-state probe: every PG of every pool has a live
        primary and a full acting set, every up OSD is on the current
        map with peering settled, no recovery pending or running, and
        no client ops in flight on any EC pipeline."""
        from ..crush.map import CRUSH_ITEM_NONE
        from ..osd.types import pg_t
        m = self.mon.osdmap
        epoch = m.epoch
        live = []
        for osd in self.osds:
            if osd is None:
                continue          # decommissioned (remove_osd)
            if not m.is_up(osd.osd_id):
                return False, f"osd.{osd.osd_id} down"
            if osd.osdmap.epoch < epoch:
                return False, (f"osd.{osd.osd_id} on epoch "
                               f"{osd.osdmap.epoch} < {epoch}")
            live.append(osd)
        for osd in live:
            if osd._pgs_needing_recovery:
                return False, (f"osd.{osd.osd_id} recovery pending: "
                               f"{sorted(map(str, osd._pgs_needing_recovery))[:4]}")
            if osd._recovery_inflight:
                return False, f"osd.{osd.osd_id} recovery running"
            if osd._split_push_pending:
                return False, (f"osd.{osd.osd_id} split pushes "
                               f"pending: {len(osd._split_push_pending)}")
            for pgid, state in list(osd.pgs.items()):
                if state.kind == "ec":
                    if state.needs_peer:
                        return False, f"pg {pgid} unpeered on " \
                                      f"osd.{osd.osd_id}"
                    be = state.backend
                    if be.waiting_state or be.waiting_reads or \
                            be.waiting_commit:
                        return False, f"pg {pgid} ops in flight"
        for pool in m.pools.values():
            for seed in range(pool.pg_num):
                pgid = pg_t(pool.id, seed)
                try:
                    _, acting, _, primary = m.pg_to_up_acting_osds(pgid)
                except Exception:  # noqa: BLE001
                    return False, f"pg {pgid} unmapped"
                alive = sum(1 for o in acting
                            if o != CRUSH_ITEM_NONE and m.is_up(o))
                if primary < 0 or alive < pool.size:
                    return False, (f"pg {pgid} acting {alive}/"
                                   f"{pool.size}")
        return True, "active+clean"

    def wait_active_clean(self, timeout: float = 180.0,
                          stable_for: float = 1.0) -> None:
        """Block until the cluster is quiescent — all PGs active+clean
        with in-flight ops and recovery drained, and STAYS so for
        `stable_for` seconds — or raise with the blocking condition.
        Event-driven settling for thrash tests: a liveness regression
        surfaces as the named stuck condition instead of hiding behind
        a wall-clock grace."""
        deadline = time.time() + timeout
        stable_since = None
        why = "never probed"
        while time.time() < deadline:
            ok, why = self._active_clean_once()
            if ok:
                if stable_since is None:
                    stable_since = time.time()
                elif time.time() - stable_since >= stable_for:
                    return
            else:
                stable_since = None
            time.sleep(0.2)
        raise TimeoutError(
            f"cluster not active+clean within {timeout}s: {why}")

    def stop(self) -> None:
        for c in self._clients:
            c.shutdown()
        for osd in self.osds:
            if osd is not None:
                osd.shutdown()
        for m in self.mons:
            m.shutdown()

    def __enter__(self) -> "Cluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="vstart")
    ap.add_argument("--osds", type=int, default=6)
    ap.add_argument("--mons", type=int, default=1)
    ap.add_argument("--heartbeat", type=float, default=1.0)
    ap.add_argument("--objectstore",
                    choices=("memstore", "filestore", "bluestore",
                             "bluestore-zlib"),
                    default="memstore")
    ap.add_argument("--data-dir", default=None,
                    help="store root (filestore/bluestore; a temp dir "
                         "is created when omitted)")
    ap.add_argument("--asok-dir", default=None)
    ap.add_argument("--auth", choices=("none", "cephx"), default="none")
    ap.add_argument("--secure", action="store_true")
    ap.add_argument("--mesh-devices", default=None, metavar="SxD|N",
                    help="enable the multichip EC mesh plane on this "
                         "host: 'SHARDxDATA' shape, a device count, "
                         "or '' for all visible devices")
    ap.add_argument("--keyring-out", default=None,
                    help="write the client keyring here (cephx)")
    args = ap.parse_args(argv)
    if args.objectstore != "memstore" and not args.data_dir:
        import tempfile
        args.data_dir = tempfile.mkdtemp(prefix="vstart_")
        print(f"data dir: {args.data_dir}", flush=True)
    cluster = Cluster(args.osds, heartbeat_interval=args.heartbeat,
                      asok_dir=args.asok_dir,
                      objectstore=args.objectstore,
                      data_dir=args.data_dir, n_mons=args.mons,
                      auth=args.auth, secure=args.secure,
                      mesh_devices=args.mesh_devices).start()
    if args.auth == "cephx" and args.keyring_out:
        cluster.keyring.save(args.keyring_out)
        print(f"keyring written to {args.keyring_out}", flush=True)
    print(f"mon at {cluster.mon.addr}; {args.mons} mons, "
          f"{args.osds} osds up; Ctrl-C to stop", flush=True)
    try:
        while True:
            time.sleep(1)
    except KeyboardInterrupt:
        cluster.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
