"""High-concurrency load harness: tail latency and QoS as first-class,
gated metrics (ROADMAP open item 4; reference `rados bench` +
qa/tasks/radosbench.py crossed with the dmclock QoS test matrix).

Throughput benches (cluster_bench.py) answer "how fast"; production
serving is ruled by p99.  This harness drives million-client-SHAPED
load — many concurrent client sessions, mixed read/write, Zipf-skewed
hot objects, burst arrival schedules — over raw rados, RBD and RGW S3,
records every op's end-to-end latency, and pulls per-stage latency
from the PR 4 tracing histograms so a p99/p999 regression lands on a
STAGE (queue wait, encode launch vs materialize, sub-write ack,
commit), not a blob.  The QoS scenarios make the mClock scheduler's
isolation claim falsifiable: a greedy tenant must not move a
well-behaved tenant's p99 by more than a bounded factor.

One JSON line per scenario (BENCH-artifact compatible, so BENCH_r0N
rounds can carry p99 trajectories):

  python -m ceph_tpu.tools.load_harness --scenario rados --clients 64
  python -m ceph_tpu.tools.load_harness --scenario qos-sim
  python -m ceph_tpu.tools.load_harness --scenario all --seconds 5

Scenarios: rados | rbd | s3 | qos-sim | qos-sim-recovery |
qos-cluster | ec-pg-sweep | degraded-read | s3-shard-sweep | all.  The qos-sim rows run the mClock dequeuer in
VIRTUAL time (deterministic, no cluster, milliseconds of wall clock)
— they are the tier-1-gated isolation proof; the cluster scenarios
exercise the same claim end to end and run under the `slow` marker.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np

from ..common.perf_counters import (LATENCY_QUANTILES,
                                    percentiles_from_samples,
                                    quantile_from_cumulative)
from ..osd.scheduler import (MCLOCK_PROFILES, ClientProfile,
                             MClockScheduler)
from .latency import LatencyRecorder, ZipfSampler, burst_gaps

# QoS isolation bound: the harness (and bench.py --smoke) assert a
# greedy tenant moves a well-behaved tenant's p99 queue wait by no
# more than this factor.  The sim is deterministic; 2x leaves room
# for the one-service-time quantization a reservation can't remove.
QOS_ISOLATION_MAX = 2.0


# -- per-stage percentile extraction (the tracing histograms) ---------------

def merge_stage_histograms(perf_dumps) -> dict[str, list]:
    """Merge `lat_<stage>` histogram buckets across daemons' `perf
    dump` payloads (all histograms share the same le axis, so the
    cumulative columns add): {stage: [[le, cum], ..., ['+Inf', n]]}.
    Accepts the exact dict `perf dump` returns — works in-process
    (osd.cct.perf.dump()) and over the asok alike.

    Beyond the OpTracker's op-timeline stages this also sweeps up the
    device-plane series on the same DEFAULT_LAT_BUCKETS axis: the
    host launch queue's `ec_batch_wait` and the flight recorder's
    `launch_submit` / `launch_device` / `launch_queue_wait`
    (ops/profiler.py) — so per-stage blame decomposes a write's tail
    BELOW the host boundary (queue wait vs device time vs compile)."""
    merged: dict[str, list] = {}
    for dump in perf_dumps:
        for counters in dump.values():
            if not isinstance(counters, dict):
                continue
            for key, val in counters.items():
                if not key.startswith("lat_") or \
                        not isinstance(val, dict) or \
                        "buckets" not in val:
                    continue
                stage = key[len("lat_"):]
                if stage not in merged:
                    merged[stage] = [[le, cum] for le, cum
                                     in val["buckets"]]
                else:
                    have = merged[stage]
                    for i, (_le, cum) in enumerate(val["buckets"]):
                        have[i][1] += cum
    return merged


def stage_quantiles(perf_dumps, unit_ms: bool = True) -> dict:
    """{stage: {count, p50/p95/p99/p999}} from merged tracing
    histograms — the "blame lands on a stage" payload."""
    scale = 1e3 if unit_ms else 1.0
    suffix = "_ms" if unit_ms else "_s"
    out = {}
    for stage, buckets in merge_stage_histograms(perf_dumps).items():
        total = buckets[-1][1]
        if not total:
            continue
        row = {"count": total}
        for q, label in LATENCY_QUANTILES:
            est = quantile_from_cumulative(buckets, q)
            row[f"{label}{suffix}"] = round(est[0] * scale, 4) \
                if est else None
        out[stage] = row
    return out


def cluster_stage_quantiles(cluster) -> dict:
    """Per-stage percentiles aggregated over every live OSD of an
    in-process Cluster (tools/vstart.py)."""
    return stage_quantiles(
        osd.cct.perf.dump() for osd in cluster.osds if osd is not None)


# -- mixed-workload drivers -------------------------------------------------

class WorkloadSpec:
    """One scenario's knobs (shared by the rados/rbd/s3 drivers)."""

    def __init__(self, clients: int = 32, seconds: float = 3.0,
                 size: int = 64 << 10, read_frac: float = 0.5,
                 n_objects: int = 512, zipf_alpha: float = 1.1,
                 rate: float = 0.0, burst_factor: float = 4.0,
                 burst_every: int = 0, burst_len: int = 0,
                 sessions_per_client: int = 1, seed: int = 1):
        self.clients = clients
        self.seconds = seconds
        self.size = size
        self.read_frac = read_frac
        self.n_objects = n_objects
        self.zipf_alpha = zipf_alpha
        self.rate = rate                  # per-session ops/sec (0 = closed loop)
        self.burst_factor = burst_factor
        self.burst_every = burst_every
        self.burst_len = burst_len
        # open-loop only: each worker thread multiplexes this many
        # logical client sessions, each with its own arrival schedule
        # — thousands of client sessions without thousands of Python
        # threads (the million-client SHAPE at harness scale)
        self.sessions_per_client = max(1, sessions_per_client)
        self.seed = seed

    def meta(self) -> dict:
        return {"clients": self.clients, "seconds": self.seconds,
                "sessions": self.clients * self.sessions_per_client,
                "obj_size": self.size, "read_frac": self.read_frac,
                "n_objects": self.n_objects,
                "zipf_alpha": self.zipf_alpha,
                "rate_per_session": self.rate,
                "burst": [self.burst_factor, self.burst_every,
                          self.burst_len]}


def _run_workers(spec: WorkloadSpec, make_op) -> LatencyRecorder:
    """Drive `spec.clients` concurrent sessions for `spec.seconds`.
    make_op(worker_idx) -> op(is_read, obj_idx) callable; every call
    is timed into the shared recorder, exceptions bucket by type.
    Arrival pacing: closed loop by default; with spec.rate, each
    session follows an open-loop Poisson/burst schedule (ops whose
    slot already passed fire immediately — the backlogged-queue shape
    a real burst produces)."""
    lat = LatencyRecorder()
    zipf = ZipfSampler(spec.n_objects, spec.zipf_alpha, spec.seed)
    stop_at = [0.0]

    def worker(widx: int) -> None:
        import heapq
        rng = np.random.default_rng(spec.seed + 1000 + widx)
        sampler = zipf.spawn(spec.seed + 2000 + widx)
        op = make_op(widx)
        # one arrival schedule per logical session; the worker fires
        # whichever session is due next (earliest-deadline heap)
        nsess = spec.sessions_per_client if spec.rate > 0 else 1
        gaps = [burst_gaps(spec.rate, 1 << 30, spec.burst_factor,
                           spec.burst_every, spec.burst_len,
                           seed=spec.seed + 3000 + widx * 10007 + s)
                for s in range(nsess)]
        t_start = time.perf_counter()
        due = [(t_start + next(gaps[s]), s) for s in range(nsess)] \
            if spec.rate > 0 else None
        if due:
            heapq.heapify(due)
        while True:
            now = time.perf_counter()
            if now >= stop_at[0]:
                return
            if due is not None:
                next_due, sess = heapq.heappop(due)
                if next_due > now:
                    time.sleep(min(next_due - now,
                                   max(0.0, stop_at[0] - now)))
                    if time.perf_counter() >= stop_at[0]:
                        return
                heapq.heappush(due, (next_due + next(gaps[sess]),
                                     sess))
            is_read = rng.random() < spec.read_frac
            obj = sampler.draw()
            t0 = time.perf_counter()
            try:
                op(is_read, obj)
                lat.record(time.perf_counter() - t0)
            except Exception as e:  # noqa: BLE001 - bucketed, reported
                lat.error(e)

    threads = [threading.Thread(target=worker, args=(w,), daemon=True)
               for w in range(spec.clients)]
    stop_at[0] = time.perf_counter() + spec.seconds
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return lat


def run_rados_mixed(cluster, client, pool: str,
                    spec: WorkloadSpec, qos_class: str | None = None
                    ) -> dict:
    """Mixed read/write over raw rados.  Objects are pre-seeded so
    reads never miss; writes overwrite (the hot-object overwrite
    pattern the extent cache and batch window exist for)."""
    payload = np.random.default_rng(5).integers(
        0, 256, spec.size, dtype=np.uint8).tobytes()
    seed_io = client.open_ioctx(pool)
    for i in range(spec.n_objects):
        seed_io.write_full(f"h_{i}", payload)

    def make_op(widx: int):
        io = client.open_ioctx(pool)
        if qos_class:
            io.set_qos_class(qos_class)

        def op(is_read: bool, obj: int) -> None:
            if is_read:
                io.read(f"h_{obj}", spec.size)
            else:
                io.write_full(f"h_{obj}", payload)
        return op

    lat = _run_workers(spec, make_op)
    row = {"metric": "harness_rados_mixed", "pool": pool,
           **spec.meta(), **lat.summary(),
           "stages": cluster_stage_quantiles(cluster)}
    return row


def run_rbd_mixed(cluster, client, pool: str, spec: WorkloadSpec
                  ) -> dict:
    """Mixed block I/O over RBD: one image per client session (the
    many-VMs shape), Zipf-hot blocks inside each image."""
    from ..rbd import RBD, Image
    io = client.open_ioctx(pool)
    rbd = RBD(io)
    block = 1 << 16
    blocks_per_img = max(4, spec.n_objects // max(spec.clients, 1))
    img_size = blocks_per_img * block
    payload = np.random.default_rng(6).integers(
        0, 256, spec.size, dtype=np.uint8).tobytes()
    for w in range(spec.clients):
        rbd.create(f"hl_img_{w}", img_size)

    def make_op(widx: int):
        img = Image(client.open_ioctx(pool), f"hl_img_{widx}")

        def op(is_read: bool, obj: int) -> None:
            off = (obj % blocks_per_img) * block
            if is_read:
                img.read(off, min(spec.size, block))
            else:
                img.write(off, payload[:min(spec.size, block)])
        return op

    spec_blocks = WorkloadSpec(**{**spec.__dict__,
                                  "n_objects": blocks_per_img})
    lat = _run_workers(spec_blocks, make_op)
    return {"metric": "harness_rbd_mixed", "pool": pool,
            **spec_blocks.meta(), **lat.summary(),
            "stages": cluster_stage_quantiles(cluster)}


def run_s3_mixed(cluster, client, spec: WorkloadSpec) -> dict:
    """Mixed PUT/GET over the RGW S3 gateway (SigV4-signed raw HTTP,
    the full client->gateway->rados path)."""
    import urllib.request

    from ..rgw import S3Gateway, sigv4
    creds = ("loadkey", "loadsecret")
    gw = S3Gateway(client, creds={creds[0]: creds[1]})
    host = f"{gw.addr[0]}:{gw.addr[1]}"
    base = f"http://{host}"

    def request(method: str, path: str, body: bytes = b"") -> None:
        headers = {"host": host}
        headers.update(sigv4.sign_request(
            method, path, "", headers, body, creds[0], creds[1]))
        req = urllib.request.Request(
            base + path, data=body if body else None,
            method=method, headers=headers)
        with urllib.request.urlopen(req, timeout=30) as resp:
            resp.read()

    payload = np.random.default_rng(8).integers(
        0, 256, spec.size, dtype=np.uint8).tobytes()
    try:
        request("PUT", "/loadbucket")
        for i in range(spec.n_objects):
            request("PUT", f"/loadbucket/h_{i}", payload)

        def make_op(widx: int):
            def op(is_read: bool, obj: int) -> None:
                if is_read:
                    request("GET", f"/loadbucket/h_{obj}")
                else:
                    request("PUT", f"/loadbucket/h_{obj}", payload)
            return op

        lat = _run_workers(spec, make_op)
    finally:
        gw.shutdown()
    return {"metric": "harness_s3_mixed", **spec.meta(),
            **lat.summary(),
            "stages": cluster_stage_quantiles(cluster)}


# -- QoS isolation: virtual-time mClock experiments -------------------------

def _sim_isolation(profiles: dict[str, ClientProfile],
                   victim_class: str, victim_rate: float,
                   greedy_class: str, greedy: bool,
                   service_rate: float, duration: float,
                   seed: int, shared_queue: bool = False) -> dict:
    """Drive an MClockScheduler in VIRTUAL time: one server of
    `service_rate` ops/sec, a victim arriving Poisson at
    `victim_rate`, and (optionally) a greedy class with an
    inexhaustible backlog.  Deterministic given the seed — no
    threads, no sleeps, no wall clock — so the isolation bound can be
    asserted in tier-1 without flake.  shared_queue collapses both
    tenants into one scheduler class — the single-FIFO behavior of
    the non-mClock op path, the contrast case QoS must beat.  Returns
    the victim's queue-wait percentiles and the greedy class's served
    share."""
    sched = MClockScheduler(profiles)
    rng = np.random.default_rng(seed)
    arrivals = []
    t = 0.0
    while t < duration:
        t += float(rng.exponential(1.0 / victim_rate))
        if t < duration:
            arrivals.append(t)
    victim_waits: list[float] = []
    served = {victim_class: 0, greedy_class: 0}
    svc = 1.0 / service_rate
    now, next_arrival = 0.0, 0
    greedy_backlog = 0

    def qclass(cls: str) -> str:
        return "client" if shared_queue else cls

    while next_arrival < len(arrivals) or not sched.empty():
        while next_arrival < len(arrivals) and \
                arrivals[next_arrival] <= now:
            ts = arrivals[next_arrival]
            sched.enqueue((victim_class, ts), qclass(victim_class),
                          now=ts)
            next_arrival += 1
        if greedy and now < duration:
            while greedy_backlog < 16:
                sched.enqueue((greedy_class, now),
                              qclass(greedy_class), now=now)
                greedy_backlog += 1
        item = sched.dequeue(now=now)
        if item is None:
            if next_arrival < len(arrivals):
                now = arrivals[next_arrival]
                continue
            break
        cls, ts = item
        served[cls] = served.get(cls, 0) + 1
        if cls == victim_class:
            victim_waits.append(now - ts)
        else:
            greedy_backlog -= 1
        now += svc
    pcts = percentiles_from_samples(
        victim_waits, [(0.5, "p50"), (0.99, "p99"), (0.999, "p999")])
    return {"victim_ops": len(victim_waits),
            "victim_p50_ms": round(pcts.get("p50", 0.0) * 1e3, 4),
            "victim_p99_ms": round(pcts.get("p99", 0.0) * 1e3, 4),
            "victim_p999_ms": round(pcts.get("p999", 0.0) * 1e3, 4),
            "greedy_ops": served.get(greedy_class, 0)}


def run_qos_isolation_sim(scenario: str = "tenant",
                          service_rate: float = 2000.0,
                          victim_rate: float = 200.0,
                          duration: float = 4.0,
                          seed: int = 7) -> dict:
    """The gated isolation experiment, three runs in virtual time:
    victim alone (baseline p99), victim + greedy under mClock QoS
    (must stay within QOS_ISOLATION_MAX of baseline), and victim +
    greedy with QoS neutralized (no reservation — shows the contrast
    that proves the scheduler, not the light load, kept the tail).

    scenario 'tenant': two tenant classes, the victim holding a
    reservation above its offered rate.  scenario 'recovery': the
    victim is the client class and recovery floods, using the
    balanced profile's shipped triples."""
    if scenario == "recovery":
        profiles = {c: ClientProfile(p.reservation, p.weight, p.limit)
                    for c, p in MCLOCK_PROFILES["balanced"].items()}
        victim_class, greedy_class = "client", "recovery"
        profiles[victim_class] = ClientProfile(
            reservation=victim_rate * 1.5, weight=2.0)
    else:
        victim_class, greedy_class = "tenant_victim", "tenant_greedy"
        profiles = {
            victim_class: ClientProfile(reservation=victim_rate * 1.5,
                                        weight=2.0),
            greedy_class: ClientProfile(reservation=0.0, weight=1.0),
        }
    base = _sim_isolation(profiles, victim_class, victim_rate,
                          greedy_class, False, service_rate, duration,
                          seed)
    qos = _sim_isolation(profiles, victim_class, victim_rate,
                         greedy_class, True, service_rate, duration,
                         seed)
    # contrast: both tenants through ONE FIFO class — the non-mClock
    # op path's behavior; the greedy backlog sits in front of every
    # victim arrival and the tail blows up
    raw = _sim_isolation({"client": ClientProfile(weight=1.0)},
                         victim_class, victim_rate,
                         greedy_class, True, service_rate, duration,
                         seed, shared_queue=True)
    # floor at one service time: an idle-baseline p99 below the
    # service quantum would make the ratio noise, not signal
    floor = 1e3 / service_rate
    denom = max(base["victim_p99_ms"], floor)
    ratio = max(qos["victim_p99_ms"], floor) / denom
    ratio_no_qos = max(raw["victim_p99_ms"], floor) / denom
    return {"metric": f"harness_qos_sim_{scenario}",
            "service_rate": service_rate,
            "victim_rate": victim_rate,
            "duration_s": duration,
            "victim_alone_p99_ms": base["victim_p99_ms"],
            "victim_qos_p99_ms": qos["victim_p99_ms"],
            "victim_no_qos_p99_ms": raw["victim_p99_ms"],
            "greedy_ops_qos": qos["greedy_ops"],
            "qos_isolation_ratio": round(ratio, 3),
            "no_qos_ratio": round(ratio_no_qos, 3),
            "bound": QOS_ISOLATION_MAX,
            "isolated": ratio <= QOS_ISOLATION_MAX}


def run_qos_cluster_tenants(n_osds: int = 4, clients: int = 4,
                            greedy_clients: int = 12,
                            seconds: float = 3.0,
                            size: int = 16 << 10) -> dict:
    """End-to-end tenant isolation on a live cluster: OSDs run the
    mClock op queue, the victim tenant holds a reservation, the
    greedy tenant is weight-only and floods.  Reports the victim's
    e2e p99 alone vs contended plus the schedulers' per-class serve
    counts.  Wall-clock and GIL noise make this a `slow`-marker
    experiment; the virtual-time sim is the gated bound."""
    from .vstart import Cluster
    custom = ("tenant_victim:400,4,0;"
              "tenant_greedy:0,1,0")
    with Cluster(n_osds=n_osds,
                 conf={"osd_op_queue": "mclock",
                       "osd_mclock_custom_profile": custom}) as c:
        client = c.client()
        client.create_pool("qospool", "replicated", size=3, pg_num=16)
        payload = np.random.default_rng(9).integers(
            0, 256, size, dtype=np.uint8).tobytes()
        seed_io = client.open_ioctx("qospool")
        for i in range(64):
            seed_io.write_full(f"q_{i}", payload)

        def tenant_load(qos_class: str, n_workers: int,
                        stop_at: float, lat: LatencyRecorder) -> list:
            def worker(w: int) -> None:
                io = client.open_ioctx("qospool")
                io.set_qos_class(qos_class)
                rng = np.random.default_rng(40 + w)
                while time.perf_counter() < stop_at:
                    obj = int(rng.integers(0, 64))
                    t0 = time.perf_counter()
                    try:
                        if rng.random() < 0.5:
                            io.read(f"q_{obj}", size)
                        else:
                            io.write_full(f"q_{obj}", payload)
                        lat.record(time.perf_counter() - t0)
                    except Exception as e:  # noqa: BLE001
                        lat.error(e)
            ts = [threading.Thread(target=worker, args=(w,),
                                   daemon=True)
                  for w in range(n_workers)]
            for t in ts:
                t.start()
            return ts

        # phase 1: victim alone
        alone = LatencyRecorder()
        ts = tenant_load("tenant_victim", clients,
                         time.perf_counter() + seconds, alone)
        for t in ts:
            t.join()
        # phase 2: victim + greedy flood
        contended = LatencyRecorder()
        greedy = LatencyRecorder()
        stop_at = time.perf_counter() + seconds
        ts = tenant_load("tenant_victim", clients, stop_at, contended)
        ts += tenant_load("tenant_greedy", greedy_clients, stop_at,
                          greedy)
        for t in ts:
            t.join()
        sched = {f"osd.{osd.osd_id}": osd.op_wq.dump()
                 for osd in c.osds
                 if osd is not None and osd.op_wq is not None}
        stages = cluster_stage_quantiles(c)
    a, b = alone.summary(), contended.summary()
    denom = max(a.get("p99_ms", 0.0) or 0.0, 0.05)
    ratio = (b.get("p99_ms", 0.0) or 0.0) / denom
    return {"metric": "harness_qos_cluster_tenants",
            "clients": clients, "greedy_clients": greedy_clients,
            "victim_alone": a, "victim_contended": b,
            "greedy": greedy.summary(),
            "qos_isolation_ratio": round(ratio, 3),
            "schedulers": sched, "stages": stages}


# -- CLI --------------------------------------------------------------------

# -- many-PG EC write fan-out (cross-PG continuous batching) ----------------
#
# The per-host launch queue (parallel/launch_queue.py, docs/PIPELINE.md
# "Host launch queue") exists so aggregate EC write GB/s survives PG
# fan-out: a host with hundreds of post-split PGs must not decay into
# hundreds of partial-occupancy launches.  run_many_pg_write is the
# direct-backend driver (no cluster: the measured axis is the launch
# path, not the messenger); run_ec_pg_sweep is the gated scenario —
# aggregate GB/s at growing PG counts, asserting the largest count
# keeps at least EC_PG_SWEEP_MIN_FRAC of the single-PG rate while the
# queue's occupancy counters prove the coalescing actually happened.

def run_many_pg_write(npg: int, total_objs: int, objsize: int,
                      chunk: int = 1024, k: int = 8, m: int = 3,
                      window_us: float = 50_000.0,
                      max_bytes: int = 64 << 20,
                      plugin: str = "jax", depth: int = 2
                      ) -> tuple[float, dict]:
    """Write `total_objs` objects of `objsize` bytes round-robin
    across `npg` ECBackends (each its own PG + MemStore shard set, the
    bench topology of ec_write_pipeline_k8_m3_GBps) that all share ONE
    per-host launch queue, every backend holding a dispatch-ahead
    window open.  Returns (aggregate input bytes/sec, the shared
    queue's status() — launches / runs-per-launch / occupancy /
    cross-PG mix)."""
    import contextlib

    from ceph_tpu.ec import ErasureCodePluginRegistry
    from ceph_tpu.osd.ec_backend import ECBackend, LocalShardBackend
    from ceph_tpu.osd.ec_transaction import PGTransaction
    from ceph_tpu.osd.ec_util import StripeInfo
    from ceph_tpu.osd.types import eversion_t, hobject_t, pg_t
    from ceph_tpu.parallel.launch_queue import ECLaunchQueue
    from ceph_tpu.store import MemStore
    reg = ErasureCodePluginRegistry.instance()
    queue = ECLaunchQueue(window_us=window_us, max_bytes=max_bytes)
    prof = {"k": str(k), "m": str(m)}
    if plugin == "jax":
        prof["technique"] = "cauchy"
    backends = []
    for i in range(npg):
        codec = reg.factory(plugin, dict(prof))
        store = MemStore()
        store.mount()
        backends.append(ECBackend(
            codec, StripeInfo(k * chunk, chunk),
            LocalShardBackend(store, pg_t(1, i), k + m),
            launch_queue=queue, dispatch_depth=depth,
            perf_name=f"ec.1.{i}"))
    rng = np.random.default_rng(13)
    payload = rng.integers(0, 256, objsize, dtype=np.uint8)
    acked: list[int] = []
    try:
        t0 = time.perf_counter()
        with contextlib.ExitStack() as stack:
            for b in backends:
                stack.enter_context(b.pipeline())
            for j in range(total_objs):
                txn = PGTransaction()
                txn.write(hobject_t(pool=1, name=f"o{j}"), 0, payload)
                backends[j % npg].submit_transaction(
                    txn, eversion_t(1, j // npg + 1),
                    lambda: acked.append(1))
        dt = time.perf_counter() - t0
    finally:
        queue.close()    # throwaway queue: retire its window worker
    if len(acked) != total_objs:
        raise RuntimeError(
            f"many-pg write: {len(acked)}/{total_objs} acked")
    return total_objs * objsize / dt, queue.status()


def run_ec_pg_sweep(pg_counts=(1, 8, 64), total_objs: int = 128,
                    objsize: int = 64 << 10, chunk: int = 1024,
                    passes: int = 3, min_frac: float | None = None
                    ) -> dict:
    """The gated many-PG scenario: the SAME total op count spread over
    growing PG counts; every fan-out count's aggregate GB/s must reach
    at least `min_frac` (env EC_PG_SWEEP_MIN_FRAC, default 0.8) of the
    same-pass single-PG rate in its best paired pass — the
    continuous-batching claim, falsifiable."""
    import os
    if min_frac is None:
        min_frac = float(os.environ.get("EC_PG_SWEEP_MIN_FRAC", "0.8"))
    rates: dict[int, float] = {}
    queues: dict[int, dict] = {}
    # per-config warm pass first: the coalesced super-batch width (its
    # pow2 jit bucket) depends on (npg, objs, window timing) — an
    # uncompiled bucket hit mid-measurement gates compile time, not
    # throughput
    for npg in pg_counts:
        rates[npg], queues[npg] = run_many_pg_write(
            npg, total_objs, objsize, chunk)
    # measured passes sweep every PG count per pass; each fan-out
    # count is gated on its best PAIRED pass (its rate / the SAME
    # pass's base rate) — the box's rate wanders ~2x between passes,
    # so an unpaired best-vs-best comparison gates that wander, not
    # fan-out — and the scenario fraction is the worst count's best
    # paired showing
    best_frac = {n: 0.0 for n in pg_counts[1:]}
    for _ in range(passes):
        row = {}
        for npg in pg_counts:
            rate, qst = run_many_pg_write(npg, total_objs, objsize,
                                          chunk)
            row[npg] = rate
            if rate > rates[npg]:
                rates[npg], queues[npg] = rate, qst
        if row[pg_counts[0]]:
            for n in pg_counts[1:]:
                best_frac[n] = max(best_frac[n],
                                   row[n] / row[pg_counts[0]])
    frac = min(best_frac.values()) if best_frac else 1.0
    top = queues[pg_counts[-1]]
    return {
        "metric": "harness_ec_pg_sweep",
        "pg_counts": list(pg_counts),
        "total_objs": total_objs,
        "objsize": objsize,
        # agg_GBps are each count's best rate across ALL passes
        # (informational); degradation_frac is each count's best
        # PAIRED pass (its rate / the same pass's base rate), so
        # recomputing the fraction from agg_GBps will NOT match on a
        # box whose rate wanders between passes — frac_method says so
        "agg_GBps": {str(n): round(rates[n] / 1e9, 3)
                     for n in pg_counts},
        "degradation_frac": round(frac, 3),
        "frac_method": "best_paired_pass",
        "min_frac": min_frac,
        "ok": frac >= min_frac,
        "launches": top["launches"],
        "runs_per_launch": top["avg_runs_per_launch"],
        "cross_pg_launches": top["cross_pg_launches"],
        "occupancy_pct": top["occupancy_pct_avg"],
    }


# -- degraded-read SLO: client reads DURING a kill/revive storm -------------
#
# The repair subsystem's acceptance metric (docs/REPAIR.md): a cluster
# that is only fast when healthy is not production, so the benchmarked
# path here is failure itself — an EC k=8,m=3 pool under a kill/revive
# storm, client reads landing THROUGH the degraded window, p99 of those
# reads published, every acked byte verified after heal (zero acked
# loss), and the reconstruct-on-read / recovery-class counters proving
# WHICH path served them.

def run_degraded_read_storm(n_osds: int = 12, objects: int = 6,
                            size: int = 32 << 10, cycles: int = 1,
                            read_passes: int = 3,
                            heartbeat: float = 1.0) -> dict:
    """Kill/revive storm on a k=8,m=3 pool with timed degraded reads.

    Box realities (see test_mesh_service's thrash notes): first writes
    pay per-PG peering + codec compile, so the write phase retries;
    heartbeats get the 1 s interval multi-daemon tests need on loaded
    boxes.  The fast CPU variant (small counts) is the tier-1 gate;
    bigger counts are the TPU-round configuration."""
    import numpy as np

    from ..crush.hash import crush_hash32
    from ..osd.types import pg_t
    from ..osdc.objecter import TimedOut
    from ..rados.client import RadosError
    from .vstart import Cluster
    t_start = time.perf_counter()
    rng = np.random.default_rng(41)
    with Cluster(n_osds=n_osds, heartbeat_interval=heartbeat,
                 conf={"osd_ec_read_timeout": 10.0,
                       # the production-shaped configuration: rebuild
                       # units ride the mClock recovery class so client
                       # reads preempt them (docs/QOS.md)
                       "osd_op_queue": "mclock"}) as c:
        client = c.client()
        client.set_ec_profile("dr83", {
            "plugin": "jax", "k": "8", "m": "3",
            "technique": "cauchy", "stripe_unit": "1024"})
        client.create_pool("drpool", "erasure",
                           erasure_code_profile="dr83", pg_num=4)
        io = client.open_ioctx("drpool")
        acked: dict[str, bytes] = {}

        def write_some(tag: str, count: int, retries: int = 3) -> None:
            for j in range(count):
                name = f"{tag}{j}"
                payload = rng.integers(0, 256, size,
                                       dtype=np.uint8).tobytes()
                for _ in range(retries):
                    try:
                        io.write_full(name, payload)
                        acked[name] = payload
                        break
                    except (TimedOut, RadosError):
                        time.sleep(0.5)

        write_some("base", objects)
        if not acked:
            return {"metric": "harness_degraded_read", "ok": False,
                    "error": "no base object acked"}
        # victim: a DATA-shard holder (acting position < k) of the
        # first acked object's PG — its loss forces reconstruct-on-
        # read for that object, and killing a real holder (possibly
        # mid-acting) is the storm the SLO is about
        osdmap = c.osds[0].osdmap
        pool_id = [pid for pid, pl in osdmap.pools.items()
                   if pl.name == "drpool"][0]
        pgnum = osdmap.pools[pool_id].pg_num
        probe = sorted(acked)[0]
        seed = crush_hash32(probe) % pgnum
        _, acting, _, _primary = osdmap.pg_to_up_acting_osds(
            pg_t(pool_id, seed))
        lat = LatencyRecorder()
        mismatches = 0
        for cycle in range(cycles):
            victim = acting[(2 + cycle) % 8]     # a data shard holder
            c.kill_osd(victim)
            c.mark_osd_down(victim)
            # degraded window: timed reads of every acked object, plus
            # fresh writes (the storm keeps serving both directions)
            for _p in range(read_passes):
                for name, payload in sorted(acked.items()):
                    t0 = time.perf_counter()
                    try:
                        got = io.read(name, len(payload))
                        lat.record(time.perf_counter() - t0)
                        if got != payload:
                            mismatches += 1
                    except Exception as e:  # noqa: BLE001
                        lat.error(e)
            write_some(f"deg{cycle}_", 2)
            c.revive_osd(victim)
            write_some(f"rev{cycle}_", 1)
        c.wait_active_clean(timeout=180)
        # zero acked loss: every acked byte readable and intact after
        # the storm heals (bounded retry sweep for map refresh)
        missing = dict(acked)
        for _ in range(3):
            for name in list(missing):
                try:
                    if io.read(name, len(missing[name])) == \
                            missing[name]:
                        del missing[name]
                    else:
                        mismatches += 1
                        del missing[name]
                except Exception:  # noqa: BLE001
                    pass
            if not missing:
                break
            time.sleep(1.0)
        # provenance: reconstruct-on-read + recovery counters summed
        # over the cluster's EC backends / daemons
        recon = timeouts = helper = rebuilt = 0
        recovery_q = 0
        for osd in c.osds:
            if osd is None:
                continue
            for cname, counters in osd.cct.perf.dump().items():
                if not isinstance(counters, dict):
                    continue
                if cname.startswith("ec."):
                    recon += int(counters.get(
                        "ec_reconstruct_reads", 0) or 0)
                    timeouts += int(counters.get(
                        "ec_read_timeouts", 0) or 0)
                    helper += int(counters.get(
                        "ec_repair_helper_bytes", 0) or 0)
                    rebuilt += int(counters.get(
                        "ec_repair_reconstructed_bytes", 0) or 0)
                elif cname == f"osd.{osd.osd_id}":
                    recovery_q += int(counters.get(
                        "recovery_queued_ops", 0) or 0)
        # per-stage blame incl. the device-plane series (ec_batch_wait
        # from the host queue, launch_device/launch_submit from the
        # flight recorder) — the row carries its own explanation
        stages = cluster_stage_quantiles(c)
        summary = lat.summary()
        # degraded-window ledger summary (ISSUE 19): how long the
        # windows this storm opened stayed open, and how many client
        # writes were acked while inside one — summed over daemons
        deg_windows = deg_acked = deg_open = 0
        deg_stage_s: dict[str, float] = {}
        for osd in c.osds:
            if osd is None:
                continue
            try:
                t = osd.pg_ledger.totals()
            except Exception:  # noqa: BLE001 - daemon mid-shutdown
                continue
            deg_windows += t.get("degraded_windows", 0)
            deg_acked += t.get("degraded_acked", 0)
            deg_open += t.get("degraded_open", 0)
            for k in ("peering_s", "scan_s", "decode_s", "push_s",
                      "throttle_s"):
                deg_stage_s[k] = round(
                    deg_stage_s.get(k, 0.0) + t.get(k, 0.0), 4)
        # wire-plane ledger (ISSUE 20): the kill/revive storm's
        # reconnect/replay rounds + reactor-lag/dispatch percentiles —
        # a degraded window that was really a starved reactor shows up
        # here instead of staying folklore
        from ..msg.msgr_ledger import msgr_ledger
        msgr_row = msgr_ledger().bench_summary()
    row = {
        "metric": "harness_degraded_read",
        "osds": n_osds, "objects_acked": len(acked),
        "cycles": cycles, "obj_size": size,
        **{f"read_{key}": val for key, val in summary.items()},
        "mismatches": mismatches,
        "unreadable": len(missing),
        "zero_acked_loss": mismatches == 0 and not missing,
        "reconstruct_reads": recon,
        "read_timeouts": timeouts,
        "repair_helper_bytes": helper,
        "repair_reconstructed_bytes": rebuilt,
        "recovery_queued_ops": recovery_q,
        "stages": stages,
        "degraded_ledger": {
            "windows_closed": deg_windows,
            "windows_open": deg_open,
            "acked_writes_degraded": deg_acked,
            "recovery_stage_s": deg_stage_s,
        },
        "msgr_ledger": msgr_row,
        "duration_s": round(time.perf_counter() - t_start, 1),
    }
    errors = summary.get("errors", 0) or 0
    row["ok"] = bool(
        row["zero_acked_loss"] and summary.get("ops", 0) and
        not errors and
        isinstance(summary.get("p99_ms"), (int, float)) and
        summary["p99_ms"] > 0 and
        recon >= 1)
    return row


# -- sharded bucket index: ingest scaling, bounded listing, reshard --------
#
# The bucket-index subsystem's acceptance gate (docs/ARCHITECTURE.md
# "Bucket index sharding & online resharding").  On this box the win
# is serialization, not device parallelism: every index mutation
# read-modify-writes its shard's whole JSON directory doc, so a
# K-entry bucket pays O(K) serialized bytes per PUT on one shard and
# O(K/8) on eight.  Leg 1 gates that scaling with the PR 12
# best-paired-pass rule; leg 2 gates paginated-list p99 bounded and
# flat vs key count; leg 3 reshards 1->8 under concurrent
# puts/deletes with an OSD kill/revive through the dual-write window
# and verifies the surviving key set exactly (zero lost / duplicated
# / misrouted keys).

def _prefill_index(store, bucket: str, entries: int) -> None:
    """Blow the bucket's index docs up to `entries` rows via direct
    dir_merge (one bulk RMW per shard).  The point: a PUT's index
    cost is the O(doc) RMW the shard count divides, but on an empty
    bucket the ~ms fixed per-request overhead (socket round trips,
    data-pool write) swamps it and no shard count can look faster.
    Prefilled docs restore the production shape — index work
    dominates — so the sweep measures what sharding actually buys."""
    lay = store.index.read_layout(bucket)
    meta = {"size": 0, "etag": "prefill"}
    byshard: dict = {}
    for i in range(entries):
        k = f"f{i:06d}"
        byshard.setdefault(lay.shard_oid("index", k),
                           []).append([k, meta])
    for oid, ents in byshard.items():
        store.index._cls(oid, "dir_merge", {"entries": ents})


def _shard_ingest(store, bucket: str, nshards: int, keys: int,
                  writers: int, zipf, payload: bytes,
                  prefill: int = 0) -> float:
    """Create an nshards-index bucket (no owner: quota admission is
    out of scope here) and PUT `keys` objects from `writers` threads
    — key i is fresh except every third op, which re-PUTs a Zipf-hot
    key (the skewed-overwrite traffic production sees).  Returns
    keys/sec over the measured puts (prefill excluded)."""
    store.create_bucket(bucket, shards=nshards)
    if prefill:
        _prefill_index(store, bucket, prefill)
    start = threading.Barrier(writers)

    def work(w: int) -> None:
        samp = zipf.spawn(w + 1)
        start.wait()
        for i in range(w, keys, writers):
            kid = samp.draw() if i % 3 == 0 else i
            store.put_object(bucket, f"k{kid:05d}", payload)

    threads = [threading.Thread(target=work, args=(w,))
               for w in range(writers)]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return keys / (time.perf_counter() - t0)


def _list_p99(store, bucket: str, page: int, repeats: int) -> float:
    """p99 (ms) of individual paginated list_objects pages over
    `repeats` full drains of the bucket."""
    lat = LatencyRecorder()
    for _ in range(repeats):
        resume = ""
        while True:
            t0 = time.perf_counter()
            ents, _cp, trunc, resume = store.list_objects(
                bucket, max_keys=page, resume=resume)
            lat.record(time.perf_counter() - t0)
            if not trunc:
                break
    return lat.summary().get("p99_ms") or 0.0


def run_s3_shard_sweep(shard_counts=(1, 4, 8), keys: int = 600,
                       writers: int = 4, passes: int = 3,
                       list_page: int = 64, zipf_alpha: float = 1.1,
                       prefill: int = 12000,
                       min_x: float | None = None,
                       p99_max_ms: float | None = None,
                       flat_factor: float | None = None) -> dict:
    """Gated sharded-bucket-index scenario; env knobs
    S3_SHARD_SWEEP_MIN_X / S3_LIST_P99_MAX_MS /
    S3_LIST_P99_FLAT_FACTOR / S3_SHARD_PREFILL."""
    import os

    from ..rados.client import RadosError
    from .vstart import Cluster
    if min_x is None:
        min_x = float(os.environ.get("S3_SHARD_SWEEP_MIN_X", "2.0"))
    if p99_max_ms is None:
        p99_max_ms = float(os.environ.get("S3_LIST_P99_MAX_MS",
                                          "200.0"))
    if flat_factor is None:
        flat_factor = float(os.environ.get("S3_LIST_P99_FLAT_FACTOR",
                                           "3.0"))
    prefill = int(os.environ.get("S3_SHARD_PREFILL", str(prefill)))
    t_start = time.perf_counter()
    rng = np.random.default_rng(17)
    payload = rng.integers(0, 256, 64, dtype=np.uint8).tobytes()
    base = shard_counts[0]
    top = shard_counts[-1]
    # measured puts per ingest: enough for a stable rate, few enough
    # that 4 rounds x len(shard_counts) buckets stay inside a CI
    # budget — the doc-RMW cost prefill restores is per-put, so the
    # put count does not change the signal, only the noise floor
    ingest_keys = max(writers * 25, keys // 4)
    with Cluster(n_osds=4) as c:
        from ..rgw.store import RGWStore
        st = RGWStore(c.client())
        zipf = ZipfSampler(ingest_keys, zipf_alpha, seed=3)

        # leg 1 — ingest scaling over prefilled buckets (see
        # _prefill_index: shard count divides the O(doc) index RMW,
        # which only dominates once docs carry a production-sized
        # entry count).  Warm pass per count first (pool peering +
        # the buckets-doc working set), then `passes` measured
        # sweeps; each fan-out count is gated on its best PAIRED
        # pass (its rate / the SAME pass's base rate) because the
        # box's absolute rate wanders ~2x between passes (the
        # ec-pg-sweep rule, PR 12)
        rates: dict[int, float] = {}
        for n in shard_counts:
            rates[n] = _shard_ingest(st, f"sww{n}", n, ingest_keys,
                                     writers, zipf, payload,
                                     prefill=prefill)
        best_x = {n: 0.0 for n in shard_counts[1:]}
        for p in range(passes):
            row = {}
            for n in shard_counts:
                rate = _shard_ingest(st, f"swp{p}n{n}", n,
                                     ingest_keys, writers, zipf,
                                     payload, prefill=prefill)
                row[n] = rate
                rates[n] = max(rates[n], rate)
            if row[base]:
                for n in shard_counts[1:]:
                    best_x[n] = max(best_x[n], row[n] / row[base])
        ingest_ok = best_x.get(top, 1.0) >= min_x

        # leg 2 — paginated listing: p99 per page bounded, and flat
        # between a small bucket and one 4x its key count at the
        # same (top) shard count — what the cls parsed-doc cache and
        # the gateway continuation-cursor cache buy (without them a
        # page costs one full-doc parse per shard and the ratio
        # tracks key count).  Both buckets are dir_merge-prefilled
        # (listing never touches data objects, only index docs).
        # Paired per round for the wander reason above; the
        # denominator floor keeps a microsecond-fast small-bucket
        # page from failing the ratio on noise.
        nlarge = st.bucket_stats(f"swp{passes - 1}n{top}")["objects"]
        nsmall = max(list_page, nlarge // 4)
        st.create_bucket("swlsmall", shards=top)
        _prefill_index(st, "swlsmall", nsmall)
        large_p99 = flat_ratio = float("inf")
        for _ in range(3):
            p99_s = _list_p99(st, "swlsmall", list_page, repeats=3)
            p99_l = _list_p99(st, f"swp{passes - 1}n{top}", list_page,
                              repeats=3)
            large_p99 = min(large_p99, p99_l)
            flat_ratio = min(flat_ratio, p99_l / max(p99_s, 0.5))
        list_ok = large_p99 <= p99_max_ms and flat_ratio <= flat_factor

        # leg 3 — online reshard 1->top under concurrent writers with
        # an OSD kill/revive through the dual window.  The marker is
        # durable, so the killed copy resumes from sweep(); writers
        # retry through the outage and the final key set must match
        # the acked history exactly.
        st.create_bucket("swre", shards=1)
        npre = keys // 2
        for i in range(npre):
            st.put_object("swre", f"pre{i:05d}", payload)
        expected = {f"pre{i:05d}" for i in range(npre)}
        acked_put: set[str] = set()
        acked_del: set[str] = set()
        uncertain: set[str] = set()
        write_errors = [0]
        mu = threading.Lock()

        def attempt(fn, *a, absent_ok: bool = False) -> bool:
            from ..rgw.store import RGWError
            for i in range(5):
                try:
                    fn(*a)
                    return True
                except RGWError as e:
                    # a delete whose FIRST try timed out ambiguously
                    # may find the key already gone on retry
                    if absent_ok and e.status == 404 and i > 0:
                        return True
                    return False
                except Exception:  # noqa: BLE001 — outage window
                    time.sleep(0.3)
            return False

        def churn(w: int) -> None:
            for i in range(keys // 4):
                k = f"w{w}_{i:04d}"
                ok_put = attempt(st.put_object, "swre", k, payload)
                with mu:
                    (acked_put if ok_put else uncertain).add(k)
                    if not ok_put:
                        write_errors[0] += 1
                if ok_put and i % 3 == 2:
                    ok_del = attempt(st.delete_object, "swre", k,
                                     absent_ok=True)
                    with mu:
                        (acked_del if ok_del else uncertain).add(k)
                        if not ok_del:
                            write_errors[0] += 1

        st.resharder.start("swre", top)
        churners = [threading.Thread(target=churn, args=(w,))
                    for w in range(2)]
        for t in churners:
            t.start()
        time.sleep(0.1)
        victim = 3
        c.kill_osd(victim)
        c.mark_osd_down(victim)
        st.reshard_sweep()          # interrupted mid-copy (or errors)
        time.sleep(0.4)
        c.revive_osd(victim)
        resumed = 0
        for _ in range(60):
            sw = st.reshard_sweep()
            resumed += sw.get("resumed", 0)
            if not st.reshard_status("swre").get("reshard"):
                break
            time.sleep(0.3)
        for t in churners:
            t.join()
        # writers may have raced past the cutover; bounded extra
        # sweeps drive any still-live marker to a final state before
        # the audit (a stuck marker fails reshard_ok below)
        for _ in range(20):
            if not st.reshard_status("swre").get("reshard"):
                break
            st.reshard_sweep()
            time.sleep(0.3)
        c.wait_active_clean(timeout=120)
        expected |= acked_put
        expected -= acked_del
        expected -= uncertain
        listed: list[str] = []
        resume = ""
        while True:
            ents, _cp, trunc, resume = st.list_objects(
                "swre", max_keys=100, resume=resume)
            listed.extend(k for k, _m in ents)
            if not trunc:
                break
        got = set(listed) - uncertain
        misrouted = 0
        for k in got:
            try:
                st.index.get("swre", "index", k)
            except RadosError:
                misrouted += 1
        stat = st.bucket_stats("swre")
        reshard = {
            "shards": stat["shards"], "gen": stat["gen"],
            "resumed_sweeps": resumed,
            "expected": len(expected), "listed": len(got),
            "lost": len(expected - got),
            "extra": len(got - expected),
            "duplicated": len(listed) - len(set(listed)),
            "misrouted": misrouted,
            "uncertain": len(uncertain),
            "write_errors": write_errors[0],
        }
        reshard_ok = (stat["shards"] == top and
                      not stat["reshard"] and
                      reshard["lost"] == 0 and
                      reshard["extra"] == 0 and
                      reshard["duplicated"] == 0 and
                      misrouted == 0)
    return {
        "metric": "harness_s3_shard_sweep",
        "shard_counts": list(shard_counts), "keys": keys,
        "ingest_keys": ingest_keys, "prefill": prefill,
        "writers": writers,
        "ingest_keys_per_s": {str(n): round(rates[n], 1)
                              for n in shard_counts},
        # speedup_x is each count's best PAIRED pass (vs the same
        # pass's base rate) — recomputing from ingest_keys_per_s
        # (best across ALL passes) will not match on a wandering box
        "speedup_x": {str(n): round(best_x[n], 3)
                      for n in shard_counts[1:]},
        "frac_method": "best_paired_pass", "min_x": min_x,
        "ingest_ok": ingest_ok,
        "list_p99_ms": round(large_p99, 3),
        "list_flat_ratio": round(flat_ratio, 3),
        "list_keys": {"small": nsmall, "large": nlarge},
        "p99_max_ms": p99_max_ms, "flat_factor": flat_factor,
        "list_ok": list_ok,
        "reshard": reshard, "reshard_ok": reshard_ok,
        "duration_s": round(time.perf_counter() - t_start, 1),
        "ok": ingest_ok and list_ok and reshard_ok,
    }


def _emit(row: dict) -> None:
    print(json.dumps(row), flush=True)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="load_harness")
    ap.add_argument("--scenario", default="all",
                    choices=("rados", "rbd", "s3", "qos-sim",
                             "qos-sim-recovery", "qos-cluster",
                             "ec-pg-sweep", "degraded-read",
                             "s3-shard-sweep", "all"))
    ap.add_argument("--cycles", type=int, default=1,
                    help="degraded-read: kill/revive cycles")
    ap.add_argument("--read-passes", type=int, default=3,
                    help="degraded-read: timed read sweeps per "
                         "degraded window")
    ap.add_argument("--pg-counts", default="1,8,64",
                    help="ec-pg-sweep: comma-separated PG fan-outs")
    ap.add_argument("--shard-counts", default="1,4,8",
                    help="s3-shard-sweep: comma-separated bucket "
                         "index shard counts (first is the base)")
    ap.add_argument("--shard-keys", type=int, default=600,
                    help="s3-shard-sweep: keys ingested per bucket")
    ap.add_argument("--clients", type=int, default=32,
                    help="concurrent client sessions")
    ap.add_argument("--seconds", type=float, default=3.0)
    ap.add_argument("--size", type=int, default=64 << 10)
    ap.add_argument("--read-frac", type=float, default=0.5)
    ap.add_argument("--objects", type=int, default=256)
    ap.add_argument("--zipf-alpha", type=float, default=1.1)
    ap.add_argument("--rate", type=float, default=0.0,
                    help="per-session open-loop ops/sec (0=closed loop)")
    ap.add_argument("--sessions", type=int, default=1,
                    help="logical client sessions multiplexed per "
                         "worker thread (open-loop only): "
                         "--clients 50 --sessions 100 --rate 2 = "
                         "5000 clients' worth of arrivals")
    ap.add_argument("--burst-every", type=int, default=0)
    ap.add_argument("--burst-len", type=int, default=0)
    ap.add_argument("--burst-factor", type=float, default=4.0)
    ap.add_argument("--osds", type=int, default=4)
    ap.add_argument("--ec", action="store_true",
                    help="EC k=8,m=3 pool for the rados scenario")
    args = ap.parse_args(argv)

    scenarios = [args.scenario] if args.scenario != "all" else \
        ["qos-sim", "qos-sim-recovery", "ec-pg-sweep", "rados", "rbd",
         "s3"]
    spec = WorkloadSpec(
        clients=args.clients, seconds=args.seconds, size=args.size,
        read_frac=args.read_frac, n_objects=args.objects,
        zipf_alpha=args.zipf_alpha, rate=args.rate,
        burst_factor=args.burst_factor, burst_every=args.burst_every,
        burst_len=args.burst_len, sessions_per_client=args.sessions)

    rc = 0
    sims = [s for s in scenarios if s.startswith("qos-sim")]
    for s in sims:
        _emit(run_qos_isolation_sim(
            "recovery" if s == "qos-sim-recovery" else "tenant"))
    if "ec-pg-sweep" in scenarios:
        counts = tuple(int(t) for t in args.pg_counts.split(","))
        row = run_ec_pg_sweep(pg_counts=counts,
                              total_objs=min(args.objects, 256),
                              objsize=args.size)
        _emit(row)
        if not row["ok"]:
            # record the gate failure but keep running: under
            # --scenario all the remaining scenarios still emit their
            # rows (a wall-clock-sensitive sweep dip must not silently
            # skip the rados/rbd/s3 runs)
            print(f"ec-pg-sweep: aggregate GB/s degraded to "
                  f"{row['degradation_frac']} of the 1-PG rate "
                  f"(min {row['min_frac']})", file=sys.stderr)
            rc = 1
    if "degraded-read" in scenarios:
        row = run_degraded_read_storm(
            n_osds=max(args.osds, 12), objects=min(args.objects, 32),
            size=args.size, cycles=args.cycles,
            read_passes=args.read_passes)
        _emit(row)
        if not row.get("ok"):
            # the degraded-read SLO is a gate: reads during the storm
            # must complete via reconstruct-on-read with zero acked
            # loss (rc != 0 fails tier-1)
            print(f"degraded-read: gate failed "
                  f"(zero_acked_loss={row.get('zero_acked_loss')}, "
                  f"errors={row.get('read_errors')}, "
                  f"reconstructs={row.get('reconstruct_reads')}, "
                  f"p99={row.get('read_p99_ms')})", file=sys.stderr)
            rc = 1
    if "s3-shard-sweep" in scenarios:
        counts = tuple(int(t) for t in args.shard_counts.split(","))
        row = run_s3_shard_sweep(shard_counts=counts,
                                 keys=args.shard_keys)
        _emit(row)
        if not row["ok"]:
            # the sharded-index gate: ingest must scale with shard
            # count, merged listing must stay bounded/flat, and an
            # interrupted online reshard must converge losslessly
            print(f"s3-shard-sweep: gate failed "
                  f"(ingest_ok={row['ingest_ok']} "
                  f"speedup={row['speedup_x']}, "
                  f"list_ok={row['list_ok']} "
                  f"p99={row['list_p99_ms']}ms "
                  f"flat={row['list_flat_ratio']}, "
                  f"reshard_ok={row['reshard_ok']} "
                  f"{row['reshard']})", file=sys.stderr)
            rc = 1
    if "qos-cluster" in scenarios:
        _emit(run_qos_cluster_tenants(
            n_osds=args.osds, clients=max(2, args.clients // 8),
            greedy_clients=args.clients, seconds=args.seconds,
            size=args.size))
    cluster_scenarios = [s for s in scenarios
                         if s in ("rados", "rbd", "s3")]
    if cluster_scenarios:
        if args.ec and "rados" in cluster_scenarios and args.osds < 11:
            print("--ec needs >= 11 OSDs for k=8,m=3 (pass --osds 12)",
                  file=sys.stderr)
            return 2
        from .vstart import Cluster
        with Cluster(n_osds=args.osds) as c:
            client = c.client()
            if "rados" in cluster_scenarios:
                if args.ec:
                    client.set_ec_profile("hl83", {
                        "plugin": "jerasure", "k": "8", "m": "3",
                        "stripe_unit": "4096"})
                    client.create_pool("hl_rados", "erasure",
                                       erasure_code_profile="hl83",
                                       pg_num=16)
                else:
                    client.create_pool("hl_rados", "replicated",
                                       size=3, pg_num=16)
                _emit(run_rados_mixed(c, client, "hl_rados", spec))
            if "rbd" in cluster_scenarios:
                client.create_pool("hl_rbd", "replicated", size=3,
                                   pg_num=16)
                _emit(run_rbd_mixed(c, client, "hl_rbd", spec))
            if "s3" in cluster_scenarios:
                _emit(run_s3_mixed(c, client, spec))
    return rc


if __name__ == "__main__":
    sys.exit(main())
