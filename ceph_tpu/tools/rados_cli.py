"""`rados` CLI: object-level operations + bench.

Re-expresses the reference's src/tools/rados/rados.cc surface (put/get/
ls-free subset + `rados bench` style throughput run) over the client
API.  Usage:

  python -m ceph_tpu.tools.rados_cli -m HOST:PORT -p POOL put NAME FILE
  python -m ceph_tpu.tools.rados_cli -m HOST:PORT -p POOL get NAME FILE
  python -m ceph_tpu.tools.rados_cli -m HOST:PORT -p POOL rm NAME
  python -m ceph_tpu.tools.rados_cli -m HOST:PORT -p POOL bench SECONDS write
"""

from __future__ import annotations

import argparse
import sys
import time

import numpy as np


def parse_addr(s: str) -> tuple[str, int]:
    host, port = s.rsplit(":", 1)
    return host, int(port)


def cli_auth(args):
    """--keyring/--name/--secure -> (auth_ctx, secure) for CLIs
    (reference CEPH_KEYRING + --name plumbing in the tool frontends)."""
    if not getattr(args, "keyring", None):
        return None, False
    from ..auth import CephxAuth, Keyring
    kr = Keyring.load(args.keyring)
    key = kr.get(args.name)
    if key is None:
        raise SystemExit(f"entity {args.name!r} not in {args.keyring}")
    return CephxAuth(args.name, key=key), bool(args.secure)


def add_auth_args(ap) -> None:
    ap.add_argument("--keyring", default=None,
                    help="keyring file (enables cephx)")
    ap.add_argument("--name", default="client.admin")
    ap.add_argument("--secure", action="store_true",
                    help="AES-GCM frame mode")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rados")
    ap.add_argument("-m", "--mon", required=True, help="mon HOST:PORT")
    ap.add_argument("-p", "--pool", required=True)
    ap.add_argument("command", choices=(
        "put", "get", "rm", "bench", "listomapkeys", "listomapvals",
        "getomapval", "setomapval", "rmomapkey", "getomapheader",
        "setomapheader"))
    ap.add_argument("args", nargs="*")
    ap.add_argument("-b", "--block-size", type=int, default=1 << 20)
    add_auth_args(ap)
    args = ap.parse_args(argv)

    from ..rados import RadosClient

    auth, secure = cli_auth(args)
    client = RadosClient(parse_addr(args.mon), auth=auth,
                         secure=secure).connect()
    try:
        io = client.open_ioctx(args.pool)
        if args.command == "put":
            name, path = args.args
            data = sys.stdin.buffer.read() if path == "-" else \
                open(path, "rb").read()
            io.write_full(name, data)
            print(f"wrote {len(data)} bytes to {name}")
        elif args.command == "get":
            name, path = args.args
            data = io.read(name, 0)
            if path == "-":
                sys.stdout.buffer.write(data)
            else:
                open(path, "wb").write(data)
                print(f"read {len(data)} bytes from {name}")
        elif args.command == "rm":
            io.remove(args.args[0])
            print(f"removed {args.args[0]}")
        elif args.command == "listomapkeys":
            for k in io.omap_get_keys(args.args[0]):
                print(k.decode(errors="replace"))
        elif args.command == "listomapvals":
            for k, v in sorted(io.omap_get_vals(args.args[0]).items()):
                print(f"{k.decode(errors='replace')}")
                print(f"value ({len(v)} bytes) :")
                print(v.decode(errors="replace"))
        elif args.command == "getomapval":
            name, key = args.args[:2]
            kv = io.omap_get_vals_by_keys(name, [key.encode()])
            if key.encode() not in kv:
                print(f"error getting omap value {key}: no such key")
                return 1
            sys.stdout.flush()
            sys.stdout.buffer.write(kv[key.encode()] + b"\n")
            sys.stdout.buffer.flush()
        elif args.command == "setomapval":
            name, key, val = args.args[:3]
            io.omap_set(name, {key.encode(): val.encode()})
        elif args.command == "rmomapkey":
            name, key = args.args[:2]
            io.omap_rm_keys(name, [key.encode()])
        elif args.command == "getomapheader":
            hdr = io.omap_get_header(args.args[0])
            print(f"header ({len(hdr)} bytes) :")
            sys.stdout.flush()
            sys.stdout.buffer.write(hdr + b"\n")
            sys.stdout.buffer.flush()
        elif args.command == "setomapheader":
            name, val = args.args[:2]
            io.omap_set_header(name, val.encode())
        elif args.command == "bench":
            seconds = float(args.args[0]) if args.args else 5.0
            payload = np.random.default_rng(0).integers(
                0, 256, args.block_size, dtype=np.uint8).tobytes()
            t0 = time.time()
            n = 0
            while time.time() - t0 < seconds:
                io.write_full(f"bench_{n}", payload)
                n += 1
            dt = time.time() - t0
            mb = n * args.block_size / 1e6
            print(f"wrote {n} x {args.block_size}B in {dt:.2f}s = "
                  f"{mb / dt:.1f} MB/s")
        return 0
    finally:
        client.shutdown()


if __name__ == "__main__":
    sys.exit(main())
