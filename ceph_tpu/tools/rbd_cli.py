"""`rbd` CLI: image management + I/O over the rbd library.

Re-expresses the reference's src/tools/rbd surface (rbd.cc action
dispatch) at the subset the library supports:

  rbd -m MON -p POOL create NAME --size BYTES [--order N]
  rbd -m MON -p POOL ls
  rbd -m MON -p POOL info NAME
  rbd -m MON -p POOL rm NAME
  rbd -m MON -p POOL resize NAME --size BYTES
  rbd -m MON -p POOL snap create NAME@SNAP
  rbd -m MON -p POOL snap ls NAME
  rbd -m MON -p POOL snap rm NAME@SNAP
  rbd -m MON -p POOL snap rollback NAME@SNAP
  rbd -m MON -p POOL clone PARENT@SNAP CHILD
  rbd -m MON -p POOL flatten NAME
  rbd -m MON -p POOL export NAME FILE      ('-' = stdout)
  rbd -m MON -p POOL import FILE NAME      ('-' = stdin)
  rbd -m MON -p POOL export-diff [--from-snap A] NAME[@B] FILE
  rbd -m MON -p POOL import-diff FILE NAME
  rbd -m MON -p POOL du NAME
  rbd -m MON -p POOL lock ls NAME
  rbd -m MON -p POOL bench NAME --io-size N --io-total N
"""

from __future__ import annotations

import argparse
import sys
import time

from .rados_cli import add_auth_args, cli_auth, parse_addr


def _split_at(spec: str) -> tuple[str, str]:
    name, _, snap = spec.partition("@")
    if not snap:
        raise SystemExit(f"expected IMAGE@SNAP, got {spec!r}")
    return name, snap


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="rbd")
    ap.add_argument("-m", "--mon", required=True, help="mon HOST:PORT")
    ap.add_argument("-p", "--pool", required=True)
    ap.add_argument("command")
    ap.add_argument("args", nargs="*")
    ap.add_argument("--size", type=int, default=None)
    ap.add_argument("--order", type=int, default=22)
    ap.add_argument("--io-size", type=int, default=1 << 20)
    ap.add_argument("--io-total", type=int, default=64 << 20)
    ap.add_argument("--exclusive", action="store_true",
                    help="hold the exclusive lock during I/O commands")
    ap.add_argument("--from-snap", default=None,
                    help="export-diff: the base snapshot")
    add_auth_args(ap)
    # parse_intermixed_args, not parse_args: with the greedy
    # (command, args*) positional pattern, plain parse_args consumes
    # the positional group BEFORE a following option and then rejects
    # positionals after it — `rbd create --size N NAME` died with
    # "unrecognized arguments: NAME" while `rbd create NAME --size N`
    # worked; intermixed parsing collects positionals across option
    # boundaries the way the reference rbd CLI accepts them
    args = ap.parse_intermixed_args(argv)

    from ..rados import RadosClient
    from ..rados.client import RadosError
    from ..rbd import RBD, Image

    auth, secure = cli_auth(args)
    client = RadosClient(parse_addr(args.mon), auth=auth,
                         secure=secure).connect()
    try:
        io = client.open_ioctx(args.pool)
        rbd = RBD(io)
        cmd, rest = args.command, args.args
        if cmd == "create":
            if args.size is None:
                raise SystemExit("create requires --size")
            rbd.create(rest[0], args.size, order=args.order)
        elif cmd == "ls":
            for n in rbd.list():
                print(n)
        elif cmd == "info":
            img = Image(io, rest[0])
            print(f"rbd image '{rest[0]}':")
            print(f"\tsize {img.size()} bytes in "
                  f"{img._nblocks()} objects")
            print(f"\torder {img._header['order']} "
                  f"({img.block_size} byte objects)")
            if img._header.get("parent"):
                p, s = img._header["parent"]
                print(f"\tparent: {p} (snap id {s})")
            snaps = img.snap_list()
            if snaps:
                print(f"\tsnapshots: {', '.join(snaps)}")
        elif cmd == "rm":
            rbd.remove(rest[0])
        elif cmd == "resize":
            if args.size is None:
                raise SystemExit("resize requires --size")
            img = Image(io, rest[0], exclusive=args.exclusive)
            img.resize(args.size)
            img.close()
        elif cmd == "snap":
            sub = rest[0]
            if sub == "ls":
                for s in Image(io, rest[1]).snap_list():
                    print(s)
            else:
                name, snap = _split_at(rest[1])
                img = Image(io, name, exclusive=args.exclusive)
                if sub == "create":
                    img.snap_create(snap)
                elif sub == "rm":
                    img.snap_remove(snap)
                elif sub == "rollback":
                    img.snap_rollback(snap)
                else:
                    raise SystemExit(f"unknown snap subcommand {sub!r}")
                img.close()
        elif cmd == "clone":
            parent, snap = _split_at(rest[0])
            rbd.clone(parent, snap, rest[1])
        elif cmd == "flatten":
            img = Image(io, rest[0], exclusive=args.exclusive)
            img.flatten()
            img.close()
        elif cmd == "export":
            img = Image(io, rest[0])
            data = img.read(0, img.size())
            if rest[1] == "-":
                sys.stdout.buffer.write(data)
            else:
                open(rest[1], "wb").write(data)
                print(f"exported {len(data)} bytes")
        elif cmd == "import":
            data = sys.stdin.buffer.read() if rest[0] == "-" else \
                open(rest[0], "rb").read()
            name = rest[1]
            rbd.create(name, len(data), order=args.order)
            img = Image(io, name, exclusive=args.exclusive)
            img.write(0, data)
            img.close()
            print(f"imported {len(data)} bytes to {name}")
        elif cmd == "export-diff":
            # rbd export-diff [--from-snap S] IMG[@TO] FILE
            name, to_snap = _split_at(rest[0]) if "@" in rest[0] \
                else (rest[0], None)
            img = Image(io, name)
            out = sys.stdout.buffer if rest[1] == "-" else \
                open(rest[1], "wb")
            n = img.export_diff(out, from_snap=args.from_snap,
                                to_snap=to_snap)
            if rest[1] != "-":
                out.close()
                print(f"exported {n} changed extents")
        elif cmd == "import-diff":
            # rbd import-diff FILE IMG
            inp = sys.stdin.buffer if rest[0] == "-" else \
                open(rest[0], "rb")
            img = Image(io, rest[1], exclusive=args.exclusive)
            stats = img.import_diff(inp)
            img.close()
            print(f"applied {stats['w']} writes / {stats['z']} zero "
                  f"runs ({stats['bytes']} bytes)")
        elif cmd == "du":
            img = Image(io, rest[0], exclusive=args.exclusive)
            used = img.du()
            print(f"{rest[0]}: {img.size()} provisioned, {used} used")
            img.close()
        elif cmd == "lock":
            if rest[0] != "ls":
                raise SystemExit(f"unknown lock subcommand {rest[0]!r}")
            for owner in Image(io, rest[1]).lock_owners():
                print(owner)
        elif cmd == "bench":
            img = Image(io, rest[0], exclusive=args.exclusive)
            import numpy as np
            payload = np.random.default_rng(0).integers(
                0, 256, args.io_size, dtype=np.uint8).tobytes()
            total = min(args.io_total, img.size())
            t0 = time.time()
            off = 0
            n = 0
            while off + args.io_size <= total:
                img.write(off, payload)
                off += args.io_size
                n += 1
            dt = time.time() - t0
            img.close()
            print(f"wrote {n} x {args.io_size}B in {dt:.2f}s = "
                  f"{n * args.io_size / dt / 1e6:.1f} MB/s")
        else:
            raise SystemExit(f"unknown command {cmd!r}")
        return 0
    except RadosError as e:
        print(f"rbd: {e}", file=sys.stderr)
        return 1
    finally:
        client.shutdown()


if __name__ == "__main__":
    sys.exit(main())
