"""End-to-end cluster throughput bench (reference `rados bench`,
src/tools/rados/rados.cc + qa/tasks/radosbench.py).

Measures the FULL system tier no codec-level number covers: client ->
objecter -> messenger -> OSD dispatch -> EC/replication pipeline ->
store commit -> ack, with concurrent writers, on an in-process vstart
cluster.  Rows (one JSON line each):

  python -m ceph_tpu.tools.cluster_bench            # default matrix
  python -m ceph_tpu.tools.cluster_bench --seconds 5 --threads 8

Matrix: replicated x3, EC k=2 m=1, EC k=8 m=3 (the reference's
canonical profile) — each on MemStore; EC additionally with the
dynamic batch window on vs off (tpu_batch_window_ms) to quantify the
cross-transaction batching the TPU pipeline exists for.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def bench_pool(cluster, client, pool: str, seconds: float,
               threads: int, size: int) -> dict:
    from .latency import LatencyRecorder
    io = client.open_ioctx(pool)
    payload = np.random.default_rng(7).integers(
        0, 256, size, dtype=np.uint8).tobytes()
    stop = time.time() + seconds
    counts = [0] * threads
    # per-op latency samples + errors bucketed by exception type (a
    # bare error count hid WHAT failed; reference `rados bench` keeps
    # per-op latencies the same way)
    wlat = LatencyRecorder("write")
    rlat = LatencyRecorder("read")

    def writer(t: int) -> None:
        i = 0
        myio = client.open_ioctx(pool)
        while time.time() < stop:
            t0 = time.perf_counter()
            try:
                myio.write_full(f"b_{t}_{i}", payload)
                wlat.record(time.perf_counter() - t0)
                counts[t] += 1
            except Exception as e:  # noqa: BLE001
                wlat.error(e)
            i += 1

    ts = [threading.Thread(target=writer, args=(t,)) for t in
          range(threads)]
    t0 = time.time()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    elapsed = time.time() - t0
    wrote = sum(counts)
    # Settle before the read phase: trailing write-pipeline work
    # (acks, roll-forward, retention trims) otherwise competes with
    # the reads and understates the read path ~2x.  The reference's
    # `rados bench seq` is likewise a separate phase run against a
    # settled pool, not the tail of the write storm.
    time.sleep(2.0)
    # read-back verification pass (sequential, first writer's objects)
    r0 = time.time()
    rn = 0
    for i in range(min(counts[0], 64)):
        rt0 = time.perf_counter()
        try:
            got = io.read(f"b_0_{i}", size)
        except Exception as e:  # noqa: BLE001
            rlat.error(e)
            continue
        rlat.record(time.perf_counter() - rt0)
        assert got == payload, "read-back mismatch"
        rn += 1
    relapsed = time.time() - r0
    wsum, rsum = wlat.summary(), rlat.summary()
    by_type = dict(wsum["errors_by_type"])
    for k, v in rsum["errors_by_type"].items():
        by_type[k] = by_type.get(k, 0) + v
    return {
        "write_mb_s": round(wrote * size / elapsed / 1e6, 2),
        "write_iops": round(wrote / elapsed, 1),
        "ops": wrote,
        "errors": wsum["errors"] + rsum["errors"],
        "errors_by_type": by_type,
        "write_lat": {k: v for k, v in wsum.items()
                      if k not in ("errors", "errors_by_type")},
        "read_lat": {k: v for k, v in rsum.items()
                     if k not in ("errors", "errors_by_type")},
        "read_mb_s": round(rn * size / relapsed / 1e6, 2)
        if relapsed > 0 and rn else None,
    }


def _setup_profiles(client, mesh: bool = False) -> None:
    client.set_ec_profile("cb21", {
        "plugin": "jerasure", "k": "2", "m": "1",
        "stripe_unit": "4096"})
    client.set_ec_profile("cb83", {
        "plugin": "jerasure", "k": "8", "m": "3",
        "stripe_unit": "4096"})
    if mesh:
        # the mesh plane requires a matrix-compatible plugin (the jax
        # cauchy codec shares the MeshService generator matrix;
        # jerasure's cauchy_good would fall back with a config error)
        client.set_ec_profile("cb83x", {
            "plugin": "jax", "k": "8", "m": "3",
            "technique": "cauchy", "stripe_unit": "4096"})


def _make_pool(client, name: str, profile: str | None) -> str:
    pool = f"pool_{name}"
    if profile:
        client.create_pool(pool, "erasure",
                           erasure_code_profile=profile, pg_num=16)
    else:
        client.create_pool(pool, "replicated", size=3, pg_num=16)
    return pool


def _matrix(args) -> list[tuple[str, str | None, float]]:
    """ONE matrix for both topologies (the A/B claim depends on it)."""
    rows = [("replicated", None, 0.0)]
    if not args.quick:
        rows.append(("ec_k2m1", "cb21", 0.0))
    rows += [("ec_k8m3", "cb83", 0.0),
             ("ec_k8m3_batched", "cb83", args.window_ms)]
    if args.mesh is not None:
        # mesh-plane A/B row: jax-plugin profile so the EC backends
        # actually acquire the MeshService codec (docs/MULTICHIP.md)
        rows.append(("ec_k8m3_mesh", "cb83x", 0.0))
    return rows


def _row_mesh(c, args, profile) -> str | None:
    """The `mesh` field for a published row: the shape string only
    when a mesh plane ACTUALLY served the row, else null.  Thread
    topology reads the live backends (an ECBackend that fell back to
    the single-chip plane must not be published as a mesh run); the
    process topology can't introspect other interpreters, so it
    reports the shape the daemons' parser resolves — the best honest
    claim available there."""
    if args.mesh is None or profile != "cb83x":
        return None
    from ..parallel.service import MeshError, parse_mesh_shape
    if hasattr(c, "osds"):          # thread topology: inspect planes
        for osd in c.osds:
            for st in getattr(osd, "pgs", {}).values():
                if st.kind != "ec":
                    continue
                ms = st.backend.mesh_status()
                if ms["active"]:
                    m = ms["mesh"]
                    return f"{m['shard']}x{m['data']}"
        return None
    try:
        s, d = parse_mesh_shape(args.mesh, 8)
        return f"{s}x{d}"
    except MeshError:
        return None


def _bench_row(c, client, args, name, profile, window,
               extra: dict) -> dict:
    pool = _make_pool(client, name, profile)
    res = bench_pool(c, client, pool, args.seconds, args.threads,
                     args.size)
    # `mesh` distinguishes mesh-plane rows from single-chip rows in
    # the published JSON (shape string, or null) — resolved from the
    # cluster AFTER the row ran, not from the CLI flag
    row = {"config": name, "objectstore": args.objectstore,
           "threads": args.threads, "obj_size": args.size,
           "batch_window_ms": window,
           "mesh": _row_mesh(c, args, profile), **res, **extra}
    print(json.dumps(row), flush=True)
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="cluster_bench")
    ap.add_argument("--seconds", type=float, default=5.0)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--size", type=int, default=1 << 20)
    ap.add_argument("--osds", type=int, default=12)
    ap.add_argument("--objectstore", default="memstore")
    ap.add_argument("--window-ms", type=float, default=4.0,
                    help="batch window for the windowed EC rows")
    ap.add_argument("--quick", action="store_true",
                    help="small matrix (replicated + one EC profile)")
    ap.add_argument("--mesh", nargs="?", const="", default=None,
                    metavar="SxD|N",
                    help="add a mesh-plane EC row: enable the "
                         "multichip MeshService on the cluster "
                         "('SxD' shape, device count, or bare flag = "
                         "all visible devices)")
    ap.add_argument("--processes", action="store_true",
                    help="multi-process topology (ProcCluster): each "
                         "daemon its own interpreter — cluster numbers "
                         "measure the system, not one GIL")
    args = ap.parse_args(argv)

    if args.mesh is not None:
        # CPU hosts need the virtual devices BEFORE jax initializes
        # (in the process topology daemon_main does this per daemon;
        # the thread topology shares THIS interpreter's backend)
        import os
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            from ..parallel.service import MeshError, parse_mesh_shape
            try:
                s, d = parse_mesh_shape(args.mesh, 8)
                os.environ["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_"
                    f"count={s * d}").strip()
            except MeshError:
                pass    # the service will surface the bad spec

    if args.processes:
        return _main_processes(args)

    from ..tools.vstart import Cluster

    import tempfile
    data_dir = tempfile.mkdtemp(prefix="cbench_") \
        if args.objectstore != "memstore" else None
    with Cluster(n_osds=args.osds, objectstore=args.objectstore,
                 data_dir=data_dir, mesh_devices=args.mesh) as c:
        client = c.client()
        _setup_profiles(client, mesh=args.mesh is not None)
        for name, profile, window in _matrix(args):
            for osd in c.osds:
                osd.cct.conf.set("tpu_batch_window_ms", window)
            counters = {
                "codec_launches": -sum(
                    getattr(st.backend, "batched_launches", 0)
                    for osd in c.osds
                    for st in getattr(osd, "pgs", {}).values()),
                "codec_extents": -sum(
                    getattr(st.backend, "batched_extents", 0)
                    for osd in c.osds
                    for st in getattr(osd, "pgs", {}).values())}
            _bench_row(c, client, args, name, profile, window, {})
            # report per-row deltas of the cumulative in-process
            # counters (unavailable cross-process)
            counters["codec_launches"] += sum(
                getattr(st.backend, "batched_launches", 0)
                for osd in c.osds
                for st in getattr(osd, "pgs", {}).values())
            counters["codec_extents"] += sum(
                getattr(st.backend, "batched_extents", 0)
                for osd in c.osds
                for st in getattr(osd, "pgs", {}).values())
            print(json.dumps({"config": name, **counters}), flush=True)
    return 0


def _main_processes(args) -> int:
    """Process-topology twin of the SAME matrix.  Per-OSD conf must
    ride the spawn command line, so rows whose batch window differs
    get their own cluster; codec launch counters live in other
    processes and are not reported."""
    from ..tools.proc_cluster import ProcCluster

    by_window: dict[float, list] = {}
    for name, profile, window in _matrix(args):
        by_window.setdefault(window, []).append((name, profile, window))
    for window, rows in by_window.items():
        conf = {"tpu_batch_window_ms": window} if window else {}
        with ProcCluster(n_osds=args.osds,
                         objectstore=args.objectstore,
                         conf=conf, mesh_devices=args.mesh) as c:
            client = c.client()
            _setup_profiles(client, mesh=args.mesh is not None)
            for name, profile, w in rows:
                _bench_row(c, client, args, name, profile, w,
                           {"topology": "processes"})
    return 0


if __name__ == "__main__":
    sys.exit(main())
