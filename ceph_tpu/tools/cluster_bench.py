"""End-to-end cluster throughput bench (reference `rados bench`,
src/tools/rados/rados.cc + qa/tasks/radosbench.py).

Measures the FULL system tier no codec-level number covers: client ->
objecter -> messenger -> OSD dispatch -> EC/replication pipeline ->
store commit -> ack, with concurrent writers, on an in-process vstart
cluster.  Rows (one JSON line each):

  python -m ceph_tpu.tools.cluster_bench            # default matrix
  python -m ceph_tpu.tools.cluster_bench --seconds 5 --threads 8

Matrix: replicated x3, EC k=2 m=1, EC k=8 m=3 (the reference's
canonical profile) — each on MemStore; EC additionally with the
dynamic batch window on vs off (tpu_batch_window_ms) to quantify the
cross-transaction batching the TPU pipeline exists for.

`--scale [N]` (default 64) is the CONTROL-PLANE row instead: stand up
the largest thread-topology cluster the box allows, churn map epochs
via split + merge + drain + kill/revive UNDER write load, and gate
  - map bytes shipped per epoch vs the full-publish equivalent
    (>= SCALE_MAP_RATIO_MIN, default 10x — the incremental-publish
    claim, docs/ARCHITECTURE.md "Map distribution"),
  - heartbeat keepalives counted (a current daemon's tick is ~free),
  - incremental-applied maps bit-equal to the mon's on every daemon,
  - time-to-active-clean after the churn with ZERO acked-write loss.
One BENCH-comparable JSON line; rc != 0 on any gate failure.
"""

from __future__ import annotations

import argparse
import json
import sys
import threading
import time

import numpy as np


def bench_pool(cluster, client, pool: str, seconds: float,
               threads: int, size: int) -> dict:
    from .latency import LatencyRecorder
    io = client.open_ioctx(pool)
    payload = np.random.default_rng(7).integers(
        0, 256, size, dtype=np.uint8).tobytes()
    stop = time.time() + seconds
    counts = [0] * threads
    # per-op latency samples + errors bucketed by exception type (a
    # bare error count hid WHAT failed; reference `rados bench` keeps
    # per-op latencies the same way)
    wlat = LatencyRecorder("write")
    rlat = LatencyRecorder("read")

    def writer(t: int) -> None:
        i = 0
        myio = client.open_ioctx(pool)
        while time.time() < stop:
            t0 = time.perf_counter()
            try:
                myio.write_full(f"b_{t}_{i}", payload)
                wlat.record(time.perf_counter() - t0)
                counts[t] += 1
            except Exception as e:  # noqa: BLE001
                wlat.error(e)
            i += 1

    ts = [threading.Thread(target=writer, args=(t,)) for t in
          range(threads)]
    t0 = time.time()
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    elapsed = time.time() - t0
    wrote = sum(counts)
    # Settle before the read phase: trailing write-pipeline work
    # (acks, roll-forward, retention trims) otherwise competes with
    # the reads and understates the read path ~2x.  The reference's
    # `rados bench seq` is likewise a separate phase run against a
    # settled pool, not the tail of the write storm.
    time.sleep(2.0)
    # read-back verification pass (sequential, first writer's objects)
    r0 = time.time()
    rn = 0
    for i in range(min(counts[0], 64)):
        rt0 = time.perf_counter()
        try:
            got = io.read(f"b_0_{i}", size)
        except Exception as e:  # noqa: BLE001
            rlat.error(e)
            continue
        rlat.record(time.perf_counter() - rt0)
        assert got == payload, "read-back mismatch"
        rn += 1
    relapsed = time.time() - r0
    wsum, rsum = wlat.summary(), rlat.summary()
    by_type = dict(wsum["errors_by_type"])
    for k, v in rsum["errors_by_type"].items():
        by_type[k] = by_type.get(k, 0) + v
    return {
        "write_mb_s": round(wrote * size / elapsed / 1e6, 2),
        "write_iops": round(wrote / elapsed, 1),
        "ops": wrote,
        "errors": wsum["errors"] + rsum["errors"],
        "errors_by_type": by_type,
        "write_lat": {k: v for k, v in wsum.items()
                      if k not in ("errors", "errors_by_type")},
        "read_lat": {k: v for k, v in rsum.items()
                     if k not in ("errors", "errors_by_type")},
        "read_mb_s": round(rn * size / relapsed / 1e6, 2)
        if relapsed > 0 and rn else None,
    }


def _setup_profiles(client, mesh: bool = False) -> None:
    client.set_ec_profile("cb21", {
        "plugin": "jerasure", "k": "2", "m": "1",
        "stripe_unit": "4096"})
    client.set_ec_profile("cb83", {
        "plugin": "jerasure", "k": "8", "m": "3",
        "stripe_unit": "4096"})
    if mesh:
        # the mesh plane requires a matrix-compatible plugin (the jax
        # cauchy codec shares the MeshService generator matrix;
        # jerasure's cauchy_good would fall back with a config error)
        client.set_ec_profile("cb83x", {
            "plugin": "jax", "k": "8", "m": "3",
            "technique": "cauchy", "stripe_unit": "4096"})


def _make_pool(client, name: str, profile: str | None) -> str:
    pool = f"pool_{name}"
    if profile:
        client.create_pool(pool, "erasure",
                           erasure_code_profile=profile, pg_num=16)
    else:
        client.create_pool(pool, "replicated", size=3, pg_num=16)
    return pool


def _matrix(args) -> list[tuple[str, str | None, float]]:
    """ONE matrix for both topologies (the A/B claim depends on it)."""
    rows = [("replicated", None, 0.0)]
    if not args.quick:
        rows.append(("ec_k2m1", "cb21", 0.0))
    rows += [("ec_k8m3", "cb83", 0.0),
             ("ec_k8m3_batched", "cb83", args.window_ms)]
    if args.mesh is not None:
        # mesh-plane A/B row: jax-plugin profile so the EC backends
        # actually acquire the MeshService codec (docs/MULTICHIP.md)
        rows.append(("ec_k8m3_mesh", "cb83x", 0.0))
    return rows


def _row_mesh(c, args, profile) -> str | None:
    """The `mesh` field for a published row: the shape string only
    when a mesh plane ACTUALLY served the row, else null.  Thread
    topology reads the live backends (an ECBackend that fell back to
    the single-chip plane must not be published as a mesh run); the
    process topology can't introspect other interpreters, so it
    reports the shape the daemons' parser resolves — the best honest
    claim available there."""
    if args.mesh is None or profile != "cb83x":
        return None
    from ..parallel.service import MeshError, parse_mesh_shape
    if hasattr(c, "osds"):          # thread topology: inspect planes
        for osd in c.osds:
            for st in getattr(osd, "pgs", {}).values():
                if st.kind != "ec":
                    continue
                ms = st.backend.mesh_status()
                if ms["active"]:
                    m = ms["mesh"]
                    return f"{m['shard']}x{m['data']}"
        return None
    try:
        s, d = parse_mesh_shape(args.mesh, 8)
        return f"{s}x{d}"
    except MeshError:
        return None


def _bench_row(c, client, args, name, profile, window,
               extra: dict) -> dict:
    pool = _make_pool(client, name, profile)
    res = bench_pool(c, client, pool, args.seconds, args.threads,
                     args.size)
    # `mesh` distinguishes mesh-plane rows from single-chip rows in
    # the published JSON (shape string, or null) — resolved from the
    # cluster AFTER the row ran, not from the CLI flag
    row = {"config": name, "objectstore": args.objectstore,
           "threads": args.threads, "obj_size": args.size,
           "batch_window_ms": window,
           "mesh": _row_mesh(c, args, profile), **res, **extra}
    # device-plane provenance (ISSUE 15): EC rows embed the host
    # flight recorder's summary so a rate move is attributable to
    # compiles / launch occupancy without re-running with an asok
    from ..ops.profiler import device_profiler
    row["launch_ledger"] = device_profiler().bench_summary()
    print(json.dumps(row), flush=True)
    return row


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="cluster_bench")
    ap.add_argument("--seconds", type=float, default=5.0)
    ap.add_argument("--threads", type=int, default=8)
    ap.add_argument("--size", type=int, default=1 << 20)
    ap.add_argument("--osds", type=int, default=12)
    ap.add_argument("--objectstore", default="memstore")
    ap.add_argument("--window-ms", type=float, default=4.0,
                    help="batch window for the windowed EC rows")
    ap.add_argument("--quick", action="store_true",
                    help="small matrix (replicated + one EC profile)")
    ap.add_argument("--mesh", nargs="?", const="", default=None,
                    metavar="SxD|N",
                    help="add a mesh-plane EC row: enable the "
                         "multichip MeshService on the cluster "
                         "('SxD' shape, device count, or bare flag = "
                         "all visible devices)")
    ap.add_argument("--processes", action="store_true",
                    help="multi-process topology (ProcCluster): each "
                         "daemon its own interpreter — cluster numbers "
                         "measure the system, not one GIL")
    ap.add_argument("--scale", nargs="?", type=int, const=64,
                    default=None, metavar="N",
                    help="control-plane scale row instead of the I/O "
                         "matrix: N-OSD cluster (default 64), epoch "
                         "churn under load, incremental-map + "
                         "active-clean + zero-loss gates, rc!=0 on "
                         "failure")
    ap.add_argument("--heartbeat", type=float, default=2.0,
                    help="heartbeat interval for the --scale cluster "
                         "(failure detection + mon keepalive cadence)")
    ap.add_argument("--hb-peers", type=int, default=6,
                    help="osd_heartbeat_min_peers for the --scale "
                         "cluster (ring-subset ping fan-out)")
    ap.add_argument("--hb-grace", type=float, default=10.0,
                    help="osd_heartbeat_grace for the --scale cluster "
                         "(missed-ping multiplier; generous so python "
                         "thread scheduling jitter on a small box "
                         "doesn't flap daemons down)")
    ap.add_argument("--prewarm", action="store_true",
                    help="--scale only: boot with the jit-bucket "
                         "prewarm + persistent compile cache "
                         "(CEPH_TPU_COMPILE_CACHE for a hermetic "
                         "dir), drive an EC pool through the churn "
                         "with compile-stall injection armed, and "
                         "gate ec_compile_stalls == 0 / no "
                         "COMPILE_STORM (ISSUE 16)")
    args = ap.parse_args(argv)

    if args.scale is not None:
        return _main_scale(args)

    if args.mesh is not None:
        # CPU hosts need the virtual devices BEFORE jax initializes
        # (in the process topology daemon_main does this per daemon;
        # the thread topology shares THIS interpreter's backend)
        import os
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            from ..parallel.service import MeshError, parse_mesh_shape
            try:
                s, d = parse_mesh_shape(args.mesh, 8)
                os.environ["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_"
                    f"count={s * d}").strip()
            except MeshError:
                pass    # the service will surface the bad spec

    if args.processes:
        return _main_processes(args)

    from ..tools.vstart import Cluster

    import tempfile
    data_dir = tempfile.mkdtemp(prefix="cbench_") \
        if args.objectstore != "memstore" else None
    with Cluster(n_osds=args.osds, objectstore=args.objectstore,
                 data_dir=data_dir, mesh_devices=args.mesh) as c:
        client = c.client()
        _setup_profiles(client, mesh=args.mesh is not None)
        for name, profile, window in _matrix(args):
            for osd in c.osds:
                osd.cct.conf.set("tpu_batch_window_ms", window)
            counters = {
                "codec_launches": -sum(
                    getattr(st.backend, "batched_launches", 0)
                    for osd in c.osds
                    for st in getattr(osd, "pgs", {}).values()),
                "codec_extents": -sum(
                    getattr(st.backend, "batched_extents", 0)
                    for osd in c.osds
                    for st in getattr(osd, "pgs", {}).values())}
            _bench_row(c, client, args, name, profile, window, {})
            # report per-row deltas of the cumulative in-process
            # counters (unavailable cross-process)
            counters["codec_launches"] += sum(
                getattr(st.backend, "batched_launches", 0)
                for osd in c.osds
                for st in getattr(osd, "pgs", {}).values())
            counters["codec_extents"] += sum(
                getattr(st.backend, "batched_extents", 0)
                for osd in c.osds
                for st in getattr(osd, "pgs", {}).values())
            print(json.dumps({"config": name, **counters}), flush=True)
    return 0


BLAME_STAGES = ("peering_s", "scan_s", "decode_s", "push_s",
                "throttle_s")
BLAME_COUNTERS = ("remote_lists", "objects_scanned",
                  "objects_recovered", "transitions")


def _ledger_snapshot(cluster) -> dict[int, dict]:
    """Per-OSD cumulative pg_ledger blame block (osd/pg_ledger)."""
    snaps: dict[int, dict] = {}
    for osd in cluster.osds:
        if osd is None:
            continue
        try:
            snaps[osd.osd_id] = dict(osd.pg_ledger.blame_block())
        except Exception:  # noqa: BLE001 - daemon mid-shutdown
            pass
    return snaps


def _recovery_blame(before: dict[int, dict], after: dict[int, dict],
                    ttac: float | None, window_s: float) -> dict:
    """time_to_active_clean decomposition from the per-OSD control-
    plane ledgers (ISSUE 19 payoff gate).  Per stage the value is the
    MAX across OSDs of the window delta — concurrent recovery overlaps
    across daemons, so the max approximates the critical path where a
    sum would count the same wall-second n_osds times.  When the stage
    total still exceeds ttac (stages overlap within one daemon too:
    throttle inside push loops, scans while peers push) the stages are
    folded proportionally onto ttac (`overlap_folded: true`) so the
    published decomposition reads as shares of the clean wait; the raw
    per-stage maxima ride along unfolded."""
    deltas: dict[int, dict] = {}
    for oid, a in after.items():
        b = before.get(oid, {})
        if a.get("transitions", 0) < b.get("transitions", 0):
            b = {}   # daemon restarted mid-window: fresh ledger
        deltas[oid] = {k: a.get(k, 0) - b.get(k, 0)
                       for k in set(a) | set(b)}
    raw = {k: round(max((d.get(k, 0.0) for d in deltas.values()),
                        default=0.0), 4)
           for k in BLAME_STAGES}
    counters = {k: int(sum(d.get(k, 0) for d in deltas.values()))
                for k in BLAME_COUNTERS}
    block: dict = {"stages_raw_s": raw, **counters,
                   "window_s": round(window_s, 2),
                   "osds_reporting": len(deltas)}
    raw_sum = sum(raw.values())
    if ttac is not None and ttac > 0:
        folded = raw_sum > ttac
        scale = (ttac / raw_sum) if folded and raw_sum > 0 else 1.0
        stages = {k: round(v * scale, 4) for k, v in raw.items()}
        block.update(stages)
        block["other_s"] = round(
            max(0.0, ttac - sum(stages.values())), 4)
        block["overlap_folded"] = folded
        block["time_to_active_clean_s"] = ttac
    return block


def _main_scale(args) -> int:
    """The ROADMAP-item-5 scale row: where does the control plane
    actually stop scaling?  Epoch churn (split, merge, drain walk,
    kill/revive) on the biggest thread-topology cluster the box
    allows, write load running THROUGH the churn, and the map
    distribution ledger gated against the full-publish baseline."""
    import os
    import queue as _q

    from ..osdc.objecter import TimedOut
    from ..rados.client import RadosError
    from .vstart import Cluster

    n = args.scale
    min_ratio = float(os.environ.get("SCALE_MAP_RATIO_MIN", "10"))
    clean_timeout = float(os.environ.get("SCALE_CLEAN_TIMEOUT_S",
                                         "180"))
    rng = np.random.default_rng(7)
    payload = rng.integers(0, 256, args.size, dtype=np.uint8).tobytes()
    row: dict = {"metric": "cluster_scale", "osds": n,
                 "obj_size": args.size}
    fail: list[str] = []
    conf = {"osd_heartbeat_min_peers": args.hb_peers,
            "osd_heartbeat_grace": args.hb_grace}
    prewarm_ec = bool(getattr(args, "prewarm", False)) and n >= 12
    if prewarm_ec:
        # ISSUE 16 churn gate: boot prewarmed (persistent cache dir
        # from CEPH_TPU_COMPILE_CACHE when hermetic CI points one),
        # and ARM the compile-stall injection — any EC launch on a
        # bucket the prewarm failed to cover sleeps 0.5 s in its
        # submit and fails the zero-stall gate below.  Deterministic:
        # with full coverage the injection can never fire.
        conf.update({"osd_ec_prewarm": True,
                     "osd_ec_prewarm_budget_s": 60.0,
                     "osd_ec_inject_compile_stall": 0.5})
    t0 = time.time()
    with Cluster(n_osds=n, heartbeat_interval=args.heartbeat,
                 boot_parallel=True, conf=conf) as c:
        row["boot_s"] = round(time.time() - t0, 2)
        client = None
        for _ in range(5):      # map RT right after a big boot can
            try:                # exceed the client's 10 s start budget
                client = c.client()
                break
            except TimedOut:
                time.sleep(1.0)
        if client is None:
            client = c.client()

        def mcmd(cmd: dict, budget: float = 180.0) -> dict:
            """Mon command with a generous single-attempt window: with
            N daemons + recovery threads sharing this interpreter, an
            ack can starve well past the client's default 3 s attempt
            (and a blind resend of a landed create answers EEXIST);
            EBUSY/EAGAIN (interleave guard, stats refresh) retry."""
            deadline = time.time() + budget
            while True:
                try:
                    r, out = client.objecter.mon_command(
                        cmd, timeout=min(60.0, budget))
                except TimedOut:
                    r, out = -1, {"error": "mon command timeout"}
                if r == 0 or -r == 17:        # ok / EEXIST on resend
                    return out
                if time.time() > deadline:
                    raise RuntimeError(f"{cmd.get('prefix')}: {out}")
                time.sleep(0.5)

        mcmd({"prefix": "osd pool create", "name": "scale",
              "type": "replicated", "size": 3, "pg_num": 32})
        io = client.open_ioctx("scale")
        acked: dict[str, bool] = {}
        acked_q: _q.Queue = _q.Queue()
        stop_writing = threading.Event()
        ec_io = None
        ec_payload = b""
        ec_acked_q: _q.Queue = _q.Queue()
        if prewarm_ec:
            # EC churn lane (ISSUE 16): k=8,m=3 writes at the default
            # profile's prewarmed geometry (32 KiB objects -> 4 KiB
            # chunk columns) ride THROUGH the kill/revive below, so
            # the zero-stall gate covers encode, degraded decode, and
            # post-revive recovery launches
            mcmd({"prefix": "osd erasure-code-profile set",
                  "name": "scale_ec",
                  "profile": {"plugin": "jax", "technique": "cauchy",
                              "k": "8", "m": "3",
                              "stripe_unit": "1024"}})
            mcmd({"prefix": "osd pool create", "name": "scaleec",
                  "type": "erasure",
                  "erasure_code_profile": "scale_ec", "pg_num": 4})
            ec_io = client.open_ioctx("scaleec")
            ec_payload = rng.integers(0, 256, 32768,
                                      dtype=np.uint8).tobytes()

        def writer(t: int) -> None:
            i = 0
            while not stop_writing.is_set():
                name = f"s_{t}_{i}"
                try:
                    # short per-op budget: a write racing a killed
                    # primary must fail fast and move on, not pin the
                    # churn phase on a 30 s default timeout
                    reply = client.objecter.op_submit(
                        io.pool_id, name,
                        [["writefull", len(payload)]], payload,
                        timeout=5.0, attempts=2)
                    if reply.result == 0:
                        acked_q.put(name)
                except Exception:  # noqa: BLE001 - churn makes every
                    pass           # failure shape expected here
                i += 1

        # the EC lane pauses across the remap windows (ec_gate: drain
        # walk through kill/revive): a write in flight when its shard
        # holders re-peer can wedge the EC pipeline or leave a partial
        # object past the clean-wait (sub-write acks are not resent on
        # re-peer — a known reduction, docs/PIPELINE.md) and that
        # liveness axis is not what this gate measures.  Writes BEFORE
        # the remaps cover the cold-boot buckets, writes AFTER the
        # revives are the acceptance point (warm first launches on a
        # revived daemon); the replicated lane keeps load through the
        # windows themselves.
        ec_gate = threading.Event()
        ec_gate.set()

        def ec_writer(t: int) -> None:
            i = 0
            while not stop_writing.is_set():
                if not ec_gate.is_set():
                    time.sleep(0.1)
                    continue
                name = f"ec_{t}_{i}"
                try:
                    reply = client.objecter.op_submit(
                        ec_io.pool_id, name,
                        [["writefull", len(ec_payload)]], ec_payload,
                        timeout=5.0, attempts=2)
                    if reply.result == 0:
                        ec_acked_q.put(name)
                except Exception:  # noqa: BLE001 - churn failures
                    pass           # expected, like the replicated lane
                i += 1

        # lighter write load at high N: the point is load DURING
        # churn, not peak IOPS — at 64 in-process daemons the GIL is
        # the scarce resource
        n_writers = 2 if n >= 32 else min(args.threads, 4)
        writers = [threading.Thread(target=writer, args=(t,),
                                    daemon=True)
                   for t in range(n_writers)]
        if ec_io is not None:
            writers += [threading.Thread(target=ec_writer, args=(t,),
                                         daemon=True)
                        for t in range(2)]
        for t in writers:
            t.start()
        time.sleep(max(1.0, args.seconds / 2))

        # split/merge churn rides its own pool: the measured axis is
        # CONTROL-PLANE fan-out (epochs, sweeps, re-peering on every
        # daemon), not data migration — resizing the loaded pool at
        # 64 OSDs additionally triggers O(PGs x OSDs) recovery wide
        # scans that swamp a small box for minutes (tier-1's
        # pg_split/pg_merge thrash suites own that axis); drain +
        # kill/revive below still remap the LOADED pool
        mcmd({"prefix": "osd pool create", "name": "churn",
              "type": "replicated", "size": 3, "pg_num": 8})

        def pool_set(val: int, budget: float = 180.0) -> None:
            mcmd({"prefix": "osd pool set", "pool": "churn",
                  "var": "pg_num", "val": val}, budget)

        # recovery-blame EC lane (ISSUE 19): a small k=2,m=1 pool
        # written BEFORE the churn so the kill/revive below has EC
        # shards to reconstruct — the decode/push stages of the
        # recovery_blame block need a PG that actually decodes.  The
        # prewarm row already runs a live EC lane; reuse it there.
        blame_pool_id = None
        if not prewarm_ec:
            mcmd({"prefix": "osd erasure-code-profile set",
                  "name": "blame_ec",
                  "profile": {"plugin": "jax", "technique": "cauchy",
                              "k": "2", "m": "1",
                              "stripe_unit": "1024"}})
            mcmd({"prefix": "osd pool create", "name": "blameec",
                  "type": "erasure",
                  "erasure_code_profile": "blame_ec", "pg_num": 4})
            bio = client.open_ioctx("blameec")
            blame_payload = rng.integers(
                0, 256, 16384, dtype=np.uint8).tobytes()
            for i in range(8):
                bio.write_full(f"blame_{i}", blame_payload)
            blame_pool_id = bio.pool_id

        churn_t0 = time.time()
        epoch0 = c.mon.osdmap.epoch
        ledger_t0 = _ledger_snapshot(c)
        pool_set(16)                       # split under load
        time.sleep(1.0)
        pool_set(8)                        # merge back (interleave-
        # guarded: retries until split pushes settle)
        # drain, reweight, and kill/revive below all remap the EC
        # pool's acting sets — close the gate across ALL of them, not
        # just the kills: a k=8,m=3 write in flight across ANY remap
        # can strand sub-writes (acks are not resent on re-peer) into
        # a partial object recovery can neither rebuild (> m shards
        # short) nor latch unfound, wedging active+clean.  The
        # split/merge above only resizes the "churn" pool, so the EC
        # lane keeps writing through it.
        if ec_io is not None:
            ec_gate.clear()
            time.sleep(2.0)     # let in-flight EC ops resolve first
        # drain walk: one committed epoch per weight step
        mcmd({"prefix": "osd drain", "id": n - 1, "step": 0.5})
        deadline = time.time() + 60
        while c.mon.osdmap.osds[n - 1].weight > 0 and \
                time.time() < deadline:
            time.sleep(0.2)
        mcmd({"prefix": "osd reweight", "id": n - 1, "weight": 1.0})
        # kill/revive: heartbeat failure reports mark them down (a
        # burst the mon coalesces), revival re-boots them
        victims = [n // 2, n // 2 + 1]
        # blame lane: make sure at least one victim holds blameec
        # shards, so its fresh-store revive below forces a real
        # reconstruct (decode + push) instead of a no-op re-peer
        wipe_victim = None
        if blame_pool_id is not None:
            from ..osd.types import pg_t
            holders: set[int] = set()
            for seed in range(4):
                try:
                    _, acting, _, _ = c.mon.osdmap.pg_to_up_acting_osds(
                        pg_t(blame_pool_id, seed))
                    holders.update(o for o in acting if o >= 0)
                except Exception:  # noqa: BLE001 - mapping gap
                    pass
            hit = [v for v in victims if v in holders]
            if hit:
                wipe_victim = hit[0]
            else:
                extra = [o for o in sorted(holders)
                         if o != n - 1 and o not in victims]
                if extra:
                    wipe_victim = extra[0]
                    victims.append(wipe_victim)
        for v in victims:
            c.kill_osd(v)
        # detection takes heartbeat * grace on the watching peers
        deadline = time.time() + \
            max(30, 3 * args.heartbeat * args.hb_grace)
        while any(c.mon.osdmap.is_up(v) for v in victims) and \
                time.time() < deadline:
            time.sleep(0.2)
        down_ok = not any(c.mon.osdmap.is_up(v) for v in victims)
        if not down_ok:
            fail.append("failure detection never marked victims down")
        for v in victims:
            if v == wipe_victim and c.objectstore == "memstore":
                # revive on an EMPTY store (a reimaged disk): MemStore
                # data normally survives in-process, which would let
                # re-peering skip reconstruction entirely — the blame
                # lane needs the decode path to actually run
                from ..store import create_store
                c.osds[v].store = create_store(c.objectstore, None)
            c.revive_osd(v)
        if ec_io is not None:
            # resume once the map shows the revived daemons up: the
            # post-revive EC writes are the warm-first-launch check
            deadline = time.time() + 30
            while not all(c.mon.osdmap.is_up(v) for v in victims) \
                    and time.time() < deadline:
                time.sleep(0.2)
            ec_gate.set()
        time.sleep(max(1.0, args.seconds / 2))
        stop_writing.set()
        for t in writers:
            t.join(timeout=30)
        while not acked_q.empty():
            acked[acked_q.get()] = True
        row["churn_s"] = round(time.time() - churn_t0, 2)
        row["epochs_churned"] = c.mon.osdmap.epoch - epoch0

        clean_t0 = time.time()
        try:
            c.wait_active_clean(timeout=clean_timeout)
            row["time_to_active_clean_s"] = round(
                time.time() - clean_t0, 2)
        except TimeoutError as e:
            row["time_to_active_clean_s"] = None
            fail.append(f"not active+clean: {e}")

        # the ISSUE 19 payoff: decompose the churn's recovery into the
        # control-plane ledger's stages (window = churn start through
        # active+clean, so work done while writes still flowed counts)
        blame = _recovery_blame(
            ledger_t0, _ledger_snapshot(c),
            row["time_to_active_clean_s"],
            time.time() - churn_t0)
        blame["map_epochs_churned"] = c.mon.osdmap.epoch - epoch0
        row["recovery_blame"] = blame
        if not prewarm_ec:
            # the tier-1 --scale 16 gate (ISSUE 19 satellite): every
            # stage must have recorded real time and the published
            # decomposition must account for the clean wait.  The
            # prewarm row keeps its store across revive (ISSUE 16's
            # lane), so its decode stage legitimately reads zero —
            # gate only the plain row.
            zero = [s for s in BLAME_STAGES
                    if blame["stages_raw_s"].get(s, 0.0) <= 0.0]
            if zero:
                fail.append(f"recovery_blame stages never ran: {zero}")
            ttac = row["time_to_active_clean_s"]
            if ttac:
                total = sum(blame.get(s, 0.0) for s in BLAME_STAGES) \
                    + blame.get("other_s", 0.0)
                if abs(total - ttac) > 0.1 * ttac:
                    fail.append(
                        f"recovery_blame decomposition {total} "
                        f"off time_to_active_clean {ttac} by >10%")
            if blame.get("remote_lists", 0) <= 0:
                fail.append("recovery_blame saw no remote collection "
                            "lists (re-peer scan accounting dead)")

        # zero acked loss: every acked write reads back intact
        lost = 0
        for name in acked:
            try:
                if io.read(name, len(payload)) != payload:
                    lost += 1
            except (TimedOut, RadosError):
                lost += 1
        row["acked_objects"] = len(acked)
        row["lost_objects"] = lost
        if not acked:
            fail.append("no write ever acked")
        if lost:
            fail.append(f"{lost}/{len(acked)} acked objects lost")

        # bit-equality: incremental adoption converged every daemon to
        # the mon's exact committed state
        mon_can = c.mon.osdmap.canonical()
        diverged = [osd.osd_id for osd in c.osds
                    if osd is not None and
                    osd.osdmap.canonical() != mon_can]
        row["maps_bit_equal"] = not diverged
        if diverged:
            fail.append(f"osd maps diverged from mon: {diverged}")

        # the map-distribution ledger + its gates
        st = c.mon.map_stats()
        epochs = max(1, st["epochs_committed"])
        shipped = st["bytes"]["shipped"]
        row["map_epochs"] = st["epochs_committed"]
        row["map_fulls"] = st["sends"]["full"]
        row["map_incrementals"] = st["sends"]["inc"]
        row["map_keepalives"] = st["sends"]["keepalive"]
        row["map_bytes_shipped"] = shipped
        row["map_bytes_per_epoch"] = round(shipped / epochs, 1)
        row["map_full_equiv_bytes"] = st["bytes"]["full_equiv"]
        row["map_bytes_ratio"] = st["bytes_saved_ratio"]
        row["map_batched_mutations"] = st["batched_mutations"]
        row["mon_commit_ms_avg"] = st["commit"]["avg_ms"]
        if (st["bytes_saved_ratio"] or 0) < min_ratio:
            fail.append(f"map bytes ratio {st['bytes_saved_ratio']} "
                        f"< {min_ratio} (incremental publish not "
                        f"saving vs full-publish baseline)")
        if st["sends"]["keepalive"] <= 0:
            fail.append("no heartbeat keepalive was served (have_"
                        "epoch path dead: every tick pulls a map)")
        # device-plane provenance (ISSUE 15): the scale row carries
        # the host launch/compile ledger like every bench row
        from ..ops.profiler import device_profiler
        row["launch_ledger"] = device_profiler().bench_summary()
        # wire-plane provenance (ISSUE 20): the scale row carries the
        # messenger ledger beside recovery_blame — reactor lag and
        # dispatch-queue percentiles, per-peer bytes, reconnects — so
        # a slow boot-RT ships with its own wire explanation
        from ..msg.msgr_ledger import msgr_ledger
        mled = msgr_ledger().bench_summary()
        row["msgr_ledger"] = mled
        for k in ("reactor_lag_ms_p50", "reactor_lag_ms_p99",
                  "qwait_ms_p50", "qwait_ms_p99"):
            if mled.get(k) is None:
                fail.append(f"msgr_ledger {k} never populated "
                            f"(wire-plane recorder dead)")
        if not mled.get("peer_bytes"):
            fail.append("msgr_ledger saw no per-peer traffic")
        if "reconnects" not in mled:
            fail.append("msgr_ledger reconnects missing")
        if prewarm_ec:
            # ISSUE 16 gates: with the boot prewarm + persistent
            # cache, the armed stall injection must never have fired
            # (zero compile stalls), the mon must never have raised
            # COMPILE_STORM, and the EC lane must actually have
            # written through the churn
            ec_acked = 0
            while not ec_acked_q.empty():
                ec_acked_q.get()
                ec_acked += 1
            prof = device_profiler()
            ledger = row["launch_ledger"]
            row["ec_acked_objects"] = ec_acked
            row["prewarm"] = prof.prewarm_summary()
            row["ec_compile_stalls"] = ledger.get("compile_stalls", 0)
            _rc, health = c.mon.handle_command({"prefix": "health"})
            storm = (health.get("checks") or {}).get("COMPILE_STORM")
            row["compile_storm"] = storm is not None
            if not ec_acked:
                fail.append("prewarm churn lane: no EC write acked")
            if row["prewarm"].get("buckets", 0) <= 0:
                fail.append("prewarm ran no buckets (boot hook dead)")
            if row["ec_compile_stalls"]:
                cold = [r["bucket"] for r in
                        prof.compile_ledger()["buckets"]
                        if r.get("count") and not r.get("prewarmed")
                        and not r.get("cache_hit")]
                row["cold_buckets"] = cold
                fail.append(
                    f"{row['ec_compile_stalls']} compile stalls with "
                    f"prewarm on (runtime launches hit cold buckets "
                    f"the boot prewarm should have covered: {cold})")
            if storm is not None:
                fail.append(f"COMPILE_STORM with prewarm on: "
                            f"{storm.get('summary')}")
    row["ok"] = not fail
    if fail:
        row["failures"] = fail
    print(json.dumps(row), flush=True)
    if fail:
        print(f"# cluster_bench --scale FAILED: {fail}",
              file=sys.stderr)
        return 1
    return 0


def _main_processes(args) -> int:
    """Process-topology twin of the SAME matrix.  Per-OSD conf must
    ride the spawn command line, so rows whose batch window differs
    get their own cluster; codec launch counters live in other
    processes and are not reported."""
    from ..tools.proc_cluster import ProcCluster

    by_window: dict[float, list] = {}
    for name, profile, window in _matrix(args):
        by_window.setdefault(window, []).append((name, profile, window))
    for window, rows in by_window.items():
        conf = {"tpu_batch_window_ms": window} if window else {}
        with ProcCluster(n_osds=args.osds,
                         objectstore=args.objectstore,
                         conf=conf, mesh_devices=args.mesh) as c:
            client = c.client()
            _setup_profiles(client, mesh=args.mesh is not None)
            for name, profile, w in rows:
                _bench_row(c, client, args, name, profile, w,
                           {"topology": "processes"})
    return 0


if __name__ == "__main__":
    sys.exit(main())
