"""ProcCluster: the multi-process dev cluster.

Same surface as vstart.Cluster but every daemon is its own OS process
(reference qa/standalone/ceph-helpers.sh run_mon/run_osd: real daemons,
one host).  What this buys over the thread topology:

  * kill -9 is a REAL SIGKILL — no destructor, no flushed buffer, no
    shared-memory state surviving by accident; revive replays whatever
    the store made durable, exactly like a crashed host
  * concurrency is real parallelism (each daemon owns a Python
    interpreter — no shared GIL), so cluster throughput numbers measure
    the system, not one interpreter's scheduler
  * serialization is load-bearing: every byte between daemons crosses
    a socket; nothing can lean on sharing objects in memory

Library use:
    with ProcCluster(n_osds=4, objectstore="filestore") as c:
        client = c.client()
        ...
        c.kill_osd(2)          # SIGKILL the process
        c.revive_osd(2)        # respawn on the surviving store
"""

from __future__ import annotations

import socket
import subprocess
import sys
import tempfile
import time
from pathlib import Path

from ..rados import RadosClient


def wait_ready(proc: subprocess.Popen, what: str,
               timeout: float = 120.0) -> str:
    """Wait for daemon_main's one-line READY handshake on a raw-fd
    pipe (buffered wrappers can strand the line — see _wait_ready's
    original note).  Scans for READY BEFORE checking liveness so a
    daemon that prints READY and exits still reports its address.
    Shared by ProcCluster and the cephadm-role deployer."""
    import os
    import select
    fd = proc.stdout.fileno()
    buf = b""
    deadline = time.time() + timeout
    while time.time() < deadline:
        *complete, _partial = buf.split(b"\n")
        for line in complete:
            if line.startswith(b"READY"):
                return line.split()[1].decode()
        if proc.poll() is not None:
            raise RuntimeError(f"{what} died at boot "
                               f"(rc={proc.returncode})")
        r, _, _ = select.select([fd], [], [], 0.2)
        if r:
            chunk = os.read(fd, 4096)
            if chunk:
                buf += chunk
    raise RuntimeError(f"{what} not ready in {timeout}s")


def _free_ports(n: int) -> list[int]:
    """Reserve n distinct loopback ports (bind-then-release; the race
    window on a dev box is acceptable for test clusters — the reference
    helpers pick fixed port ranges the same way)."""
    socks, ports = [], []
    for _ in range(n):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        socks.append(s)
        ports.append(s.getsockname()[1])
    for s in socks:
        s.close()
    return ports


class ProcCluster:
    def __init__(self, n_osds: int = 4, n_mons: int = 1,
                 objectstore: str = "filestore",
                 data_dir: str | None = None,
                 heartbeat_interval: float = 1.0,
                 failure_quorum: int = 2,
                 conf: dict | None = None,
                 boot_timeout: float = 120.0,
                 mesh_devices: str | None = None,
                 prewarm: bool = False,
                 compile_cache_dir: str | None = None):
        # compile lifecycle (docs/PIPELINE.md): in the process
        # topology EVERY OSD process prewarms its own interpreter's
        # jit caches, so the shared persistent compile cache does the
        # cross-process heavy lifting (first booter compiles to disk,
        # the rest read).  compile_cache_dir points it at a private
        # dir for hermetic CI.
        if prewarm or compile_cache_dir is not None:
            conf = dict(conf or {})
            if prewarm:
                conf.setdefault("osd_ec_prewarm", True)
            if compile_cache_dir is not None:
                conf.setdefault("osd_ec_compile_cache_dir",
                                str(compile_cache_dir))
        self.n_osds = n_osds
        self.n_mons = n_mons
        self.objectstore = objectstore
        # multichip mode in the process topology: each OSD process
        # stands in for a host and owns its OWN mesh (a jax mesh
        # cannot span OS processes here); daemon_main pre-sets
        # XLA_FLAGS from this conf before jax initializes so CPU
        # meshes get their virtual devices — docs/MULTICHIP.md
        self.mesh_devices = mesh_devices
        if mesh_devices is not None:
            conf = dict(conf or {})
            conf.setdefault("osd_ec_use_mesh", True)
            conf.setdefault("mesh_devices", mesh_devices)
        self.data_dir = Path(data_dir or tempfile.mkdtemp(
            prefix="ceph_tpu_proc_"))
        self.heartbeat_interval = heartbeat_interval
        self.failure_quorum = failure_quorum
        self.conf = dict(conf or {})
        # per-OSD conf overrides carried across revive (chaos knobs
        # must survive restarts): merged over self.conf at every
        # (re)spawn of that daemon
        self.osd_conf: dict[int, dict] = {}
        self.boot_timeout = boot_timeout
        self.mon_ports = _free_ports(n_mons)
        self.mon_addrs = [("127.0.0.1", p) for p in self.mon_ports]
        self.mon_procs: list[subprocess.Popen] = []
        self.osd_procs: list[subprocess.Popen | None] = []
        self.extra_procs: list[subprocess.Popen] = []
        self._clients: list[RadosClient] = []

    # -- spawning -----------------------------------------------------------

    def _spawn(self, argv: list[str]) -> subprocess.Popen:
        return subprocess.Popen(
            [sys.executable, "-m", "ceph_tpu.tools.daemon_main", *argv],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True)

    def _wait_ready(self, proc: subprocess.Popen, what: str) -> str:
        return wait_ready(proc, what, self.boot_timeout)

    def start(self) -> "ProcCluster":
        try:
            return self._start()
        except Exception:
            self.stop()        # never leak orphan daemon processes
            raise

    def _start(self) -> "ProcCluster":
        addrs = ",".join(f"{h}:{p}" for h, p in self.mon_addrs)
        for rank in range(self.n_mons):
            p = self._spawn([
                "mon", "--rank", str(rank), "--addrs", addrs,
                "--failure-quorum", str(self.failure_quorum),
                "--data-dir", str(self.data_dir / f"mon.{rank}")])
            self.mon_procs.append(p)
        for rank, p in enumerate(self.mon_procs):
            self._wait_ready(p, f"mon.{rank}")
        for i in range(self.n_osds):
            self.osd_procs.append(self._spawn_osd(i))
        for i, p in enumerate(self.osd_procs):
            self._wait_ready(p, f"osd.{i}")
        # wait until the map shows every OSD up
        admin = self.admin()
        deadline = time.time() + self.boot_timeout
        while time.time() < deadline:
            admin.objecter.refresh_map(timeout=2.0)
            osds = admin.objecter.osdmap.osds
            if len(osds) == self.n_osds and \
                    all(o.up for o in osds.values()):
                return self
            time.sleep(0.2)
        raise RuntimeError("OSDs never all came up")

    def _spawn_osd(self, osd_id: int) -> subprocess.Popen:
        argv = ["osd", "--id", str(osd_id),
                "--mon", ",".join(f"{h}:{p}" for h, p in self.mon_addrs),
                "--objectstore", self.objectstore,
                "--data-dir", str(self.data_dir / f"osd.{osd_id}"),
                "--heartbeat", str(self.heartbeat_interval)]
        merged = {**self.conf, **self.osd_conf.get(osd_id, {})}
        for k, v in merged.items():
            argv += ["--conf", f"{k}={v}"]
        return self._spawn(argv)

    def set_osd_conf(self, osd_id: int, key: str, value) -> None:
        """Record a per-OSD conf override applied at every (re)spawn —
        the process analog of Cluster.set_osd_conf.  A running daemon
        picks it up on its next revive (live injection would need the
        asok injectargs path; spawn-time conf is what the thrasher
        needs to survive kill/revive)."""
        self.osd_conf.setdefault(osd_id, {})[key] = value

    def spawn_rgw(self) -> tuple[str, int]:
        p = self._spawn([
            "rgw", "--mon",
            ",".join(f"{h}:{p}" for h, p in self.mon_addrs)])
        self.extra_procs.append(p)
        addr = self._wait_ready(p, "rgw")
        host, _, port = addr.rpartition(":")
        return host, int(port)

    def spawn_mds(self, name: str = "a") -> tuple[str, int]:
        p = self._spawn([
            "mds", "--name", name, "--mon",
            ",".join(f"{h}:{p}" for h, p in self.mon_addrs)])
        self.extra_procs.append(p)
        addr = self._wait_ready(p, f"mds.{name}")
        host, _, port = addr.rpartition(":")
        return host, int(port)

    # -- cluster surface (vstart.Cluster-compatible subset) -----------------

    def client(self) -> RadosClient:
        c = RadosClient(self.mon_addrs).connect()
        self._clients.append(c)
        return c

    def admin(self) -> RadosClient:
        if not self._clients:
            return self.client()
        return self._clients[0]

    def kill_osd(self, osd_id: int) -> None:
        """SIGKILL — the real thing (reference ceph_manager kill_osd)."""
        p = self.osd_procs[osd_id]
        if p is not None:
            p.kill()
            p.wait()
            self.osd_procs[osd_id] = None

    def revive_osd(self, osd_id: int) -> None:
        assert self.osd_procs[osd_id] is None, "still running"
        p = self._spawn_osd(osd_id)
        self.osd_procs[osd_id] = p
        self._wait_ready(p, f"osd.{osd_id}")

    def mark_osd_down(self, osd_id: int) -> None:
        r, _ = self.admin().mon_command(
            {"prefix": "osd down", "id": osd_id})
        assert r == 0, f"osd down failed: {r}"

    def stop(self) -> None:
        for c in self._clients:
            try:
                c.shutdown()
            except Exception:  # noqa: BLE001
                pass
        for p in self.extra_procs + \
                [p for p in self.osd_procs if p is not None] + \
                self.mon_procs:
            p.terminate()
        for p in self.extra_procs + \
                [p for p in self.osd_procs if p is not None] + \
                self.mon_procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait()

    def __enter__(self) -> "ProcCluster":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()
