"""Prometheus-format metrics exporter (mgr prometheus module role).

Re-expresses the reference's mgr prometheus module
(src/pybind/mgr/prometheus/): scrapes every daemon's perf counters via
their admin sockets and serves them as prometheus text exposition on
an HTTP endpoint.

  python -m ceph_tpu.tools.metrics_exporter --asok-dir DIR --port 9283
"""

from __future__ import annotations

import argparse
import glob
import http.server
import os
import sys

from ..common.perf_counters import (LATENCY_QUANTILES,
                                    quantile_from_cumulative)


# perf-counter type -> prometheus metric type (u64 counters are
# monotonic; gauges settable; time/avg expand to _sum/_count pairs,
# which prometheus models as counters; hist is a native histogram)
_PROM_TYPE = {"u64": "counter", "gauge": "gauge",
              "time": "counter", "avg": "counter",
              "hist": "histogram"}

# Cumulative scrape failures per daemon, for the whole exporter
# process lifetime: a daemon whose asok stops answering must be
# VISIBLE (daemon_up 0 + a rising error counter), not silently absent
# from the exposition.
_SCRAPE_ERRORS: dict[str, int] = {}


def collect(asok_dir: str) -> str:
    from ..common.admin_socket import admin_command
    lines = [
        "# HELP ceph_tpu_perf daemon perf counters",
    ]
    typed: set[str] = set()

    def emit_type(name: str, ctype: str | None) -> None:
        if name in typed:
            return
        typed.add(name)
        lines.append(f"# TYPE {name} "
                     f"{_PROM_TYPE.get(ctype, 'untyped')}")

    for path in sorted(glob.glob(os.path.join(asok_dir, "*.asok"))):
        daemon = os.path.basename(path).rsplit(".asok", 1)[0]
        dlabel = f'{{daemon="{daemon}"}}'
        try:
            dump = admin_command(path, {"prefix": "perf dump"}, timeout=2)
        except Exception:  # noqa: BLE001 - daemon down: say so
            _SCRAPE_ERRORS[daemon] = _SCRAPE_ERRORS.get(daemon, 0) + 1
            emit_type("ceph_tpu_daemon_up", "gauge")
            lines.append(f"ceph_tpu_daemon_up{dlabel} 0")
            emit_type("ceph_tpu_scrape_errors_total", "u64")
            lines.append(f"ceph_tpu_scrape_errors_total{dlabel} "
                         f"{_SCRAPE_ERRORS[daemon]}")
            continue
        emit_type("ceph_tpu_daemon_up", "gauge")
        lines.append(f"ceph_tpu_daemon_up{dlabel} 1")
        if daemon in _SCRAPE_ERRORS:
            emit_type("ceph_tpu_scrape_errors_total", "u64")
            lines.append(f"ceph_tpu_scrape_errors_total{dlabel} "
                         f"{_SCRAPE_ERRORS[daemon]}")
        try:
            schema = admin_command(path, {"prefix": "perf schema"},
                                   timeout=2)
        except Exception:  # noqa: BLE001 - older daemon: untyped
            schema = {}
        for group, counters in dump.items():
            if not isinstance(counters, dict):
                continue
            gschema = schema.get(group, {}) if isinstance(schema, dict) \
                else {}
            for key, val in counters.items():
                name = f"ceph_tpu_{key}"
                ctype = gschema.get(key)
                labels = f'{{daemon="{daemon}",group="{group}"}}'
                if isinstance(val, dict) and "buckets" in val:
                    # histogram: cumulative le buckets + sum/count
                    emit_type(name, "hist")
                    for le, cum in val["buckets"]:
                        lines.append(
                            f'{name}_bucket{{daemon="{daemon}",'
                            f'group="{group}",le="{le}"}} {cum}')
                    lines.append(
                        f'{name}_sum{labels} {val.get("sum", 0)}')
                    lines.append(
                        f'{name}_count{labels} {val.get("count", 0)}')
                    # precomputed tail gauges (p50/p95/p99/p999,
                    # bucket-interpolated): dashboards and alerts read
                    # these directly instead of re-deriving quantiles
                    # from _bucket series (docs/QOS.md)
                    for q, qlabel in LATENCY_QUANTILES:
                        est = quantile_from_cumulative(
                            val["buckets"], q)
                        if est is None:
                            continue
                        emit_type(f"{name}_{qlabel}", "gauge")
                        lines.append(
                            f"{name}_{qlabel}{labels} {est[0]:.9f}")
                elif isinstance(val, dict):   # time-avg
                    emit_type(f"{name}_sum", ctype)
                    emit_type(f"{name}_count", ctype)
                    lines.append(
                        f'ceph_tpu_{key}_sum{labels} {val.get("sum", 0)}')
                    lines.append(
                        f'ceph_tpu_{key}_count{labels} '
                        f'{val.get("avgcount", 0)}')
                else:
                    emit_type(name, ctype)
                    lines.append(f"{name}{labels} {val}")
        # per-pool PG state gauges from the control-plane ledger
        # (ISSUE 19): OSD daemons only — mons/others lack the command,
        # and a missing surface must not count as a scrape error
        if daemon.startswith("osd."):
            try:
                led = admin_command(path, {"prefix": "pg ledger"},
                                    timeout=2)
            except Exception:  # noqa: BLE001 - older daemon
                led = None
            counts = (led or {}).get("pg_state_counts")
            if isinstance(counts, dict):
                emit_type("ceph_tpu_pg_state", "gauge")
                for pool, states in sorted(counts.items()):
                    if not isinstance(states, dict):
                        continue
                    for state, n in sorted(states.items()):
                        lines.append(
                            f'ceph_tpu_pg_state{{daemon="{daemon}",'
                            f'pool="{pool}",state="{state}"}} {n}')
    return "\n".join(lines) + "\n"


def serve(asok_dir: str, port: int) -> None:
    class Handler(http.server.BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802
            if self.path != "/metrics":
                self.send_response(404)
                self.end_headers()
                return
            body = collect(asok_dir).encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):
            pass

    httpd = http.server.HTTPServer(("127.0.0.1", port), Handler)
    print(f"metrics on http://127.0.0.1:{httpd.server_port}/metrics",
          flush=True)
    httpd.serve_forever()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="metrics-exporter")
    ap.add_argument("--asok-dir", required=True)
    ap.add_argument("--port", type=int, default=9283)
    ap.add_argument("--once", action="store_true",
                    help="print one scrape to stdout and exit")
    args = ap.parse_args(argv)
    if args.once:
        sys.stdout.write(collect(args.asok_dir))
        return 0
    serve(args.asok_dir, args.port)
    return 0


if __name__ == "__main__":
    sys.exit(main())
