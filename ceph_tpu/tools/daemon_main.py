"""Single-daemon process entrypoint (reference src/ceph_mon.cc /
src/ceph_osd.cc main(): one daemon per OS process).

Spawned by ProcCluster (proc_cluster.py) — the multi-process topology
in which kill -9 is a real SIGKILL, concurrency is real parallelism
(no shared GIL), and serialization bugs can't hide behind shared
memory.  Also usable standalone:

    python -m ceph_tpu.tools.daemon_main mon --rank 0 \
        --addrs 127.0.0.1:7001,127.0.0.1:7002,127.0.0.1:7003
    python -m ceph_tpu.tools.daemon_main osd --id 0 \
        --mon 127.0.0.1:7001 --objectstore filestore --data-dir /tmp/o0

Prints one "READY <addr>" line on stdout once serving, then runs until
SIGTERM/SIGKILL.
"""

from __future__ import annotations

import argparse
import signal
import sys
import time


def _force_cpu() -> None:
    """Daemon processes must not race each other onto the TPU tunnel:
    the OSD's codec work defaults to CPU plugins here; the TPU belongs
    to whichever single process the operator gives it (sitecustomize
    ignores JAX_PLATFORMS, so this must run before any jax backend
    init)."""
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:  # noqa: BLE001 - no jax: CPU plugins only anyway
        pass


def _parse_addr(s: str) -> tuple[str, int]:
    host, _, port = s.rpartition(":")
    return (host, int(port))


def run_mon(args) -> int:
    from ..mon import Monitor
    addrs = [_parse_addr(a) for a in args.addrs.split(",")]
    mon = Monitor(addr=addrs[args.rank],
                  failure_quorum=args.failure_quorum,
                  data_dir=args.data_dir)
    if len(addrs) > 1:
        mon.join(addrs, args.rank)
    print(f"READY {mon.addr[0]}:{mon.addr[1]}", flush=True)
    _serve_forever(mon.shutdown)
    return 0


def _prep_mesh_env(conf: dict) -> None:
    """CPU meshes need their virtual devices BEFORE the jax backend
    initializes: when this daemon is mesh-enabled and XLA_FLAGS does
    not already force a host device count, derive one from the
    mesh_devices conf (shape product, count, or the 8-device default).
    A no-op for daemons without mesh mode or with the flag pre-set."""
    import os
    val = str(conf.get("osd_ec_use_mesh", "")).lower()
    if val not in ("true", "1", "yes", "on"):
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" in flags:
        return
    # same parser the MeshService will apply (parallel/service.py is
    # jax-free at module level, so importing it here cannot trip the
    # backend init this function exists to pre-empt)
    from ..parallel.service import MeshError, parse_mesh_shape
    try:
        n_shard, n_data = parse_mesh_shape(
            str(conf.get("mesh_devices", "")), 8)
        n = n_shard * n_data
    except MeshError:
        n = 8      # the service will surface the bad spec itself
    os.environ["XLA_FLAGS"] = (
        f"{flags} --xla_force_host_platform_device_count={n}").strip()


def run_osd(args) -> int:
    conf = {}
    for kv in args.conf or []:
        k, _, v = kv.partition("=")
        conf[k] = v
    _prep_mesh_env(conf)   # before create_store/daemon import any jax
    from ..osd.daemon import OSDDaemon
    from ..store import create_store
    store = create_store(args.objectstore, args.data_dir)
    mons = [_parse_addr(a) for a in args.mon.split(",")]
    # conf rides the constructor: startup options (osd_op_queue) pick
    # construction-time shape and must precede anything reading them
    osd = OSDDaemon(args.id, mons, store=store,
                    heartbeat_interval=args.heartbeat, conf=conf)
    osd.boot()
    print(f"READY {osd.addr[0]}:{osd.addr[1]}", flush=True)
    _serve_forever(osd.shutdown)
    return 0


def run_mds(args) -> int:
    from ..fs.mds import MDSDaemon
    mons = [_parse_addr(a) for a in args.mon.split(",")]
    mds = MDSDaemon(mons, name=args.name)
    print(f"READY {mds.addr[0]}:{mds.addr[1]}", flush=True)
    _serve_forever(mds.shutdown)
    return 0


def run_rgw(args) -> int:
    from ..rados import RadosClient
    from ..rgw import S3Gateway
    mons = [_parse_addr(a) for a in args.mon.split(",")]
    client = RadosClient(mons).connect()
    gw = S3Gateway(client, addr=("127.0.0.1", args.port))
    print(f"READY {gw.addr[0]}:{gw.addr[1]}", flush=True)
    _serve_forever(gw.shutdown)
    return 0


def _serve_forever(on_term) -> None:
    stop = []

    def _term(_sig, _frm):
        stop.append(1)
    signal.signal(signal.SIGTERM, _term)
    signal.signal(signal.SIGINT, _term)
    while not stop:
        time.sleep(0.2)
    try:
        on_term()
    except Exception:  # noqa: BLE001 - dying anyway
        pass


def main(argv=None) -> int:
    _force_cpu()
    ap = argparse.ArgumentParser(prog="daemon_main")
    sub = ap.add_subparsers(dest="role", required=True)

    mp = sub.add_parser("mon")
    mp.add_argument("--rank", type=int, default=0)
    mp.add_argument("--addrs", required=True,
                    help="comma list of host:port for ALL mon ranks")
    mp.add_argument("--failure-quorum", type=int, default=2)
    mp.add_argument("--data-dir", default=None)

    op = sub.add_parser("osd")
    op.add_argument("--id", type=int, required=True)
    op.add_argument("--mon", required=True,
                    help="comma list of mon host:port")
    op.add_argument("--objectstore", default="memstore")
    op.add_argument("--data-dir", default=None)
    op.add_argument("--heartbeat", type=float, default=1.0)
    op.add_argument("--conf", action="append", default=[],
                    help="k=v config overrides (repeatable)")

    dp = sub.add_parser("mds")
    dp.add_argument("--mon", required=True)
    dp.add_argument("--name", default="a")

    gp = sub.add_parser("rgw")
    gp.add_argument("--mon", required=True)
    gp.add_argument("--port", type=int, default=0)

    args = ap.parse_args(argv)
    return {"mon": run_mon, "osd": run_osd,
            "mds": run_mds, "rgw": run_rgw}[args.role](args)


if __name__ == "__main__":
    sys.exit(main())
