"""Stable integer hash for CRUSH draws.

Fills the role of the reference's rjenkins1 crush_hash32_* family
(src/crush/hash.c): a deterministic, platform-independent, well-mixed
hash of small integer tuples, stable forever (placement must never
change across versions).  We use our own construction (splitmix64-style
finalizers over packed operands) rather than porting rjenkins bit-for-
bit: this framework's clusters need internal stability, not placement
compatibility with foreign Ceph clusters.
"""

from __future__ import annotations

_MASK64 = (1 << 64) - 1
_MASK32 = (1 << 32) - 1
_SEED = 0x9E3779B97F4A7C15  # golden-ratio seed, fixed forever


def _mix64(x: int) -> int:
    x &= _MASK64
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & _MASK64
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & _MASK64
    x ^= x >> 31
    return x


def crush_hash32(*args) -> int:
    """Hash ints and/or byte-strings to 32 bits, order-sensitive.

    Fills crush_hash32_*'s role for placement draws and ceph_str_hash's
    (src/common/ceph_hash.cc) for object-name -> pg seed hashing.
    """
    h = _SEED
    for a in args:
        if isinstance(a, str):
            a = a.encode()
        if isinstance(a, (bytes, bytearray)):
            for i in range(0, len(a), 8):
                h = _mix64(h ^ int.from_bytes(a[i:i + 8], "little"))
            h = _mix64(h ^ len(a))
        else:
            h = _mix64(h ^ ((a & _MASK64) + 0x9E3779B97F4A7C15
                            + ((h << 6) & _MASK64) + (h >> 2)))
    return h & _MASK32


def crush_unit_interval(*args: int) -> float:
    """Map a draw to (0, 1]; never returns 0 (ln must be finite)."""
    h = crush_hash32(*args)
    return (h + 1) / 4294967296.0
