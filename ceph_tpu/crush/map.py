"""CRUSH map + rule evaluation.

Re-expresses the reference's crush map model and `crush_do_rule`
(src/crush/crush.h, src/crush/mapper.c) with straw2 bucket selection:

* devices: id >= 0, weight, optional class
* buckets: id < 0, a type (host/rack/root/...), straw2 items
* rules: take -> choose/chooseleaf {firstn|indep} n {type} -> emit

straw2 semantics (reference bucket_straw2_choose, mapper.c:361): each
item draws ln(u)/w with u a per-(input, item, trial) uniform draw and w
its weight; highest draw wins.  This gives weight-proportional selection
and optimal data movement on weight change — the property that matters.
We compute ln in float (the reference uses a 128-entry fixed-point log
table for kernel compatibility; same math, different precision — our
placements are internally stable, which is the actual contract).

firstn vs indep (reference crush_choose_firstn/_indep): firstn fills a
result vector compactly (replicated pools); indep is positional and
leaves holes as NONE (erasure-coded pools, where position = shard id).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field

from .hash import crush_hash32, crush_unit_interval

CRUSH_ITEM_NONE = 0x7FFFFFFF


@dataclass
class Device:
    id: int
    weight: float
    device_class: str | None = None


@dataclass
class Bucket:
    id: int                       # < 0
    name: str
    type_name: str                # e.g. "host", "rack", "root"
    items: list[int] = field(default_factory=list)   # device or bucket ids
    weights: list[float] = field(default_factory=list)

    @property
    def weight(self) -> float:
        return sum(self.weights)


@dataclass
class Step:
    op: str                       # take | choose | chooseleaf | emit
    num: int = 0                  # for choose*: replica count (0 = all)
    type_name: str | None = None  # failure-domain type for choose*
    mode: str = "firstn"          # firstn | indep
    item: int | str | None = None  # for take: bucket name/id


@dataclass
class Rule:
    id: int
    name: str
    steps: list[Step]
    mode: str = "firstn"          # overall replicated/EC intent


class CrushMap:
    def __init__(self) -> None:
        self.devices: dict[int, Device] = {}
        self.buckets: dict[int, Bucket] = {}
        self.buckets_by_name: dict[str, Bucket] = {}
        self.rules: dict[int, Rule] = {}
        self.tunable_choose_tries = 50   # reference choose_total_tries

    # -- construction -------------------------------------------------------

    def add_device(self, dev_id: int, weight: float,
                   device_class: str | None = None) -> None:
        self.devices[dev_id] = Device(dev_id, weight, device_class)

    def add_bucket(self, bucket_id: int, name: str, type_name: str) -> Bucket:
        assert bucket_id < 0, "bucket ids are negative"
        b = Bucket(bucket_id, name, type_name)
        self.buckets[bucket_id] = b
        self.buckets_by_name[name] = b
        return b

    def bucket_add_item(self, bucket: Bucket, item_id: int,
                        weight: float) -> None:
        bucket.items.append(item_id)
        bucket.weights.append(weight)

    def add_rule(self, rule: Rule) -> int:
        self.rules[rule.id] = rule
        return rule.id

    def item_weight(self, item_id: int) -> float:
        if item_id >= 0:
            d = self.devices.get(item_id)
            return d.weight if d else 0.0
        b = self.buckets.get(item_id)
        return b.weight if b else 0.0

    def item_type(self, item_id: int) -> str:
        if item_id >= 0:
            return "osd"
        return self.buckets[item_id].type_name

    # -- straw2 -------------------------------------------------------------

    def _straw2_choose(self, bucket: Bucket, x: int, r: int,
                       exclude: set[int],
                       weight_of=None) -> int | None:
        """Pick one item of `bucket` for input x, trial r (reference
        bucket_straw2_choose)."""
        best, best_draw = None, -math.inf
        for item, w in zip(bucket.items, bucket.weights):
            if item in exclude:
                continue
            w = weight_of(item) if weight_of else w
            if w <= 0:
                continue
            u = crush_unit_interval(x, item & 0xFFFFFFFF, r)
            draw = math.log(u) / w
            if draw > best_draw:
                best, best_draw = item, draw
        return best

    def _descend_to_type(self, start: int, x: int, r: int,
                         type_name: str, exclude: set[int],
                         weight_of) -> int | None:
        """Walk from `start` down to an item of `type_name` with straw2
        draws at every level."""
        cur = start
        for _ in range(32):  # depth bound
            if self.item_type(cur) == type_name:
                return cur
            b = self.buckets.get(cur)
            if b is None:
                return None
            nxt = self._straw2_choose(b, x, r, exclude, weight_of)
            if nxt is None:
                return None
            cur = nxt
        return None

    # -- rule evaluation (reference crush_do_rule) --------------------------

    def do_rule(self, rule_id: int, x: int, num_rep: int,
                weight_of=None) -> list[int]:
        """Evaluate a rule for input x (pg seed), wanting num_rep items.

        weight_of(item_id)->float overrides device weights (the OSDMap
        layers reweight/out on top of crush weights, reference
        mapper.c's weight vector argument).
        Returns device ids; indep rules return positional results with
        CRUSH_ITEM_NONE holes.
        """
        rule = self.rules[rule_id]
        working: list[int] = []
        out: list[int] = []
        for step in rule.steps:
            if step.op == "take":
                item = step.item
                if isinstance(item, str):
                    item = self.buckets_by_name[item].id
                working = [item]
            elif step.op in ("choose", "chooseleaf"):
                n = step.num or num_rep
                chosen = self._choose(
                    working, x, n, step.type_name, step.mode,
                    leaf=(step.op == "chooseleaf"), weight_of=weight_of)
                working = chosen
            elif step.op == "emit":
                out.extend(working)
                working = []
            else:
                raise ValueError(f"unknown step {step.op}")
        return out[:num_rep] if rule.mode == "firstn" else out

    def _choose(self, parents: list[int], x: int, n: int,
                type_name: str, mode: str, leaf: bool,
                weight_of) -> list[int]:
        results: list[int] = []
        for parent in parents:
            if mode == "indep":
                results.extend(self._choose_indep(
                    parent, x, n, type_name, leaf, weight_of))
            else:
                results.extend(self._choose_firstn(
                    parent, x, n, type_name, leaf, weight_of))
        return results

    def _leaf_of(self, item: int, x: int, r: int,
                 weight_of) -> int | None:
        """chooseleaf: descend from a failure-domain item to an osd."""
        if item >= 0:
            return item
        return self._descend_to_type(item, x, r, "osd", set(), weight_of)

    def _choose_firstn(self, parent: int, x: int, n: int,
                       type_name: str, leaf: bool, weight_of) -> list[int]:
        chosen: list[int] = []
        chosen_domains: set[int] = set()
        r = 0
        tries = 0
        while len(chosen) < n and tries < self.tunable_choose_tries * n:
            tries += 1
            item = self._descend_to_type(parent, x, r, type_name,
                                         chosen_domains, weight_of)
            r += 1
            if item is None:
                continue
            if item in chosen_domains:
                continue
            dev = self._leaf_of(item, x, r, weight_of) if leaf else item
            if dev is None or dev in chosen:
                continue
            if leaf and weight_of and weight_of(dev) <= 0:
                continue
            chosen_domains.add(item)
            chosen.append(dev)
        return chosen

    def _choose_indep(self, parent: int, x: int, n: int,
                      type_name: str, leaf: bool, weight_of) -> list[int]:
        """Positional selection: slot s keeps its draw stream so a failed
        slot doesn't shift the others (reference crush_choose_indep)."""
        slots: list[int] = [CRUSH_ITEM_NONE] * n
        used_domains: set[int] = set()
        used_devs: set[int] = set()
        for s in range(n):
            for attempt in range(self.tunable_choose_tries):
                r = s + attempt * n   # per-slot independent trial stream
                item = self._descend_to_type(parent, x, r, type_name,
                                             used_domains, weight_of)
                if item is None or item in used_domains:
                    continue
                dev = self._leaf_of(item, x, r, weight_of) if leaf else item
                if dev is None or dev in used_devs:
                    continue
                if leaf and weight_of and weight_of(dev) <= 0:
                    continue
                used_domains.add(item)
                used_devs.add(dev)
                slots[s] = dev
                break
        return slots
