"""CRUSH: deterministic pseudo-random placement (reference src/crush/)."""

from .map import CrushMap, Rule, Step
from .wrapper import CrushWrapper

__all__ = ["CrushMap", "CrushWrapper", "Rule", "Step"]
