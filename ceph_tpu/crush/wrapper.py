"""CrushWrapper: map-building convenience API.

Re-expresses the reference's CrushWrapper (src/crush/CrushWrapper.h)
surface the rest of the system uses: build a hierarchy from a flat
device list, add_simple_rule (what EC create_rule calls, reference
src/erasure-code/ErasureCode.cc:64-83), lookup by name.
"""

from __future__ import annotations

from .map import Bucket, CrushMap, Rule, Step


class CrushWrapper:
    def __init__(self) -> None:
        self.map = CrushMap()
        self._next_bucket_id = -1
        self._next_rule_id = 0

    # -- hierarchy building -------------------------------------------------

    def _alloc_bucket_id(self) -> int:
        bid = self._next_bucket_id
        self._next_bucket_id -= 1
        return bid

    def ensure_bucket(self, name: str, type_name: str) -> Bucket:
        b = self.map.buckets_by_name.get(name)
        if b is None:
            b = self.map.add_bucket(self._alloc_bucket_id(), name, type_name)
        return b

    def add_osd(self, osd_id: int, weight: float, host: str,
                root: str = "default") -> None:
        """Add a device under host under root (the standard 3-level
        default hierarchy cephadm builds)."""
        self.map.add_device(osd_id, weight)
        rb = self.ensure_bucket(root, "root")
        hb = self.ensure_bucket(host, "host")
        if hb.id not in rb.items:
            self.map.bucket_add_item(rb, hb.id, 0.0)
        self.map.bucket_add_item(hb, osd_id, weight)
        # parent weight = sum of children
        rb.weights[rb.items.index(hb.id)] = hb.weight

    def remove_osd(self, osd_id: int) -> None:
        """Remove a device and its bucket membership (reference
        CrushWrapper::remove_item): the device row goes away and every
        bucket drops it, with parent weights re-summed so straw2 draws
        stop landing on the hole."""
        self.map.devices.pop(osd_id, None)
        for b in self.map.buckets.values():
            if osd_id in b.items:
                i = b.items.index(osd_id)
                del b.items[i]
                del b.weights[i]
        # re-sum interior bucket weights to a fixpoint: dict order is
        # insertion order (parents usually precede children), so one
        # pass could copy a stale child weight in a >=3-level
        # hierarchy — iterate until no entry changes (bounded by the
        # hierarchy depth)
        for _ in range(len(self.map.buckets) + 1):
            changed = False
            for b in self.map.buckets.values():
                for i, item in enumerate(b.items):
                    if item < 0:
                        child = self.map.buckets.get(item)
                        if child is not None and \
                                b.weights[i] != child.weight:
                            b.weights[i] = child.weight
                            changed = True
            if not changed:
                break

    # -- rules --------------------------------------------------------------

    def add_simple_rule(self, name: str, root: str, failure_domain: str,
                        num_rep: int = 0, rule_mode: str = "firstn") -> int:
        """reference CrushWrapper::add_simple_rule; EC passes indep +
        k+m (ErasureCode.cc:69)."""
        for r in self.map.rules.values():
            if r.name == name:
                return r.id
        rid = self._next_rule_id
        self._next_rule_id += 1
        steps = [
            Step(op="take", item=root),
            Step(op="chooseleaf", num=num_rep, type_name=failure_domain,
                 mode=rule_mode),
            Step(op="emit"),
        ]
        self.map.add_rule(Rule(rid, name, steps, mode=rule_mode))
        return rid

    def rule_id_by_name(self, name: str) -> int | None:
        for r in self.map.rules.values():
            if r.name == name:
                return r.id
        return None

    def do_rule(self, rule_id: int, x: int, num_rep: int,
                weight_of=None) -> list[int]:
        return self.map.do_rule(rule_id, x, num_rep, weight_of)
