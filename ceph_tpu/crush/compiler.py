"""CRUSH map text compiler/decompiler + placement tester.

Re-expresses reference src/crush/CrushCompiler.{h,cc} (the crushtool
text format: device / type / bucket / rule stanzas) and the
CrushTester role (src/crush/CrushTester.cc: run a rule over many
inputs and check the outputs hold the placement invariants) over this
build's CrushMap.

Supported grammar (the subset CrushMap models):

    # devices
    device 0 osd.0
    device 1 osd.1 class ssd

    # types
    type 0 osd
    type 1 host
    type 10 root

    # buckets
    host node1 {
        id -2
        alg straw2
        hash 0
        item osd.0 weight 1.000
    }
    root default {
        id -1
        alg straw2
        item node1 weight 2.000
    }

    # rules
    rule replicated_rule {
        id 0
        type replicated
        step take default
        step chooseleaf firstn 0 type host
        step emit
    }

`alg`/`hash` lines parse and must be straw2/0 when present (the only
bucket algorithm this build implements — a deliberate deviation noted
in crush/map.py); other algs raise a compile error rather than
silently changing placement.
"""

from __future__ import annotations

from dataclasses import dataclass, field

from .map import Bucket, CrushMap, Device, Rule, Step

DEFAULT_TYPES = {0: "osd", 1: "host", 2: "chassis", 3: "rack",
                 4: "row", 5: "pdu", 6: "pod", 7: "room",
                 8: "datacenter", 9: "zone", 10: "region", 11: "root"}


class CrushCompileError(ValueError):
    pass


@dataclass
class CompiledMap:
    """A CrushMap plus the text-format side tables (type ids, rule
    metadata) needed to round-trip."""
    map: CrushMap
    types: dict[int, str] = field(default_factory=dict)
    rule_types: dict[int, str] = field(default_factory=dict)


def _tokens(text: str):
    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.split("#", 1)[0].strip()
        if line:
            yield lineno, line.split()


def compile_text(text: str) -> CompiledMap:
    """crushmap text -> CompiledMap.  Raises CrushCompileError with
    line numbers on malformed input (reference CrushCompiler::compile)."""
    cm = CrushMap()
    types: dict[int, str] = {}
    rule_types: dict[int, str] = {}
    dev_by_name: dict[str, int] = {}
    lines = list(_tokens(text))
    i = 0

    def err(lineno, msg):
        raise CrushCompileError(f"line {lineno}: {msg}")

    def resolve_item(lineno, name):
        if name in dev_by_name:
            return dev_by_name[name]
        b = cm.buckets_by_name.get(name)
        if b is not None:
            return b.id
        err(lineno, f"unknown item {name!r}")

    # pass 1: flat stanzas + collect bucket blocks (buckets may
    # reference buckets defined earlier; the reference requires
    # definition-before-use the same way)
    while i < len(lines):
        lineno, t = lines[i]
        if t[0] == "device":
            if not (len(t) == 3 or
                    (len(t) == 5 and t[3] == "class")):
                err(lineno, "device <id> <name> [class <c>]")
            did = int(t[1])
            dev_class = t[4] if len(t) == 5 else None
            cm.add_device(did, 1.0, dev_class)
            dev_by_name[t[2]] = did
            i += 1
        elif t[0] == "type":
            if len(t) != 3:
                err(lineno, "type <id> <name>")
            types[int(t[1])] = t[2]
            i += 1
        elif t[0] == "tunable":
            i += 1                       # accepted and ignored
        elif t[0] == "rule":
            i = _parse_rule(cm, rule_types, lines, i, err,
                            resolve_item)
        elif len(t) >= 2 and t[-1] == "{":
            i = _parse_bucket(cm, types, lines, i, err,
                              resolve_item, dev_by_name)
        else:
            err(lineno, f"unexpected {' '.join(t)!r}")
    # device weights live on the bucket ITEM lines in the text format;
    # mirror them onto the Device records so weight-based checks (and
    # item_weight) see what placement actually uses
    for b in cm.buckets.values():
        for item, w in zip(b.items, b.weights):
            if item >= 0 and item in cm.devices:
                cm.devices[item].weight = w
    return CompiledMap(cm, types or dict(DEFAULT_TYPES), rule_types)


def _parse_bucket(cm, types, lines, i, err, resolve_item,
                  dev_by_name):
    lineno, t = lines[i]
    type_name, name = t[0], t[1]
    if types and type_name not in types.values():
        err(lineno, f"unknown bucket type {type_name!r}")
    bid = None
    items: list[tuple[int, float]] = []
    i += 1
    while i < len(lines):
        lineno, t = lines[i]
        if t[0] == "}":
            i += 1
            break
        if t[0] == "id":
            bid = int(t[1])
        elif t[0] == "alg":
            if t[1] != "straw2":
                err(lineno, f"unsupported bucket alg {t[1]!r} "
                            "(this build implements straw2 only)")
        elif t[0] == "hash":
            pass                          # rjenkins selector: N/A here
        elif t[0] == "item":
            weight = 1.0
            if "weight" in t:
                weight = float(t[t.index("weight") + 1])
            items.append((resolve_item(lineno, t[1]), weight))
        else:
            err(lineno, f"unknown bucket field {t[0]!r}")
        i += 1
    else:
        err(lineno, f"bucket {name!r}: missing closing brace")
    if bid is None:
        err(lineno, f"bucket {name!r}: missing id")
    if bid >= 0:
        err(lineno, f"bucket {name!r}: id must be negative")
    b = cm.add_bucket(bid, name, type_name)
    for item_id, w in items:
        cm.bucket_add_item(b, item_id, w)
    return i


def _parse_rule(cm, rule_types, lines, i, err, resolve_item):
    lineno, t = lines[i]
    if len(t) != 3 or t[2] != "{":
        err(lineno, "rule <name> {")
    name = t[1]
    rid = None
    rtype = "replicated"
    steps: list[Step] = []
    mode = "firstn"
    i += 1
    while i < len(lines):
        lineno, t = lines[i]
        if t[0] == "}":
            i += 1
            break
        if t[0] == "id" or t[0] == "ruleset":
            rid = int(t[1])
        elif t[0] == "type":
            rtype = t[1]
        elif t[0] in ("min_size", "max_size"):
            pass                          # legacy fields: accepted
        elif t[0] == "step":
            if t[1] == "take":
                # resolve to the numeric id NOW: unknown targets error
                # with a line number, and device-name takes work at
                # map time (do_rule only name-resolves buckets)
                steps.append(Step(op="take",
                                  item=resolve_item(lineno, t[2])))
            elif t[1] == "emit":
                steps.append(Step(op="emit"))
            elif t[1] in ("choose", "chooseleaf"):
                # step chooseleaf firstn 0 type host
                if len(t) != 6 or t[4] != "type":
                    err(lineno, "step choose[leaf] "
                                "{firstn|indep} <n> type <t>")
                mode = t[2]
                if mode not in ("firstn", "indep"):
                    err(lineno, f"unknown mode {mode!r}")
                steps.append(Step(op=t[1], num=int(t[3]),
                                  type_name=t[5], mode=mode))
            else:
                err(lineno, f"unknown step {t[1]!r}")
        else:
            err(lineno, f"unknown rule field {t[0]!r}")
        i += 1
    else:
        err(lineno, f"rule {name!r}: missing closing brace")
    if rid is None:
        err(lineno, f"rule {name!r}: missing id")
    if not steps or steps[0].op != "take" or steps[-1].op != "emit":
        err(lineno, f"rule {name!r}: must be take ... emit")
    cm.add_rule(Rule(rid, name, steps, mode=mode))
    rule_types[rid] = rtype
    return i


def decompile(compiled: CompiledMap) -> str:
    """CompiledMap -> crushmap text (reference CrushCompiler::decompile).
    compile_text(decompile(m)) reproduces the same placements."""
    cm = compiled.map
    out = ["# begin crush map", "", "# devices"]
    for did in sorted(cm.devices):
        dev = cm.devices[did]
        line = f"device {did} osd.{did}"
        if dev.device_class:
            line += f" class {dev.device_class}"
        out.append(line)
    out += ["", "# types"]
    for tid in sorted(compiled.types):
        out.append(f"type {tid} {compiled.types[tid]}")
    out += ["", "# buckets"]
    # children before parents (definition-before-use)
    emitted: set[int] = set()

    def emit_bucket(bid: int):
        if bid in emitted:
            return
        b = cm.buckets[bid]
        for item in b.items:
            if item < 0:
                emit_bucket(item)
        emitted.add(bid)
        out.append(f"{b.type_name} {b.name} {{")
        out.append(f"    id {b.id}")
        out.append("    alg straw2")
        out.append("    hash 0")
        for item, w in zip(b.items, b.weights):
            iname = f"osd.{item}" if item >= 0 \
                else cm.buckets[item].name
            out.append(f"    item {iname} weight {w:.3f}")
        out.append("}")

    for bid in sorted(cm.buckets, reverse=True):
        emit_bucket(bid)
    out += ["", "# rules"]
    for rid in sorted(cm.rules):
        r = cm.rules[rid]
        out.append(f"rule {r.name} {{")
        out.append(f"    id {rid}")
        out.append(f"    type {compiled.rule_types.get(rid, 'replicated')}")
        for st in r.steps:
            if st.op == "take":
                iname = st.item if isinstance(st.item, str) \
                    else (f"osd.{st.item}" if st.item >= 0
                          else cm.buckets[st.item].name)
                out.append(f"    step take {iname}")
            elif st.op == "emit":
                out.append("    step emit")
            else:
                out.append(f"    step {st.op} {st.mode} {st.num} "
                           f"type {st.type_name}")
        out.append("}")
    out.append("# end crush map")
    return "\n".join(out) + "\n"


# ----------------------------------------------------------------------------
# CrushTester role
# ----------------------------------------------------------------------------

def test_rule(cm: CrushMap, rule_id: int, num_rep: int,
              n_inputs: int = 1024, weight_of=None) -> dict:
    """Run a rule over n_inputs and validate placement invariants
    (reference CrushTester::test_with_fork, reduced to the checks that
    matter): full result vectors, no duplicate devices, failure-domain
    uniqueness for chooseleaf rules, and weight-proportional usage.
    Returns {ok, problems[], utilization{osd: count}, expected{...}}."""
    from .map import CRUSH_ITEM_NONE
    rule = cm.rules[rule_id]
    leaf_types = [st.type_name for st in rule.steps
                  if st.op == "chooseleaf"]
    problems: list[str] = []
    util: dict[int, int] = {d: 0 for d in cm.devices}

    parent: dict[int, int] = {}
    for b in cm.buckets.values():
        for item in b.items:
            parent[item] = b.id

    def domain_of(dev: int, type_name: str) -> int | None:
        """Nearest ancestor bucket of type_name (a chooseleaf type may
        sit levels above the device's direct parent)."""
        cur = parent.get(dev)
        while cur is not None:
            if cm.buckets[cur].type_name == type_name:
                return cur
            cur = parent.get(cur)
        return None

    for x in range(n_inputs):
        out = cm.do_rule(rule_id, x, num_rep, weight_of)
        live = [d for d in out if d != CRUSH_ITEM_NONE]
        if len(out) != num_rep:
            problems.append(f"x={x}: got {len(out)} results, "
                            f"want {num_rep}")
        if len(set(live)) != len(live):
            problems.append(f"x={x}: duplicate devices {out}")
        for lt in leaf_types:
            doms = [domain_of(d, lt) for d in live]
            if len(set(doms)) != len(doms):
                problems.append(
                    f"x={x}: two replicas share a {lt}: {out}")
        for d in live:
            util[d] += 1
        if len(problems) > 16:
            break
    # weight proportionality (loose bound: straw2 converges ~1/sqrt(n))
    # — over the devices REACHABLE from the rule's take roots only:
    # declared-but-unbucketed spares must not skew the baseline
    reachable: set[int] = set()

    def walk(item: int):
        if item >= 0:
            reachable.add(item)
            return
        for child in cm.buckets[item].items:
            walk(child)

    for st in rule.steps:
        if st.op == "take":
            item = st.item
            if isinstance(item, str):
                item = cm.buckets_by_name[item].id
            walk(item)
    total_w = sum((cm.item_weight(d) if weight_of is None
                   else weight_of(d)) or 0.0 for d in reachable)
    expected = {}
    placed = sum(util.values())
    if total_w > 0 and placed:
        for d in sorted(reachable):
            w = (cm.item_weight(d) if weight_of is None
                 else weight_of(d)) or 0.0
            expected[d] = placed * w / total_w
            if expected[d] >= 16 and \
                    abs(util[d] - expected[d]) > 0.5 * expected[d]:
                problems.append(
                    f"osd.{d}: utilization {util[d]} vs expected "
                    f"~{expected[d]:.0f} (weight skew)")
    return {"ok": not problems, "problems": problems,
            "utilization": util, "expected": expected}
