"""Foundations: buffers, checksums, config, logging, perf counters.

Reference layer 0 (src/common/, src/include/, src/log/, src/global/).
"""
