"""TrackedOp/OpTracker: per-request event timelines and slow-op latching.

Re-expresses the reference's op tracking subsystem
(src/common/TrackedOp.{h,cc}: TrackedOp::mark_event / OpTracker ::
RegisterOnFlight + History, consumed by `dump_ops_in_flight`,
`dump_historic_ops`, `dump_historic_slow_ops` and the slow-request
health warning path in OSD::check_ops_in_flight) crossed with
Dapper-style trace propagation (Sigelman et al., 2010): every op
carries a TraceContext (trace id + span id + parent span) that rides
messenger messages, so the client's objecter span, the primary's op
span and each shard-holder's sub-op span stitch into one tree keyed by
trace id.

Design constraints (the subsystem is ALWAYS ON in the daemons):

- Tracing-off fast path: with the tracker disabled, `create()` returns
  the shared NULL_TRACKED singleton whose every method is a no-op —
  zero allocations, zero timestamps, zero lock traffic per op.
- Cheap events: `mark_event` is one `time.time()` + one list append
  (atomic under the GIL); no locks on the hot path.  The tracker lock
  is taken only on register/unregister (deque ops) and dumps.
- Bounded memory: in-flight ops live in a dict; completed ops move to
  a bounded ring (`history_size`), slow ops additionally latch into
  their own bounded ring (`history_slow_size`) — the reference's
  OpHistory double ring.
- Per-stage blame: a slow op names the stage that consumed the most
  wall time (for completed ops: the largest inter-event gap; for
  stuck in-flight ops: the time since the last event), so "which op,
  stuck at which stage, on which shard?" has an answer.
"""

from __future__ import annotations

import collections
import threading
import time
import uuid


class TraceContext:
    """Dapper-style trace context: (trace_id, span_id, parent_span).

    trace_id identifies the whole request tree; span_id this hop's
    span; parent_span the span that caused it.  Wire form is a small
    JSON dict riding message meta (see msg/messages.py `trace` fields).
    """

    __slots__ = ("trace_id", "span_id", "parent_span", "origin_ts")

    def __init__(self, trace_id: str, span_id: str,
                 parent_span: str | None = None,
                 origin_ts: float | None = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_span = parent_span
        # wall-clock of the root submit, carried along so downstream
        # daemons can place "objecter_submit" on their timelines
        self.origin_ts = origin_ts

    @classmethod
    def new(cls) -> "TraceContext":
        return cls(uuid.uuid4().hex[:16], uuid.uuid4().hex[:8],
                   None, time.time())

    def child(self) -> "TraceContext":
        """A child span of this one (same trace, fresh span id)."""
        return TraceContext(self.trace_id, uuid.uuid4().hex[:8],
                            self.span_id, self.origin_ts)

    def to_wire(self) -> dict:
        w = {"id": self.trace_id, "span": self.span_id}
        if self.parent_span is not None:
            w["parent"] = self.parent_span
        if self.origin_ts is not None:
            w["ts"] = self.origin_ts
        return w

    @classmethod
    def from_wire(cls, w: dict | None) -> "TraceContext | None":
        if not w or "id" not in w:
            return None
        return cls(str(w["id"]), str(w.get("span", "")),
                   w.get("parent"), w.get("ts"))


def canonical_stage(event: str) -> str:
    """Histogram key for an event: per-shard detail stripped, so
    sub_write_ack(2) and sub_write_ack(0) share one latency series."""
    i = event.find("(")
    return event if i < 0 else event[:i]


class TrackedOp:
    """One in-flight (then historic) operation with an event timeline.

    Events are (wall_ts, name) pairs; wall clock (not monotonic) so
    timelines from different daemons of one trace can be merged — the
    reference's utime_t event stamps make the same choice.
    """

    __slots__ = ("tracker", "op_type", "desc", "trace", "events",
                 "initiated_at", "completed_at", "result", "info",
                 "slow", "slow_since", "blamed_stage", "_unregistered")

    def __init__(self, tracker: "OpTracker | None", op_type: str,
                 desc: str, trace: TraceContext | None = None):
        self.tracker = tracker
        self.op_type = op_type
        self.desc = desc
        provided = trace is not None
        self.trace = trace if provided else TraceContext.new()
        self.initiated_at = time.time()
        self.completed_at: float | None = None
        self.events: list[tuple[float, str]] = []
        self.result: int | None = None
        self.info: dict = {}         # pg / version / client-visible tags
        self.slow = False
        self.slow_since: float | None = None
        self.blamed_stage: str | None = None
        self._unregistered = False
        # the origin (objecter submit) event, when a CALLER-supplied
        # trace carried it, anchors the timeline before any local
        # event — only on root spans (a sub-op span starts at its own
        # hop, not at the client; a self-created trace has no remote
        # origin to anchor)
        if provided and self.trace.origin_ts is not None and \
                self.trace.parent_span is None:
            self.events.append((self.trace.origin_ts, "objecter_submit"))

    # -- hot path -----------------------------------------------------------

    def mark_event(self, name: str, ts: float | None = None) -> None:
        self.events.append((ts if ts is not None else time.time(), name))

    def set_info(self, key: str, value) -> None:
        self.info[key] = value

    # -- introspection -------------------------------------------------------

    @property
    def is_tracked(self) -> bool:
        return True

    def age(self, now: float | None = None) -> float:
        return (now if now is not None else time.time()) - \
            self.initiated_at

    def duration(self) -> float:
        end = self.completed_at if self.completed_at is not None \
            else time.time()
        return end - self.initiated_at

    def current_stage(self) -> str:
        return self.events[-1][1] if self.events else "initiated"

    def stage_durations(self) -> list[tuple[str, float]]:
        """[(event_name, seconds spent reaching it)] — the interval is
        attributed to the event that ENDS it (waiting for sub_write_ack
        is blamed on sub_write_ack, not on the send that preceded it)."""
        out = []
        prev = self.initiated_at
        for ts, name in self.events:
            out.append((name, max(0.0, ts - prev)))
            prev = ts
        return out

    def blame(self, now: float | None = None) -> str:
        """The stage that ate the op's wall time (see module doc)."""
        now = now if now is not None else time.time()
        gaps = self.stage_durations()
        if self.completed_at is None and self.events:
            # still in flight: time stalled past the last event counts
            # as a gap "inside" the current stage
            gaps.append((f"waiting after {self.events[-1][1]}",
                         now - self.events[-1][0]))
        if not gaps:
            return "initiated"
        return max(gaps, key=lambda g: g[1])[0]

    def to_dict(self, now: float | None = None) -> dict:
        now = now if now is not None else time.time()
        d = {
            "type": self.op_type,
            "description": self.desc,
            "trace_id": self.trace.trace_id,
            "span_id": self.trace.span_id,
            "parent_span": self.trace.parent_span,
            "initiated_at": self.initiated_at,
            "age": round(self.age(now), 6),
            "duration": round(self.duration(), 6),
            "current_stage": self.current_stage(),
            "events": [{"ts": ts, "event": name}
                       for ts, name in self.events],
        }
        if self.result is not None:
            d["result"] = self.result
        if self.slow:
            d["slow"] = True
            d["blamed_stage"] = self.blamed_stage
        d.update(self.info)
        return d


class _NullTrackedOp:
    """The tracing-off fast path: one shared instance, every method a
    no-op (reference: OpTracker::create_request returns early when
    tracking_enabled is false).  Identity-comparable via NULL_TRACKED."""

    __slots__ = ()

    trace = None
    events: tuple = ()
    info: dict = {}
    op_type = desc = ""
    slow = False
    blamed_stage = None
    result = None
    initiated_at = completed_at = 0.0

    @property
    def is_tracked(self) -> bool:
        return False

    def mark_event(self, name: str, ts: float | None = None) -> None:
        pass

    def set_info(self, key: str, value) -> None:
        pass

    def age(self, now: float | None = None) -> float:
        return 0.0

    def duration(self) -> float:
        return 0.0

    def current_stage(self) -> str:
        return ""

    def stage_durations(self) -> list:
        return []

    def blame(self, now: float | None = None) -> str:
        return ""

    def to_dict(self, now: float | None = None) -> dict:
        return {}


NULL_TRACKED = _NullTrackedOp()

# only client-request op types feed the SLOW_OPS complaint path:
# background recovery/scrub ops legitimately outlive complaint_time
# (the reference warns on slow *requests*, never on background work)
COMPLAINT_OP_TYPES = frozenset({"osd_op", "ec_sub_write"})


class OpTracker:
    """Per-daemon registry of tracked ops (reference OpTracker).

    perf: optional PerfCounters with histogram support — on op
    completion every stage interval lands in a `lat_<stage>` latency
    histogram (common/perf_counters.py HISTOGRAM type), exported by
    tools/metrics_exporter.py.
    """

    def __init__(self, enabled: bool = True,
                 complaint_time: float = 30.0,
                 history_size: int = 20,
                 history_slow_size: int = 20,
                 perf=None):
        self.enabled = enabled
        self.complaint_time = complaint_time
        self.history_size = history_size
        self.perf = perf
        self._lock = threading.Lock()
        self._inflight: dict[int, TrackedOp] = {}
        self._history: collections.deque[TrackedOp] = \
            collections.deque(maxlen=history_size)
        self._slow_history: collections.deque[TrackedOp] = \
            collections.deque(maxlen=history_slow_size)
        # monotonic counters for the health report
        self.num_tracked = 0
        self.num_slow = 0

    # -- lifecycle -----------------------------------------------------------

    def create(self, op_type: str, desc: str = "",
               trace: TraceContext | None = None):
        """New tracked op (registered in flight) — or NULL_TRACKED when
        tracking is off (the zero-cost path)."""
        if not self.enabled:
            return NULL_TRACKED
        top = TrackedOp(self, op_type, desc, trace)
        with self._lock:
            self._inflight[id(top)] = top
            self.num_tracked += 1
        return top

    def unregister(self, top, result: int | None = None) -> None:
        """Op finished: move to the historic ring; latch as slow when
        it exceeded the complaint time; feed the stage histograms."""
        if top is NULL_TRACKED or not getattr(top, "is_tracked", False):
            return
        if top._unregistered:       # idempotent (error paths may race)
            return
        top._unregistered = True
        top.completed_at = time.time()
        if result is not None:
            top.result = result
        newly_slow = False
        if self.complaint_time > 0 and \
                top.op_type in COMPLAINT_OP_TYPES and \
                top.duration() > self.complaint_time:
            newly_slow = not top.slow
            if newly_slow:
                top.slow = True
                top.slow_since = top.completed_at
            # final blame from the COMPLETE timeline: an op the
            # in-flight scanner latched carries a provisional
            # "waiting after X" (the stall was still open when it was
            # scanned) — once the op finishes, the stage that actually
            # ended the wait (e.g. a late msgr_send(peer)) owns it
            top.blamed_stage = top.blame()
        with self._lock:
            self._inflight.pop(id(top), None)
            self._history.append(top)
            if newly_slow:      # in-flight latching already ringed it
                self.num_slow += 1
                self._slow_history.append(top)
        if self.perf is not None:
            for name, dt in top.stage_durations():
                self.perf.hinc(f"lat_{canonical_stage(name)}", dt)
            # end-to-end per-op-type series: the p99 every per-stage
            # series decomposes (dump_latencies / the exporter's
            # precomputed tail gauges read it like any stage)
            self.perf.hinc(f"lat_total_{top.op_type}", top.duration())

    # -- slow-op surveillance ------------------------------------------------

    def check_ops_in_flight(self, now: float | None = None
                            ) -> list[TrackedOp]:
        """Latch in-flight ops older than the complaint time (the
        reference's visit_ops_in_flight + slow-request warning).
        Returns every CURRENTLY slow in-flight op; newly latched ones
        also enter the slow history ring."""
        if not self.enabled or self.complaint_time <= 0:
            return []
        now = now if now is not None else time.time()
        slow: list[TrackedOp] = []
        newly: list[TrackedOp] = []
        with self._lock:
            for top in self._inflight.values():
                if top.op_type in COMPLAINT_OP_TYPES and \
                        top.age(now) > self.complaint_time:
                    if not top.slow:
                        top.slow = True
                        top.slow_since = now
                        newly.append(top)
                        self.num_slow += 1
                        self._slow_history.append(top)
                    top.blamed_stage = top.blame(now)
                    slow.append(top)
        return slow

    def slow_op_summary(self, window: float | None = None) -> dict:
        """The OSD->mon health payload: slow in-flight ops plus ops
        that latched within the recency `window` (a slow write that
        finally commits must not flicker the health warning off before
        anyone sees it).  Consumed by mon/monitor.py `health`."""
        inflight = self.check_ops_in_flight()
        now = time.time()
        if window is None:
            window = max(2.0, min(60.0, 2 * self.complaint_time))
        seen = {id(t) for t in inflight}
        recent = []
        with self._lock:
            for t in self._slow_history:
                if id(t) not in seen and t.completed_at is not None \
                        and t.slow_since is not None and \
                        now - t.slow_since <= window:
                    recent.append(t)
        slow = inflight + recent
        return {
            "count": len(slow),
            "oldest_age": round(max(
                [t.age(now) for t in inflight] +
                [t.duration() for t in recent], default=0.0), 3),
            "total_slow": self.num_slow,
            "ops": [{"type": t.op_type, "desc": t.desc,
                     "trace_id": t.trace.trace_id,
                     "age": round(t.age(now) if t.completed_at is None
                                  else t.duration(), 3),
                     "blamed_stage": t.blamed_stage,
                     # op owner (the PG primary) when known: the mon
                     # names IT in the SLOW_OPS daemons list, so a
                     # replica's sub-op report blames the right daemon
                     "primary": t.info.get("primary")}
                    for t in slow[:10]],
        }

    # -- dumps (asok command backends) ---------------------------------------

    def dump_ops_in_flight(self) -> dict:
        now = time.time()
        with self._lock:
            ops = [t.to_dict(now) for t in self._inflight.values()]
        ops.sort(key=lambda d: d["initiated_at"])
        return {"num_ops": len(ops), "ops": ops}

    def dump_historic_ops(self) -> dict:
        now = time.time()
        with self._lock:
            ops = [t.to_dict(now) for t in self._history]
        return {"num_ops": len(ops), "size": self.history_size,
                "ops": ops}

    def dump_historic_slow_ops(self) -> dict:
        now = time.time()
        with self._lock:
            ops = [t.to_dict(now) for t in self._slow_history]
        return {"num_ops": len(ops), "complaint_time":
                self.complaint_time, "ops": ops}

    def get_historic(self, trace_id: str) -> list[TrackedOp]:
        """Historic ops of one trace (test/debug convenience)."""
        with self._lock:
            return [t for t in self._history
                    if t.trace.trace_id == trace_id]
