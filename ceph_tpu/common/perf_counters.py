"""Per-daemon performance counters.

Re-expresses the reference's PerfCounters (src/common/perf_counters.h):
typed counters built once per component (counter / gauge / time /
long-running-average), updated lock-free on the hot path (here: plain
int/float updates under the GIL, with a lock only for dump), dumped via
the admin socket (`perf dump`) and shipped to the mgr role.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass
from enum import Enum


class CounterType(Enum):
    U64 = "u64"              # monotonically increasing counter
    GAUGE = "gauge"          # settable level
    TIME = "time"            # accumulated seconds
    AVG = "avg"              # (sum, count) long-running average


@dataclass
class _Counter:
    name: str
    type: CounterType
    desc: str = ""
    value: float = 0
    sum: float = 0
    count: int = 0


class PerfCountersBuilder:
    def __init__(self, name: str):
        self.name = name
        self._counters: dict[str, _Counter] = {}

    def add_u64_counter(self, key: str, desc: str = ""):
        self._counters[key] = _Counter(key, CounterType.U64, desc)
        return self

    def add_gauge(self, key: str, desc: str = ""):
        self._counters[key] = _Counter(key, CounterType.GAUGE, desc)
        return self

    def add_time_avg(self, key: str, desc: str = ""):
        self._counters[key] = _Counter(key, CounterType.AVG, desc)
        return self

    def create_perf_counters(self) -> "PerfCounters":
        return PerfCounters(self.name, self._counters)


class PerfCounters:
    def __init__(self, name: str, counters: dict[str, _Counter]):
        self.name = name
        self._c = counters
        self._lock = threading.Lock()

    def inc(self, key: str, by: float = 1) -> None:
        self._c[key].value += by

    def set(self, key: str, value: float) -> None:
        self._c[key].value = value

    def tinc(self, key: str, seconds: float) -> None:
        c = self._c[key]
        c.sum += seconds
        c.count += 1

    def time(self, key: str):
        """Context manager timing a block into a time-avg counter."""
        pc = self

        class _T:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                pc.tinc(key, time.perf_counter() - self.t0)
        return _T()

    def dump(self) -> dict:
        with self._lock:
            out = {}
            for key, c in self._c.items():
                if c.type == CounterType.AVG:
                    out[key] = {"avgcount": c.count, "sum": c.sum,
                                "avgtime": c.sum / c.count if c.count else 0}
                else:
                    out[key] = c.value
            return out

    def schema(self) -> dict:
        """key -> counter type name (reference `perf schema`): lets the
        prometheus exporter emit correct # TYPE lines instead of
        untyped."""
        return {key: c.type.value for key, c in self._c.items()}


class PerfCountersCollection:
    """All counter sets of one daemon (reference PerfCountersCollection),
    the object `perf dump` walks."""

    def __init__(self) -> None:
        self._sets: dict[str, PerfCounters] = {}
        self._lock = threading.Lock()

    def add(self, pc: PerfCounters) -> PerfCounters:
        with self._lock:
            self._sets[pc.name] = pc
        return pc

    def dump(self) -> dict:
        with self._lock:
            return {name: pc.dump() for name, pc in self._sets.items()}

    def schema(self) -> dict:
        with self._lock:
            return {name: pc.schema() for name, pc in self._sets.items()}
