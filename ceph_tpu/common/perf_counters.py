"""Per-daemon performance counters.

Re-expresses the reference's PerfCounters (src/common/perf_counters.h):
typed counters built once per component (counter / gauge / time /
long-running-average), updated lock-free on the hot path (here: plain
int/float updates under the GIL, with a lock only for dump), dumped via
the admin socket (`perf dump`) and shipped to the mgr role.
"""

from __future__ import annotations

import bisect
import threading
import time
from dataclasses import dataclass, field
from enum import Enum


class CounterType(Enum):
    U64 = "u64"              # monotonically increasing counter
    GAUGE = "gauge"          # settable level
    TIME = "time"            # accumulated seconds
    AVG = "avg"              # (sum, count) long-running average
    HISTOGRAM = "hist"       # bucketed samples (prometheus histogram)


# The percentile set every latency surface publishes (dump_latencies
# asok, the exporter's precomputed gauges, the load harness rows):
# production tails are ruled by p99/p999, p50/p95 anchor the body.
LATENCY_QUANTILES = ((0.5, "p50"), (0.95, "p95"),
                     (0.99, "p99"), (0.999, "p999"))


def quantile_from_cumulative(buckets: list, q: float
                             ) -> tuple[float, float, float] | None:
    """Quantile estimate from prometheus-style cumulative buckets
    [[le, cum], ..., ["+Inf", total]] — the exact shape PerfCounters
    histograms dump and the exporter scrapes.

    Returns (estimate, err_lo, err_hi) or None for an empty histogram.
    The estimate linearly interpolates inside the bucket holding the
    q-th sample (the classic histogram_quantile estimator); err_lo/
    err_hi are the bucket bounds — the true quantile provably lies in
    [err_lo, err_hi], so the publication carries its own error bar.
    A quantile landing in the +Inf bucket reports the last finite
    bound as the estimate with err_hi = inf (the honest answer: the
    axis ran out, widen the buckets)."""
    if not buckets:
        return None
    total = buckets[-1][1]
    if total <= 0:
        return None
    if not 0.0 <= q <= 1.0:
        raise ValueError(f"quantile {q} outside [0, 1]")
    rank = q * total
    prev_le, prev_cum = 0.0, 0
    for le, cum in buckets:
        if le == "+Inf":
            if cum > prev_cum and rank > prev_cum:
                return (prev_le, prev_le, float("inf"))
            # rank landed exactly on the finite edge
            return (prev_le, prev_le, prev_le)
        if cum >= rank:
            lo = prev_le
            frac = ((rank - prev_cum) / (cum - prev_cum)) \
                if cum > prev_cum else 1.0
            return (lo + frac * (le - lo), lo, le)
        prev_le, prev_cum = le, cum
    return (prev_le, prev_le, float("inf"))


def percentiles_from_samples(samples: list, quantiles=None) -> dict:
    """Exact percentiles from raw latency samples (the harness's
    per-op recordings; nearest-rank on the sorted list).  Returns
    {label: seconds} for LATENCY_QUANTILES (or the given
    [(q, label), ...]); empty dict when there are no samples."""
    if not samples:
        return {}
    import math
    s = sorted(samples)
    out = {}
    for q, label in (quantiles or LATENCY_QUANTILES):
        # nearest-rank: the ceil(q*n)-th order statistic (1-indexed)
        idx = min(len(s) - 1, max(0, math.ceil(q * len(s)) - 1))
        out[label] = s[idx]
    return out


# Log-spaced latency bounds in seconds (reference PerfHistogram axis
# config; prometheus-style, the implicit +Inf bucket holds the rest).
DEFAULT_LAT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

# Control-plane axis (peering rounds, recovery passes, mon dispatch
# under churn): the device-plane buckets top out at 10 s, but a
# 128-OSD re-peer or a wide backfill scan legitimately runs minutes —
# a lat_peering_* histogram on the default axis would park every
# interesting sample in +Inf and the p99 would read "10 s, probably".
CONTROL_LAT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0, 600.0)


@dataclass
class _Counter:
    name: str
    type: CounterType
    desc: str = ""
    value: float = 0
    sum: float = 0
    count: int = 0
    buckets: tuple = ()           # histogram upper bounds
    hist: list = field(default_factory=list)  # per-bucket counts (+Inf last)


class PerfCountersBuilder:
    def __init__(self, name: str):
        self.name = name
        self._counters: dict[str, _Counter] = {}

    def add_u64_counter(self, key: str, desc: str = ""):
        self._counters[key] = _Counter(key, CounterType.U64, desc)
        return self

    def add_gauge(self, key: str, desc: str = ""):
        self._counters[key] = _Counter(key, CounterType.GAUGE, desc)
        return self

    def add_time_avg(self, key: str, desc: str = ""):
        self._counters[key] = _Counter(key, CounterType.AVG, desc)
        return self

    def add_histogram(self, key: str, desc: str = "",
                      buckets: tuple = DEFAULT_LAT_BUCKETS):
        c = _Counter(key, CounterType.HISTOGRAM, desc,
                     buckets=tuple(buckets))
        c.hist = [0] * (len(c.buckets) + 1)
        self._counters[key] = c
        return self

    def create_perf_counters(self) -> "PerfCounters":
        return PerfCounters(self.name, self._counters)


class PerfCounters:
    def __init__(self, name: str, counters: dict[str, _Counter]):
        self.name = name
        self._c = counters
        self._lock = threading.Lock()

    def inc(self, key: str, by: float = 1) -> None:
        self._c[key].value += by

    def dinc(self, key: str, by: float = 1) -> None:
        """inc() for dynamic key sets (the mClock per-class counters:
        op classes appear at runtime as tenants do): creates the U64
        counter on first use, like hinc does for histograms."""
        c = self._c.get(key)
        if c is None:
            with self._lock:
                c = self._c.get(key)
                if c is None:
                    c = _Counter(key, CounterType.U64)
                    self._c[key] = c
        c.value += by

    def set(self, key: str, value: float) -> None:
        self._c[key].value = value

    def tinc(self, key: str, seconds: float) -> None:
        c = self._c[key]
        c.sum += seconds
        c.count += 1

    def hinc(self, key: str, value: float) -> None:
        """Observe one sample into a histogram counter.  Creates the
        histogram on first use — consumers with dynamic key sets (the
        OpTracker's per-stage latency series) need not predeclare."""
        c = self._c.get(key)
        if c is None:
            with self._lock:
                c = self._c.get(key)
                if c is None:
                    c = _Counter(key, CounterType.HISTOGRAM,
                                 buckets=DEFAULT_LAT_BUCKETS)
                    c.hist = [0] * (len(c.buckets) + 1)
                    self._c[key] = c
        c.hist[bisect.bisect_left(c.buckets, value)] += 1
        c.sum += value
        c.count += 1

    def time(self, key: str):
        """Context manager timing a block into a time-avg counter."""
        pc = self

        class _T:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                pc.tinc(key, time.perf_counter() - self.t0)
        return _T()

    def dump(self) -> dict:
        with self._lock:
            out = {}
            for key, c in self._c.items():
                if c.type == CounterType.AVG:
                    out[key] = {"avgcount": c.count, "sum": c.sum,
                                "avgtime": c.sum / c.count if c.count else 0}
                elif c.type == CounterType.HISTOGRAM:
                    # cumulative prometheus-style buckets, +Inf last
                    out[key] = {"sum": c.sum, "count": c.count,
                                "buckets": self._cumulative(c)}
                else:
                    out[key] = c.value
            return out

    def schema(self) -> dict:
        """key -> counter type name (reference `perf schema`): lets the
        prometheus exporter emit correct # TYPE lines instead of
        untyped."""
        return {key: c.type.value for key, c in self._c.items()}

    # -- percentile pipeline (tail-latency observability) --------------------

    def _cumulative(self, c: _Counter) -> list:
        cum, buckets = 0, []
        for le, n in zip(c.buckets, c.hist):
            cum += n
            buckets.append([le, cum])
        buckets.append(["+Inf", cum + c.hist[-1]])
        return buckets

    def quantile(self, key: str, q: float
                 ) -> tuple[float, float, float] | None:
        """(estimate, err_lo, err_hi) of a histogram counter's q-th
        quantile, or None when the key is absent/empty/not a
        histogram (see quantile_from_cumulative)."""
        c = self._c.get(key)
        if c is None or c.type != CounterType.HISTOGRAM:
            return None
        with self._lock:
            buckets = self._cumulative(c)
        return quantile_from_cumulative(buckets, q)

    def dump_latencies(self) -> dict:
        """Precomputed percentile summary of every histogram counter:
        {key: {count, sum, p50, p95, p99, p999, p99_err: [lo, hi]}} —
        the `dump_latencies` asok payload and the exporter's gauge
        source.  Estimates are bucket-interpolated; p99_err carries
        the p99's bucket bounds so consumers see the resolution."""
        with self._lock:
            snap = [(key, c.count, c.sum, self._cumulative(c))
                    for key, c in self._c.items()
                    if c.type == CounterType.HISTOGRAM]
        out = {}
        for key, count, total, buckets in snap:
            row = {"count": count, "sum": round(total, 9)}
            for q, label in LATENCY_QUANTILES:
                est = quantile_from_cumulative(buckets, q)
                row[label] = round(est[0], 9) if est else None
                if est and label == "p99":
                    row["p99_err"] = [round(est[1], 9),
                                      est[2] if est[2] == float("inf")
                                      else round(est[2], 9)]
            out[key] = row
        return out


class PerfCountersCollection:
    """All counter sets of one daemon (reference PerfCountersCollection),
    the object `perf dump` walks."""

    def __init__(self) -> None:
        self._sets: dict[str, PerfCounters] = {}
        self._lock = threading.Lock()

    def add(self, pc: PerfCounters) -> PerfCounters:
        with self._lock:
            self._sets[pc.name] = pc
        return pc

    def dump(self) -> dict:
        with self._lock:
            return {name: pc.dump() for name, pc in self._sets.items()}

    def schema(self) -> dict:
        with self._lock:
            return {name: pc.schema() for name, pc in self._sets.items()}

    def dump_latencies(self) -> dict:
        """Percentile summaries of every set's histogram counters
        (the daemon-wide `dump_latencies` asok command); sets without
        histograms are omitted."""
        with self._lock:
            sets = list(self._sets.items())
        out = {}
        for name, pc in sets:
            lat = pc.dump_latencies()
            if lat:
                out[name] = lat
        return out
