"""Per-daemon performance counters.

Re-expresses the reference's PerfCounters (src/common/perf_counters.h):
typed counters built once per component (counter / gauge / time /
long-running-average), updated lock-free on the hot path (here: plain
int/float updates under the GIL, with a lock only for dump), dumped via
the admin socket (`perf dump`) and shipped to the mgr role.
"""

from __future__ import annotations

import bisect
import threading
import time
from dataclasses import dataclass, field
from enum import Enum


class CounterType(Enum):
    U64 = "u64"              # monotonically increasing counter
    GAUGE = "gauge"          # settable level
    TIME = "time"            # accumulated seconds
    AVG = "avg"              # (sum, count) long-running average
    HISTOGRAM = "hist"       # bucketed samples (prometheus histogram)


# Log-spaced latency bounds in seconds (reference PerfHistogram axis
# config; prometheus-style, the implicit +Inf bucket holds the rest).
DEFAULT_LAT_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


@dataclass
class _Counter:
    name: str
    type: CounterType
    desc: str = ""
    value: float = 0
    sum: float = 0
    count: int = 0
    buckets: tuple = ()           # histogram upper bounds
    hist: list = field(default_factory=list)  # per-bucket counts (+Inf last)


class PerfCountersBuilder:
    def __init__(self, name: str):
        self.name = name
        self._counters: dict[str, _Counter] = {}

    def add_u64_counter(self, key: str, desc: str = ""):
        self._counters[key] = _Counter(key, CounterType.U64, desc)
        return self

    def add_gauge(self, key: str, desc: str = ""):
        self._counters[key] = _Counter(key, CounterType.GAUGE, desc)
        return self

    def add_time_avg(self, key: str, desc: str = ""):
        self._counters[key] = _Counter(key, CounterType.AVG, desc)
        return self

    def add_histogram(self, key: str, desc: str = "",
                      buckets: tuple = DEFAULT_LAT_BUCKETS):
        c = _Counter(key, CounterType.HISTOGRAM, desc,
                     buckets=tuple(buckets))
        c.hist = [0] * (len(c.buckets) + 1)
        self._counters[key] = c
        return self

    def create_perf_counters(self) -> "PerfCounters":
        return PerfCounters(self.name, self._counters)


class PerfCounters:
    def __init__(self, name: str, counters: dict[str, _Counter]):
        self.name = name
        self._c = counters
        self._lock = threading.Lock()

    def inc(self, key: str, by: float = 1) -> None:
        self._c[key].value += by

    def set(self, key: str, value: float) -> None:
        self._c[key].value = value

    def tinc(self, key: str, seconds: float) -> None:
        c = self._c[key]
        c.sum += seconds
        c.count += 1

    def hinc(self, key: str, value: float) -> None:
        """Observe one sample into a histogram counter.  Creates the
        histogram on first use — consumers with dynamic key sets (the
        OpTracker's per-stage latency series) need not predeclare."""
        c = self._c.get(key)
        if c is None:
            with self._lock:
                c = self._c.get(key)
                if c is None:
                    c = _Counter(key, CounterType.HISTOGRAM,
                                 buckets=DEFAULT_LAT_BUCKETS)
                    c.hist = [0] * (len(c.buckets) + 1)
                    self._c[key] = c
        c.hist[bisect.bisect_left(c.buckets, value)] += 1
        c.sum += value
        c.count += 1

    def time(self, key: str):
        """Context manager timing a block into a time-avg counter."""
        pc = self

        class _T:
            def __enter__(self):
                self.t0 = time.perf_counter()
                return self

            def __exit__(self, *exc):
                pc.tinc(key, time.perf_counter() - self.t0)
        return _T()

    def dump(self) -> dict:
        with self._lock:
            out = {}
            for key, c in self._c.items():
                if c.type == CounterType.AVG:
                    out[key] = {"avgcount": c.count, "sum": c.sum,
                                "avgtime": c.sum / c.count if c.count else 0}
                elif c.type == CounterType.HISTOGRAM:
                    # cumulative prometheus-style buckets, +Inf last
                    cum, buckets = 0, []
                    for le, n in zip(c.buckets, c.hist):
                        cum += n
                        buckets.append([le, cum])
                    buckets.append(["+Inf", cum + c.hist[-1]])
                    out[key] = {"sum": c.sum, "count": c.count,
                                "buckets": buckets}
                else:
                    out[key] = c.value
            return out

    def schema(self) -> dict:
        """key -> counter type name (reference `perf schema`): lets the
        prometheus exporter emit correct # TYPE lines instead of
        untyped."""
        return {key: c.type.value for key, c in self._c.items()}


class PerfCountersCollection:
    """All counter sets of one daemon (reference PerfCountersCollection),
    the object `perf dump` walks."""

    def __init__(self) -> None:
        self._sets: dict[str, PerfCounters] = {}
        self._lock = threading.Lock()

    def add(self, pc: PerfCounters) -> PerfCounters:
        with self._lock:
            self._sets[pc.name] = pc
        return pc

    def dump(self) -> dict:
        with self._lock:
            return {name: pc.dump() for name, pc in self._sets.items()}

    def schema(self) -> dict:
        with self._lock:
            return {name: pc.schema() for name, pc in self._sets.items()}
