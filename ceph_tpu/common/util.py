"""Small shared helpers with no dependencies above common/."""

import numpy as np


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1).

    The launch-shape bucketing rule: continuous batching produces a
    new super-batch width on every launch, and each distinct width is
    a fresh XLA compile — rounding every launch dimension (tile
    counts, run counts, column widths) up to a power of two keeps the
    number of jit keys ~log2 of the largest width ever seen.  Every
    bucketing site must use the SAME rounding rule, or a width one
    site considers cached recompiles at another.
    """
    return 1 << (n - 1).bit_length()


def concat_columns(arrs):
    """[(R, W_i) arrays] -> (concatenated (R, sum W_i), [W_i]).

    The batching idiom of the repair/decode paths: independent
    objects' byte axes ride one launch and demux by column
    (split_columns) — one shared helper so every site slices the
    same way."""
    widths = [a.shape[1] for a in arrs]
    big = arrs[0] if len(arrs) == 1 else np.concatenate(arrs, axis=1)
    return big, widths


def split_columns(out, widths):
    """Inverse of concat_columns on the result array: per-object
    column slices in submission order (trailing pad columns, if the
    launch bucketed, are never touched)."""
    res = []
    col = 0
    for w in widths:
        res.append(out[:, col:col + w])
        col += w
    return res
