"""Small shared helpers with no dependencies above common/."""


def next_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1).

    The launch-shape bucketing rule: continuous batching produces a
    new super-batch width on every launch, and each distinct width is
    a fresh XLA compile — rounding every launch dimension (tile
    counts, run counts, column widths) up to a power of two keeps the
    number of jit keys ~log2 of the largest width ever seen.  Every
    bucketing site must use the SAME rounding rule, or a width one
    site considers cached recompiles at another.
    """
    return 1 << (n - 1).bit_length()
