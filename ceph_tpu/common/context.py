"""CephContext equivalent: per-daemon config + logging + perf + asok.

Re-expresses the reference's CephContext/global_init pairing
(src/common/ceph_context.h, src/global/global_init.cc): one object a
daemon threads everywhere, owning its Config, DoutStream, perf-counter
collection and admin socket, plus the startup EC-plugin preload
(global_init_preload_erasure_code, reference global_init.cc:571).
"""

from __future__ import annotations

from .admin_socket import AdminSocket
from .dout import DoutStream
from .options import Config
from .perf_counters import PerfCountersCollection


class CephContext:
    def __init__(self, name: str = "client",
                 asok_path: str | None = None):
        self.name = name
        self.conf = Config()
        self.log = DoutStream()
        self.log.name = name
        self.perf = PerfCountersCollection()
        self.asok: AdminSocket | None = None
        if asok_path:
            self.asok = AdminSocket(asok_path)
            self._register_builtin_asok()

    def dout(self, subsys: str, level: int, msg: str) -> None:
        self.log.log(subsys, level, msg)

    def preload_erasure_code(self) -> list[str]:
        """global_init_preload_erasure_code: eager-load the configured
        plugins so pool creation can't stall a daemon later."""
        from ..ec import ErasureCodePluginRegistry
        plugins = [p for p in
                   str(self.conf.get("osd_erasure_code_plugins")).split()
                   if p and p != "jax"]  # jax loads lazily: device init
        directory = str(self.conf.get("erasure_code_dir")) or None
        ErasureCodePluginRegistry.instance().preload(plugins, directory)
        self.dout("ec", 10, f"load: preloaded {plugins}")
        return plugins

    def _register_builtin_asok(self) -> None:
        self.asok.register_command(
            "perf dump", lambda cmd: self.perf.dump())
        self.asok.register_command(
            "perf schema", lambda cmd: self.perf.schema())
        # precomputed p50/p95/p99/p999 (+ error bounds) of every
        # latency histogram — the tail-latency answer to `perf dump`'s
        # raw buckets (docs/QOS.md, docs/TRACING.md)
        self.asok.register_command(
            "dump_latencies", lambda cmd: self.perf.dump_latencies())
        self.asok.register_command(
            "config show", lambda cmd: self.conf.show())

        def config_set(cmd):
            self.conf.set(cmd["key"], cmd["value"])
            return {"success": True, cmd["key"]: self.conf.get(cmd["key"])}
        self.asok.register_command("config set", config_set)

        def log_dump(cmd):
            """Structured dump of the in-memory recent-events ring
            (reference `log dump`: the higher-verbosity ring kept for
            post-hoc debugging); optional `count` bounds the tail."""
            count = cmd.get("count")
            return {"ok": 1, "count": len(self.log.ring),
                    "entries": self.log.recent(
                        int(count) if count is not None else None)}
        self.asok.register_command("log dump", log_dump)

    def shutdown(self) -> None:
        if self.asok is not None:
            self.asok.shutdown()
