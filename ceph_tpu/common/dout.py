"""dout-style subsystem logging with a crash-dump ring.

Re-expresses the reference's logging (src/common/dout.h macro family,
src/common/subsys.h 62 subsystems, src/log/Log.h async collector):
per-subsystem log/gather levels, cheap level gating, and an in-memory
ring kept at higher verbosity than what reaches the sink, dumped on
crash ("recent events") — the feature that makes field debugging of a
storage daemon possible.
"""

from __future__ import annotations

import collections
import sys
import threading
import time

SUBSYS = {
    # (log_level, gather_level) defaults, reference subsys.h style
    "osd": (1, 5),
    "ec": (1, 5),
    "ms": (0, 5),
    "mon": (1, 5),
    "crush": (1, 5),
    "store": (1, 5),
    "tpu": (1, 5),
    "client": (1, 5),
    "scrub": (1, 5),
}


class LogRing:
    """In-memory recent-events ring (reference m_recent)."""

    def __init__(self, capacity: int = 10000):
        self.ring = collections.deque(maxlen=capacity)
        self.lock = threading.Lock()

    def add(self, entry: tuple) -> None:
        with self.lock:
            self.ring.append(entry)

    def __len__(self) -> int:
        with self.lock:
            return len(self.ring)

    def recent(self, count: int | None = None) -> list[tuple]:
        """Tail of the ring, newest last (allocation-light: entries
        stay tuples; formatting happens only at dump time)."""
        with self.lock:
            entries = list(self.ring)
        if count is not None:
            entries = entries[-count:] if count > 0 else []
        return entries

    def dump(self, out=sys.stderr) -> None:
        with self.lock:
            entries = list(self.ring)
        print(f"--- begin dump of recent events ({len(entries)}) ---",
              file=out)
        for ts, subsys, level, msg in entries:
            print(f"{ts:.6f} {subsys:>6} {level} : {msg}", file=out)
        print("--- end dump of recent events ---", file=out)


class DoutStream:
    def __init__(self, sink=None):
        self.levels = dict(SUBSYS)
        self.ring = LogRing()
        self.sink = sink if sink is not None else sys.stderr
        self.name = ""

    def set_level(self, subsys: str, log: int, gather: int | None = None):
        g = gather if gather is not None else max(
            log, self.levels.get(subsys, (1, 5))[1])
        self.levels[subsys] = (log, g)

    def should_gather(self, subsys: str, level: int) -> bool:
        return level <= self.levels.get(subsys, (1, 5))[1]

    def log(self, subsys: str, level: int, msg: str) -> None:
        log_lvl, gather_lvl = self.levels.get(subsys, (1, 5))
        if level > gather_lvl:
            return
        ts = time.time()
        self.ring.add((ts, subsys, level, msg))
        if level <= log_lvl:
            try:
                print(f"{ts:.6f} {self.name} {subsys:>6} {level} : {msg}",
                      file=self.sink)
            except ValueError:
                pass   # sink closed (daemon thread logging at teardown)

    def dump_recent(self, out=sys.stderr) -> None:
        self.ring.dump(out)

    def recent(self, count: int | None = None) -> list[dict]:
        """Structured view of the recent-events ring (the `log dump`
        asok command payload)."""
        return [{"ts": ts, "subsys": subsys, "level": level, "msg": msg}
                for ts, subsys, level, msg in self.ring.recent(count)]


_default = DoutStream()


def dout(subsys: str, level: int, msg: str,
         stream: DoutStream | None = None) -> None:
    (stream or _default).log(subsys, level, msg)


def default_stream() -> DoutStream:
    return _default
