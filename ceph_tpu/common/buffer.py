"""bufferlist — the zero-copy rope that is the data-plane currency.

Re-expresses the reference's `ceph::bufferlist`/`bufferptr`
(src/include/buffer.h:441, src/common/buffer.cc): an ordered list of
byte segments supporting append without copy, substr views, alignment
rebuilds, and crc32c with a per-segment crc cache (reference keeps the
crc cache on the raw buffer, :1199 + buffer.cc crc_map) so repeated
checksums of unchanged data are free and crcs of concatenations combine
in O(log n) instead of re-scanning bytes.

Idiomatic difference: segments are numpy uint8 arrays (zero-copy views
of bytes/memoryview/ndarray), which is what both the TPU path (device
transfer wants contiguous aligned pages) and the native path (ctypes
pointers) consume directly.
"""

from __future__ import annotations

import numpy as np

from . import crc32c as _crc


class BufferPtr:
    """One segment: a numpy view plus its cached crc (keyed by seed)."""

    __slots__ = ("array", "_crc_cache")

    def __init__(self, data):
        if isinstance(data, np.ndarray):
            self.array = data.astype(np.uint8, copy=False).ravel()
        else:
            self.array = np.frombuffer(data, dtype=np.uint8)
        self._crc_cache: dict[int, int] = {}

    def __len__(self) -> int:
        return self.array.size

    def crc32c(self, seed: int) -> int:
        got = self._crc_cache.get(seed)
        if got is None:
            got = _crc.crc32c(self.array.tobytes(), seed)
            self._crc_cache[seed] = got
        return got


class BufferList:
    """Rope of BufferPtr segments."""

    def __init__(self, data=None):
        self._ptrs: list[BufferPtr] = []
        self._length = 0
        if data is not None:
            self.append(data)

    # -- building -----------------------------------------------------------

    def append(self, data) -> None:
        if isinstance(data, BufferList):
            self._ptrs.extend(data._ptrs)
            self._length += data._length
            return
        ptr = data if isinstance(data, BufferPtr) else BufferPtr(data)
        if len(ptr):
            self._ptrs.append(ptr)
            self._length += len(ptr)

    def append_zero(self, n: int) -> None:
        if n > 0:
            self.append(np.zeros(n, dtype=np.uint8))

    def __len__(self) -> int:
        return self._length

    def clear(self) -> None:
        self._ptrs.clear()
        self._length = 0

    # -- reading ------------------------------------------------------------

    def to_numpy(self) -> np.ndarray:
        """Contiguous view; zero-copy when single-segment."""
        if len(self._ptrs) == 1:
            return self._ptrs[0].array
        if not self._ptrs:
            return np.empty(0, dtype=np.uint8)
        return np.concatenate([p.array for p in self._ptrs])

    def to_bytes(self) -> bytes:
        return self.to_numpy().tobytes()

    def substr(self, off: int, length: int) -> "BufferList":
        """View of [off, off+length) without copying segment bodies."""
        if off < 0 or off + length > self._length:
            raise IndexError(f"substr({off}, {length}) of {self._length}")
        out = BufferList()
        pos = 0
        remaining = length
        for p in self._ptrs:
            if remaining == 0:
                break
            seg_end = pos + len(p)
            if seg_end <= off:
                pos = seg_end
                continue
            start = max(0, off - pos)
            take = min(len(p) - start, remaining)
            out.append(p.array[start:start + take])
            remaining -= take
            pos = seg_end
        return out

    # -- layout -------------------------------------------------------------

    def is_contiguous(self) -> bool:
        return len(self._ptrs) <= 1

    def rebuild(self) -> None:
        """Coalesce into one segment (reference bufferlist::rebuild)."""
        arr = self.to_numpy().copy()
        self._ptrs = [BufferPtr(arr)] if arr.size else []

    def rebuild_aligned(self, align: int) -> None:
        """Coalesce into one segment whose base is `align`-aligned
        (reference rebuild_aligned, used by the EC benchmark at
        ceph_erasure_code_benchmark.cc:170)."""
        arr = self.to_numpy()
        padded = np.empty(arr.size + align, dtype=np.uint8)
        off = (-padded.ctypes.data) % align
        aligned = padded[off:off + arr.size]
        aligned[:] = arr
        self._ptrs = [BufferPtr(aligned)] if arr.size else []

    # -- checksum -----------------------------------------------------------

    def crc32c(self, seed: int = 0xFFFFFFFF) -> int:
        """crc over all segments, combining per-segment cached crcs
        (reference buffer.h:1199 semantics: cache hit when the same
        segment was crc'd before with a seed we can shift from)."""
        crc = seed & 0xFFFFFFFF
        for p in self._ptrs:
            # Per-segment cache is seeded at 0; combine shifts it under
            # the running crc.  (cache(0) then combine == crc(run) over
            # segment bytes, by linearity of crc.)
            seg = p.crc32c(0)
            crc = _crc.crc32c_combine(crc, seg, len(p))
        return crc
