"""Typed option schema + layered runtime config.

Re-expresses the reference's config system (src/common/options.cc —
1,602 Option() entries with type/default/min/max/enum/level/flags/
see_also — and md_config_t, src/common/config.h:55): a single typed
schema, values layered  compiled defaults < conf file < mon central
config < env < cli < injectargs,  and observer callbacks fired on
runtime change.

Only the options this framework actually reads are declared (new ones
register at import time from the subsystem that owns them — same
discipline as the reference's per-component option blocks).
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass, field
from enum import IntEnum
from typing import Any, Callable


class Level(IntEnum):
    BASIC = 0
    ADVANCED = 1
    DEV = 2


@dataclass
class Option:
    name: str
    type: type                   # int, float, str, bool
    default: Any
    desc: str = ""
    level: Level = Level.ADVANCED
    min: float | None = None
    max: float | None = None
    enum_values: tuple | None = None
    see_also: tuple = ()
    flags: tuple = ()            # e.g. ("startup",)

    def validate(self, value: Any) -> Any:
        if self.type is bool and isinstance(value, str):
            value = value.lower() in ("true", "1", "yes", "on")
        value = self.type(value)
        if self.min is not None and value < self.min:
            raise ValueError(f"{self.name}={value} < min {self.min}")
        if self.max is not None and value > self.max:
            raise ValueError(f"{self.name}={value} > max {self.max}")
        if self.enum_values and value not in self.enum_values:
            raise ValueError(
                f"{self.name}={value!r} not in {self.enum_values}")
        return value


SCHEMA: dict[str, Option] = {}


def register_options(opts: list[Option]) -> None:
    for o in opts:
        SCHEMA[o.name] = o


register_options([
    # EC (reference options.cc:564, :2610-2613)
    Option("erasure_code_dir", str, "",
           "directory for out-of-tree EC plugins", Level.ADVANCED,
           flags=("startup",)),
    Option("osd_erasure_code_plugins", str, "jerasure isa jax",
           "EC plugins to preload at daemon start", Level.ADVANCED,
           flags=("startup",)),
    Option("osd_pool_default_erasure_code_profile", str,
           "plugin=jax technique=cauchy k=8 m=3",
           "default EC profile for new pools"),
    # messenger
    Option("ms_dispatch_workers", int, 64,
           "dispatcher thread pool width", Level.ADVANCED, min=1),
    Option("ms_crc_data", bool, True, "crc-protect message payloads"),
    Option("ms_inject_socket_failures", int, 0,
           "inject a socket reset roughly every N frames (0 = off; "
           "reference ms_inject_socket_failures, options.cc:1071)",
           min=0),
    Option("ms_inject_delay_probability", float, 0.0,
           "probability of delaying a frame write (reference "
           "ms_inject_delay_probability)", min=0.0, max=1.0),
    Option("ms_inject_delay_max", float, 0.1,
           "max injected delay in seconds", min=0.0),
    Option("ms_compress", str, "",
           "on-wire frame compression algorithm (reference msgr2.1 "
           "compression / ms_osd_compress_mode); empty = off",
           enum_values=("", "zlib", "bz2", "lzma")),
    Option("ms_compress_min_size", int, 4096,
           "only compress frames at least this large (reference "
           "ms_osd_compress_min_size)", min=0),
    Option("ms_async_op_threads", int, 0,
           "reactor pool size (reference ms_async_op_threads); 0 = "
           "auto (max(1, min(4, cpu_count))).  Startup-only: the pool "
           "is created with the first messenger and pinned loops "
           "cannot be resized live", min=0, max=64,
           flags=("startup",)),
    Option("ms_sync_timeout", float, 30.0,
           "deadline of the blocking bridge into the reactor "
           "(Messenger._run_sync; was a hardcoded 30 s); expiries "
           "count in the wire ledger's msgr_sync_timeouts", min=0.1),
    # wire-plane flight recorder (docs/TRACING.md "Wire plane")
    Option("ms_ledger", bool, True,
           "record per-connection wire accounting, reactor loop-lag "
           "probes and dispatch-executor wait/run histograms in the "
           "wire-plane ledger (msg/msgr_ledger.py): feeds the "
           "`messenger status` / `conn profile` asoks, the MPGStats "
           "msgr block, the MSGR_REACTOR_LAG health warning and "
           "cluster_bench's msgr_ledger rows; off = the null fast "
           "path"),
    Option("ms_ledger_peers", int, 256,
           "peers kept per messenger in the bounded per-connection "
           "table (oldest evicted past the cap)", Level.DEV, min=1),
    Option("ms_reactor_lag_interval", float, 0.25,
           "seconds between reactor loop-lag probe fires; a probe "
           "arriving a FULL extra interval late counts as a lag event "
           "(the heartbeat tick-lag rule)", min=0.01),
    Option("ms_reactor_lag_warn_s", float, 1.0,
           "worst in-window reactor lag above which the mon raises "
           "the MSGR_REACTOR_LAG health warning (rides the MPGStats "
           "msgr block, so the mon needs no config)", min=0.0),
    Option("ms_inject_dispatch_stall", float, 0.0,
           "fault injection: sleep this long in the messenger send "
           "path before every wire write — a stalled dispatch for the "
           "slow-op blame / ledger gates", Level.DEV, min=0.0),
    # osd
    Option("osd_heartbeat_interval", float, 1.0,
           "seconds between peer pings", min=0.05),
    Option("osd_heartbeat_grace", float, 4.0,
           "missed-ping multiplier before reporting failure", min=1.0),
    Option("osd_heartbeat_min_peers", int, 10,
           "target heartbeat peer count (reference "
           "osd_heartbeat_min_peers): above this many up OSDs, each "
           "daemon pings only its ring neighbors by id instead of the "
           "full O(N^2) mesh — every OSD stays watched by ~this many "
           "reporters, which is what the mon's failure quorum needs",
           min=2),
    Option("osd_pg_stat_keepalive", float, 3.0,
           "re-send cadence for an UNCHANGED MPGStats report: a "
           "changed report still sends every osd_pg_stat_interval "
           "tick, but steady-state identical reports only refresh "
           "the mon's freshness window at this slower pace (must sit "
           "well inside the mon's 10 s PG_STAT_FRESH horizon)",
           min=0.1, max=8.0),
    Option("osd_pool_default_pg_num", int, 8, "default pg count", min=1),
    Option("osd_op_queue", str, "wpq", "op scheduler",
           enum_values=("wpq", "mclock")),
    # mClock QoS (reference osd_mclock_profile + the dmclock
    # reservation/weight/limit triples it expands to; docs/QOS.md)
    Option("osd_mclock_profile", str, "balanced",
           "named (reservation, weight, limit) preset per op class",
           enum_values=("balanced", "high_client_ops",
                        "high_recovery_ops", "custom")),
    Option("osd_mclock_custom_profile", str, "",
           "per-class overrides applied on top of the named profile: "
           "'class:res,wgt,lim;...' (res/lim in ops/sec, 0 = none); "
           "also how tenant classes get their QoS triples"),
    Option("osd_max_backfills", int, 1,
           "concurrent recovery ops per OSD", min=1),
    # repair subsystem (docs/REPAIR.md)
    Option("osd_ec_read_timeout", float, 30.0,
           "seconds a degraded EC client-read fan-out waits for shard "
           "replies before widening to parity shards / giving up; "
           "expiries count in the ec_read_timeouts perf counter "
           "(was a hardcoded 30 s in ec_backend.read)", min=0.05),
    Option("osd_ec_clay_repair", bool, True,
           "serve single-shard repair of sub-chunked (CLAY) pools "
           "from repair-plane reads + the batched GF-matmul repair "
           "plan (1/q of each helper chunk read, d helpers); off = "
           "always full-read decode"),
    Option("osd_recovery_max_bytes_per_sec", int, 0,
           "repair-bandwidth throttle: cap on rebuilt shard bytes "
           "pushed per second per OSD (token bucket; 0 = unlimited).  "
           "Client reads of degraded objects are NOT throttled — they "
           "reconstruct inline via reconstruct-on-read", min=0),
    Option("osd_recovery_sleep", float, 0.0,
           "seconds to pause between recovery object pushes "
           "(reference osd_recovery_sleep); coarse-grain brake "
           "alongside the byte-rate throttle", min=0.0),
    Option("osd_scrub_auto", bool, False, "run background scrub"),
    Option("osd_scrub_interval", float, 60.0,
           "seconds between background shallow scrubs (reference "
           "osd_scrub_min_interval)", min=0.1),
    Option("osd_deep_scrub_interval", float, 600.0,
           "seconds between background deep scrubs (reference "
           "osd_deep_scrub_interval)", min=0.1),
    Option("osd_scrub_auto_repair", bool, False,
           "repair inconsistencies found by background scrub "
           "(reference osd_scrub_auto_repair)"),
    Option("osd_pg_stat_interval", float, 0.5,
           "seconds between MPGStats reports to the mon (degraded/"
           "misplaced/unfound counts + pending split/merge pushes; "
           "reference mgr stats period).  Capped well below the "
           "mon's 10s report-freshness window (PG_STAT_FRESH) — a "
           "report that expires before its renewal would make the "
           "ok-to-stop/safe-to-destroy/merge gates flap EAGAIN",
           min=0.05, max=5.0),
    # op tracking (reference TrackedOp/OpTracker options)
    Option("osd_enable_op_tracker", bool, True,
           "track per-op event timelines (reference "
           "osd_enable_op_tracker; off = zero-cost null path)"),
    Option("osd_op_complaint_time", float, 30.0,
           "seconds before an op latches as slow and is reported to "
           "the mon (reference osd_op_complaint_time)", min=0.0),
    Option("osd_op_history_size", int, 20,
           "completed ops kept for dump_historic_ops (reference "
           "osd_op_history_size)", min=0),
    Option("osd_op_history_slow_size", int, 20,
           "slow ops kept for dump_historic_slow_ops (reference "
           "osd_op_history_slow_op_size)", min=0),
    # tpu data plane
    Option("tpu_encode_tile", int, 8192,
           "byte-axis tile of the GF matmul kernel", Level.DEV, min=128),
    Option("tpu_fused_crc", bool, True,
           "emit shard crc32c from the encode launch", Level.DEV),
    Option("tpu_batch_window_ms", float, 0.0,
           "max time to hold EC ops for cross-transaction batching",
           Level.DEV, min=0.0),
    Option("ec_dispatch_ahead_depth", int, 2,
           "max encode drains kept in flight on the device before the "
           "completion stage materializes the oldest (dispatch-ahead "
           "pipeline, docs/PIPELINE.md)", Level.DEV, min=1),
    Option("ec_dispatch_ahead", bool, False,
           "hold an always-open dispatch-ahead window on EC backends "
           "(drains materialize when pushed out by depth or by the "
           "flush timer instead of synchronously)", Level.DEV),
    Option("ec_dispatch_flush_ms", float, 2.0,
           "idle flush timer for the always-open dispatch-ahead window",
           Level.DEV, min=0.1),
    Option("osd_deep_scrub_device", bool, True,
           "verify deep-scrub crc32c with the device kernel when an "
           "accelerator backend is active (host crc fallback otherwise)",
           Level.DEV),
    # per-host EC launch queue (cross-PG continuous batching on the
    # MeshService seam; docs/PIPELINE.md "Host launch queue")
    Option("osd_ec_host_batch", bool, True,
           "route EC encode launches of every PG on the host through "
           "one per-device launch queue that coalesces runs from "
           "different PGs into super-batch launches (per-PG in-order "
           "completion and failure containment preserved); off = each "
           "PG launches its own drains"),
    Option("osd_ec_host_batch_window_us", float, 250.0,
           "max microseconds a submitted run waits in the host launch "
           "queue for co-batching before the window fires; 0 launches "
           "every submission immediately (no cross-PG batching).  A "
           "ticket finalized earlier flushes the queue on demand, so "
           "a lone synchronous writer never waits the window out",
           min=0.0),
    Option("osd_ec_host_batch_max_bytes", int, 32 << 20,
           "input-byte cap per super-batch launch (the occupancy "
           "denominator of the launch-queue counters); reaching it "
           "launches immediately", min=1 << 16),
    # device-plane flight recorder (docs/TRACING.md "Device plane")
    Option("osd_ec_profiler", bool, True,
           "record every device launch (fused/plain encode, decode, "
           "CLAY repair, scrub CRC) in the per-host launch ledger "
           "with compile attribution; off = the null fast path "
           "(ops/profiler.py)"),
    Option("osd_ec_profiler_ring", int, 256,
           "completed launch records kept in the flight-recorder "
           "ring (the `launch profile` asok tail)", Level.DEV,
           min=1, flags=("startup",)),
    Option("osd_ec_compile_stall_s", float, 0.25,
           "a first-seen jit bucket whose submit wall time exceeds "
           "this counts as a compile stall (ec_compile_stalls, "
           "slow-op first_compile blame, COMPILE_STORM events)",
           min=0.0),
    Option("osd_ec_compile_storm_budget_s", float, 5.0,
           "compile seconds inside the storm window above which the "
           "mon raises the COMPILE_STORM health warning", min=0.0),
    Option("osd_ec_compile_storm_window_s", float, 60.0,
           "sliding window for the COMPILE_STORM compile-seconds "
           "budget", min=1.0),
    Option("osd_ec_inject_compile_stall", float, 0.0,
           "fault injection: sleep this long inside the submit of "
           "every FIRST-seen jit bucket (a synthetic compile stall "
           "for the smoke/health gates)", Level.DEV, min=0.0),
    # control-plane flight recorder (docs/TRACING.md "Control plane")
    Option("osd_pg_ledger", bool, True,
           "record every PG peering/recovery/backfill transition in "
           "the per-PG state-machine ledger (osd/pg_ledger.py): "
           "timed stages feed lat_peering_*/lat_recovery_* "
           "histograms, the `pg ledger` asok, the MPGStats ledger "
           "block, and cluster_bench's recovery_blame rows; off = "
           "the null fast path"),
    Option("osd_pg_ledger_ring", int, 64,
           "state transitions kept per PG in the control-plane "
           "ledger ring (the `pg ledger` asok transition tail)",
           Level.DEV, min=1, flags=("startup",)),
    Option("osd_stuck_subwrite_s", float, 10.0,
           "an EC client write whose shard sub-writes have been in "
           "flight longer than this is surfaced as stuck_subwrite(pg) "
           "in `repair status` and slow-op blame (the PR 16 known "
           "reduction: a write wedged across a SIGKILL re-peer must "
           "be visible, not a silent active+clean stall)", min=0.0),
    # compile lifecycle: persistent cache + boot prewarm
    # (docs/PIPELINE.md "Compile lifecycle")
    Option("osd_ec_compile_cache", bool, True,
           "persist every XLA/Mosaic compile to disk "
           "(ops/compile_cache.py): a restarted daemon re-traces its "
           "jit buckets but never re-compiles them; hits surface in "
           "the compile ledger as fast first-launches, not stalls",
           flags=("startup",)),
    Option("osd_ec_compile_cache_dir", str, "",
           "persistent compile cache directory; empty = "
           "~/.cache/ceph_tpu/xla beside the autotune v2 cache "
           "(CEPH_TPU_COMPILE_CACHE also honored).  One directory per "
           "host — the first daemon to enable it wins",
           flags=("startup",)),
    Option("osd_ec_prewarm", bool, False,
           "compile the expected jit-bucket set at OSD boot BEFORE "
           "the daemon reports up (ops/prewarm.py): pow2 fused-drain "
           "widths x run counts at the autotuned point, plain-encode "
           "and single-loss decode shapes.  Off by default to keep "
           "unit-test boots cheap; benches and tier-1 churn gates "
           "turn it on", flags=("startup",)),
    Option("osd_ec_prewarm_budget_s", float, 8.0,
           "wall-clock cap on the boot-time prewarm pass; on cutoff "
           "the plan is marked truncated and the daemon boots with "
           "whatever was warmed (prewarm is an optimization, never a "
           "boot dependency)", min=0.0, flags=("startup",)),
    # multichip mesh scale-out (docs/MULTICHIP.md)
    Option("osd_ec_use_mesh", bool, False,
           "acquire the per-host MeshService multichip data plane for "
           "EC PGs: batched drains and distributed repair run as "
           "sharded collective programs across the device mesh; "
           "geometry/matrix mismatches log a config error and fall "
           "back to the single-chip codec", flags=("startup",)),
    Option("mesh_devices", str, "",
           "device mesh shape 'SHARDxDATA' (e.g. '4x2') or a device "
           "count; empty = all visible devices with the default "
           "shard-axis heuristic.  One mesh per host: the first "
           "daemon to configure it wins", flags=("startup",)),
])

# rgw bucket index sharding / dynamic resharding / quota admission
# (rgw/bucket_index.py, rgw/reshard.py, rgw/store.py); reference
# option names match src/common/options/rgw.yaml.in where one exists
register_options([
    Option("rgw_bucket_index_shards", int, 1,
           "index shard count for newly created buckets (reference "
           "rgw_override_bucket_index_max_shards); 1 keeps the legacy "
           "single directory object layout", min=1),
    Option("rgw_max_objs_per_shard", int, 100_000,
           "dynamic-reshard trigger: when a bucket's entry count "
           "exceeds shards*this, the reshard sweep scales the shard "
           "count to the next power of two that brings the per-shard "
           "load back under it", min=1),
    Option("rgw_reshard_max_shards", int, 64,
           "ceiling on automatic reshard targets (manual 'bucket "
           "reshard' may still exceed it)", min=1),
    Option("rgw_reshard_grace_s", float, 0.25,
           "dwell in the dual-write state before copying begins: "
           "writers that read the bucket meta just before the reshard "
           "marker landed finish their single-layout writes inside "
           "this window, so the copier's old-shard pages see them",
           min=0.0),
    Option("rgw_reshard_batch", int, 512,
           "entries per dir_merge page while copying a shard (one "
           "atomic class call each)", min=1),
    Option("rgw_quota_reservation_ttl_s", float, 30.0,
           "lifetime of a cls_user quota reservation; a writer that "
           "died between reserve and release stops counting against "
           "its user's quota after this", min=0.0),
])


class Config:
    """Layered md_config_t equivalent with change observers."""

    LAYERS = ("default", "file", "mon", "env", "cli", "override")

    def __init__(self) -> None:
        self._layers: dict[str, dict[str, Any]] = {
            layer: {} for layer in self.LAYERS}
        self._observers: dict[str, list[Callable[[str, Any], None]]] = {}
        self._lock = threading.RLock()
        for name, opt in SCHEMA.items():
            self._layers["default"][name] = opt.default
        # CEPH_TPU_<OPTION> env overrides (reference env layer)
        for name in SCHEMA:
            env = os.environ.get(f"CEPH_TPU_{name.upper()}")
            if env is not None:
                self._layers["env"][name] = SCHEMA[name].validate(env)

    def get(self, name: str) -> Any:
        with self._lock:
            for layer in reversed(self.LAYERS):
                if name in self._layers[layer]:
                    return self._layers[layer][name]
        raise KeyError(f"unknown option {name}")

    def set(self, name: str, value: Any, layer: str = "override") -> None:
        opt = SCHEMA.get(name)
        if opt is None:
            raise KeyError(f"unknown option {name}")
        value = opt.validate(value)
        with self._lock:
            old = self.get(name)
            self._layers[layer][name] = value
            observers = list(self._observers.get(name, []))
        if value != old:
            for cb in observers:
                cb(name, value)

    def apply_mon_layer(self, values: dict[str, Any]) -> None:
        """Replace the 'mon' layer wholesale with the central-config
        sections relevant to this daemon (reference ConfigMonitor ->
        MConfig push).  Keys the schema doesn't know are skipped (a
        newer mon may carry options this build lacks); observers fire
        for every effectively-changed option — including ones whose
        mon override was REMOVED (they fall back to a lower layer)."""
        validated: dict[str, Any] = {}
        for name, raw in values.items():
            opt = SCHEMA.get(name)
            if opt is None:
                continue
            try:
                validated[name] = opt.validate(raw)
            except (ValueError, TypeError):
                continue
        with self._lock:
            touched = set(self._layers["mon"]) | set(validated)
            old = {name: self.get(name) for name in touched}
            self._layers["mon"] = validated
            changed = [(name, self.get(name)) for name in touched
                       if self.get(name) != old[name]]
            observers = [(cb, name, val) for name, val in changed
                         for cb in self._observers.get(name, [])]
        for cb, name, val in observers:
            # isolate observer failures: the layer is already swapped,
            # so a skipped notification would never be retried — one
            # bad consumer must not eat its siblings' callbacks
            try:
                cb(name, val)
            except Exception:  # noqa: BLE001
                import traceback
                traceback.print_exc()

    def add_observer(self, name: str,
                     cb: Callable[[str, Any], None]) -> None:
        with self._lock:
            self._observers.setdefault(name, []).append(cb)

    def show(self) -> dict[str, Any]:
        with self._lock:
            return {name: self.get(name) for name in sorted(SCHEMA)}

    def inject_args(self, args: str) -> None:
        """`injectargs`-style "--opt value --flag" runtime updates."""
        toks = args.split()
        i = 0
        while i < len(toks):
            name = toks[i].lstrip("-").replace("-", "_")
            if i + 1 < len(toks) and not toks[i + 1].startswith("--"):
                self.set(name, toks[i + 1])
                i += 2
            else:
                self.set(name, True)
                i += 1
