"""Lockdep: lock-order cycle detection (reference src/common/lockdep.cc).

The reference registers every named mutex, records the per-thread
acquisition ORDER as a directed graph, and aborts when an acquisition
would close a cycle — catching ABBA deadlocks on the first run that
exercises both orders, even if the timing never actually deadlocks.

Same design here, as an opt-in instrument (the reference enables
lockdep in debug builds and test runs only):

    handle = lockdep.instrument()
    try:
        ... run the workload ...
    finally:
        handle.restore()
    handle.check()     # raises LockOrderError on any cycle seen

instrument() patches threading.Lock/RLock so EVERY lock created while
instrumented participates — daemon-internal locks included, no code
changes.  Edges record the stacks of both acquisitions so a report
says who took what in which order.  RLock re-entry and locks acquired
with blocking=False that fail are ignored (neither can deadlock).
"""

from __future__ import annotations

import threading
import traceback

_real_lock = threading.Lock
_real_rlock = threading.RLock


class LockOrderError(AssertionError):
    pass


class _Graph:
    """Order graph: edge a->b = 'a was held while acquiring b'."""

    def __init__(self):
        self.edges: dict[int, set[int]] = {}
        self.names: dict[int, str] = {}
        self.sites: dict[tuple[int, int], str] = {}
        self.cycles: list[str] = []
        self.mu = _real_lock()

    def _reaches(self, src: int, dst: int) -> bool:
        seen = set()
        stack = [src]
        while stack:
            n = stack.pop()
            if n == dst:
                return True
            if n in seen:
                continue
            seen.add(n)
            stack.extend(self.edges.get(n, ()))
        return False

    def add_edge(self, a: int, b: int, site: str) -> None:
        with self.mu:
            if b in self.edges.get(a, ()):
                return
            if self._reaches(b, a):
                back = self.sites.get((b, a)) or next(
                    (self.sites[(b, x)] for x in self.edges.get(b, ())
                     if (b, x) in self.sites), "?")
                self.cycles.append(
                    f"lock order cycle: {self.names.get(a, a)} -> "
                    f"{self.names.get(b, b)} at\n{site}\n"
                    f"while the reverse order was seen at\n{back}")
                return
            self.edges.setdefault(a, set()).add(b)
            self.sites[(a, b)] = site


class _Held(threading.local):
    def __init__(self):
        self.stack: list[int] = []


class _LockdepBase:
    """Shared wrapper: order tracking around a real lock."""

    _factory = None

    def __init__(self, name: str | None = None):
        self._lk = self._factory()
        # serial ids, not id(self): a GC'd lock's reused address would
        # inherit stale graph edges and report false cycles (the
        # reference lockdep unregisters freed locks for the same
        # reason)
        with _SERIAL_MU:
            _STATE["serial"] += 1
            self._id = _STATE["serial"]
        g = _STATE["graph"]
        if g is not None:
            g.names[self._id] = name or \
                f"{type(self).__name__}#{self._id}"

    def _record(self):
        g = _STATE["graph"]
        if g is None:
            return
        held = _STATE["held"].stack
        if held and held[-1] != self._id:
            site = "".join(traceback.format_stack(limit=8)[:-2])
            for h in held:
                if h != self._id:
                    g.add_edge(h, self._id, site)

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            self._record()
        ok = self._lk.acquire(blocking, timeout) if timeout != -1 else \
            self._lk.acquire(blocking)
        if ok:
            _STATE["held"].stack.append(self._id)
        return ok

    def release(self):
        held = _STATE["held"].stack
        if self._id in held:
            held.reverse()
            held.remove(self._id)
            held.reverse()
        self._lk.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._lk.locked()


class LockdepLock(_LockdepBase):
    _factory = staticmethod(_real_lock)


class LockdepRLock(_LockdepBase):
    _factory = staticmethod(_real_rlock)

    def _record(self):
        # re-entry of a held RLock cannot deadlock: skip the edge
        if self._id in _STATE["held"].stack:
            return
        super()._record()

    # (release bookkeeping is inherited from _LockdepBase)

    # Condition-variable hooks MUST come from the real RLock: the
    # stdlib's generic _is_owned fallback probes acquire(False), which
    # SUCCEEDS on a reentrant lock the caller owns and misreports
    # "un-acquired" (breaking every Future/Event built on Condition()).
    def _is_owned(self):
        return self._lk._is_owned()

    def _release_save(self):
        return self._lk._release_save()

    def _acquire_restore(self, state):
        return self._lk._acquire_restore(state)


_STATE: dict = {"graph": None, "held": _Held(), "serial": 0}
_SERIAL_MU = _real_lock()


class Handle:
    def __init__(self, graph: _Graph):
        self.graph = graph

    def restore(self) -> None:
        threading.Lock = _real_lock
        threading.RLock = _real_rlock
        _STATE["graph"] = None

    def check(self) -> None:
        """Raise if any acquisition closed an order cycle."""
        if self.graph.cycles:
            raise LockOrderError(
                f"{len(self.graph.cycles)} lock-order cycle(s):\n\n"
                + "\n\n".join(self.graph.cycles[:5]))

    def edge_count(self) -> int:
        return sum(len(v) for v in self.graph.edges.values())


def instrument() -> Handle:
    """Patch threading.Lock/RLock so every lock created from now on is
    order-tracked; returns the handle for restore()/check()."""
    # stdlib modules that lazily self-initialize with threading.Lock at
    # first import must load BEFORE the patch
    import concurrent.futures.thread  # noqa: F401
    graph = _Graph()
    _STATE["graph"] = graph
    threading.Lock = LockdepLock
    threading.RLock = LockdepRLock
    return Handle(graph)
