"""crc32c (Castagnoli) — the framework's data-plane checksum.

Same conventions as the reference's `bufferlist::crc32c`
(src/include/buffer.h:1199, src/common/crc32c.cc): reflected polynomial
0x82F63B78, caller-supplied seed, no final xor (callers that want the
RFC "crc32c of a message" semantics pass 0xffffffff and invert).

Paths: native SSE4.2/slice-by-8 via ctypes (ceph_tpu.common.native),
numpy table fallback, plus `crc32c_zeros`/`combine` (extend a crc over a
gap without touching memory — the reference's ceph_crc32c_zeros role,
and the host-side half of the TPU fused-crc design: per-tile crcs from
the kernel are folded together with combine).
"""

from __future__ import annotations

import functools

import numpy as np

from . import native

POLY_REFLECTED = 0x82F63B78


@functools.lru_cache(maxsize=1)
def _sw_table() -> np.ndarray:
    t = np.zeros(256, dtype=np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ POLY_REFLECTED if c & 1 else c >> 1
        t[i] = c
    return t


def _crc32c_sw(crc: int, data: bytes) -> int:
    t = _sw_table()
    c = np.uint32(crc)
    tl = t
    for b in np.frombuffer(data, dtype=np.uint8):
        c = np.uint32(tl[(c ^ b) & np.uint32(0xFF)] ^ (c >> np.uint32(8)))
    return int(c)


def crc32c(data, crc: int = 0xFFFFFFFF) -> int:
    """crc32c of `data` seeded with `crc` (default matches bufferlist's -1
    convention for standalone checksums)."""
    buf = bytes(data) if not isinstance(data, (bytes, bytearray)) else data
    lib = native.load()
    if lib is not None:
        return lib.ceph_tpu_crc32c(crc & 0xFFFFFFFF, bytes(buf), len(buf))
    return _crc32c_sw(crc & 0xFFFFFFFF, bytes(buf))


def crc32c_rows(rows: np.ndarray, seeds) -> list[int]:
    """Per-row crc32c of a (R, L) byte matrix, row r seeded seeds[r] —
    the host fold of one encoded run's k+m shard rows in a single pass
    (HashInfo.append and the ECBackend plain-path drain fold).  Native
    path: one C call per row, no intermediate Python structures; table
    fallback: ONE walk over the byte axis updating all R states per
    column (R-wide vectorized, vs R separate byte loops)."""
    rows = np.ascontiguousarray(rows, dtype=np.uint8)
    lib = native.load()
    if lib is not None:
        return [lib.ceph_tpu_crc32c(int(s) & 0xFFFFFFFF,
                                    rows[r].tobytes(), rows.shape[1])
                for r, s in enumerate(seeds)]
    t = _sw_table()
    c = np.array([int(s) & 0xFFFFFFFF for s in seeds], dtype=np.uint32)
    for col in rows.T:
        c = t[(c ^ col) & np.uint32(0xFF)] ^ (c >> np.uint32(8))
    return [int(v) for v in c]


def crc32c_zeros(crc: int, length: int) -> int:
    """Advance `crc` over `length` zero bytes in O(log length)."""
    if length == 0:
        return crc
    lib = native.load()
    if lib is not None:
        return lib.ceph_tpu_crc32c_zeros(crc & 0xFFFFFFFF, length)
    return _zeros_sw(crc & 0xFFFFFFFF, length)


def crc32c_combine(crc_a: int, crc_b: int, len_b: int) -> int:
    """crc of A||B from crc(A) (seeded arbitrarily) and crc(B) (seeded 0)."""
    return crc32c_zeros(crc_a, len_b) ^ crc_b


# -- software combine (GF(2) matrix squaring, zlib-style) -------------------

def _gf2_times(mat: list[int], vec: int) -> int:
    s = 0
    i = 0
    while vec:
        if vec & 1:
            s ^= mat[i]
        vec >>= 1
        i += 1
    return s


def _gf2_square(mat: list[int]) -> list[int]:
    return [_gf2_times(mat, m) for m in mat]


@functools.lru_cache(maxsize=1)
def _byte_matrix() -> tuple[int, ...]:
    odd = [POLY_REFLECTED] + [1 << (i - 1) for i in range(1, 32)]
    even = _gf2_square(odd)    # 2 bits
    odd = _gf2_square(even)    # 4
    even = _gf2_square(odd)    # 8 bits = 1 byte
    return tuple(even)


def _zeros_sw(crc: int, length: int) -> int:
    cur = list(_byte_matrix())
    n = length
    while True:
        if n & 1:
            crc = _gf2_times(cur, crc)
        n >>= 1
        if not n:
            return crc
        cur = _gf2_square(cur)
