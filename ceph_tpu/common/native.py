"""ctypes loader for the native data-plane library (native/).

Builds on demand with `make -C native` the first time, caches the .so.
Every entry point has a pure-python fallback, so the framework works
without a C toolchain — but the native path is what makes the CPU
baseline honest (reference analog: crc32c_intel_fast + ISA-L/gf-complete
SIMD kernels vs their table fallbacks).
"""

from __future__ import annotations

import ctypes
import subprocess
import threading
from pathlib import Path

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libceph_tpu_native.so"

_lock = threading.Lock()
_lib: ctypes.CDLL | None = None
_tried = False


def load() -> ctypes.CDLL | None:
    """Load (building if needed) the native library; None if unavailable."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if not _LIB_PATH.exists():
                subprocess.run(["make", "-C", str(_NATIVE_DIR), "-s"],
                               check=True, capture_output=True, timeout=120)
            lib = ctypes.CDLL(str(_LIB_PATH))
        except Exception:  # noqa: BLE001 - fall back to pure python
            return None
        lib.ceph_tpu_crc32c.restype = ctypes.c_uint32
        lib.ceph_tpu_crc32c.argtypes = [
            ctypes.c_uint32, ctypes.c_char_p, ctypes.c_size_t]
        lib.ceph_tpu_crc32c_zeros.restype = ctypes.c_uint32
        lib.ceph_tpu_crc32c_zeros.argtypes = [ctypes.c_uint32, ctypes.c_uint64]
        lib.ceph_tpu_crc32c_combine.restype = ctypes.c_uint32
        lib.ceph_tpu_crc32c_combine.argtypes = [
            ctypes.c_uint32, ctypes.c_uint32, ctypes.c_uint64]
        lib.gf8_init.restype = None
        lib.gf8_mul_region_xor.restype = None
        lib.gf8_mul_region_xor.argtypes = [
            ctypes.c_uint8, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_size_t]
        lib.gf8_encode.restype = None
        lib.gf8_encode.argtypes = [
            ctypes.c_int, ctypes.c_int, ctypes.c_void_p,
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_void_p),
            ctypes.c_size_t]
        lib.gf8_init()
        _lib = lib
        return _lib


def available() -> bool:
    return load() is not None


def gf8_matvec(mat, chunks):
    """Native GF(2^8) matrix x chunks product: (r, k) x (k, n) -> (r, n).

    Returns None when the native library is unavailable (caller falls
    back to the numpy LUT path).
    """
    import numpy as np
    lib = load()
    if lib is None:
        return None
    mat = np.ascontiguousarray(mat, dtype=np.uint8)
    chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
    r, k = mat.shape
    n = chunks.shape[1]
    out = np.empty((r, n), dtype=np.uint8)
    data_ptrs = (ctypes.c_void_p * k)(
        *[chunks[j].ctypes.data for j in range(k)])
    par_ptrs = (ctypes.c_void_p * r)(
        *[out[i].ctypes.data for i in range(r)])
    lib.gf8_encode(k, r, mat.ctypes.data, data_ptrs, par_ptrs, n)
    return out
