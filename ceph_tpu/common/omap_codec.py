"""Omap payload framing for client<->OSD op payloads.

The reference encodes omap kv maps with ceph::encode into the op's
bufferlist (osd/osd_types wire maps consumed by the OMAP cases of
PrimaryLogPG::do_osd_ops, PrimaryLogPG.cc:5643).  Here the equivalent
is a minimal length-prefixed binary form shared by librados and the
OSD: u32 count, then per entry u32 klen + key [+ u32 vlen + value].
"""

from __future__ import annotations

import struct

_U32 = struct.Struct("<I")


def encode_kv(kv: dict[bytes, bytes]) -> bytes:
    out = [_U32.pack(len(kv))]
    for k, v in kv.items():
        out.append(_U32.pack(len(k)))
        out.append(k)
        out.append(_U32.pack(len(v)))
        out.append(v)
    return b"".join(out)


def decode_kv(buf: bytes, off: int = 0) -> tuple[dict[bytes, bytes], int]:
    (n,) = _U32.unpack_from(buf, off)
    off += 4
    kv: dict[bytes, bytes] = {}
    for _ in range(n):
        (kl,) = _U32.unpack_from(buf, off)
        off += 4
        k = bytes(buf[off:off + kl])
        off += kl
        (vl,) = _U32.unpack_from(buf, off)
        off += 4
        kv[k] = bytes(buf[off:off + vl])
        off += vl
    return kv, off


def encode_keys(keys) -> bytes:
    keys = list(keys)
    out = [_U32.pack(len(keys))]
    for k in keys:
        out.append(_U32.pack(len(k)))
        out.append(bytes(k))
    return b"".join(out)


def decode_keys(buf: bytes, off: int = 0) -> tuple[list[bytes], int]:
    (n,) = _U32.unpack_from(buf, off)
    off += 4
    keys: list[bytes] = []
    for _ in range(n):
        (kl,) = _U32.unpack_from(buf, off)
        off += 4
        keys.append(bytes(buf[off:off + kl]))
        off += kl
    return keys, off
