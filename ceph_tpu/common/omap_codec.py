"""Omap payload framing for client<->OSD op payloads.

The reference encodes omap kv maps with ceph::encode into the op's
bufferlist (osd/osd_types wire maps consumed by the OMAP cases of
PrimaryLogPG::do_osd_ops, PrimaryLogPG.cc:5643).  Here the equivalent
is a minimal length-prefixed binary form shared by librados and the
OSD: u32 count, then per entry u32 klen + key [+ u32 vlen + value].
"""

from __future__ import annotations

import struct

_U32 = struct.Struct("<I")


def encode_kv(kv: dict[bytes, bytes]) -> bytes:
    out = [_U32.pack(len(kv))]
    for k, v in kv.items():
        out.append(_U32.pack(len(k)))
        out.append(k)
        out.append(_U32.pack(len(v)))
        out.append(v)
    return b"".join(out)


def _take(buf: bytes, off: int, ln: int) -> tuple[bytes, int]:
    """Bounds-checked slice: a hostile/corrupt length must raise a
    clean ValueError (mapped to -EINVAL at the op switch), never a
    struct.error past the end or a silent truncation."""
    if ln < 0 or off + ln > len(buf):
        raise ValueError(
            f"omap frame truncated: need {ln} bytes at {off}, "
            f"have {len(buf)}")
    return bytes(buf[off:off + ln]), off + ln


def _u32(buf: bytes, off: int) -> tuple[int, int]:
    if off + 4 > len(buf):
        raise ValueError(f"omap frame truncated at {off}")
    return _U32.unpack_from(buf, off)[0], off + 4


def decode_kv(buf: bytes, off: int = 0) -> tuple[dict[bytes, bytes], int]:
    n, off = _u32(buf, off)
    kv: dict[bytes, bytes] = {}
    for _ in range(n):
        kl, off = _u32(buf, off)
        k, off = _take(buf, off, kl)
        vl, off = _u32(buf, off)
        kv[k], off = _take(buf, off, vl)
    return kv, off


def encode_keys(keys) -> bytes:
    keys = list(keys)
    out = [_U32.pack(len(keys))]
    for k in keys:
        out.append(_U32.pack(len(k)))
        out.append(bytes(k))
    return b"".join(out)


def decode_keys(buf: bytes, off: int = 0) -> tuple[list[bytes], int]:
    n, off = _u32(buf, off)
    keys: list[bytes] = []
    for _ in range(n):
        kl, off = _u32(buf, off)
        k, off = _take(buf, off, kl)
        keys.append(k)
    return keys, off
