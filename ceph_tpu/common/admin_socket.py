"""Admin socket: per-daemon out-of-band introspection.

Re-expresses the reference's AdminSocket (src/common/admin_socket.h:105):
a unix-domain socket every daemon exposes regardless of cluster health,
answering JSON commands — `perf dump`, `config show`, `config set`,
`dump_ops_in_flight`, plus commands components register at runtime.

Protocol: client sends one JSON line {"prefix": ...}, daemon replies
with a 4-byte big-endian length + JSON body (close enough to the
reference's framing to feel familiar, simple enough for `nc -U`).
"""

from __future__ import annotations

import json
import os
import socket
import struct
import threading
from typing import Callable

Handler = Callable[[dict], dict]


class AdminSocket:
    def __init__(self, path: str):
        self.path = path
        self._handlers: dict[str, Handler] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        if os.path.exists(path):
            os.unlink(path)
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.bind(path)
        self._sock.listen(8)
        self._sock.settimeout(0.25)
        self._thread = threading.Thread(target=self._serve, daemon=True,
                                        name=f"asok:{os.path.basename(path)}")
        self._thread.start()

    def register_command(self, prefix: str, handler: Handler) -> None:
        with self._lock:
            self._handlers[prefix] = handler

    def shutdown(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)
        try:
            self._sock.close()
            os.unlink(self.path)
        except OSError:
            pass

    # -- server -------------------------------------------------------------

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._handle, args=(conn,),
                             daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(5)
            data = b""
            while not data.endswith(b"\n"):
                chunk = conn.recv(65536)
                if not chunk:
                    break
                data += chunk
            req = json.loads(data.decode() or "{}")
            prefix = req.get("prefix", "")
            with self._lock:
                handler = self._handlers.get(prefix)
            if handler is None:
                reply = {"error": f"unknown command {prefix!r}",
                         "known": sorted(self._handlers)}
            else:
                reply = handler(req)
            body = json.dumps(reply).encode()
            conn.sendall(struct.pack(">I", len(body)) + body)
        except Exception as e:  # noqa: BLE001
            try:
                body = json.dumps({"error": repr(e)}).encode()
                conn.sendall(struct.pack(">I", len(body)) + body)
            except OSError:
                pass
        finally:
            conn.close()


def admin_command(path: str, cmd: dict, timeout: float = 5.0) -> dict:
    """Client side: one round trip to a daemon's admin socket."""
    s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    s.settimeout(timeout)
    try:
        s.connect(path)
        s.sendall(json.dumps(cmd).encode() + b"\n")
        raw = b""
        while len(raw) < 4:
            raw += s.recv(4 - len(raw))
        (ln,) = struct.unpack(">I", raw)
        body = b""
        while len(body) < ln:
            body += s.recv(ln - len(body))
        return json.loads(body.decode())
    finally:
        s.close()
