"""Erasure-code subsystem (reference src/erasure-code/)."""

from .interface import ErasureCodeError, ErasureCodeInterface, Profile
from .registry import ErasureCodePlugin, ErasureCodePluginRegistry

__all__ = [
    "ErasureCodeError",
    "ErasureCodeInterface",
    "Profile",
    "ErasureCodePlugin",
    "ErasureCodePluginRegistry",
]
