"""CPU RS plugin with cached decode tables ("isa" role).

Fills the role of the reference's ISA-L plugin
(src/erasure-code/isa/ErasureCodeIsa.{h,cc}): Vandermonde or Cauchy
matrices, an LRU cache of decode matrices keyed by the erasure signature
(reference ErasureCodeIsaTableCache.{h,cc}, "good up to (12,4)"), and a
pure-XOR fast path when exactly one data chunk is lost and m>=1 row of
ones exists (reference xor_op.h:74 region_xor).

The heavy region kernels here are numpy LUT ops; the honest "CPU best"
baseline additionally dispatches to the native C library when built (see
native/, loaded via ceph_tpu.common.native).
"""

from __future__ import annotations

import errno
import threading
from collections import OrderedDict

import numpy as np

from .. import gf
from ..base import ErasureCode
from ..interface import ErasureCodeError, Profile
from ..registry import ErasureCodePlugin, ErasureCodePluginRegistry

__erasure_code_version__ = ErasureCodePlugin.abi_version


class DecodeTableCache:
    """LRU cache of inverted decode matrices keyed by (k, m, erasures).

    Reference: ErasureCodeIsaTableCache caches `ec_init_tables` outputs per
    erasure signature so repeated degraded reads skip the inversion.
    """

    def __init__(self, capacity: int = 2516):  # reference cache ~ (12,4) space
        self.capacity = capacity
        self.lock = threading.Lock()
        self._cache: OrderedDict[tuple, np.ndarray] = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, key: tuple) -> np.ndarray | None:
        with self.lock:
            m = self._cache.get(key)
            if m is not None:
                self.hits += 1
                self._cache.move_to_end(key)
            else:
                self.misses += 1
            return m

    def put(self, key: tuple, mat: np.ndarray) -> None:
        with self.lock:
            self._cache[key] = mat
            self._cache.move_to_end(key)
            while len(self._cache) > self.capacity:
                self._cache.popitem(last=False)


_TABLE_CACHE = DecodeTableCache()


class ErasureCodeIsa(ErasureCode):
    technique = "reed_sol_van"

    # encode_chunks is exactly gf_matvec(matrix[k:]): equal matrices
    # mean bit-equal parity, so instances may co-batch in the per-host
    # launch queue (parallel/launch_queue.codec_signature)
    matrix_determines_encode = True

    def __init__(self, technique: str = "reed_sol_van"):
        super().__init__()
        self.technique = technique
        self.matrix: np.ndarray | None = None

    def init(self, profile: Profile) -> None:
        self.k = profile.to_int("k", 7)
        self.m = profile.to_int("m", 3)
        if self.k < 1 or self.m < 1 or self.k + self.m > gf.GF_SIZE:
            raise ErasureCodeError(errno.EINVAL, f"bad k={self.k} m={self.m}")
        if self.technique == "cauchy":
            self.matrix = gf.cauchy_rs_matrix(self.k, self.m)
        else:
            self.matrix = gf.vandermonde_rs_matrix(self.k, self.m)
        super().init(profile)

    def encode_chunks(self, chunks: np.ndarray) -> np.ndarray:
        return gf.gf_matvec(self.matrix[self.k:], chunks)

    def decode_chunks(self, dense: np.ndarray, erasures) -> np.ndarray:
        n = self.get_chunk_count()
        erased = sorted(set(erasures))
        survivors = [i for i in range(n) if i not in set(erased)][: self.k]
        if len(survivors) < self.k:
            raise ErasureCodeError(errno.EIO, "not enough survivors")
        out = dense.copy()

        # Fast path: single erasure recoverable by pure XOR when the
        # decode row is all-ones (always true for the XOR parity row of a
        # Vandermonde systematic matrix when only that relation is needed).
        if len(erased) == 1 and erased[0] < self.k:
            row = self._decode_rows(tuple(survivors), tuple(erased))[0]
            if set(np.unique(row)) <= {0, 1}:
                acc = np.zeros_like(out[0])
                for j, s in enumerate(survivors):
                    if row[j]:
                        acc ^= dense[s]
                out[erased[0]] = acc
                return out

        need_data = [e for e in erased if e < self.k]
        if need_data:
            rows = self._decode_rows(tuple(survivors), tuple(need_data))
            rec = gf.gf_matvec(rows, dense[survivors])
            for idx, e in enumerate(need_data):
                out[e] = rec[idx]
        need_par = [e for e in erased if e >= self.k]
        if need_par:
            rec = gf.gf_matvec(self.matrix[need_par, :], out[: self.k])
            for idx, e in enumerate(need_par):
                out[e] = rec[idx]
        return out

    def _decode_rows(self, survivors: tuple, targets: tuple) -> np.ndarray:
        key = (self.k, self.m, self.technique, survivors, targets)
        rows = _TABLE_CACHE.get(key)
        if rows is None:
            inv = gf.gf_invert_matrix(self.matrix[list(survivors), :])
            rows = np.stack([inv[t] for t in targets])
            _TABLE_CACHE.put(key, rows)
        return rows


class ErasureCodePluginIsa(ErasureCodePlugin):
    def factory(self, profile: Profile):
        technique = profile.get("technique", "reed_sol_van") or "reed_sol_van"
        if technique not in ("reed_sol_van", "cauchy"):
            raise ErasureCodeError(
                errno.ENOENT, f"unknown isa technique {technique!r}")
        return ErasureCodeIsa(technique)


def __erasure_code_init__(name: str, directory: str | None) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginIsa())
