"""SHEC plugin: Shingled Erasure Code.

Fills the role of reference src/erasure-code/shec/ErasureCodeShec.{h,cc}
(k, m, c profile): m parity chunks each covering a sliding window
("shingle") of the data chunks, overlapping so that any failure pattern
of up to c chunks is recoverable while single-failure recovery reads
fewer than k chunks (recovery efficiency is the point of SHEC).

Construction: parity row i covers a cyclic window of
w = k - floor((m - c) * k / m) ... following the published SHEC layout
intent, we size windows as w = ceil(k * c / m) + (k mod?) — rather than
replicate the reference's exact matrix, we place windows of width
w = k - (m - c) evenly and fill coefficients from a Cauchy row so each
window submatrix is MDS-like, then VERIFY at init() by brute force that
every erasure pattern of size <= c is decodable (k+m is small; this
check is the contract the reference's recovery-efficiency calculators
assume).  minimum_to_decode returns, for each erasure set, a minimal
hitting set of covering windows — fewer chunks than k for local
failures.
"""

from __future__ import annotations

import errno
import itertools

import numpy as np

from .. import gf
from ..base import ErasureCode
from ..interface import ErasureCodeError, Profile
from ..registry import ErasureCodePlugin, ErasureCodePluginRegistry

__erasure_code_version__ = ErasureCodePlugin.abi_version


class ErasureCodeShec(ErasureCode):
    ALLOW_PARTIAL_DECODE = True

    def __init__(self):
        super().__init__()
        self.c = 0
        self.matrix: np.ndarray | None = None  # (m, k) with zero outside windows
        self.windows: list[list[int]] = []

    def init(self, profile: Profile) -> None:
        self.k = profile.to_int("k", 4)
        self.m = profile.to_int("m", 3)
        self.c = profile.to_int("c", 2)
        if not (1 <= self.c <= self.m <= self.k + self.m):
            raise ErasureCodeError(
                errno.EINVAL, f"bad k={self.k} m={self.m} c={self.c}")
        if self.c > self.m:
            raise ErasureCodeError(errno.EINVAL, "c must be <= m")
        self._build_matrix()
        super().init(profile)

    def _build_matrix(self) -> None:
        k, m, c = self.k, self.m, self.c
        # window width: each parity covers w consecutive (cyclic) data
        # chunks; total coverage m*w must give every chunk >= c covers.
        w = max(2, -(-k * c // m))
        if w > k:
            w = k
        cauchy = gf.cauchy_rs_matrix(k, m)[k:]
        mat = np.zeros((m, k), dtype=np.uint8)
        self.windows = []
        for i in range(m):
            start = (i * k) // m
            cols = [(start + j) % k for j in range(w)]
            self.windows.append(sorted(set(cols)))
            for j in cols:
                mat[i, j] = cauchy[i, j] if cauchy[i, j] else 1
        self.matrix = mat
        # Contract check: every erasure pattern of size <= c decodable.
        n = k + m
        for r in range(1, c + 1):
            for pattern in itertools.combinations(range(n), r):
                if not self._decodable(set(pattern)):
                    raise ErasureCodeError(
                        errno.EINVAL,
                        f"shec k={k} m={m} c={c}: pattern {pattern} "
                        f"not recoverable; profile unsupported")

    def _full_matrix(self) -> np.ndarray:
        g = np.zeros((self.k + self.m, self.k), dtype=np.uint8)
        g[: self.k] = np.eye(self.k, dtype=np.uint8)
        g[self.k:] = self.matrix
        return g

    def _decodable(self, erased: set[int]) -> bool:
        data_erased = [e for e in erased if e < self.k]
        if not data_erased:
            return True
        avail_parity = [i for i in range(self.m)
                        if self.k + i not in erased]
        avail_data = [j for j in range(self.k) if j not in erased]
        # rank test: can the erased data columns be solved from available
        # parity rows restricted to erased columns?
        rows = []
        for i in avail_parity:
            rows.append([self.matrix[i, j] for j in data_erased])
        a = np.array(rows, dtype=np.uint8) if rows else \
            np.zeros((0, len(data_erased)), dtype=np.uint8)
        return self._gf_rank(a) == len(data_erased)

    @staticmethod
    def _gf_rank(a: np.ndarray) -> int:
        a = a.astype(np.uint8).copy()
        rank = 0
        rows, cols = a.shape
        for col in range(cols):
            piv = next((r for r in range(rank, rows) if a[r, col]), None)
            if piv is None:
                continue
            a[[rank, piv]] = a[[piv, rank]]
            lut = gf.mul_table()[gf.gf_inv(int(a[rank, col]))]
            a[rank] = lut[a[rank]]
            for r in range(rows):
                if r != rank and a[r, col]:
                    a[r] ^= gf.mul_table()[int(a[r, col])][a[rank]]
            rank += 1
        return rank

    # -- codec --------------------------------------------------------------

    def encode_chunks(self, chunks: np.ndarray) -> np.ndarray:
        return gf.gf_matvec(self.matrix, chunks)

    def minimum_to_decode(self, want_to_read, available):
        want = set(want_to_read)
        avail = set(available)
        missing = want - avail
        if not missing:
            return {i: [(0, 1)] for i in want}
        need: set[int] = set(want & avail)
        if len(missing) <= self.c:
            helper_set = self._local_helpers(missing, avail)
            if helper_set is not None:
                return {h: [(0, 1)] for h in (helper_set | need)}
        # generic: any k available data+parity chunks that decode
        usable = sorted(avail)
        if len(usable) < self.k:
            raise ErasureCodeError(errno.EIO, "shec: not enough chunks")
        return {i: [(0, 1)] for i in (set(usable[: self.k]) | need)}

    def _local_helpers(self, missing: set[int],
                       avail: set[int]) -> set[int] | None:
        """Smallest window-based helper set that recovers `missing`, or
        None when no local recovery exists (the recovery-efficiency path
        the reference's shec calculators optimize)."""
        helpers: set[int] = set()
        parities: list[int] = []
        data_missing = sorted(e for e in missing if e < self.k)
        for e in data_missing:
            cover = [i for i in range(self.m)
                     if e in self.windows[i] and (self.k + i) in avail
                     and (self.k + i) not in missing]
            if not cover:
                return None
            # prefer a window whose other members are all available
            cover.sort(key=lambda i: sum(
                1 for j in self.windows[i] if j != e and j not in avail))
            i = cover[0]
            parities.append(i)
            helpers.add(self.k + i)
            helpers |= {j for j in self.windows[i] if j != e}
        # lost parity chunks rebuild from their window's data directly
        for e in (e for e in missing if e >= self.k):
            helpers |= set(self.windows[e - self.k])
        if not helpers <= avail:
            return None
        # solvability: chosen parity rows restricted to the missing data
        # columns must have full rank (all other window terms are in
        # helpers, hence known)
        if data_missing:
            a = np.array([[self.matrix[i, j] for j in data_missing]
                          for i in parities], dtype=np.uint8)
            if self._gf_rank(a) < len(data_missing):
                return None
        return helpers

    def decode(self, want_to_read, chunks, chunk_size):
        self._unsolved = set()   # base may shortcut past decode_chunks
        out = super().decode(want_to_read, chunks, chunk_size)
        bad = set(want_to_read) & self._unsolved
        if bad:
            raise ErasureCodeError(
                errno.EIO, f"shec: chunks {sorted(bad)} unrecoverable "
                f"from provided set")
        return out

    def decode_chunks(self, dense: np.ndarray, erasures) -> np.ndarray:
        """Recover what the provided chunks allow.

        Pass 1 propagates single-unknown windows (the shingle-local
        repair).  Pass 2 solves the restricted linear system over the
        remaining unknown data columns using parity rows whose windows
        are fully known-or-unknown-in-system.  Chunks that stay
        unrecoverable are recorded in self._unsolved; decode() errors if
        any of them were wanted (partial helper sets legitimately leave
        unwanted chunks unsolved).
        """
        out = dense.copy()
        erased = set(erasures)
        unknown = set(e for e in erased if e < self.k)
        known_parity = {i for i in range(self.m) if self.k + i not in erased}
        lut_all = gf.mul_table()

        def row_rhs(i: int, unknowns: list[int]) -> np.ndarray:
            rhs = out[self.k + i].copy()
            for j in self.windows[i]:
                if j not in unknowns and j not in unknown:
                    cij = int(self.matrix[i, j])
                    if cij:
                        rhs ^= lut_all[cij][out[j]]
            return rhs

        # pass 1: single-unknown propagation
        progress = True
        while progress and unknown:
            progress = False
            for i in known_parity:
                win_unknown = [j for j in self.windows[i] if j in unknown]
                if len(win_unknown) == 1:
                    j = win_unknown[0]
                    rhs = row_rhs(i, [j])
                    inv = gf.gf_inv(int(self.matrix[i, j]))
                    out[j] = lut_all[inv][rhs]
                    unknown.discard(j)
                    progress = True
        # pass 2: restricted system over remaining unknowns
        if unknown:
            unknowns = sorted(unknown)
            usable = [i for i in known_parity
                      if all(j not in unknown or j in unknowns
                             for j in self.windows[i])]
            rows = [[self.matrix[i, j] for j in unknowns] for i in usable]
            a = np.array(rows, dtype=np.uint8) if rows else \
                np.zeros((0, len(unknowns)), dtype=np.uint8)
            if rows and self._gf_rank(a) == len(unknowns):
                rhs = np.stack([row_rhs(i, unknowns) for i in usable])
                sol = self._gf_solve(a, rhs)
                if sol is not None:
                    for idx, e in enumerate(unknowns):
                        out[e] = sol[idx]
                    unknown.clear()
        # recompute erased parities whose windows are fully known
        self._unsolved = set(unknown)
        for e in (e for e in erased if e >= self.k):
            win = self.windows[e - self.k]
            if not any(j in unknown for j in win):
                acc = np.zeros_like(out[0])
                for j in win:
                    acc ^= lut_all[int(self.matrix[e - self.k, j])][out[j]]
                out[e] = acc
            else:
                self._unsolved.add(e)
        return out

    @staticmethod
    def _gf_solve(a: np.ndarray, rhs: np.ndarray) -> np.ndarray | None:
        """Solve a (rows x unknowns) GF system for each byte column."""
        rows, unknowns = a.shape
        aug_a = a.copy()
        aug_r = rhs.copy()
        lut_all = gf.mul_table()
        rank = 0
        pivots = []
        for col in range(unknowns):
            piv = next((r for r in range(rank, rows) if aug_a[r, col]), None)
            if piv is None:
                return None
            aug_a[[rank, piv]] = aug_a[[piv, rank]]
            aug_r[[rank, piv]] = aug_r[[piv, rank]]
            inv = gf.gf_inv(int(aug_a[rank, col]))
            lut = lut_all[inv]
            aug_a[rank] = lut[aug_a[rank]]
            aug_r[rank] = lut[aug_r[rank]]
            for r in range(rows):
                if r != rank and aug_a[r, col]:
                    c = int(aug_a[r, col])
                    aug_a[r] ^= lut_all[c][aug_a[rank]]
                    aug_r[r] ^= lut_all[c][aug_r[rank]]
            pivots.append(col)
            rank += 1
            if rank == unknowns:
                break
        return aug_r[:unknowns]


class ErasureCodePluginShec(ErasureCodePlugin):
    def factory(self, profile: Profile):
        return ErasureCodeShec()


def __erasure_code_init__(name: str, directory: str | None) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginShec())
