"""LRC plugin: Locally Repairable Code.

Fills the role of reference src/erasure-code/lrc/ErasureCodeLrc.{h,cc}:
cheap single-failure repair by adding local parities over groups.

Two profile forms, like the reference:

1. k/m/l (doc/rados/operations/erasure-code-lrc.rst "low-level"): k
   data chunks, m global RS parities, and one local XOR parity per
   group of l chunks over the ordered [data..., global parities...]
   sequence.
2. layers=/mapping= (reference ErasureCodeLrc.h:61): the recursive
   grammar.  mapping= is a string over the physical chunk positions
   ('D' = user data, anything else = derived); layers= is a JSON list
   of [layer_string, layer_profile] pairs, each layer running its own
   plugin (default jerasure) whose data inputs are the positions its
   string marks 'D' and whose coding outputs are the positions marked
   'c'.  Earlier layers' outputs may feed later layers' inputs; decode
   iterates layers, repairing locally wherever a single layer can.

minimum_to_decode prefers the smallest repair set — the property LRC
exists for.
"""

from __future__ import annotations

import errno
import json

import numpy as np

from .. import gf
from ..base import ErasureCode
from ..interface import ErasureCodeError, Profile
from ..registry import ErasureCodePlugin, ErasureCodePluginRegistry

__erasure_code_version__ = ErasureCodePlugin.abi_version


class ErasureCodeLrc(ErasureCode):
    ALLOW_PARTIAL_DECODE = True

    def __init__(self):
        super().__init__()
        self.l = 0
        self.n_local = 0
        self.global_matrix: np.ndarray | None = None
        self.groups: list[list[int]] = []  # member chunk ids per group

    def init(self, profile: Profile) -> None:
        self.k = profile.to_int("k", 4)
        m = profile.to_int("m", 2)
        self.l = profile.to_int("l", 3)
        if self.k < 1 or m < 1 or self.l < 2:
            raise ErasureCodeError(errno.EINVAL,
                                   f"bad k={self.k} m={m} l={self.l}")
        if (self.k + m) % self.l:
            raise ErasureCodeError(
                errno.EINVAL,
                f"k+m={self.k + m} must be divisible by l={self.l}")
        self._m_global = m
        self.n_local = (self.k + m) // self.l
        self.m = m + self.n_local  # interface m = all parity chunks
        self.global_matrix = gf.cauchy_rs_matrix(self.k, m)
        # groups over the ordered [data, global parity] sequence; the
        # local parity chunk of group g sits at index k + m + g
        self.groups = []
        for g in range(self.n_local):
            members = list(range(g * self.l, (g + 1) * self.l))
            self.groups.append(members)
        super().init(profile)

    # -- geometry -----------------------------------------------------------

    def group_of(self, chunk: int) -> list[int] | None:
        """Group members + local parity for a data/global chunk id."""
        km = self.k + self._m_global
        if chunk < km:
            g = chunk // self.l
            return self.groups[g] + [km + g]
        if chunk < self.get_chunk_count():
            g = chunk - km
            return self.groups[g] + [km + g]
        return None

    # -- codec --------------------------------------------------------------

    def encode_chunks(self, chunks: np.ndarray) -> np.ndarray:
        glob = gf.gf_matvec(self.global_matrix[self.k:], chunks)
        seq = np.concatenate([chunks, glob], axis=0)
        locals_ = np.stack([
            np.bitwise_xor.reduce(seq[members], axis=0)
            for members in self.groups])
        return np.concatenate([glob, locals_], axis=0)

    def minimum_to_decode(self, want_to_read, available):
        want = set(want_to_read)
        avail = set(available)
        missing = want - avail
        if not missing:
            return {i: [(0, 1)] for i in want}
        if len(missing) == 1:
            # local repair: the group of the missing chunk
            mchunk = next(iter(missing))
            grp = self.group_of(mchunk)
            if grp is not None:
                helpers = [c for c in grp if c != mchunk]
                if all(h in avail for h in helpers):
                    out = {h: [(0, 1)] for h in helpers}
                    for w in want & avail:
                        out[w] = [(0, 1)]
                    return out
        # global: any k of the data+global chunks
        km = self.k + self._m_global
        usable = sorted(a for a in avail if a < km)
        if len(usable) < self.k:
            raise ErasureCodeError(
                errno.EIO, f"LRC cannot decode: {sorted(avail)}")
        out = {c: [(0, 1)] for c in usable[: self.k]}
        for w in want & avail:
            out[w] = [(0, 1)]
        return out

    def decode_chunks(self, dense: np.ndarray, erasures) -> np.ndarray:
        out = dense.copy()
        erased = set(erasures)
        km = self.k + self._m_global
        # pass 1: local XOR repairs while possible
        progress = True
        while progress and erased:
            progress = False
            for e in sorted(erased):
                grp = self.group_of(e)
                if grp is None:
                    continue
                helpers = [c for c in grp if c != e]
                if all(h not in erased for h in helpers):
                    out[e] = np.bitwise_xor.reduce(out[helpers], axis=0)
                    erased.discard(e)
                    progress = True
        self._unsolved = set()
        if not erased:
            return out
        # pass 2: global RS over data+global parities
        survivors = [i for i in range(km) if i not in erased][: self.k]
        if len(survivors) < self.k:
            # partial helper set: whatever pass 1 recovered is all we
            # can do; decode() errors if a wanted chunk is still missing
            self._unsolved = set(erased)
            return out
        inv = gf.gf_invert_matrix(self.global_matrix[survivors, :])
        need_data = [e for e in erased if e < self.k]
        if need_data:
            rows = np.stack([inv[e] for e in need_data])
            rec = gf.gf_matvec(rows, out[survivors])
            for idx, e in enumerate(need_data):
                out[e] = rec[idx]
            erased -= set(need_data)
        # re-derive any remaining parity chunks from complete data
        if erased:
            glob = gf.gf_matvec(self.global_matrix[self.k:], out[: self.k])
            out[self.k:km] = glob
            seq = out[:km]
            for g, members in enumerate(self.groups):
                out[km + g] = np.bitwise_xor.reduce(seq[members], axis=0)
        return out

    def decode(self, want_to_read, chunks, chunk_size):
        self._unsolved = set()   # base may shortcut past decode_chunks
        out = super().decode(want_to_read, chunks, chunk_size)
        bad = set(want_to_read) & self._unsolved
        if bad:
            raise ErasureCodeError(
                errno.EIO,
                f"LRC: chunks {sorted(bad)} unrecoverable from provided set")
        return out


class _Layer:
    """One grammar layer: a sub-codec over a subset of positions."""

    def __init__(self, spec: str, prof_str: str, phys2log: dict[int, int]):
        self.spec = spec
        try:
            self.d_rows = [phys2log[p] for p, ch in enumerate(spec)
                           if ch == "D"]
            self.c_rows = [phys2log[p] for p, ch in enumerate(spec)
                           if ch == "c"]
        except KeyError as e:
            raise ErasureCodeError(
                errno.EINVAL, f"layer {spec!r} indexes beyond the "
                f"mapping: {e}") from e
        if not self.d_rows or not self.c_rows:
            raise ErasureCodeError(
                errno.EINVAL, f"layer {spec!r} needs both D and c")
        prof = {"plugin": "jerasure"}
        for tok in prof_str.split():
            if "=" in tok:
                key, val = tok.split("=", 1)
                prof[key] = val
        prof["k"] = str(len(self.d_rows))
        prof["m"] = str(len(self.c_rows))
        plugin = prof.pop("plugin")
        self.codec = ErasureCodePluginRegistry.instance().factory(
            plugin, Profile(prof))
        self.rows = self.d_rows + self.c_rows   # sub logical order

    def members(self) -> list[int]:
        return self.rows


class ErasureCodeLrcLayered(ErasureCode):
    """The layers=/mapping= grammar (reference ErasureCodeLrc.cc
    parse_kml's general path + layers_description/layers_init)."""

    ALLOW_PARTIAL_DECODE = True

    def init(self, profile: Profile) -> None:
        mapping = profile.get("mapping") or ""
        try:
            layer_list = json.loads(profile.get("layers") or "[]")
        except ValueError as e:
            raise ErasureCodeError(errno.EINVAL,
                                   f"bad layers JSON: {e}") from e
        if not mapping or not layer_list:
            raise ErasureCodeError(errno.EINVAL,
                                   "layered LRC needs mapping= and layers=")
        n = len(mapping)
        data_pos = [p for p, ch in enumerate(mapping) if ch == "D"]
        if not data_pos:
            raise ErasureCodeError(errno.EINVAL,
                                   f"mapping {mapping!r} has no D")
        self.k = len(data_pos)
        self.m = n - self.k
        # logical order: data chunks (mapping D's) then derived chunks;
        # chunk_mapping records the physical position of each logical id
        # (the placement contract of get_chunk_mapping)
        other_pos = [p for p in range(n) if mapping[p] != "D"]
        self.chunk_mapping = data_pos + other_pos
        phys2log = {p: i for i, p in enumerate(self.chunk_mapping)}
        self.layers: list[_Layer] = []
        computed = set(range(self.k))
        for ent in layer_list:
            spec, prof_str = (ent[0], ent[1] if len(ent) > 1 else "")
            if len(spec) != n:
                raise ErasureCodeError(
                    errno.EINVAL,
                    f"layer {spec!r} length != mapping length {n}")
            layer = _Layer(spec, prof_str, phys2log)
            clobbers = [r for r in layer.c_rows if r < self.k]
            if clobbers:
                raise ErasureCodeError(
                    errno.EINVAL,
                    f"layer {spec!r} writes coding output over data "
                    f"positions {clobbers}")
            missing_inputs = set(layer.d_rows) - computed
            if missing_inputs:
                raise ErasureCodeError(
                    errno.EINVAL,
                    f"layer {spec!r} consumes chunks no earlier layer "
                    f"produced: logical {sorted(missing_inputs)}")
            computed |= set(layer.c_rows)
            self.layers.append(layer)
        uncovered = set(range(n)) - computed
        if uncovered:
            raise ErasureCodeError(
                errno.EINVAL,
                f"no layer produces logical chunks {sorted(uncovered)}")
        self.profile = profile

    # -- codec ---------------------------------------------------------------

    def encode_chunks(self, chunks: np.ndarray) -> np.ndarray:
        n = self.get_chunk_count()
        full = np.zeros((n, chunks.shape[1]), dtype=np.uint8)
        full[: self.k] = chunks
        for layer in self.layers:
            parity = np.asarray(
                layer.codec.encode_chunks(full[layer.d_rows]))
            for i, row in enumerate(layer.c_rows):
                full[row] = parity[i]
        return full[self.k:]

    def decode_chunks(self, dense: np.ndarray, erasures) -> np.ndarray:
        out = dense.copy()
        erased = set(erasures)
        progress = True
        while erased and progress:
            progress = False
            for layer in self.layers:
                rows = layer.members()
                gone = [r for r in rows if r in erased]
                if not gone or \
                        len(gone) > layer.codec.get_coding_chunk_count():
                    continue
                sub = out[rows]
                sub_erasures = [rows.index(r) for r in gone]
                try:
                    rebuilt = np.asarray(layer.codec.decode_chunks(
                        sub, sub_erasures))
                except ErasureCodeError:
                    continue
                for i, r in enumerate(rows):
                    out[r] = rebuilt[i]
                erased -= set(gone)
                progress = True
        self._unsolved = set(erased)
        return out

    def minimum_to_decode(self, want_to_read, available):
        want, avail = set(want_to_read), set(available)
        missing = want - avail
        if not missing:
            return {i: [(0, 1)] for i in want}
        helpers: set[int] = set(want & avail)
        for mchunk in missing:
            best = None
            for layer in self.layers:
                rows = set(layer.members())
                if mchunk not in rows:
                    continue
                others = rows - {mchunk}
                # a layer only repairs from chunks that actually exist
                if others <= avail and (best is None or
                                        len(others) < len(best)):
                    best = others
            if best is None:
                # no single layer repairs it: offer everything we have
                # (the iterative decode may still chain layers)
                return {i: [(0, 1)] for i in avail}
            helpers |= best
        return {i: [(0, 1)] for i in helpers}

    def decode(self, want_to_read, chunks, chunk_size):
        # reset per call: the base class shortcuts past decode_chunks
        # when everything wanted is present, which must not read a
        # PREVIOUS failed decode's unsolved set
        self._unsolved = set()
        out = super().decode(want_to_read, chunks, chunk_size)
        bad = set(want_to_read) & self._unsolved
        if bad:
            raise ErasureCodeError(
                errno.EIO,
                f"LRC: chunks {sorted(bad)} unrecoverable from provided set")
        return out


class ErasureCodePluginLrc(ErasureCodePlugin):
    def factory(self, profile: Profile):
        if profile.get("layers") or profile.get("mapping"):
            return ErasureCodeLrcLayered()
        return ErasureCodeLrc()


def __erasure_code_init__(name: str, directory: str | None) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginLrc())
