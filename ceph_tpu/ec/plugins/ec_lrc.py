"""LRC plugin: Locally Repairable Code.

Fills the role of reference src/erasure-code/lrc/ErasureCodeLrc.{h,cc}:
cheap single-failure repair by adding local parities over groups.

Profile (the reference's "low-level" k/m/l form, doc/rados/operations/
erasure-code-lrc.rst): k data chunks, m global RS parities, and one
local XOR parity per group of l chunks taken over the ordered sequence
[data..., global parities...] — so k=8 m=4 l=4 yields 3 groups and 15
chunks total, and a single lost chunk rebuilds from its group's l
surviving members instead of k.

The layered-grammar form of the reference (layers= / mapping= JSON with
recursive plugin composition) is intentionally not replicated; the k/m/l
form covers the placement/repair capability the grammar exists to
describe.  minimum_to_decode prefers the local group for single
erasures — the property LRC exists for.
"""

from __future__ import annotations

import errno

import numpy as np

from .. import gf
from ..base import ErasureCode
from ..interface import ErasureCodeError, Profile
from ..registry import ErasureCodePlugin, ErasureCodePluginRegistry

__erasure_code_version__ = ErasureCodePlugin.abi_version


class ErasureCodeLrc(ErasureCode):
    ALLOW_PARTIAL_DECODE = True

    def __init__(self):
        super().__init__()
        self.l = 0
        self.n_local = 0
        self.global_matrix: np.ndarray | None = None
        self.groups: list[list[int]] = []  # member chunk ids per group

    def init(self, profile: Profile) -> None:
        self.k = profile.to_int("k", 4)
        m = profile.to_int("m", 2)
        self.l = profile.to_int("l", 3)
        if self.k < 1 or m < 1 or self.l < 2:
            raise ErasureCodeError(errno.EINVAL,
                                   f"bad k={self.k} m={m} l={self.l}")
        if (self.k + m) % self.l:
            raise ErasureCodeError(
                errno.EINVAL,
                f"k+m={self.k + m} must be divisible by l={self.l}")
        self._m_global = m
        self.n_local = (self.k + m) // self.l
        self.m = m + self.n_local  # interface m = all parity chunks
        self.global_matrix = gf.cauchy_rs_matrix(self.k, m)
        # groups over the ordered [data, global parity] sequence; the
        # local parity chunk of group g sits at index k + m + g
        self.groups = []
        for g in range(self.n_local):
            members = list(range(g * self.l, (g + 1) * self.l))
            self.groups.append(members)
        super().init(profile)

    # -- geometry -----------------------------------------------------------

    def group_of(self, chunk: int) -> list[int] | None:
        """Group members + local parity for a data/global chunk id."""
        km = self.k + self._m_global
        if chunk < km:
            g = chunk // self.l
            return self.groups[g] + [km + g]
        if chunk < self.get_chunk_count():
            g = chunk - km
            return self.groups[g] + [km + g]
        return None

    # -- codec --------------------------------------------------------------

    def encode_chunks(self, chunks: np.ndarray) -> np.ndarray:
        glob = gf.gf_matvec(self.global_matrix[self.k:], chunks)
        seq = np.concatenate([chunks, glob], axis=0)
        locals_ = np.stack([
            np.bitwise_xor.reduce(seq[members], axis=0)
            for members in self.groups])
        return np.concatenate([glob, locals_], axis=0)

    def minimum_to_decode(self, want_to_read, available):
        want = set(want_to_read)
        avail = set(available)
        missing = want - avail
        if not missing:
            return {i: [(0, 1)] for i in want}
        if len(missing) == 1:
            # local repair: the group of the missing chunk
            mchunk = next(iter(missing))
            grp = self.group_of(mchunk)
            if grp is not None:
                helpers = [c for c in grp if c != mchunk]
                if all(h in avail for h in helpers):
                    out = {h: [(0, 1)] for h in helpers}
                    for w in want & avail:
                        out[w] = [(0, 1)]
                    return out
        # global: any k of the data+global chunks
        km = self.k + self._m_global
        usable = sorted(a for a in avail if a < km)
        if len(usable) < self.k:
            raise ErasureCodeError(
                errno.EIO, f"LRC cannot decode: {sorted(avail)}")
        out = {c: [(0, 1)] for c in usable[: self.k]}
        for w in want & avail:
            out[w] = [(0, 1)]
        return out

    def decode_chunks(self, dense: np.ndarray, erasures) -> np.ndarray:
        out = dense.copy()
        erased = set(erasures)
        km = self.k + self._m_global
        # pass 1: local XOR repairs while possible
        progress = True
        while progress and erased:
            progress = False
            for e in sorted(erased):
                grp = self.group_of(e)
                if grp is None:
                    continue
                helpers = [c for c in grp if c != e]
                if all(h not in erased for h in helpers):
                    out[e] = np.bitwise_xor.reduce(out[helpers], axis=0)
                    erased.discard(e)
                    progress = True
        self._unsolved = set()
        if not erased:
            return out
        # pass 2: global RS over data+global parities
        survivors = [i for i in range(km) if i not in erased][: self.k]
        if len(survivors) < self.k:
            # partial helper set: whatever pass 1 recovered is all we
            # can do; decode() errors if a wanted chunk is still missing
            self._unsolved = set(erased)
            return out
        inv = gf.gf_invert_matrix(self.global_matrix[survivors, :])
        need_data = [e for e in erased if e < self.k]
        if need_data:
            rows = np.stack([inv[e] for e in need_data])
            rec = gf.gf_matvec(rows, out[survivors])
            for idx, e in enumerate(need_data):
                out[e] = rec[idx]
            erased -= set(need_data)
        # re-derive any remaining parity chunks from complete data
        if erased:
            glob = gf.gf_matvec(self.global_matrix[self.k:], out[: self.k])
            out[self.k:km] = glob
            seq = out[:km]
            for g, members in enumerate(self.groups):
                out[km + g] = np.bitwise_xor.reduce(seq[members], axis=0)
        return out

    def decode(self, want_to_read, chunks, chunk_size):
        out = super().decode(want_to_read, chunks, chunk_size)
        bad = set(want_to_read) & getattr(self, "_unsolved", set())
        if bad:
            raise ErasureCodeError(
                errno.EIO,
                f"LRC: chunks {sorted(bad)} unrecoverable from provided set")
        return out


class ErasureCodePluginLrc(ErasureCodePlugin):
    def factory(self, profile: Profile):
        return ErasureCodeLrc()


def __erasure_code_init__(name: str, directory: str | None) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginLrc())
