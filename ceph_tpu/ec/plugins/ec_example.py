"""Example XOR codec: k=2, m=1.

The minimal correct codec used to test the interface itself, mirroring
reference src/test/erasure-code/ErasureCodeExample.h (k=2 m=1, parity =
data0 XOR data1; decode any one erasure by XOR of the other two).
"""

from __future__ import annotations

import errno

import numpy as np

from ..base import ErasureCode
from ..interface import ErasureCodeError, Profile
from ..registry import ErasureCodePlugin, ErasureCodePluginRegistry

__erasure_code_version__ = ErasureCodePlugin.abi_version


class ErasureCodeExample(ErasureCode):
    k = 2
    m = 1

    def init(self, profile: Profile) -> None:
        super().init(profile)

    def minimum_to_decode_with_cost(self, want_to_read, available):
        # Prefer the cheapest 2 chunks (reference ErasureCodeExample.h:59).
        if len(available) < self.k:
            raise ErasureCodeError(errno.EIO, "not enough chunks")
        cheapest = sorted(available, key=lambda i: (available[i], i))[: self.k]
        return set(cheapest)

    def encode_chunks(self, chunks: np.ndarray) -> np.ndarray:
        return (chunks[0] ^ chunks[1])[None, :]

    def decode_chunks(self, dense: np.ndarray, erasures):
        out = dense.copy()
        for e in erasures:
            others = [i for i in range(3) if i != e]
            out[e] = out[others[0]] ^ out[others[1]]
        return out


class ErasureCodePluginExample(ErasureCodePlugin):
    def factory(self, profile: Profile):
        return ErasureCodeExample()


def __erasure_code_init__(name: str, directory: str | None) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginExample())
