"""Built-in erasure-code plugins.

Naming convention mirrors the reference's shared objects: plugin `name`
lives in module `ec_<name>` (reference loads `libec_<name>.so`,
src/erasure-code/ErasureCodePlugin.cc:110).

Built-ins:
  ec_example  - trivial k=2 m=1 XOR codec (test reference, like
                src/test/erasure-code/ErasureCodeExample.h)
  ec_jerasure - CPU Reed-Solomon (reed_sol_van, cauchy_orig, cauchy_good)
  ec_isa      - CPU RS with cached decode tables (ISA-L role)
  ec_jax      - TPU bit-sliced GF(2^8) matmul codec (the north star)
  ec_lrc      - locally repairable layered code
  ec_shec     - shingled EC
  ec_clay     - coupled-layer MSR regenerating code
"""
