"""TPU erasure-code plugin ("jax"): bit-sliced GF(2^8) RS on the MXU.

The north-star codec (BASELINE.json): fills the same registry seam as the
reference's jerasure/ISA-L plugins but executes encode/decode as Pallas
bit-matrix matmuls (ceph_tpu/ops/bitsliced.py).  Parity is bit-identical
to the CPU plugins because both sides use the same generator matrices
(ceph_tpu/ec/gf.py) — the TPU path just evaluates them over GF(2)
bit-planes instead of GF(2^8) byte LUTs.

Techniques: `cauchy` (default; reference cauchy_good analog) and
`reed_sol_van` (matches ec_jerasure/ec_isa output bytes exactly).

Decode: the (survivors -> erased) coefficient matrix is computed on host
(tiny Gauss-Jordan, LRU-cached by erasure signature like the reference's
ISA-L table cache) and applied with the same TPU kernel.

Batching: `encode_stripes` folds a whole batch of stripes into one kernel
launch — the hook the OSD write pipeline uses to amortize launch latency
across in-flight transactions (reference analog: the per-stripe loop in
ECUtil::encode, src/osd/ECUtil.cc:120-150, hoisted into one call).
"""

from __future__ import annotations

import errno
import functools
import sys
import threading

import numpy as np

# the w32 host path reinterprets byte buffers as little-endian words
# (`.view('<u4').view(np.int32)`); on a big-endian host the int32 view
# would silently byte-swap relative to the kernel's layout, producing
# wrong parity rather than an error — fail loudly instead (ADVICE r1)
assert sys.byteorder == "little", \
    "ec_jax w32 paths assume a little-endian host"

from .. import gf
from ..base import ErasureCode
from ..interface import ErasureCodeError, Profile
from ..registry import ErasureCodePlugin, ErasureCodePluginRegistry

__erasure_code_version__ = ErasureCodePlugin.abi_version

_jax_state = threading.local()


def _ops():
    """Import jax lazily so merely loading the plugin registry never pulls
    in a TPU runtime (mirrors plugin dlopen being side-effect-light)."""
    import jax  # noqa: F401
    from ... import ops  # noqa: F401
    from ...ops import bitsliced
    return bitsliced


class ErasureCodeJax(ErasureCode):
    technique = "cauchy"

    def __init__(self, technique: str = "cauchy"):
        super().__init__()
        self.technique = technique
        self.matrix: np.ndarray | None = None
        self._codec_sig: tuple | None = None
        self._enc_bitmat = None           # device array, interleaved layout
        self._decode_cache: dict[tuple, tuple] = {}
        self._lock = threading.Lock()

    # -- setup --------------------------------------------------------------

    def init(self, profile: Profile) -> None:
        self.k = profile.to_int("k", 8)
        self.m = profile.to_int("m", 3)
        if self.k < 1 or self.m < 1 or self.k + self.m > gf.GF_SIZE:
            raise ErasureCodeError(errno.EINVAL, f"bad k={self.k} m={self.m}")
        if self.technique == "reed_sol_van":
            self.matrix = gf.vandermonde_rs_matrix(self.k, self.m)
        else:
            self.matrix = gf.cauchy_rs_matrix(self.k, self.m)
        bs = _ops()
        import jax
        import jax.numpy as jnp
        self._enc_bitmat = jnp.asarray(
            bs.interleave_bitmatrix(self.matrix[self.k:]), dtype=jnp.int8)
        # word-packed variant: ~4x the byte kernel on TPU (bit unpack
        # touches 4 bytes per VPU op); byte path retained for CPU/XLA
        self._use_w32 = jax.default_backend() != "cpu"
        self._enc_bitmat32 = jnp.asarray(
            bs._w32_bitmat(self.matrix[self.k:]), dtype=jnp.int8) \
            if self._use_w32 else None
        self._fused_point: dict | None = None   # lazy autotune result
        super().init(profile)

    def get_alignment(self) -> int:
        return 64

    # flight-recorder hint (ops/profiler.py): encode/decode run jitted
    # XLA programs, so a first-seen launch shape IS a compile
    jit_backed = True

    def codec_signature(self) -> tuple:
        """Coalescing key for the per-host launch queue
        (parallel/launch_queue.py): two instances with equal
        signatures produce bit-identical parity via the same launch
        paths, so their runs may share one cross-PG super-batch.
        Plugin-typed on purpose — a jax instance never co-batches
        with a CPU plugin even when the matrices match, because the
        super-batch launches through the FIRST submitter's plugin and
        the capability sets (submit/finalize halves, device layout)
        must be uniform within a launch."""
        if self._codec_sig is None:
            from ...parallel.launch_queue import matrix_signature
            self._codec_sig = ("jax",) + matrix_signature(
                self.matrix, self.k, self.m)
        return self._codec_sig

    # -- encode -------------------------------------------------------------

    def encode_chunks(self, chunks: np.ndarray) -> np.ndarray:
        return self._apply_bitmat(self._enc_bitmat32 if self._use_w32
                                  else self._enc_bitmat, chunks, self.m)

    def _apply_bitmat(self, bitmat, chunks: np.ndarray, r: int) -> np.ndarray:
        """Host-side single point of byte-vs-w32 dispatch: `bitmat` must
        be in the format matching self._use_w32 (_w32_bitmat vs
        interleave_bitmatrix layout — both builders and this dispatch
        flip together on the backend probe in init)."""
        bs = _ops()
        chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
        if not self._use_w32:
            return np.asarray(bs.gf_bitmatmul(bitmat, chunks, r))
        # word-packed TPU path; host-side views are free (row-major)
        k, n = chunks.shape
        pad = -n % 4
        if pad:
            chunks = np.pad(chunks, ((0, 0), (0, pad)))
        words = chunks.view("<u4").view(np.int32)
        out = np.asarray(bs.gf_bitmatmul_w32(bitmat, words, r))
        out = out.view("<u4").view(np.uint8).reshape(r, n + pad)
        return out[:, :n] if pad else out

    def encode_chunks_device(self, chunks):
        """Device-resident encode: chunks (k, N) jnp uint8 -> (m, N).
        No host transfer; for the OSD pipeline and benchmarks."""
        bs = _ops()
        return bs.gf_bitmatmul(self._enc_bitmat, chunks, self.m)

    def encode_words(self, words):
        """Word-packed device-resident encode: (k, W) int32 words
        (little-endian packed chunk bytes) -> (m, W) int32 parity.
        The fastest TPU path — no byte<->word relayout on device."""
        bs = _ops()
        if not self._use_w32:
            raise RuntimeError(
                "encode_words requires a TPU backend (the w32 kernel "
                "uses Mosaic bitcasts); use encode_chunks_device on CPU")
        return bs.gf_bitmatmul_w32(self._enc_bitmat32, words, self.m)

    def fused_point(self) -> dict:
        """The fused kernel's (tile, wb, extract, combine) operating
        point for this device, resolved lazily through the
        ops/autotune cache (first fused call on a fresh accelerator
        pays the sweep; CPU and opted-out runs get the static
        defaults)."""
        if self._fused_point is None:
            from ...ops import autotune
            try:
                self._fused_point = autotune.fused_operating_point(
                    self.k, self.m, mat=self.matrix[self.k:],
                    bitmat32=self._enc_bitmat32)
            except Exception:  # noqa: BLE001 — tuning must never
                self._fused_point = autotune.default_point()  # break IO
        return self._fused_point

    def encode_words_with_crc(self, words, tile: int | None = None,
                              wb: int | None = None):
        """Device-resident fused parity + crc over word-packed input at
        the autotuned operating point (the overlapped hier-crc kernel
        with the device-side combine — in-kernel VMEM accumulator or
        XLA log-fold per the point's `combine` axis; see
        ops/bitsliced.gf_encode_with_crc_w32_fold).  words (k, W)
        int32; W bytes per shard must be a tile multiple.  Returns
        (parity (m, W) int32, crc L-bits (k+m, 32) int32 — ONE
        combined L per shard, fold with crc32c_linear.fold_run_crc) —
        the write path's checksum-and-parity-in-one-launch (reference
        analog: plugin encode + ECUtil.cc:172 HashInfo append, two
        separate passes there)."""
        import jax.numpy as jnp
        bs = _ops()
        from ...ops import crc32c_linear as cl
        if not self._use_w32:
            raise RuntimeError(
                "encode_words_with_crc requires a TPU backend")
        point = self.fused_point()
        tile = tile or point["tile"]
        wb = wb or point["wb"]
        cmat_sub = jnp.asarray(cl.crc_tile_matrix_w32(wb))
        return bs.gf_encode_with_crc_w32_fold(
            self._enc_bitmat32, cmat_sub, words, self.m,
            tile=tile, wb=wb, extract=point["extract"],
            combine=point["combine"])

    def encode_stripes(self, stripes):
        """Batched encode: (B, k, C) -> (B, m, C), one kernel launch.

        Internally reorders to (k, B*C) so every stripe's chunk j lands in
        the same row — the batch rides the byte axis the kernel already
        tiles.
        """
        import jax.numpy as jnp
        bs = _ops()
        stripes = jnp.asarray(stripes, dtype=jnp.uint8)
        b, k, c = stripes.shape
        assert k == self.k
        flat = jnp.transpose(stripes, (1, 0, 2)).reshape(k, b * c)
        par = bs.gf_bitmatmul(self._enc_bitmat, flat, self.m)
        return jnp.transpose(par.reshape(self.m, b, c), (1, 0, 2))

    def encode_extents_with_crc(self, runs: list[np.ndarray]):
        """Multi-extent fused launch: every run of a pipeline drain gets
        parity + ONE device-combined crc L per shard from ONE kernel
        call (w32 on TPU — the headline kernel, not the 4x-slower byte
        variant), at the autotuned operating point.

        Returns per-run (parity (m, Wi), l (k+m,) uint32, tail_bytes,
        body_bytes); fold each with fold_extent_crcs, chaining seeds
        per object.
        """
        from ...ops import bitsliced as bs
        point = self.fused_point() if self._use_w32 else None
        return bs.gf_encode_extents_with_crc(
            self._enc_bitmat, self._enc_bitmat32, runs, self.m,
            use_w32=self._use_w32,
            tile=point["tile"] if point else None,
            wb=point["wb"] if point else None,
            extract=point["extract"] if point else "planar",
            combine=point["combine"] if point else "xla")

    def encode_extents_with_crc_submit(self, runs: list[np.ndarray]):
        """Dispatch half of encode_extents_with_crc for the OSD's
        dispatch-ahead pipeline: launches the drain's fused parity+crc
        work and returns an opaque handle of device futures — the
        caller does NOT block on the device.  Materialize with
        encode_extents_with_crc_finalize (the pipeline's completion
        stage), in submit order."""
        from ...ops import bitsliced as bs
        point = self.fused_point() if self._use_w32 else None
        return bs.gf_encode_extents_with_crc_submit(
            self._enc_bitmat, self._enc_bitmat32, runs, self.m,
            use_w32=self._use_w32,
            tile=point["tile"] if point else None,
            wb=point["wb"] if point else None,
            extract=point["extract"] if point else "planar",
            combine=point["combine"] if point else "xla")

    def launch_bucket(self, handle) -> str:
        """Flight-recorder jit-bucket key of one submit handle
        (ops/profiler.py): the axes XLA/Mosaic actually key their
        caches on — kernel path, the autotuned (tile, wb) operating
        point, and the pow2-padded (width, run-count) launch shape —
        so the compile ledger's first-seen detection matches real
        compiles instead of guessing from raw widths."""
        from ...parallel.launch_queue import _extents_bucket
        base = _extents_bucket(handle)
        point = self._fused_point
        if point and self._use_w32:
            return (f"{base}:t{point.get('tile')}"
                    f":wb{point.get('wb')}"
                    f":{point.get('extract')}.{point.get('combine')}")
        return base

    def encode_extents_with_crc_finalize(self, handle):
        """Completion half: blocks on one submit handle's device work
        and returns the per-run (parity, l, tail, body_bytes) tuples."""
        from ...ops import bitsliced as bs
        return bs.gf_encode_extents_with_crc_finalize(handle)

    def encode_chunks_submit(self, chunks: np.ndarray):
        """Plain-parity dispatch half (no crc): launch the encode of
        (k, N) uint8 chunks and return a handle without syncing — the
        pipeline's path for non-append (overwrite) extents whose
        incremental crc is dead anyway."""
        import jax.numpy as jnp
        bs = _ops()
        chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
        k, n = chunks.shape
        if not self._use_w32:
            return ("bytes", n,
                    bs.gf_bitmatmul(self._enc_bitmat,
                                    jnp.asarray(chunks), self.m))
        pad = -n % 4
        if pad:
            chunks = np.pad(chunks, ((0, 0), (0, pad)))
        words = jnp.asarray(chunks.view("<u4").view(np.int32))
        return ("w32", n,
                bs.gf_bitmatmul_w32(self._enc_bitmat32, words, self.m))

    def encode_chunks_finalize(self, handle) -> np.ndarray:
        kind, n, dev = handle
        out = np.asarray(dev)
        if kind == "w32":
            out = out.view("<u4").view(np.uint8).reshape(self.m, -1)
        return out[:, :n] if out.shape[1] != n else out

    def fold_extent_crcs(self, l, tail_bytes, seeds: list[int],
                         body_bytes: int) -> list[int]:
        """Host fold of one run's device-combined L-vectors into
        cumulative shard crcs with per-shard seeds (the hinfo chain):
        O(1) combines per shard — one seed-advance plus the sub-block
        tail — no per-tile Python loop."""
        from ...ops import crc32c_linear as cl
        return [cl.fold_run_crc(int(l[s]), body_bytes, seeds[s],
                                tail_bytes[s].tobytes())
                for s in range(self.k + self.m)]

    def encode_chunks_with_crc(self, chunks: np.ndarray,
                               seeds: list[int] | None = None
                               ) -> tuple[np.ndarray, list[int]]:
        """The fused north-star launch: parity AND per-shard crc32c from
        one kernel call (BASELINE.json; reference analog computes them
        separately: plugin encode_chunks + HashInfo::append crc loop,
        src/osd/ECUtil.cc:172).

        Returns (parity (m, N), crcs for all k+m shards seeded `seeds`
        (default 0xFFFFFFFF each, the HashInfo convention)).
        """
        chunks = np.ascontiguousarray(chunks, dtype=np.uint8)
        if seeds is None:
            seeds = [0xFFFFFFFF] * (self.k + self.m)
        [(parity, l, tail_bytes, body_bytes)] = \
            self.encode_extents_with_crc([chunks])
        crcs = self.fold_extent_crcs(l, tail_bytes, seeds, body_bytes)
        return np.asarray(parity), crcs

    # -- AOT lowering (boot-time prewarm, ops/prewarm.py) -------------------
    #
    # The headline kernels get jax.jit(...).lower().compile() paths so a
    # steady-state launch of a prewarmed shape dispatches the compiled
    # executable directly — no trace-time, ever (the jitted path still
    # retraces on the first call per process even when the persistent
    # cache serves the compile).  Shapes here MUST mirror the dispatch
    # sites in ops/bitsliced.py exactly (same pow2/lane padding), which
    # is why each method reproduces the corresponding wrapper's padding
    # arithmetic rather than guessing.  All three are best-effort: a
    # backend that can't lower the shape returns False and the jitted
    # path serves it.

    def _aot_spec(self, shape, dtype):
        import jax
        return jax.ShapeDtypeStruct(tuple(shape), np.dtype(dtype))

    def aot_compile_encode(self, width: int) -> bool:
        """AOT-lower the plain (no-crc) encode at byte width `width` —
        the gf_bitmatmul / gf_bitmatmul_w32 dispatch shapes."""
        bs = _ops()
        if not self._use_w32:
            w = width + (-width % bs.LANE)
            return bs.aot_compile(
                "mm_xla", bs.gf_bitmatmul_xla,
                (self._enc_bitmat, self._aot_spec((self.k, w), np.uint8)),
                {"r": self.m})
        w = (width + (-width % 4)) // 4            # packed word count
        wlane = w + (-w % bs.LANE)
        return bs.aot_compile(
            "mm_w32", bs.gf_bitmatmul_pallas_w32,
            (self._enc_bitmat32,
             self._aot_spec((self.k, wlane), np.int32)),
            {"r": self.m, "tile": 4 * bs._pick_wt(wlane)})

    def aot_compile_decode(self, width: int, n_erased: int = 1) -> bool:
        """AOT-lower the flat decode at byte width `width` for
        `n_erased` lost shards.  The executable is keyed by the decode
        bitmatrix SHAPE, which depends only on n_erased — one AOT
        compile covers every erasure pattern of that cardinality."""
        bs = _ops()
        n = self.get_chunk_count()
        e = max(1, min(n_erased, self.m))
        # representative pattern: last e shards lost (shape-equivalent
        # to any other pattern of e losses)
        erased = tuple(range(n - e, n))
        survivors = tuple(i for i in range(n) if i not in erased)[:self.k]
        _, bitmat = self._decode_plan(survivors, erased)
        if not self._use_w32:
            w = width + (-width % bs.LANE)
            return bs.aot_compile(
                "mm_xla", bs.gf_bitmatmul_xla,
                (bitmat, self._aot_spec((self.k, w), np.uint8)),
                {"r": e})
        w = (width + (-width % 4)) // 4
        wlane = w + (-w % bs.LANE)
        return bs.aot_compile(
            "mm_w32", bs.gf_bitmatmul_pallas_w32,
            (bitmat, self._aot_spec((self.k, wlane), np.int32)),
            {"r": e, "tile": 4 * bs._pick_wt(wlane)})

    def aot_compile_fused(self, widths: list[int]) -> bool:
        """AOT-lower the fused parity+crc launch for a drain whose runs
        have the given byte widths, at this codec's operating point —
        the gf_encode_extents_with_crc_submit dispatch shapes (tile
        padding, pow2 tile-count bucketing, pow2 run-count bucketing
        all reproduced)."""
        import jax
        import jax.numpy as jnp
        bs = _ops()
        from ...common.util import next_pow2
        from ...ops import crc32c_linear as cl
        k, m = self.k, self.m
        if not self._use_w32:                      # CPU: force_xla path
            tile = bs.FUSED_TILE
            nt = next_pow2(sum(-(-w // tile) for w in widths))
            cmat = jnp.asarray(cl.crc_tile_matrix(tile))
            return bs.aot_compile(
                "fused_xla", bs.gf_encode_with_crc_xla,
                (self._enc_bitmat, cmat,
                 self._aot_spec((k, nt * tile), np.uint8)),
                {"m": m, "tile": tile})
        point = self.fused_point()
        tile_hier = point["tile"] or bs.FUSED_TILE_HIER
        wb = point["wb"] or bs.FUSED_WB
        extract = point["extract"]
        donate = jax.default_backend() != "cpu"
        hier = min(widths) >= tile_hier
        tile = tile_hier if hier else bs.FUSED_TILE
        ntiles_run = [-(-w // tile) for w in widths]
        ntiles_total = sum(ntiles_run)
        nt2 = next_pow2(ntiles_total)
        pad_tiles = nt2 - ntiles_total
        words = self._aot_spec((k, nt2 * tile // 4), np.int32)
        cmat_sub = jnp.asarray(cl.crc_tile_matrix_w32(wb))
        if hier and point["combine"] == "kernel":
            if pad_tiles:
                ntiles_run = ntiles_run + [pad_tiles]
            nruns_acc = next_pow2(len(ntiles_run))
            ntiles_run += [0] * (nruns_acc - len(ntiles_run))
            run_map, first_map, adv, comb = bs._acc_launch_args(
                ntiles_run, tile, wb)
            acc_fn = bs._hier_acc_donate if donate else bs._hier_acc
            return bs.aot_compile(
                "hier_acc_donate" if donate else "hier_acc", acc_fn,
                (self._enc_bitmat32, cmat_sub, adv, comb, run_map,
                 first_map, words),
                {"m": m, "tile": tile, "wb": wb, "nruns": nruns_acc,
                 "interpret": False, "extract": extract})
        if hier:
            hier_fn = bs._fused_hier_lsub_donate if donate \
                else bs._fused_hier_lsub
            return bs.aot_compile(
                "hier_lsub_donate" if donate else "hier_lsub", hier_fn,
                (self._enc_bitmat32, cmat_sub, words),
                {"m": m, "tile": tile, "wb": wb, "interpret": False,
                 "extract": extract})
        cmat32 = jnp.asarray(cl.crc_tile_matrix_w32(tile // 4))
        return bs.aot_compile(
            "fused_w32", bs.gf_encode_with_crc_pallas_w32,
            (self._enc_bitmat32, cmat32, words),
            {"m": m, "interpret": False})

    # -- decode -------------------------------------------------------------

    def _decode_plan(self, survivors: tuple[int, ...],
                     targets: tuple[int, ...]):
        """Host-side: (survivors -> targets) GF matrix + device bitmatrix,
        cached by signature (reference ErasureCodeIsaTableCache role)."""
        key = (survivors, targets)
        with self._lock:
            hit = self._decode_cache.get(key)
        if hit is not None:
            return hit
        import jax.numpy as jnp
        bs = _ops()
        coeff = gf.recovery_matrix(self.matrix, self.k, survivors, targets)
        if self._use_w32:
            bitmat = jnp.asarray(bs._w32_bitmat(coeff), dtype=jnp.int8)
        else:
            bitmat = jnp.asarray(bs.interleave_bitmatrix(coeff),
                                 dtype=jnp.int8)
        plan = (coeff, bitmat)
        with self._lock:
            self._decode_cache[key] = plan
        return plan

    def decode_words(self, words, survivors, targets):
        """Device-resident word-packed decode: `words` is the survivors'
        packed chunk bytes (len(survivors)=k, W) int32; returns the
        reconstructed `targets` shards (len(targets), W) int32.  Same
        kernel as encode_words with the inverted bitmatrix — the repair
        hot loop (reference ECUtil::decode, src/osd/ECUtil.cc:9)."""
        bs = _ops()
        if not self._use_w32:
            raise RuntimeError("decode_words requires a TPU backend; "
                               "use decode_chunks on CPU")
        _, bitmat = self._decode_plan(tuple(survivors), tuple(targets))
        return bs.gf_bitmatmul_w32(bitmat, words, len(targets))

    def decode_chunks_device(self, chunks, survivors, targets):
        """Device-resident byte-path decode (CPU/XLA twin of
        decode_words): `chunks` (k, N) survivor rows in `survivors`
        order -> reconstructed (len(targets), N).  Public entry for
        benchmarks/pipelines holding device arrays."""
        bs = _ops()
        if self._use_w32:
            raise RuntimeError("backend is w32 (TPU): use decode_words")
        _, bitmat = self._decode_plan(tuple(survivors), tuple(targets))
        return bs.gf_bitmatmul(bitmat, chunks, len(tuple(targets)))

    def decode_chunks(self, dense: np.ndarray, erasures) -> np.ndarray:
        n = self.get_chunk_count()
        erased = tuple(sorted(set(erasures)))
        survivors = tuple(i for i in range(n) if i not in set(erased))[: self.k]
        if len(survivors) < self.k:
            raise ErasureCodeError(errno.EIO, "not enough survivors")
        _, bitmat = self._decode_plan(survivors, erased)
        rec = self._apply_bitmat(bitmat, dense[list(survivors)], len(erased))
        out = dense.copy()
        for idx, e in enumerate(erased):
            out[e] = rec[idx]
        return out


class ErasureCodePluginJax(ErasureCodePlugin):
    def factory(self, profile: Profile):
        technique = profile.get("technique", "cauchy") or "cauchy"
        if technique not in ("cauchy", "reed_sol_van"):
            raise ErasureCodeError(
                errno.ENOENT, f"unknown jax technique {technique!r}")
        return ErasureCodeJax(technique)


def __erasure_code_init__(name: str, directory: str | None) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginJax())
