"""CLAY plugin: Coupled-LAYer MSR regenerating code.

Fills the role of reference src/erasure-code/clay/ErasureCodeClay.{h,cc}
(profile k, m, d): an MDS code with *sub-chunked* chunks whose
single-failure repair reads only a fraction 1/q of each helper chunk —
the reason ErasureCodeInterface carries sub-chunk (offset, count) lists
in minimum_to_decode (reference ErasureCodeInterface.h:297,
ErasureCodeClay.h:57 get_sub_chunk_count).

Construction (Clay codes, FAST'18 — the same family the reference
implements): nodes are points (x, y) on a q x t grid (q = d-k+1,
t = (k+m)/q, chunk i -> x=i%q, y=i//q); every chunk splits into q^t
sub-chunks indexed by planes z = (z_0..z_{t-1}), z_y in [0,q).  An
uncoupled symbol U(x,y;z) per node per plane forms, within each plane,
a codeword of a scalar (n,k) MDS code; the stored (coupled) symbols C
relate to U by a pairwise invertible transform: vertex (x,y) in plane z
with x != z_y pairs with vertex (z_y, y) in plane z(y->x), and

    [ C_A@z ; C_B@z' ] = [[1, g], [g, 1]] [ U_A@z ; U_B@z' ]   (g^2 != 1)

while hole-aligned vertices (x == z_y) have C = U.

decode_layered processes planes in increasing order of "intersection
score" (count of erased hole-aligned vertices): by induction every
intact vertex can be decoupled using symbols from lower-score planes,
each plane's <= m unknown U's solve via the MDS parity-check system, and
the erased C's re-couple.  Encode IS decode with the parity chunks as
the erasures (exactly the reference's approach).

Repair: losing one chunk (x0,y0) with d = n-1 helpers reads only the
q^{t-1} "repair planes" {z : z_{y0} = x0} from each helper; per plane
the q unknowns (failed U + the y0-column helpers' U) solve in one m x m
system, and the coupling relation reproduces the failed chunk's
sub-chunks on the remaining planes.  Scope: d = k+m-1 (the reference's
recommended/default d, e.g. k=8 m=4 d=11); smaller d falls back to
full-chunk reads.
"""

from __future__ import annotations

import errno
import itertools

import numpy as np

from .. import gf
from ..base import ErasureCode
from ..interface import ErasureCodeError, Profile
from ..registry import ErasureCodePlugin, ErasureCodePluginRegistry

__erasure_code_version__ = ErasureCodePlugin.abi_version

GAMMA = 2  # coupling constant; needs gamma^2 != 1 in GF(2^8)


class ErasureCodeClay(ErasureCode):
    def __init__(self):
        super().__init__()
        self.d = 0
        self.q = 0
        self.t = 0
        self.sub_chunks = 0
        self.H: np.ndarray | None = None  # (m, n) parity check of base MDS

    # -- setup --------------------------------------------------------------

    def init(self, profile: Profile) -> None:
        self.k = profile.to_int("k", 4)
        self.m = profile.to_int("m", 2)
        self.d = profile.to_int("d", self.k + self.m - 1)
        n = self.k + self.m
        if self.d != n - 1:
            raise ErasureCodeError(
                errno.EINVAL,
                f"clay: only d=k+m-1 supported (got d={self.d}, k+m-1={n - 1})")
        self.q = self.d - self.k + 1
        if n % self.q:
            raise ErasureCodeError(
                errno.EINVAL, f"clay: q={self.q} must divide k+m={n}")
        self.t = n // self.q
        self.sub_chunks = self.q ** self.t
        base = gf.cauchy_rs_matrix(self.k, self.m)
        p = base[self.k:]                      # (m, k)
        self.H = np.concatenate([p, np.eye(self.m, dtype=np.uint8)], axis=1)
        det = 1 ^ gf.gf_mul(GAMMA, GAMMA)
        self._cinv = gf.gf_inv(det)
        super().init(profile)

    def get_sub_chunk_count(self) -> int:
        return self.sub_chunks

    def get_alignment(self) -> int:
        # chunk must split into q^t sub-chunks
        return 64 * self.sub_chunks // np.gcd(64, self.sub_chunks) \
            if self.sub_chunks % 64 else self.sub_chunks

    def get_chunk_size(self, stripe_width: int) -> int:
        per = (stripe_width + self.k - 1) // self.k
        align = self.sub_chunks
        return -(-per // align) * align

    # -- geometry -----------------------------------------------------------

    def _node(self, chunk: int) -> tuple[int, int]:
        return chunk % self.q, chunk // self.q

    def _chunk(self, x: int, y: int) -> int:
        return y * self.q + x

    def _planes(self):
        return itertools.product(range(self.q), repeat=self.t)

    def _z_index(self, z: tuple[int, ...]) -> int:
        idx = 0
        for zy in z:
            idx = idx * self.q + zy
        return idx

    def _score(self, z: tuple[int, ...], erased_nodes: set) -> int:
        return sum(1 for (x, y) in erased_nodes if z[y] == x)

    # -- pair transform -----------------------------------------------------

    def _decouple(self, c_a, c_b):
        """U_A = cinv * (C_A + g*C_B) for a pair (A@z, B@z')."""
        lut = gf.mul_table()
        return lut[self._cinv][c_a ^ lut[GAMMA][c_b]]

    # -- the layered decoder ------------------------------------------------

    def _solve_plane(self, u_known: dict, unknown_nodes: list,
                     shape) -> dict:
        """Solve H u = 0 for the unknown nodes of one plane."""
        n = self.k + self.m
        cols = [self._chunk(x, y) for (x, y) in unknown_nodes]
        a = self.H[:, cols]                          # (m, u)
        rhs = np.zeros((self.m, *shape), dtype=np.uint8)
        lut = gf.mul_table()
        for r in range(self.m):
            for j in range(n):
                if j in cols:
                    continue
                h = int(self.H[r, j])
                if h:
                    rhs[r] ^= lut[h][u_known[j]]
        from .ec_shec import ErasureCodeShec
        sol = ErasureCodeShec._gf_solve(
            a.astype(np.uint8), rhs.reshape(self.m, -1))
        if sol is None:
            raise ErasureCodeError(errno.EIO, "clay: plane unsolvable")
        sol = sol.reshape(len(cols), *shape)
        return {cols[i]: sol[i] for i in range(len(cols))}

    def decode_layered(self, C: np.ndarray, erased: list[int]) -> np.ndarray:
        """C: (n, sub_chunks, S); rows in `erased` are garbage on input,
        reconstructed on output."""
        n = self.k + self.m
        S = C.shape[2]
        erased_nodes = {self._node(e) for e in erased}
        if len(erased) > self.m:
            raise ErasureCodeError(errno.EIO, "clay: too many erasures")
        out = C.copy()
        U = np.zeros_like(out)
        lut = gf.mul_table()
        erased_set = set(erased)
        planes = sorted(self._planes(),
                        key=lambda z: (self._score(z, erased_nodes), z))
        # pass A: compute U everywhere, planes in score order.  Intact
        # vertex with erased partner: partner plane has score-1 (the
        # erased partner is hole-aligned here but not there), so its U is
        # already solved — use C_A = U_A + g U_B directly and skip the
        # partner's C entirely.
        for z in planes:
            zi = self._z_index(z)
            u_known: dict[int, np.ndarray] = {}
            for ch in range(n):
                x, y = self._node(ch)
                if ch in erased_set:
                    continue
                if z[y] == x:
                    U[ch, zi] = out[ch, zi]
                else:
                    bch = self._chunk(z[y], y)
                    z2 = list(z)
                    z2[y] = x
                    z2i = self._z_index(tuple(z2))
                    if bch in erased_set:
                        U[ch, zi] = out[ch, zi] ^ lut[GAMMA][U[bch, z2i]]
                    else:
                        U[ch, zi] = self._decouple(out[ch, zi],
                                                   out[bch, z2i])
                u_known[ch] = U[ch, zi]
            if erased:
                sol = self._solve_plane(u_known,
                                        [self._node(e) for e in erased],
                                        (S,))
                for ch, val in sol.items():
                    U[ch, zi] = val
        # pass B: re-couple every erased vertex from the complete U field
        for z in self._planes():
            zi = self._z_index(z)
            for e in erased:
                x, y = self._node(e)
                if z[y] == x:
                    out[e, zi] = U[e, zi]
                else:
                    bch = self._chunk(z[y], y)
                    z2 = list(z)
                    z2[y] = x
                    z2i = self._z_index(tuple(z2))
                    out[e, zi] = U[e, zi] ^ lut[GAMMA][U[bch, z2i]]
        return out

    # -- codec interface ----------------------------------------------------

    def _to_planes(self, chunks: np.ndarray) -> np.ndarray:
        n_rows, cs = chunks.shape
        assert cs % self.sub_chunks == 0, (cs, self.sub_chunks)
        return chunks.reshape(n_rows, self.sub_chunks, cs // self.sub_chunks)

    def encode_chunks(self, chunks: np.ndarray) -> np.ndarray:
        n = self.k + self.m
        cs = chunks.shape[1]
        C = np.zeros((n, self.sub_chunks, cs // self.sub_chunks),
                     dtype=np.uint8)
        C[: self.k] = self._to_planes(chunks)
        C = self.decode_layered(C, list(range(self.k, n)))
        return C[self.k:].reshape(self.m, cs)

    def decode_chunks(self, dense: np.ndarray, erasures) -> np.ndarray:
        cs = dense.shape[1]
        C = self._to_planes(dense).copy()
        C = self.decode_layered(C, sorted(set(erasures)))
        return C.reshape(dense.shape[0], cs)

    # -- repair-optimal reads ----------------------------------------------

    def repair_planes(self, lost_chunk: int) -> list[int]:
        x0, y0 = self._node(lost_chunk)
        return sorted(self._z_index(z) for z in self._planes()
                      if z[y0] == x0)

    def minimum_to_decode(self, want_to_read, available):
        """Single lost chunk with every other chunk available -> repair
        planes only (the sub-chunk (offset,count) contract,
        reference ErasureCodeClay minimum_to_repair)."""
        want = set(want_to_read)
        avail = set(available)
        missing = want - avail
        n = self.k + self.m
        if len(missing) == 1 and len(avail) >= n - 1:
            planes = self.repair_planes(next(iter(missing)))
            runs = self._runs(planes)
            return {h: list(runs) for h in sorted(avail)[: self.d]}
        return super().minimum_to_decode(want, avail)

    @staticmethod
    def _runs(idxs: list[int]) -> list[tuple[int, int]]:
        runs = []
        for i in idxs:
            if runs and runs[-1][0] + runs[-1][1] == i:
                runs[-1] = (runs[-1][0], runs[-1][1] + 1)
            else:
                runs.append((i, 1))
        return [tuple(r) for r in runs]

    def repair(self, lost_chunk: int,
               helper_planes: dict[int, np.ndarray],
               sub_size: int) -> np.ndarray:
        """Rebuild `lost_chunk` from d helpers' repair-plane sub-chunks.

        helper_planes: chunk_id -> (len(repair_planes), sub_size) array,
        rows ordered like repair_planes(lost_chunk).
        Returns the full (sub_chunks * sub_size,) chunk.
        """
        n = self.k + self.m
        x0, y0 = self._node(lost_chunk)
        rp = self.repair_planes(lost_chunk)
        rp_pos = {zi: i for i, zi in enumerate(rp)}
        if len(helper_planes) < self.d:
            raise ErasureCodeError(errno.EIO, "clay: need d helpers")
        lut = gf.mul_table()
        out = np.zeros((self.sub_chunks, sub_size), dtype=np.uint8)
        # U values on repair planes, per node
        planes = [z for z in self._planes() if z[y0] == x0]
        ua_col_y0: dict[tuple[int, int], np.ndarray] = {}  # (x, zi) -> U_A
        for z in planes:
            zi = self._z_index(z)
            u_known: dict[int, np.ndarray] = {}
            unknown_nodes = [(x0, y0)]
            for ch in range(n):
                x, y = self._node(ch)
                if ch == lost_chunk:
                    continue
                cv = helper_planes[ch][rp_pos[zi]]
                if y == y0:
                    # pairs with the lost node at a non-repair plane:
                    # U unknown, solved below
                    unknown_nodes.append((x, y))
                    continue
                if z[y] == x:
                    u_known[ch] = cv
                else:
                    bx = z[y]
                    bch = self._chunk(bx, y)
                    z2 = list(z)
                    z2[y] = x
                    z2i = self._z_index(tuple(z2))
                    c_b = helper_planes[bch][rp_pos[z2i]]
                    u_known[ch] = self._decouple(cv, c_b)
            sol = self._solve_plane(u_known, unknown_nodes, (sub_size,))
            out[zi] = sol[lost_chunk]               # hole-aligned: C = U
            for x in range(self.q):
                if x == x0:
                    continue
                ch = self._chunk(x, y0)
                ua_col_y0[(x, zi)] = sol[ch]
        # non-repair planes of the lost chunk via the coupling relation:
        # lost node B at z' pairs with A=(x,y0) at z = z'(y0->x0), z in rp
        ginv = gf.gf_inv(GAMMA)
        for z in planes:
            zi = self._z_index(z)
            for x in range(self.q):
                if x == x0:
                    continue
                ch = self._chunk(x, y0)
                zprime = list(z)
                zprime[y0] = x
                zpi = self._z_index(tuple(zprime))
                u_a = ua_col_y0[(x, zi)]
                c_a = helper_planes[ch][rp_pos[zi]]
                # C_A@z = U_A + g U_B  ->  U_B = (C_A + U_A)/g
                u_b = lut[ginv][c_a ^ u_a]
                # C_B@z' = g U_A + U_B
                out[zpi] = lut[GAMMA][u_a] ^ u_b
        return out.reshape(-1)


class ErasureCodePluginClay(ErasureCodePlugin):
    def factory(self, profile: Profile):
        return ErasureCodeClay()


def __erasure_code_init__(name: str, directory: str | None) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginClay())
