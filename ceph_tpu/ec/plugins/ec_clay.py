"""CLAY plugin: Coupled-LAYer MSR regenerating code.

Fills the role of reference src/erasure-code/clay/ErasureCodeClay.{h,cc}
(profile k, m, d): an MDS code with *sub-chunked* chunks whose
single-failure repair reads only a fraction 1/q of each helper chunk —
the reason ErasureCodeInterface carries sub-chunk (offset, count) lists
in minimum_to_decode (reference ErasureCodeInterface.h:297,
ErasureCodeClay.h:57 get_sub_chunk_count).

Construction (Clay codes, FAST'18 — the same family the reference
implements): nodes are points (x, y) on a q x t grid (q = d-k+1).  For
general d the grid is padded with nu = (-(k+m)) mod q VIRTUAL nodes —
zero-filled data chunks that exist only inside the codec (reference
ErasureCodeClay.cc:273 "shortened" codes); t = (k+m+nu)/q.  Real chunk
i maps to node i for i < k and i + nu otherwise.  Every chunk splits
into q^t sub-chunks indexed by planes z = (z_0..z_{t-1}), z_y in [0,q).
An uncoupled symbol U(x,y;z) per node per plane forms, within each
plane, a codeword of a scalar MDS code with m parities; the stored
(coupled) symbols C relate to U by a pairwise invertible transform:
vertex (x,y) in plane z with x != z_y pairs with vertex (z_y, y) in
plane z(y->x), and

    [ C_A@z ; C_B@z' ] = [[1, g], [g, 1]] [ U_A@z ; U_B@z' ]   (g^2 != 1)

while hole-aligned vertices (x == z_y) have C = U.

decode_layered processes planes in increasing order of "intersection
score" (count of erased hole-aligned vertices): by induction every
intact vertex can be decoupled using symbols from lower-score planes,
each plane's <= m unknown U's solve via the MDS parity-check system, and
the erased C's re-couple.  Encode IS decode with the parity chunks as
the erasures (exactly the reference's approach).

Repair: losing one chunk (x0,y0) with d helpers reads only the q^{t-1}
"repair planes" {z : z_{y0} = x0} from each helper — the bandwidth-
optimal d/(d-k+1) chunk-equivalents total.  The d < k+m-1 case adds
"aloof" survivors excluded from the helper set (reference
repair_one_lost_chunk's aloof_nodes): the per-plane erasure set is the
lost node's whole column plus the aloof nodes — exactly m unknowns —
and a helper paired with an erased/aloof vertex decouples through that
partner's already-solved U (score induction) instead of its unread C.
"""

from __future__ import annotations

import errno
import itertools

import numpy as np

from .. import gf
from ..base import ErasureCode
from ..interface import ErasureCodeError, Profile
from ..registry import ErasureCodePlugin, ErasureCodePluginRegistry

__erasure_code_version__ = ErasureCodePlugin.abi_version

GAMMA = 2  # coupling constant; needs gamma^2 != 1 in GF(2^8)


class ErasureCodeClay(ErasureCode):
    def __init__(self):
        super().__init__()
        self.d = 0
        self.q = 0
        self.t = 0
        self.nu = 0                       # virtual (shortening) nodes
        self.sub_chunks = 0
        self.H: np.ndarray | None = None  # (m, N) parity check of base MDS
        # cached single-failure repair matrices (the device lowering,
        # docs/REPAIR.md): (lost, helper tuple) -> (sub_chunks, d*P)
        self._repair_mats: dict[tuple, np.ndarray] = {}

    # -- setup --------------------------------------------------------------

    def init(self, profile: Profile) -> None:
        self.k = profile.to_int("k", 4)
        self.m = profile.to_int("m", 2)
        self.d = profile.to_int("d", self.k + self.m - 1)
        n = self.k + self.m
        if not self.k < self.d <= n - 1:
            raise ErasureCodeError(
                errno.EINVAL,
                f"clay: need k < d <= k+m-1 (got d={self.d}, k={self.k}, "
                f"m={self.m})")
        self.q = self.d - self.k + 1
        self.nu = (-n) % self.q
        self.t = (n + self.nu) // self.q
        self.sub_chunks = self.q ** self.t
        base = gf.cauchy_rs_matrix(self.k + self.nu, self.m)
        p = base[self.k + self.nu:]            # (m, k+nu)
        self.H = np.concatenate([p, np.eye(self.m, dtype=np.uint8)], axis=1)
        det = 1 ^ gf.gf_mul(GAMMA, GAMMA)
        self._cinv = gf.gf_inv(det)
        super().init(profile)

    def get_sub_chunk_count(self) -> int:
        return self.sub_chunks

    def get_alignment(self) -> int:
        # chunk must split into q^t sub-chunks
        return 64 * self.sub_chunks // np.gcd(64, self.sub_chunks) \
            if self.sub_chunks % 64 else self.sub_chunks

    def get_chunk_size(self, stripe_width: int) -> int:
        per = (stripe_width + self.k - 1) // self.k
        align = self.sub_chunks
        return -(-per // align) * align

    # -- geometry (all in PADDED node ids: 0..N-1, N = q*t) -----------------

    @property
    def N(self) -> int:
        return self.q * self.t

    def _pad_id(self, chunk: int) -> int:
        """Real chunk id -> padded node id (virtual nodes sit between
        data and parity, reference ErasureCodeClay.cc:312)."""
        return chunk if chunk < self.k else chunk + self.nu

    def _real_id(self, node: int) -> int | None:
        if node < self.k:
            return node
        if node < self.k + self.nu:
            return None                   # virtual
        return node - self.nu

    def _node(self, node_id: int) -> tuple[int, int]:
        return node_id % self.q, node_id // self.q

    def _chunk(self, x: int, y: int) -> int:
        return y * self.q + x

    def _planes(self):
        return itertools.product(range(self.q), repeat=self.t)

    def _z_index(self, z: tuple[int, ...]) -> int:
        idx = 0
        for zy in z:
            idx = idx * self.q + zy
        return idx

    def _score(self, z: tuple[int, ...], erased_nodes: set) -> int:
        return sum(1 for (x, y) in erased_nodes if z[y] == x)

    # -- pair transform -----------------------------------------------------

    def _decouple(self, c_a, c_b):
        """U_A = cinv * (C_A + g*C_B) for a pair (A@z, B@z')."""
        lut = gf.mul_table()
        return lut[self._cinv][c_a ^ lut[GAMMA][c_b]]

    # -- the layered decoder ------------------------------------------------

    def _solve_plane(self, u_known: dict, unknown_nodes: list,
                     shape) -> dict:
        """Solve H u = 0 for the unknown nodes of one plane."""
        cols = [self._chunk(x, y) for (x, y) in unknown_nodes]
        a = self.H[:, cols]                          # (m, u)
        rhs = np.zeros((self.m, *shape), dtype=np.uint8)
        lut = gf.mul_table()
        for r in range(self.m):
            for j in range(self.N):
                if j in cols:
                    continue
                h = int(self.H[r, j])
                if h:
                    rhs[r] ^= lut[h][u_known[j]]
        from .ec_shec import ErasureCodeShec
        sol = ErasureCodeShec._gf_solve(
            a.astype(np.uint8), rhs.reshape(self.m, -1))
        if sol is None:
            raise ErasureCodeError(errno.EIO, "clay: plane unsolvable")
        sol = sol.reshape(len(cols), *shape)
        return {cols[i]: sol[i] for i in range(len(cols))}

    def decode_layered(self, C: np.ndarray, erased: list[int]) -> np.ndarray:
        """C: (N, sub_chunks, S) in padded node order; rows in `erased`
        (padded ids) are garbage on input, reconstructed on output."""
        S = C.shape[2]
        erased_nodes = {self._node(e) for e in erased}
        if len(erased) > self.m:
            raise ErasureCodeError(errno.EIO, "clay: too many erasures")
        out = C.copy()
        U = np.zeros_like(out)
        lut = gf.mul_table()
        erased_set = set(erased)
        planes = sorted(self._planes(),
                        key=lambda z: (self._score(z, erased_nodes), z))
        # pass A: compute U everywhere, planes in score order.  Intact
        # vertex with erased partner: partner plane has score-1 (the
        # erased partner is hole-aligned here but not there), so its U is
        # already solved — use C_A = U_A + g U_B directly and skip the
        # partner's C entirely.
        for z in planes:
            zi = self._z_index(z)
            u_known: dict[int, np.ndarray] = {}
            for ch in range(self.N):
                x, y = self._node(ch)
                if ch in erased_set:
                    continue
                if z[y] == x:
                    U[ch, zi] = out[ch, zi]
                else:
                    bch = self._chunk(z[y], y)
                    z2 = list(z)
                    z2[y] = x
                    z2i = self._z_index(tuple(z2))
                    if bch in erased_set:
                        U[ch, zi] = out[ch, zi] ^ lut[GAMMA][U[bch, z2i]]
                    else:
                        U[ch, zi] = self._decouple(out[ch, zi],
                                                   out[bch, z2i])
                u_known[ch] = U[ch, zi]
            if erased:
                sol = self._solve_plane(u_known,
                                        [self._node(e) for e in erased],
                                        (S,))
                for ch, val in sol.items():
                    U[ch, zi] = val
        # pass B: re-couple every erased vertex from the complete U field
        for z in self._planes():
            zi = self._z_index(z)
            for e in erased:
                x, y = self._node(e)
                if z[y] == x:
                    out[e, zi] = U[e, zi]
                else:
                    bch = self._chunk(z[y], y)
                    z2 = list(z)
                    z2[y] = x
                    z2i = self._z_index(tuple(z2))
                    out[e, zi] = U[e, zi] ^ lut[GAMMA][U[bch, z2i]]
        return out

    # -- codec interface ----------------------------------------------------

    def _to_planes(self, chunks: np.ndarray) -> np.ndarray:
        n_rows, cs = chunks.shape
        assert cs % self.sub_chunks == 0, (cs, self.sub_chunks)
        return chunks.reshape(n_rows, self.sub_chunks, cs // self.sub_chunks)

    def _pad_rows(self, rows: np.ndarray) -> np.ndarray:
        """(k+m, sub, S) real rows -> (N, sub, S) with zero virtual
        rows spliced between data and parity."""
        if not self.nu:
            return rows
        z = np.zeros((self.nu, *rows.shape[1:]), dtype=rows.dtype)
        return np.concatenate([rows[:self.k], z, rows[self.k:]], axis=0)

    def _strip_rows(self, rows: np.ndarray) -> np.ndarray:
        if not self.nu:
            return rows
        return np.concatenate(
            [rows[:self.k], rows[self.k + self.nu:]], axis=0)

    def encode_chunks(self, chunks: np.ndarray) -> np.ndarray:
        cs = chunks.shape[1]
        C = np.zeros((self.N, self.sub_chunks, cs // self.sub_chunks),
                     dtype=np.uint8)
        C[: self.k] = self._to_planes(chunks)
        C = self.decode_layered(
            C, list(range(self.k + self.nu, self.N)))
        return C[self.k + self.nu:].reshape(self.m, cs)

    def decode_chunks(self, dense: np.ndarray, erasures) -> np.ndarray:
        cs = dense.shape[1]
        C = self._pad_rows(self._to_planes(dense).copy())
        C = self.decode_layered(
            C, sorted({self._pad_id(e) for e in erasures}))
        return self._strip_rows(C).reshape(dense.shape[0], cs)

    # -- repair-optimal reads ----------------------------------------------

    def repair_planes(self, lost_chunk: int) -> list[int]:
        x0, y0 = self._node(self._pad_id(lost_chunk))
        return sorted(self._z_index(z) for z in self._planes()
                      if z[y0] == x0)

    def _column_chunks(self, lost_chunk: int) -> set[int]:
        """REAL ids of the lost chunk's grid column (the q-1 partners
        that must be in every helper set; virtual ids excluded)."""
        _x0, y0 = self._node(self._pad_id(lost_chunk))
        out = set()
        for x in range(self.q):
            r = self._real_id(self._chunk(x, y0))
            if r is not None and r != lost_chunk:
                out.add(r)
        return out

    def choose_helpers(self, lost_chunk: int,
                       available: set[int]) -> list[int] | None:
        """The reference's helper choice (minimum_to_repair): the lost
        node's column partners first, then fill to d from the rest.
        None if single-failure repair is not applicable."""
        col = self._column_chunks(lost_chunk)
        if not col <= available or len(available) < self.d:
            return None
        helpers = sorted(col)
        for ch in sorted(available):
            if len(helpers) >= self.d:
                break
            if ch not in col and ch != lost_chunk:
                helpers.append(ch)
        return helpers if len(helpers) == self.d else None

    def minimum_to_decode(self, want_to_read, available):
        """Single lost chunk with its column intact and >= d survivors
        -> repair planes only from d chosen helpers (the sub-chunk
        (offset,count) contract, reference minimum_to_repair)."""
        want = set(want_to_read)
        avail = set(available)
        missing = want - avail
        # repair path ONLY when the lost chunk is the sole want — the
        # reference's is_repair rejects want_to_read.size() > 1 the
        # same way (a mixed want would otherwise get a map that never
        # reads the other wanted, available chunks)
        if len(missing) == 1 and want <= missing:
            lost = next(iter(missing))
            helpers = self.choose_helpers(lost, avail - want)
            if helpers is not None:
                runs = self._runs(self.repair_planes(lost))
                return {h: list(runs) for h in helpers}
        return super().minimum_to_decode(want, avail)

    @staticmethod
    def _runs(idxs: list[int]) -> list[tuple[int, int]]:
        runs = []
        for i in idxs:
            if runs and runs[-1][0] + runs[-1][1] == i:
                runs[-1] = (runs[-1][0], runs[-1][1] + 1)
            else:
                runs.append((i, 1))
        return [tuple(r) for r in runs]

    def repair(self, lost_chunk: int,
               helper_planes: dict[int, np.ndarray],
               sub_size: int) -> np.ndarray:
        """Rebuild `lost_chunk` from exactly d helpers' repair-plane
        sub-chunks.

        helper_planes: real chunk_id -> (len(repair_planes), sub_size)
        array, rows ordered like repair_planes(lost_chunk).  Survivors
        NOT in helper_planes are "aloof": their symbols are never read
        and their per-plane U's are solved as unknowns (reference
        repair_one_lost_chunk).  Returns the full chunk.
        """
        lost = self._pad_id(lost_chunk)
        x0, y0 = self._node(lost)
        rp = self.repair_planes(lost_chunk)
        rp_pos = {zi: i for i, zi in enumerate(rp)}
        if len(helper_planes) != self.d:
            raise ErasureCodeError(
                errno.EIO, f"clay: need exactly d={self.d} helpers "
                f"(got {len(helper_planes)})")
        if not self._column_chunks(lost_chunk) <= set(helper_planes):
            raise ErasureCodeError(
                errno.EIO, "clay: helper set must include the lost "
                "chunk's column partners")
        lut = gf.mul_table()
        # padded helper table; virtual nodes are zero-filled helpers
        helpers = {self._pad_id(ch): arr
                   for ch, arr in helper_planes.items()}
        for v in range(self.k, self.k + self.nu):
            helpers[v] = np.zeros((len(rp), sub_size), dtype=np.uint8)
        # erasure set per plane: the lost column + aloof survivors —
        # exactly m unknowns (q + (k+m-d-1) = m)
        column = {self._chunk(x, y0) for x in range(self.q)}
        aloof = set(range(self.N)) - set(helpers) - {lost}
        erasures = column | aloof
        erased_nodes = {self._node(e) for e in erasures}
        out = np.zeros((self.sub_chunks, sub_size), dtype=np.uint8)
        U: dict[tuple[int, int], np.ndarray] = {}  # (node, zi) -> U
        planes = sorted((z for z in self._planes() if z[y0] == x0),
                        key=lambda z: (self._score(z, erased_nodes), z))
        for z in planes:
            zi = self._z_index(z)
            u_known: dict[int, np.ndarray] = {}
            for ch in range(self.N):
                if ch in erasures:
                    continue
                x, y = self._node(ch)
                cv = helpers[ch][rp_pos[zi]]
                if z[y] == x:
                    u_known[ch] = cv
                else:
                    bch = self._chunk(z[y], y)
                    z2 = list(z)
                    z2[y] = x
                    z2i = self._z_index(tuple(z2))
                    if bch in erasures:
                        # partner unread: decouple via its U, solved in
                        # a lower-score plane (score induction — bch is
                        # hole-aligned at z, not at z2)
                        u_known[ch] = cv ^ lut[GAMMA][U[(bch, z2i)]]
                    else:
                        u_known[ch] = self._decouple(
                            cv, helpers[bch][rp_pos[z2i]])
            sol = self._solve_plane(
                u_known, [self._node(e) for e in erasures], (sub_size,))
            for ch, val in sol.items():
                U[(ch, zi)] = val
            out[zi] = sol[lost]                 # hole-aligned: C = U
        # non-repair planes of the lost chunk via the coupling relation:
        # lost node B at z' pairs with A=(x,y0) at z = z'(y0->x0), z in rp
        ginv = gf.gf_inv(GAMMA)
        for z in planes:
            zi = self._z_index(z)
            for x in range(self.q):
                if x == x0:
                    continue
                ch = self._chunk(x, y0)
                zprime = list(z)
                zprime[y0] = x
                zpi = self._z_index(tuple(zprime))
                u_a = U[(ch, zi)]               # column U: plane-solved
                if ch in helpers:
                    c_a = helpers[ch][rp_pos[zi]]
                    # C_A@z = U_A + g U_B  ->  U_B = (C_A + U_A)/g
                    u_b = lut[ginv][c_a ^ u_a]
                else:
                    raise ErasureCodeError(
                        errno.EIO, "clay: column partner missing")
                # C_B@z' = g U_A + U_B
                out[zpi] = lut[GAMMA][u_a] ^ u_b
        return out.reshape(-1)

    # -- device lowering: repair as ONE GF(2^8) matrix -----------------------
    #
    # Every step of repair() is GF(2^8)-linear in the helper symbols:
    # the pairwise decouple transform is a constant 2x2 GF matrix, the
    # per-plane solve inverts a system whose coefficient matrix depends
    # only on the erasure pattern (never the data), and the final
    # re-coupling is again constant gf_muls and XORs.  The whole
    # coupled-layer contraction therefore collapses to a single
    # (sub_chunks x d*P) matrix R over GF(2^8) applied to the stacked
    # helper repair-plane symbols — which is exactly the shape the
    # TPU/mesh data plane wants: one batched GF matmul per
    # (lost, helpers) group, objects concatenated along the byte axis
    # (parallel/mesh.py ClayRepairPlan / clay_repair_batch).  R is
    # extracted by probing repair() with an identity payload: helper
    # h's plane row p carries unit vector e_{h*P+p} (sub_size = d*P),
    # so the output IS the matrix, in one host repair call.

    def repair_helper_order(self, lost_chunk: int,
                            helper_ids=None) -> tuple[int, ...]:
        """Canonical helper row order of the repair matrix (sorted
        real chunk ids); helper h at index hi owns input rows
        [hi*P, (hi+1)*P)."""
        if helper_ids is None:
            helper_ids = self.choose_helpers(
                lost_chunk,
                set(range(self.get_chunk_count())) - {lost_chunk})
            if helper_ids is None:
                raise ErasureCodeError(
                    errno.EIO, f"clay: no helper set for {lost_chunk}")
        return tuple(sorted(helper_ids))

    def repair_matrix(self, lost_chunk: int,
                      helper_ids=None) -> np.ndarray:
        """(sub_chunks, d*P) GF(2^8) matrix R with
        rebuilt_chunk = R @ rows, rows[hi*P + p] = helper hi's p-th
        repair-plane sub-chunk (repair_helper_order order).  Cached
        per (lost, helpers) — the plane-by-plane host solver runs once
        per geometry, every later repair is a matmul."""
        helpers = self.repair_helper_order(lost_chunk, helper_ids)
        key = (lost_chunk, helpers)
        hit = self._repair_mats.get(key)
        if hit is not None:
            return hit
        P = len(self.repair_planes(lost_chunk))
        J = self.d * P
        probes = {}
        for hi, ch in enumerate(helpers):
            arr = np.zeros((P, J), dtype=np.uint8)
            arr[np.arange(P), hi * P + np.arange(P)] = 1
            probes[ch] = arr
        mat = self.repair(lost_chunk, probes, J) \
            .reshape(self.sub_chunks, J)
        self._repair_mats[key] = mat
        return mat

    def repair_rows(self, lost_chunk: int,
                    helper_planes: dict[int, np.ndarray],
                    helper_ids=None) -> np.ndarray:
        """Stack a repair() helper dict into the (d*P, sub_size) row
        layout repair_matrix expects."""
        helpers = self.repair_helper_order(
            lost_chunk, helper_ids if helper_ids is not None
            else helper_planes.keys())
        return np.concatenate(
            [np.asarray(helper_planes[ch], dtype=np.uint8)
             for ch in helpers], axis=0)

    def repair_signature(self, lost_chunk: int,
                         helper_ids=None) -> tuple:
        """Cache/coalescing key of one repair plan: geometry +
        (lost, helpers) fully determine the matrix (the base MDS
        parity check is derived from (k+nu, m) deterministically)."""
        return ("clay", self.k, self.m, self.d, lost_chunk,
                self.repair_helper_order(lost_chunk, helper_ids))


class ErasureCodePluginClay(ErasureCodePlugin):
    def factory(self, profile: Profile):
        return ErasureCodeClay()


def __erasure_code_init__(name: str, directory: str | None) -> None:
    ErasureCodePluginRegistry.instance().add(name, ErasureCodePluginClay())
