"""CPU Reed-Solomon plugin ("jerasure" role).

Fills the role of the reference's jerasure plugin
(src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc}): the default CPU
codec with multiple techniques.  The GF kernels are our own
(ceph_tpu/ec/gf.py, numpy LUT region ops) since the reference's come from
vendored submodules.  Techniques:

  reed_sol_van   - systematic Vandermonde-derived matrix (reference :162)
  reed_sol_r6_op - RAID-6 specialization: P = XOR, Q = sum 2^j * d_j
                   (reference ErasureCodeJerasure.h:102)
  cauchy_orig    - Cauchy generator matrix, elementwise GF mult
  cauchy_good    - Cauchy matrix applied via its GF(2) bitmatrix expansion
                   (the CPU twin of the TPU kernel; reference :265,353 use
                   jerasure bitmatrix "schedules" — same math, dense here)
  liberation / blaum_roth / liber8tion - accepted as aliases of
                   cauchy_good (the reference's minimal-density bitmatrix
                   codes; same interface contract, m<=2)

Default profile k=2 m=1 technique=reed_sol_van mirrors the reference
plugin defaults (src/erasure-code/jerasure/ErasureCodePluginJerasure.cc).
"""

from __future__ import annotations

import errno

import numpy as np

from .. import gf
from ..base import ErasureCode
from ..interface import ErasureCodeError, Profile
from ..registry import ErasureCodePlugin, ErasureCodePluginRegistry

__erasure_code_version__ = ErasureCodePlugin.abi_version

TECHNIQUES = (
    "reed_sol_van", "reed_sol_r6_op", "cauchy_orig", "cauchy_good",
    "liberation", "blaum_roth", "liber8tion",
)


class ErasureCodeJerasure(ErasureCode):
    """Matrix RS codec over GF(2^8) with pluggable matrix technique."""

    technique = "reed_sol_van"

    def __init__(self, technique: str = "reed_sol_van"):
        super().__init__()
        self.technique = technique
        self.matrix: np.ndarray | None = None      # (k+m, k) over GF(2^8)
        self.bitmatrix: np.ndarray | None = None   # (8(k+m), 8k) over GF(2)

    # -- setup --------------------------------------------------------------

    def init(self, profile: Profile) -> None:
        self.k = profile.to_int("k", 2)
        self.m = profile.to_int("m", 1)
        if self.k < 1 or self.m < 1:
            raise ErasureCodeError(errno.EINVAL, f"k={self.k} m={self.m} invalid")
        if self.k + self.m > gf.GF_SIZE:
            raise ErasureCodeError(
                errno.EINVAL, f"k+m={self.k + self.m} > {gf.GF_SIZE}")
        if self.technique == "reed_sol_r6_op" and self.m != 2:
            raise ErasureCodeError(errno.EINVAL, "reed_sol_r6_op requires m=2")
        if self.technique in ("liberation", "blaum_roth", "liber8tion") \
                and self.m > 2:
            raise ErasureCodeError(
                errno.EINVAL, f"{self.technique} requires m<=2")
        self.matrix = self._build_matrix()
        if self._use_bitmatrix():
            self.bitmatrix = gf.expand_to_bitmatrix(self.matrix[self.k:])
        super().init(profile)

    def _build_matrix(self) -> np.ndarray:
        if self.technique == "reed_sol_van":
            return gf.vandermonde_rs_matrix(self.k, self.m)
        if self.technique == "reed_sol_r6_op":
            g = np.zeros((self.k + 2, self.k), dtype=np.uint8)
            g[: self.k] = np.eye(self.k, dtype=np.uint8)
            g[self.k, :] = 1                                   # P: XOR
            g[self.k + 1, :] = [gf.gf_pow(2, j) for j in range(self.k)]  # Q
            return g
        # cauchy_* and the minimal-density aliases
        return gf.cauchy_rs_matrix(self.k, self.m)

    def _use_bitmatrix(self) -> bool:
        return self.technique in (
            "cauchy_good", "liberation", "blaum_roth", "liber8tion")

    # -- encode / decode ----------------------------------------------------

    def encode_chunks(self, chunks: np.ndarray) -> np.ndarray:
        # The bitmatrix (kept for oracle tests of the TPU layout) computes
        # identical bytes; the LUT/native-SIMD path is the fast CPU route
        # even for the bitmatrix techniques.
        return gf.gf_matvec(self.matrix[self.k:], chunks)

    def decode_chunks(self, dense: np.ndarray, erasures) -> np.ndarray:
        """Reconstruct erased rows: invert the surviving generator rows.

        Mirrors jerasure_matrix_decode: take k surviving rows R of the
        generator G, invert the kxk matrix G[R], then erased chunk i =
        G[i] @ inv @ surviving-chunks (reference ErasureCodeJerasure.cc:195).
        """
        n = self.get_chunk_count()
        erased = set(erasures)
        survivors = [i for i in range(n) if i not in erased][: self.k]
        if len(survivors) < self.k:
            raise ErasureCodeError(errno.EIO, "not enough survivors")
        sub = self.matrix[survivors, :]            # (k, k)
        inv = gf.gf_invert_matrix(sub)             # data = inv @ survivors
        out = dense.copy()
        need_data = [e for e in erased if e < self.k]
        need_par = [e for e in erased if e >= self.k]
        if need_data or need_par:
            rows = np.stack([inv[e] for e in need_data]) if need_data else None
            if rows is not None:
                rec = gf.gf_matvec(rows, dense[survivors])
                for idx, e in enumerate(need_data):
                    out[e] = rec[idx]
        if need_par:
            # Re-encode parity from (now complete) data chunks.
            par_rows = self.matrix[need_par, :]
            rec = gf.gf_matvec(par_rows, out[: self.k])
            for idx, e in enumerate(need_par):
                out[e] = rec[idx]
        return out


class ErasureCodePluginJerasure(ErasureCodePlugin):
    def factory(self, profile: Profile):
        technique = profile.get("technique", "reed_sol_van") or "reed_sol_van"
        if technique not in TECHNIQUES:
            raise ErasureCodeError(
                errno.ENOENT, f"unknown jerasure technique {technique!r}")
        return ErasureCodeJerasure(technique)


def __erasure_code_init__(name: str, directory: str | None) -> None:
    ErasureCodePluginRegistry.instance().add(
        name, ErasureCodePluginJerasure())
