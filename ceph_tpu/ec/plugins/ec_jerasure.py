"""CPU Reed-Solomon plugin ("jerasure" role).

Fills the role of the reference's jerasure plugin
(src/erasure-code/jerasure/ErasureCodeJerasure.{h,cc}): the default CPU
codec with multiple techniques.  The GF kernels are our own
(ceph_tpu/ec/gf.py, numpy LUT region ops) since the reference's come from
vendored submodules.  Techniques:

  reed_sol_van   - systematic Vandermonde-derived matrix (reference :162)
  reed_sol_r6_op - RAID-6 specialization: P = XOR, Q = sum 2^j * d_j
                   (reference ErasureCodeJerasure.h:102)
  cauchy_orig    - Cauchy generator matrix, elementwise GF mult
  cauchy_good    - Cauchy matrix applied via its GF(2) bitmatrix expansion
                   (the CPU twin of the TPU kernel; reference :265,353 use
                   jerasure bitmatrix "schedules" — same math, dense here)
  liberation / blaum_roth / liber8tion - real minimal-density RAID-6
                   bitmatrix codes (XOR-only, w-bit packets) built in
                   ceph_tpu/ec/bitmatrix.py (reference
                   ErasureCodeJerasure.h:198-246; same m=2 and w
                   parameter contracts).  liber8tion's search-derived
                   matrix is a documented deviation from the jerasure
                   table (see bitmatrix.py docstring).

Default profile k=2 m=1 technique=reed_sol_van mirrors the reference
plugin defaults (src/erasure-code/jerasure/ErasureCodePluginJerasure.cc).
"""

from __future__ import annotations

import errno

import numpy as np

from .. import gf
from ..base import ErasureCode
from ..interface import ErasureCodeError, Profile
from ..registry import ErasureCodePlugin, ErasureCodePluginRegistry

__erasure_code_version__ = ErasureCodePlugin.abi_version

TECHNIQUES = (
    "reed_sol_van", "reed_sol_r6_op", "cauchy_orig", "cauchy_good",
    "liberation", "blaum_roth", "liber8tion",
)


class ErasureCodeJerasure(ErasureCode):
    """Matrix RS codec over GF(2^8) with pluggable matrix technique."""

    technique = "reed_sol_van"

    MINIMAL_DENSITY = ("liberation", "blaum_roth", "liber8tion")

    # launch-queue coalescing (parallel/launch_queue.codec_signature):
    # for every technique that SETS self.matrix, encode_chunks is
    # exactly gf_matvec(matrix[k:]) — equal matrices mean bit-equal
    # parity, so such instances may share a cross-PG super-batch.
    # Minimal-density techniques encode via bitmatrix packets instead,
    # and leave self.matrix None (instance-identity batching only).
    matrix_determines_encode = True

    def __init__(self, technique: str = "reed_sol_van"):
        super().__init__()
        self.technique = technique
        self.matrix: np.ndarray | None = None      # (k+m, k) over GF(2^8)
        self.bitmatrix: np.ndarray | None = None   # (8(k+m), 8k) over GF(2)
        self.w = 8                                 # word size (bitmatrix)
        self._md_coding: np.ndarray | None = None  # (2w, kw) minimal-density
        self._md_gen: np.ndarray | None = None

    # -- setup --------------------------------------------------------------

    def init(self, profile: Profile) -> None:
        self.k = profile.to_int("k", 2)
        if self.k < 1:
            raise ErasureCodeError(errno.EINVAL, f"k={self.k} invalid")
        if self.technique in self.MINIMAL_DENSITY:
            # reference defaults: m=2 mandatory, w=7 (liberation/
            # blaum_roth) or 8 (liber8tion), packetsize accepted
            # (ErasureCodeJerasure.cc:429-513)
            self.m = profile.to_int("m", 2)
            # defaults: liberation w=7 (prime, reference DEFAULT_W);
            # blaum_roth w=6 (w+1=7 prime — the reference's legacy
            # default 7 is not double-erasure decodable, see
            # bitmatrix.blaum_roth_x); liber8tion w=8 fixed
            self.w = profile.to_int(
                "w", {"liber8tion": 8, "blaum_roth": 6}.get(
                    self.technique, 7))
            if self.m != 2:
                raise ErasureCodeError(
                    errno.EINVAL, f"{self.technique} requires m=2")
            from .. import bitmatrix as bm
            self._md_coding = bm.coding_matrix(self.technique,
                                               self.k, self.w)
            self._md_gen = bm.generator(self.technique, self.k, self.w)
            super().init(profile)
            return
        self.m = profile.to_int("m", 1)
        if self.k < 1 or self.m < 1:
            raise ErasureCodeError(errno.EINVAL, f"k={self.k} m={self.m} invalid")
        if self.k + self.m > gf.GF_SIZE:
            raise ErasureCodeError(
                errno.EINVAL, f"k+m={self.k + self.m} > {gf.GF_SIZE}")
        if self.technique == "reed_sol_r6_op" and self.m != 2:
            raise ErasureCodeError(errno.EINVAL, "reed_sol_r6_op requires m=2")
        self.matrix = self._build_matrix()
        if self._use_bitmatrix():
            self.bitmatrix = gf.expand_to_bitmatrix(self.matrix[self.k:])
        super().init(profile)

    def get_alignment(self) -> int:
        # minimal-density chunks are w packets: chunk_size % w == 0
        from ..base import SIMD_ALIGN
        if self.technique in self.MINIMAL_DENSITY:
            return SIMD_ALIGN * self.w
        return SIMD_ALIGN

    def _build_matrix(self) -> np.ndarray:
        if self.technique == "reed_sol_van":
            return gf.vandermonde_rs_matrix(self.k, self.m)
        if self.technique == "reed_sol_r6_op":
            g = np.zeros((self.k + 2, self.k), dtype=np.uint8)
            g[: self.k] = np.eye(self.k, dtype=np.uint8)
            g[self.k, :] = 1                                   # P: XOR
            g[self.k + 1, :] = [gf.gf_pow(2, j) for j in range(self.k)]  # Q
            return g
        # cauchy_*
        return gf.cauchy_rs_matrix(self.k, self.m)

    def _use_bitmatrix(self) -> bool:
        return self.technique == "cauchy_good"

    # -- encode / decode ----------------------------------------------------

    def encode_chunks(self, chunks: np.ndarray) -> np.ndarray:
        if self.technique in self.MINIMAL_DENSITY:
            from .. import bitmatrix as bm
            return bm.encode(self._md_coding, chunks, self.w)
        # The bitmatrix (kept for oracle tests of the TPU layout) computes
        # identical bytes; the LUT/native-SIMD path is the fast CPU route
        # even for the bitmatrix techniques.
        return gf.gf_matvec(self.matrix[self.k:], chunks)

    def decode_chunks(self, dense: np.ndarray, erasures) -> np.ndarray:
        """Reconstruct erased rows: invert the surviving generator rows.

        Mirrors jerasure_matrix_decode: take k surviving rows R of the
        generator G, invert the kxk matrix G[R], then erased chunk i =
        G[i] @ inv @ surviving-chunks (reference ErasureCodeJerasure.cc:195).
        """
        if self.technique in self.MINIMAL_DENSITY:
            from .. import bitmatrix as bm
            return bm.decode(self._md_gen, dense, erasures, self.k, self.w)
        n = self.get_chunk_count()
        erased = set(erasures)
        survivors = [i for i in range(n) if i not in erased][: self.k]
        if len(survivors) < self.k:
            raise ErasureCodeError(errno.EIO, "not enough survivors")
        sub = self.matrix[survivors, :]            # (k, k)
        inv = gf.gf_invert_matrix(sub)             # data = inv @ survivors
        out = dense.copy()
        need_data = [e for e in erased if e < self.k]
        need_par = [e for e in erased if e >= self.k]
        if need_data or need_par:
            rows = np.stack([inv[e] for e in need_data]) if need_data else None
            if rows is not None:
                rec = gf.gf_matvec(rows, dense[survivors])
                for idx, e in enumerate(need_data):
                    out[e] = rec[idx]
        if need_par:
            # Re-encode parity from (now complete) data chunks.
            par_rows = self.matrix[need_par, :]
            rec = gf.gf_matvec(par_rows, out[: self.k])
            for idx, e in enumerate(need_par):
                out[e] = rec[idx]
        return out


class ErasureCodePluginJerasure(ErasureCodePlugin):
    def factory(self, profile: Profile):
        technique = profile.get("technique", "reed_sol_van") or "reed_sol_van"
        if technique not in TECHNIQUES:
            raise ErasureCodeError(
                errno.ENOENT, f"unknown jerasure technique {technique!r}")
        return ErasureCodeJerasure(technique)


def __erasure_code_init__(name: str, directory: str | None) -> None:
    ErasureCodePluginRegistry.instance().add(
        name, ErasureCodePluginJerasure())
