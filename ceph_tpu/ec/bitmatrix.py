"""Minimal-density RAID-6 bitmatrix codes: liberation / blaum_roth /
liber8tion.

Fills the role of the reference's jerasure minimal-density techniques
(src/erasure-code/jerasure/ErasureCodeJerasure.h:198-246 — the
ErasureCodeJerasureLiberation/BlaumRoth/Liber8tion classes, whose
bitmatrix constructors live in the jerasure library's liberation.c).
These codes protect k data chunks with m=2 parity chunks where every
chunk is treated as w packets of bits and parity is PURE XOR of
packets — no GF multiplications — with close to the theoretical
minimum number of XORs.

Construction model (all three techniques):

  P = d_0 ^ d_1 ^ ... ^ d_{k-1}              (chunkwise XOR)
  Q = X_0 d_0 ^ X_1 d_1 ^ ... ^ X_{k-1} d_{k-1}

where each d_j is a length-w vector of packets and each X_j is a w x w
0/1 matrix.  The code corrects any two chunk erasures iff every X_j
and every X_i ^ X_j (i != j) is invertible over GF(2):

  * two data chunks i<j lost:  (X_i ^ X_j) d_i = Q' ^ X_j P'
  * one data chunk + P lost:   X_i d_i = Q'
  * anything else reduces to XOR or re-encode.

Techniques (same parameter contracts as the reference):

  liberation  — w prime > 2, k <= w (Plank, "The RAID-6 Liberation
                Codes", FAST 2008): X_j is the rotation matrix sigma^j
                (ones at (i, (i+j) mod w)) plus, for j > 0, one extra
                one at row r = j(w-1)/2 mod w, column (r+j-1) mod w.
                Total density kw + k - 1 = the proven minimum.
  blaum_roth  — w+1 prime (Blaum & Roth, "On Lowest Density MDS
                Codes", IEEE Trans. IT 1999): X_j represents
                multiplication by x^j in the polynomial ring
                GF(2)[x] / M_p(x), p = w+1, M_p(x) = 1 + x + ... + x^w.
                Column c of X_j is x^(j+c) mod M_p(x).  Invertibility
                of X_i ^ X_j follows from gcd(x^d + 1, M_p) = 1 for
                p prime.  Deviation: the legacy w=7 the reference
                tolerates is rejected here (see blaum_roth_x).
  liber8tion  — w = 8 exactly, m = 2, k <= 8 (role of Plank's
                liber8tion code).  w=8 has no liberation construction
                (8 is not prime) and the reference's matrix is an
                unpublished-formula search table, so the X_j here are
                the multiplication matrices of the k LIGHTEST elements
                of GF(2^8)/0x11d (column c of X_e = e*x^c): distinct
                nonzero elements make every X_i ^ X_j the matrix of
                multiplication by e_i + e_j != 0, hence invertible —
                decodability is a theorem, not a search result.  Total
                Q density for k=8 is 111 ones vs the 71 theoretical
                minimum and ~256 for a Cauchy bitmatrix; a documented
                deviation: low-density, not provably minimal, and not
                byte-compatible with jerasure's table.

The w-bit-packet layout maps directly onto the TPU bitsliced kernel
model (ops/bitsliced.py): a bitmatrix is one more w-plane XOR
schedule.  The CPU path below vectorizes packet XORs with numpy.
"""

from __future__ import annotations

import errno
from functools import lru_cache

import numpy as np

from .interface import ErasureCodeError


def is_prime(n: int) -> bool:
    if n < 2:
        return False
    for p in range(2, int(n ** 0.5) + 1):
        if n % p == 0:
            return False
    return True


# -- GF(2) linear algebra (rows as python ints for speed) --------------------

def _to_rowints(mat: np.ndarray) -> list[int]:
    w = mat.shape[1]
    return [int("".join("1" if mat[i, w - 1 - c] else "0"
                        for c in range(w)), 2) if mat[i].any() else 0
            for i in range(mat.shape[0])]


def gf2_invertible(mat: np.ndarray) -> bool:
    """Gaussian elimination over GF(2); True iff square mat has full rank."""
    n, m = mat.shape
    if n != m:
        return False
    rows = _to_rowints(mat)
    rank = 0
    for col in range(m):
        bit = 1 << col
        piv = next((r for r in range(rank, n) if rows[r] & bit), None)
        if piv is None:
            return False
        rows[rank], rows[piv] = rows[piv], rows[rank]
        for r in range(n):
            if r != rank and rows[r] & bit:
                rows[r] ^= rows[rank]
        rank += 1
    return True


def gf2_inverse(mat: np.ndarray) -> np.ndarray:
    """Inverse of a square 0/1 matrix over GF(2) (raises on singular)."""
    n = mat.shape[0]
    a = mat.astype(np.uint8).copy()
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        piv = next((r for r in range(col, n) if a[r, col]), None)
        if piv is None:
            raise ErasureCodeError(errno.EIO, "singular GF(2) matrix")
        if piv != col:
            a[[col, piv]] = a[[piv, col]]
            inv[[col, piv]] = inv[[piv, col]]
        for r in range(n):
            if r != col and a[r, col]:
                a[r] ^= a[col]
                inv[r] ^= inv[col]
    return inv


# -- X-matrix constructions ---------------------------------------------------

def rotation(w: int, s: int) -> np.ndarray:
    x = np.zeros((w, w), dtype=np.uint8)
    for i in range(w):
        x[i, (i + s) % w] = 1
    return x


def liberation_x(k: int, w: int) -> list[np.ndarray]:
    if not is_prime(w) or w <= 2:
        raise ErasureCodeError(
            errno.EINVAL, f"liberation: w={w} must be prime > 2")
    if k > w:
        raise ErasureCodeError(errno.EINVAL,
                               f"liberation: k={k} must be <= w={w}")
    xs = []
    for j in range(k):
        x = rotation(w, j)
        if j > 0:
            r = (j * ((w - 1) // 2)) % w
            x[r, (r + j - 1) % w] ^= 1
        xs.append(x)
    return xs


def blaum_roth_x(k: int, w: int) -> list[np.ndarray]:
    p = w + 1
    # Deviation from the reference: it tolerates w=7 (the legacy
    # Firefly default) for old-data compatibility, but p=8 gives
    # M_8(x) = (1+x)^7 so EVERY X_i^X_j is singular — the code cannot
    # correct any double data-chunk erasure.  New pools must not be
    # creatable in that state; we reject it.
    if w <= 2 or not is_prime(p):
        raise ErasureCodeError(
            errno.EINVAL,
            f"blaum_roth: w+1={p} must be prime (w > 2); note w=7 "
            f"(legacy default) is NOT double-erasure decodable")
    if k > w:
        raise ErasureCodeError(errno.EINVAL,
                               f"blaum_roth: k={k} must be <= w={w}")

    # powers of x in GF(2)[x]/M_p(x), M_p = 1 + x + ... + x^w
    def xpow(e: int) -> np.ndarray:
        poly = np.zeros(w, dtype=np.uint8)
        poly[0] = 1
        for _ in range(e):
            carry = poly[w - 1]
            poly[1:] = poly[:-1]
            poly[0] = 0
            if carry:               # x^w = 1 + x + ... + x^(w-1)
                poly ^= 1
        return poly

    xs = []
    for j in range(k):
        x = np.zeros((w, w), dtype=np.uint8)
        for c in range(w):
            x[:, c] = xpow(j + c)
        xs.append(x)
    return xs


def _gf256_mult_matrix(e: int) -> np.ndarray:
    """8x8 GF(2) matrix of y -> e*y in GF(2^8)/0x11d: column c is the
    bit vector of e * x^c."""
    x = np.zeros((8, 8), dtype=np.uint8)
    cur = e
    for c in range(8):
        for i in range(8):
            x[i, c] = (cur >> i) & 1
        cur <<= 1
        if cur & 0x100:
            cur ^= 0x11D
    return x


@lru_cache(maxsize=None)
def _lightest_elements(k: int) -> tuple[int, ...]:
    """The k elements of GF(2^8) with the sparsest multiplication
    matrices (ties by element value): 1, 2, 142, 4, 71, 8, 70, 173..."""
    ranked = sorted(range(1, 256),
                    key=lambda e: (int(_gf256_mult_matrix(e).sum()), e))
    return tuple(ranked[:k])


def liber8tion_x(k: int) -> list[np.ndarray]:
    if k > 8:
        raise ErasureCodeError(errno.EINVAL,
                               f"liber8tion: k={k} must be <= 8")
    return [_gf256_mult_matrix(e) for e in _lightest_elements(k)]


# -- coding matrix + codec paths ---------------------------------------------

def coding_matrix(technique: str, k: int, w: int) -> np.ndarray:
    """(2w, kw) GF(2) matrix: top w rows produce P, bottom w rows Q.
    Validates the pairwise invertibility contract so a non-decodable
    parameter combination fails at init, not at recovery time."""
    if technique == "liberation":
        xs = liberation_x(k, w)
    elif technique == "blaum_roth":
        xs = blaum_roth_x(k, w)
    elif technique == "liber8tion":
        if w != 8:
            raise ErasureCodeError(errno.EINVAL,
                                   f"liber8tion: w={w} must be 8")
        xs = liber8tion_x(k)
    else:
        raise ErasureCodeError(errno.ENOENT,
                               f"unknown bitmatrix technique {technique!r}")
    for j, x in enumerate(xs):
        if not gf2_invertible(x):
            raise ErasureCodeError(
                errno.EINVAL, f"{technique}: X_{j} singular (k={k}, w={w})")
        for i in range(j):
            if not gf2_invertible(x ^ xs[i]):
                raise ErasureCodeError(
                    errno.EINVAL,
                    f"{technique}: X_{i}^X_{j} singular (k={k}, w={w}) — "
                    f"this parameter combination cannot correct the "
                    f"({i},{j}) data-chunk erasure pair")
    b = np.zeros((2 * w, k * w), dtype=np.uint8)
    for j in range(k):
        b[:w, j * w:(j + 1) * w] = np.eye(w, dtype=np.uint8)
        b[w:, j * w:(j + 1) * w] = xs[j]
    return b


def generator(technique: str, k: int, w: int) -> np.ndarray:
    """((k+2)w, kw): identity rows for data chunks + coding_matrix."""
    g = np.zeros(((k + 2) * w, k * w), dtype=np.uint8)
    g[: k * w] = np.eye(k * w, dtype=np.uint8)
    g[k * w:] = coding_matrix(technique, k, w)
    return g


def _xor_apply(mat: np.ndarray, packets: np.ndarray) -> np.ndarray:
    """rows of `mat` select packets to XOR: out[r] = XOR of packets[c]
    where mat[r, c] == 1."""
    out = np.zeros((mat.shape[0], packets.shape[1]), dtype=np.uint8)
    for r in range(mat.shape[0]):
        idx = np.nonzero(mat[r])[0]
        if idx.size:
            out[r] = np.bitwise_xor.reduce(packets[idx], axis=0)
    return out


def encode(coding: np.ndarray, chunks: np.ndarray, w: int) -> np.ndarray:
    """chunks (k, chunk_size) -> parity (2, chunk_size); chunk_size
    must be a multiple of w (each chunk = w packets)."""
    k, chunk_size = chunks.shape
    if chunk_size % w:
        raise ErasureCodeError(
            errno.EINVAL, f"chunk size {chunk_size} not divisible by w={w}")
    pl = chunk_size // w
    packets = chunks.reshape(k * w, pl)
    return _xor_apply(coding, packets).reshape(2, chunk_size)


def decode(gen: np.ndarray, dense: np.ndarray, erasures: list[int],
           k: int, w: int) -> np.ndarray:
    """Rebuild erased chunk rows of dense ((k+2), chunk_size) from any
    k surviving chunks (mirrors the matrix-decode shape of
    jerasure_bitmatrix_decode)."""
    n, chunk_size = dense.shape
    pl = chunk_size // w
    erased = set(erasures)
    survivors = [i for i in range(n) if i not in erased][:k]
    if len(survivors) < k:
        raise ErasureCodeError(errno.EIO, "not enough survivors")
    sub = np.concatenate([gen[s * w:(s + 1) * w] for s in survivors])
    inv = gf2_inverse(sub)                      # (kw, kw)
    out = dense.copy()
    need_data = [e for e in erased if e < k]
    need_par = [e for e in erased if e >= k]
    if need_data:
        spackets = np.concatenate(
            [dense[s].reshape(w, pl) for s in survivors])
        data_packets = _xor_apply(inv, spackets)      # all kw data packets
        for e in need_data:
            out[e] = data_packets[e * w:(e + 1) * w].reshape(chunk_size)
    if need_par:
        # re-encode parity from (now complete) data chunks
        parity = encode(gen[k * w:], out[:k], w)
        for e in need_par:
            out[e] = parity[e - k]
    return out
