"""ErasureCode base class: shared padding / mapping / minimum_to_decode.

Re-expresses reference src/erasure-code/ErasureCode.{h,cc}.  The important
contracts preserved:

* SIMD_ALIGN padding — here ALIGN=64 host-side; the TPU plugin further
  tiles internally to lane width without changing chunk sizes.
* encode_prepare (reference ErasureCode.cc:151-186): pad the object with
  zeros to k*chunk_size and slice into k equal data chunks.
* default minimum_to_decode (reference :103-137): if everything wanted is
  available use it, else any k available chunks, full range each.
* chunk remapping via the `mapping=` profile key (reference :274).
"""

from __future__ import annotations

import errno

import numpy as np

from .interface import ErasureCodeError, ErasureCodeInterface, Profile

SIMD_ALIGN = 64  # reference uses 32 (ErasureCode.cc:42); 64 also serves cachelines


class ErasureCode(ErasureCodeInterface):
    k: int = 0
    m: int = 0
    # Locality codes (LRC/SHEC) can decode from fewer than k chunks when
    # the right ones are present; they relax the availability precheck.
    ALLOW_PARTIAL_DECODE = False

    def __init__(self) -> None:
        self.chunk_mapping: list[int] = []
        self.profile: Profile | None = None

    # -- init plumbing ------------------------------------------------------

    def init(self, profile: Profile) -> None:
        self.profile = profile
        mapping = profile.get("mapping")
        if mapping:
            self.parse_chunk_mapping(mapping)

    def parse_chunk_mapping(self, mapping: str) -> None:
        """Parse a 'DDD_D...' style remap string: position p of the string
        holds chunk c in order of D occurrences (reference
        ErasureCode.cc:274 chunk_index/chunk_mapping)."""
        n = self.get_chunk_count()
        positions = [i for i, ch in enumerate(mapping) if ch == "D"]
        if len(positions) != n:
            raise ErasureCodeError(
                errno.EINVAL,
                f"mapping {mapping!r} has {len(positions)} D's, need {n}")
        self.chunk_mapping = positions

    # -- geometry -----------------------------------------------------------

    def get_chunk_count(self) -> int:
        return self.k + self.m

    def get_data_chunk_count(self) -> int:
        return self.k

    def get_chunk_size(self, stripe_width: int) -> int:
        alignment = self.get_alignment()
        per = (stripe_width + self.k - 1) // self.k
        return -(-per // alignment) * alignment

    def get_alignment(self) -> int:
        return SIMD_ALIGN

    def get_chunk_mapping(self) -> list[int]:
        return list(self.chunk_mapping)

    def chunk_index(self, i: int) -> int:
        return self.chunk_mapping[i] if self.chunk_mapping else i

    # -- default decode planning -------------------------------------------

    def _minimum_to_decode_ids(self, want_to_read: set[int],
                               available: set[int]) -> set[int]:
        if want_to_read <= available:
            return set(want_to_read)
        if len(available) < self.k:
            raise ErasureCodeError(
                errno.EIO,
                f"want {sorted(want_to_read)} but only "
                f"{sorted(available)} available (k={self.k})")
        return set(sorted(available)[: self.k])

    def minimum_to_decode(self, want_to_read, available):
        ids = self._minimum_to_decode_ids(set(want_to_read), set(available))
        sub = self.get_sub_chunk_count()
        return {i: [(0, sub)] for i in ids}

    def minimum_to_decode_with_cost(self, want_to_read, available):
        # Default ignores cost (reference ErasureCode.cc:139-149).
        return set(self.minimum_to_decode(set(want_to_read), set(available)))

    # -- encode plumbing ----------------------------------------------------

    def encode_prepare(self, data) -> np.ndarray:
        """Pad to k*chunk_size and slice to a (k, chunk_size) array
        (reference ErasureCode.cc:151-186)."""
        buf = np.frombuffer(bytes(data), dtype=np.uint8) \
            if not isinstance(data, np.ndarray) else data.astype(np.uint8, copy=False).ravel()
        chunk_size = self.get_chunk_size(buf.size)
        padded = np.zeros(self.k * chunk_size, dtype=np.uint8)
        padded[: buf.size] = buf
        return padded.reshape(self.k, chunk_size)

    def encode(self, want_to_encode, data):
        chunks = self.encode_prepare(data)
        parity = self.encode_chunks(chunks)
        allc = np.concatenate([chunks, parity], axis=0)
        return {i: allc[i] for i in want_to_encode}

    # -- decode plumbing ----------------------------------------------------

    def _decode_prepare(self, chunks: dict[int, np.ndarray],
                        chunk_size: int) -> tuple[np.ndarray, list[int]]:
        """Assemble a dense (k+m, chunk_size) array with zeros in the holes
        and return (array, erasure list) (reference ErasureCode.cc:212)."""
        n = self.get_chunk_count()
        dense = np.zeros((n, chunk_size), dtype=np.uint8)
        erasures = []
        for i in range(n):
            if i in chunks:
                c = np.asarray(chunks[i], dtype=np.uint8).ravel()
                if c.size != chunk_size:
                    raise ErasureCodeError(
                        errno.EINVAL,
                        f"chunk {i} size {c.size} != {chunk_size}")
                dense[i] = c
            else:
                erasures.append(i)
        return dense, erasures

    def decode(self, want_to_read, chunks, chunk_size):
        dense, erasures = self._decode_prepare(chunks, chunk_size)
        if not erasures or not (set(want_to_read) - set(chunks)):
            return {i: dense[i] for i in want_to_read}
        if not self.ALLOW_PARTIAL_DECODE and \
                self.get_chunk_count() - len(erasures) < self.k:
            raise ErasureCodeError(
                errno.EIO, f"cannot decode: {len(erasures)} erasures > m={self.m}")
        decoded = self.decode_chunks(dense, erasures)
        return {i: decoded[i] for i in want_to_read}

    def decode_chunks(self, dense: np.ndarray,
                      erasures: list[int]) -> np.ndarray:
        """Reconstruct erased rows of the dense (k+m, chunk_size) array.
        Subclasses implement. (reference ErasureCodeInterface.h:411)"""
        raise NotImplementedError

    # -- CRUSH --------------------------------------------------------------

    def create_rule(self, name: str, crush) -> int:
        """Build an `indep` CRUSH rule choosing k+m independent devices
        (reference ErasureCode.cc:64-83)."""
        failure_domain = (self.profile.get("crush-failure-domain", "host")
                          if self.profile else "host")
        root = (self.profile.get("crush-root", "default")
                if self.profile else "default")
        return crush.add_simple_rule(
            name, root, failure_domain, num_rep=self.get_chunk_count(),
            rule_mode="indep")
