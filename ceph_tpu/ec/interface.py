"""Erasure-code codec contract.

Re-expresses the reference's `ErasureCodeInterface` (reference:
src/erasure-code/ErasureCodeInterface.h:170-462) for this framework.
Semantics kept exactly; types made idiomatic (numpy uint8 buffers instead
of bufferlist, dict/set instead of std::map/std::set, exceptions carrying
errno instead of negative returns).

All codecs are systematic: chunks 0..k-1 (after chunk_mapping) carry the
object's data, chunks k..k+m-1 carry parity.  An object is padded out to
k * get_chunk_size(len) before encoding (reference diagram,
ErasureCodeInterface.h:39-78).
"""

from __future__ import annotations

import abc
import errno
from dataclasses import dataclass, field


class ErasureCodeError(Exception):
    """Codec error carrying an errno, mirroring negative-int returns."""

    def __init__(self, err: int, msg: str):
        super().__init__(f"[errno {err} {errno.errorcode.get(err, '?')}] {msg}")
        self.errno = err


@dataclass
class Profile:
    """An EC profile: free-form key=value settings validated by the plugin.

    Mirrors the reference's ErasureCodeProfile (map<string,string>); the
    monitor's `normalize_profile` (src/mon/OSDMonitor.cc:7190) instantiates
    the plugin to validate and fill defaults — `ceph_tpu.mon` does the same.
    """

    data: dict[str, str] = field(default_factory=dict)

    def __getitem__(self, k: str) -> str:
        return self.data[k]

    def get(self, k: str, default: str | None = None) -> str | None:
        return self.data.get(k, default)

    def __contains__(self, k: str) -> bool:
        return k in self.data

    def to_int(self, key: str, default: int) -> int:
        """Parse an int profile value; mirrors ErasureCode::to_int
        (reference src/erasure-code/ErasureCode.cc:295) including the
        behavior that an empty/absent value takes the default and a bad
        value raises EINVAL."""
        v = self.data.get(key)
        if v is None or v == "":
            self.data[key] = str(default)
            return default
        try:
            return int(v)
        except ValueError:
            raise ErasureCodeError(
                errno.EINVAL, f"could not convert {key}={v!r} to int")

    def to_bool(self, key: str, default: bool) -> bool:
        v = self.data.get(key)
        if v is None or v == "":
            self.data[key] = str(default).lower()
            return default
        return v.lower() in ("true", "yes", "1", "on")


class ErasureCodeInterface(abc.ABC):
    """Abstract codec (reference ErasureCodeInterface.h:170).

    Chunk buffers are numpy uint8 arrays (or anything memoryview-able);
    implementations may require SIMD/TPU-friendly alignment, which
    get_chunk_size() guarantees.
    """

    @abc.abstractmethod
    def init(self, profile: Profile) -> None:
        """Initialize from a profile, filling defaults into it.
        Raises ErasureCodeError(EINVAL) on bad parameters.
        (reference :212)"""

    @abc.abstractmethod
    def get_chunk_count(self) -> int:
        """k + m. (reference :240)"""

    @abc.abstractmethod
    def get_data_chunk_count(self) -> int:
        """k. (reference :249)"""

    def get_coding_chunk_count(self) -> int:
        """m. (reference :257)"""
        return self.get_chunk_count() - self.get_data_chunk_count()

    def get_sub_chunk_count(self) -> int:
        """Sub-chunks per chunk; >1 only for regenerating codes (CLAY).
        (reference :266)"""
        return 1

    @abc.abstractmethod
    def get_chunk_size(self, stripe_width: int) -> int:
        """Chunk size for an object of `stripe_width` bytes: ceil(w/k)
        rounded up so implementation alignment holds.  All chunks of a
        stripe have the same size. (reference :281)"""

    @abc.abstractmethod
    def minimum_to_decode(
        self, want_to_read: set[int], available: set[int],
    ) -> dict[int, list[tuple[int, int]]]:
        """Which chunks (and which (offset, length) sub-chunk ranges of
        each, in sub-chunk units) must be fetched to decode
        `want_to_read` given `available`.  Plain MDS codes return k
        chunks with the full range; CLAY returns partial ranges.
        Raises ErasureCodeError(EIO) if unrecoverable. (reference :297)"""

    def minimum_to_decode_with_cost(
        self, want_to_read: set[int], available: dict[int, int],
    ) -> set[int]:
        """Like minimum_to_decode but pick the cheapest set given a
        fetch-cost per available chunk. (reference :326)"""
        # Default: ignore costs beyond preferring wanted chunks.
        got = self.minimum_to_decode(want_to_read, set(available))
        return set(got)

    @abc.abstractmethod
    def encode(self, want_to_encode: set[int], data: bytes | memoryview,
               ) -> dict[int, "np.ndarray"]:
        """Pad + split `data` into k data chunks, compute m parity chunks,
        return the subset listed in want_to_encode. (reference :365)"""

    @abc.abstractmethod
    def encode_chunks(self, chunks: "np.ndarray") -> "np.ndarray":
        """Low-level: given (k, chunk_size) data chunk array, return the
        (m, chunk_size) parity chunks. (reference :370)"""

    @abc.abstractmethod
    def decode(self, want_to_read: set[int],
               chunks: dict[int, "np.ndarray"], chunk_size: int,
               ) -> dict[int, "np.ndarray"]:
        """Reconstruct the wanted chunks from the available ones.
        (reference :407)"""

    @abc.abstractmethod
    def get_chunk_mapping(self) -> list[int]:
        """Permutation of chunk index -> shard position, empty list for
        identity.  (reference :448)"""

    def decode_concat(self, chunks: dict[int, "np.ndarray"]) -> bytes:
        """Decode all data chunks and concatenate them in order.
        (reference :460)"""
        import numpy as np
        k = self.get_data_chunk_count()
        sizes = {len(v) for v in chunks.values()}
        assert len(sizes) == 1, "mixed chunk sizes"
        out = self.decode(set(range(k)), chunks, sizes.pop())
        return b"".join(np.asarray(out[i], dtype=np.uint8).tobytes()
                        for i in range(k))

    def create_rule(self, name: str, crush) -> int:
        """Create a CRUSH rule that places k+m chunks on independent
        devices (reference ErasureCodeInterface.h:223 /
        ErasureCode.cc:64-83)."""
        raise NotImplementedError
