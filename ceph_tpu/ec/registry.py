"""Erasure-code plugin registry.

Re-expresses reference src/erasure-code/ErasureCodePlugin.{h,cc}: a
process-wide singleton that lazily loads plugins by name, verifies an ABI
version stamp, and hands out codec instances from profiles.  The dlopen of
`libec_<name>.so` becomes an import of `ceph_tpu.ec.plugins.ec_<name>` (or
of `ec_<name>` from a configured plugin directory), and the
`__erasure_code_init__` entry point keeps its name and its contract: it
must call registry.add() itself (reference ErasureCodePlugin.cc:149-175).

The error contract is preserved via ErasureCodeError errnos, matching the
reference's tested behaviors (src/test/erasure-code/TestErasureCodePlugin.cc:83-103):
  ENOENT - no such plugin module
  EXDEV  - plugin ABI version mismatch
  ENOEXEC- entry point raised during load ("expected initialization failed")
  ENOENT - entry point missing
  EBADF  - entry point ran but did not register the plugin
  EEXIST - add() of a name already registered
"""

from __future__ import annotations

import errno
import importlib
import importlib.util
import sys
import threading
from pathlib import Path

from .. import PLUGIN_ABI_VERSION
from .interface import ErasureCodeError, ErasureCodeInterface, Profile


class ErasureCodePlugin:
    """Base for plugin objects: a factory for codec instances.

    Reference ErasureCodePlugin.h:29-43.  Subclasses implement factory();
    the module's __erasure_code_init__ registers an instance.
    """

    abi_version = PLUGIN_ABI_VERSION

    def factory(self, profile: Profile) -> ErasureCodeInterface:
        raise NotImplementedError


class ErasureCodePluginRegistry:
    """Singleton registry (reference ErasureCodePlugin.h:45)."""

    _instance: "ErasureCodePluginRegistry | None" = None
    _instance_lock = threading.Lock()

    def __init__(self) -> None:
        self.lock = threading.RLock()
        self.plugins: dict[str, ErasureCodePlugin] = {}
        self.loading = False  # observable mid-load flag, as in reference
        self.disable_dlclose = False

    @classmethod
    def instance(cls) -> "ErasureCodePluginRegistry":
        with cls._instance_lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    # -- registration -------------------------------------------------------

    def add(self, name: str, plugin: ErasureCodePlugin) -> None:
        with self.lock:
            if name in self.plugins:
                raise ErasureCodeError(
                    errno.EEXIST, f"plugin {name} already registered")
            self.plugins[name] = plugin

    def get(self, name: str) -> ErasureCodePlugin | None:
        with self.lock:
            return self.plugins.get(name)

    def remove(self, name: str) -> None:
        with self.lock:
            self.plugins.pop(name, None)

    # -- loading ------------------------------------------------------------

    def load(self, name: str, directory: str | None = None) -> ErasureCodePlugin:
        """Load plugin `name` (reference ErasureCodePlugin.cc:110-182)."""
        module = self._import_module(name, directory)
        version = getattr(module, "__erasure_code_version__", None)
        if version is None:
            raise ErasureCodeError(
                errno.EXDEV,
                f"plugin {name} has no __erasure_code_version__ stamp")
        if version != PLUGIN_ABI_VERSION:
            raise ErasureCodeError(
                errno.EXDEV,
                f"plugin {name} version {version!r} != expected "
                f"{PLUGIN_ABI_VERSION!r}")
        entry = getattr(module, "__erasure_code_init__", None)
        if entry is None:
            raise ErasureCodeError(
                errno.ENOENT,
                f"plugin {name} has no __erasure_code_init__ entry point")
        try:
            entry(name, directory)
        except ErasureCodeError:
            raise
        except Exception as e:  # noqa: BLE001 - plugin boundary
            raise ErasureCodeError(
                errno.ENOEXEC, f"plugin {name} init raised: {e!r}")
        plugin = self.plugins.get(name)
        if plugin is None:
            raise ErasureCodeError(
                errno.EBADF,
                f"plugin {name} init ran but did not register itself")
        return plugin

    def _import_module(self, name: str, directory: str | None):
        modname = f"ec_{name}"
        if directory:
            path = Path(directory) / f"{modname}.py"
            if not path.exists():
                raise ErasureCodeError(
                    errno.ENOENT, f"no plugin file {path}")
            spec = importlib.util.spec_from_file_location(
                f"ceph_tpu_extplugin.{modname}", path)
            module = importlib.util.module_from_spec(spec)
            sys.modules[spec.name] = module
            try:
                spec.loader.exec_module(module)
            except Exception as e:  # noqa: BLE001
                sys.modules.pop(spec.name, None)
                raise ErasureCodeError(
                    errno.ENOEXEC, f"plugin {name} failed to import: {e!r}")
            return module
        try:
            return importlib.import_module(f"ceph_tpu.ec.plugins.{modname}")
        except ModuleNotFoundError:
            raise ErasureCodeError(errno.ENOENT, f"no plugin named {name}")
        except ErasureCodeError:
            raise
        except Exception as e:  # noqa: BLE001
            raise ErasureCodeError(
                errno.ENOEXEC, f"plugin {name} failed to import: {e!r}")

    # -- factory ------------------------------------------------------------

    def factory(self, plugin_name: str, profile: Profile | dict,
                directory: str | None = None) -> ErasureCodeInterface:
        """Instantiate a codec: lazy-load the plugin then delegate
        (reference ErasureCodePlugin.cc:90)."""
        if isinstance(profile, dict):
            profile = Profile(dict(profile))
        with self.lock:
            plugin = self.plugins.get(plugin_name)
            if plugin is None:
                self.loading = True
                try:
                    plugin = self.load(plugin_name, directory)
                finally:
                    self.loading = False
        codec = plugin.factory(profile)
        codec.init(profile)
        return codec

    def preload(self, plugins: list[str], directory: str | None = None) -> None:
        """Eagerly load a list of plugins (reference
        ErasureCodePlugin.cc:184, called from global_init_preload at
        daemon startup, src/global/global_init.cc:571)."""
        with self.lock:
            for name in plugins:
                if name not in self.plugins:
                    self.load(name, directory)


DEFAULT_PLUGINS = ["jerasure", "isa", "jax"]  # analog of osd_erasure_code_plugins
