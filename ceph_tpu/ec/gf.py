"""GF(2^8) arithmetic core for Reed-Solomon erasure codes.

The reference gets its Galois-field kernels from vendored jerasure/
gf-complete and ISA-L assembler submodules (reference .gitmodules;
src/erasure-code/jerasure/, src/erasure-code/isa/).  Here the field lives
in numpy tables on the host and — the point of this framework — as GF(2)
bit-matrices so that multiply-accumulate over the field becomes an XOR/AND
matmul the TPU MXU can run (see ceph_tpu/ops/gf_matmul.py).

Field: GF(2^8) with the primitive polynomial x^8+x^4+x^3+x^2+1 (0x11d),
the same polynomial gf-complete and ISA-L use for w=8.

Key identity used for bit-slicing: multiplication by a constant c is
GF(2)-linear on the 8 bits of the operand, so there is an 8x8 bit-matrix
M_c with  bits(c*x) = M_c @ bits(x)  (mod 2).  A full (k+m, k) generator
matrix over GF(2^8) therefore expands to an (8(k+m), 8k) 0/1 matrix, and
encode of a whole chunk is one {0,1}-matmul mod 2 — MXU food.
"""

from __future__ import annotations

import functools

import numpy as np

GF_POLY = 0x11D
GF_SIZE = 256


def _build_tables() -> tuple[np.ndarray, np.ndarray]:
    """Log/antilog tables for the generator alpha=2 of GF(2^8)/0x11d."""
    exp = np.zeros(512, dtype=np.uint8)
    log = np.zeros(256, dtype=np.int32)
    x = 1
    for i in range(255):
        exp[i] = x
        log[x] = i
        x <<= 1
        if x & 0x100:
            x ^= GF_POLY
    exp[255:510] = exp[0:255]  # wraparound so exp[a+b] works without mod
    return exp, log


GF_EXP, GF_LOG = _build_tables()


def gf_mul(a: int, b: int) -> int:
    """Scalar multiply in GF(2^8)."""
    if a == 0 or b == 0:
        return 0
    return int(GF_EXP[int(GF_LOG[a]) + int(GF_LOG[b])])


def gf_div(a: int, b: int) -> int:
    """Scalar divide in GF(2^8); b must be nonzero."""
    if b == 0:
        raise ZeroDivisionError("GF(2^8) division by zero")
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) - int(GF_LOG[b])) % 255])


def gf_inv(a: int) -> int:
    if a == 0:
        raise ZeroDivisionError("GF(2^8) inverse of zero")
    return int(GF_EXP[(255 - int(GF_LOG[a])) % 255])


def gf_pow(a: int, n: int) -> int:
    if n == 0:
        return 1
    if a == 0:
        return 0
    return int(GF_EXP[(int(GF_LOG[a]) * n) % 255])


@functools.lru_cache(maxsize=1)
def mul_table() -> np.ndarray:
    """(256, 256) uint8 full multiplication table; MUL[a][b] = a*b.

    Row c is the byte-LUT for multiply-by-c, applied to whole numpy chunks
    with a single fancy-index (the host-side analog of gf-complete's
    region multiply kernels).
    """
    a = np.arange(256)
    la = GF_LOG[a][:, None]
    lb = GF_LOG[a][None, :]
    out = GF_EXP[(la + lb) % 255].astype(np.uint8)
    out[0, :] = 0
    out[:, 0] = 0
    return out


def gf_mul_region(c: int, data: np.ndarray) -> np.ndarray:
    """Multiply every byte of `data` (uint8 ndarray) by constant c."""
    if c == 0:
        return np.zeros_like(data)
    if c == 1:
        return data.copy()
    return mul_table()[c][data]


def gf_matvec(mat: np.ndarray, chunks: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix x "vector of chunks" product.

    mat: (r, k) uint8 coefficient matrix.
    chunks: (k, n) uint8 — k chunks of n bytes.
    Returns (r, n) uint8: out[i] = XOR_j mat[i,j] * chunks[j].

    This is the CPU reference for what ops/gf_matmul.py computes on TPU;
    bit-identical by construction of the bit-matrices.
    """
    r, k = mat.shape
    assert chunks.shape[0] == k, (mat.shape, chunks.shape)
    if chunks.shape[1] >= 1024:  # native SIMD path when worth the ctypes hop
        from ..common import native
        got = native.gf8_matvec(mat, chunks)
        if got is not None:
            return got
    out = np.zeros((r, chunks.shape[1]), dtype=np.uint8)
    lut = mul_table()
    for i in range(r):
        acc = out[i]
        for j in range(k):
            c = int(mat[i, j])
            if c == 0:
                continue
            if c == 1:
                acc ^= chunks[j]
            else:
                acc ^= lut[c][chunks[j]]
        out[i] = acc
    return out


def gf_matmul(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """GF(2^8) matrix product of small coefficient matrices (uint8)."""
    r, k = a.shape
    k2, c = b.shape
    assert k == k2
    out = np.zeros((r, c), dtype=np.uint8)
    for i in range(r):
        for j in range(c):
            acc = 0
            for t in range(k):
                acc ^= gf_mul(int(a[i, t]), int(b[t, j]))
            out[i, j] = acc
    return out


def gf_invert_matrix(mat: np.ndarray) -> np.ndarray:
    """Invert a square GF(2^8) matrix by Gauss-Jordan elimination.

    Raises ValueError if singular.  Used on the host to build decode
    matrices from the surviving rows of the generator matrix (reference
    behavior: jerasure_matrix_decode / ISA-L gf_gen_decode_matrix).
    """
    n = mat.shape[0]
    assert mat.shape == (n, n)
    a = mat.astype(np.uint8).copy()
    inv = np.eye(n, dtype=np.uint8)
    for col in range(n):
        pivot = -1
        for row in range(col, n):
            if a[row, col]:
                pivot = row
                break
        if pivot < 0:
            raise ValueError("singular GF(2^8) matrix")
        if pivot != col:
            a[[col, pivot]] = a[[pivot, col]]
            inv[[col, pivot]] = inv[[pivot, col]]
        pv = gf_inv(int(a[col, col]))
        if pv != 1:
            lut = mul_table()[pv]
            a[col] = lut[a[col]]
            inv[col] = lut[inv[col]]
        for row in range(n):
            if row != col and a[row, col]:
                c = int(a[row, col])
                lut = mul_table()[c]
                a[row] ^= lut[a[col]]
                inv[row] ^= lut[inv[col]]
    return inv


# ----------------------------------------------------------------------------
# Bit-matrix expansion (the TPU-native representation)
# ----------------------------------------------------------------------------

@functools.lru_cache(maxsize=1)
def _bitmatrix_table() -> np.ndarray:
    """(256, 8, 8) uint8: BITMAT[c] is M_c with bits(c*x) = M_c @ bits(x).

    Bit order is LSB-first: bit i of a byte is (byte >> i) & 1.
    Column j of M_c holds bits(c * 2^j).
    """
    out = np.zeros((256, 8, 8), dtype=np.uint8)
    for c in range(256):
        for j in range(8):
            prod = gf_mul(c, 1 << j)
            for i in range(8):
                out[c, i, j] = (prod >> i) & 1
    return out


def expand_to_bitmatrix(mat: np.ndarray) -> np.ndarray:
    """Expand an (r, k) GF(2^8) matrix to an (8r, 8k) GF(2) 0/1 matrix.

    Block (i, j) of the result is the 8x8 bit-matrix of coefficient
    mat[i, j].  Multiplying bit-sliced data by this matrix mod 2 computes
    the same chunks gf_matvec does — this is the Cauchy-bitmatrix idea
    (reference: jerasure cauchy_orig/cauchy_good schedules,
    src/erasure-code/jerasure/ErasureCodeJerasure.cc:265,353) recast as a
    dense matmul for the MXU instead of an XOR schedule for the CPU.
    """
    r, k = mat.shape
    bm = _bitmatrix_table()
    out = np.zeros((8 * r, 8 * k), dtype=np.uint8)
    for i in range(r):
        for j in range(k):
            out[8 * i:8 * i + 8, 8 * j:8 * j + 8] = bm[mat[i, j]]
    return out


def bitmatrix_matvec(bitmat: np.ndarray, chunks: np.ndarray) -> np.ndarray:
    """CPU check-model of the TPU path: bit-unpack, 0/1 matmul mod 2, pack.

    chunks: (k, n) uint8 -> returns (r, n) uint8 where bitmat is (8r, 8k).
    """
    k8 = bitmat.shape[1]
    k = k8 // 8
    assert chunks.shape[0] == k
    bits = np.unpackbits(chunks, axis=0, bitorder="little")  # (8k, n)? no:
    # unpackbits on axis 0 expands each row into 8 rows (bit planes of that
    # row, LSB-first with bitorder="little"), giving (8k, n) with row
    # j*8+i = bit i of chunk j — matches the bitmatrix column layout.
    prod = (bitmat.astype(np.uint32) @ bits.astype(np.uint32)) & 1
    return np.packbits(prod.astype(np.uint8), axis=0, bitorder="little")


# ----------------------------------------------------------------------------
# Generator matrix constructions
# ----------------------------------------------------------------------------

def vandermonde_rs_matrix(k: int, m: int) -> np.ndarray:
    """Systematic (k+m, k) RS generator matrix from a Vandermonde matrix.

    Construction: build the (k+m, k) Vandermonde V[i,j] = i^j (distinct
    evaluation points 0..k+m-1), then column-reduce so the top k rows are
    the identity.  Any k rows of the result are invertible, which is the
    property reed_sol_van relies on (reference:
    src/erasure-code/jerasure/ErasureCodeJerasure.cc:162 via
    jerasure's reed_sol_vandermonde_coding_matrix).
    """
    n = k + m
    if n > GF_SIZE:
        raise ValueError(f"k+m={n} exceeds GF(2^8) point count")
    v = np.zeros((n, k), dtype=np.uint8)
    for i in range(n):
        for j in range(k):
            v[i, j] = gf_pow(i, j) if i else (1 if j == 0 else 0)
    # Column-reduce so rows 0..k-1 become identity; elementary column ops
    # preserve the any-k-rows-invertible property.
    top_inv = gf_invert_matrix(v[:k, :])
    return gf_matmul(v, top_inv)


def cauchy_rs_matrix(k: int, m: int) -> np.ndarray:
    """Systematic (k+m, k) generator: identity on top of a Cauchy block.

    Parity block C[i,j] = 1/(x_i + y_j) with x_i = k+i, y_j = j — distinct
    points so every square submatrix of the Cauchy block is invertible and
    the whole matrix is MDS (reference technique cauchy_orig/cauchy_good,
    src/erasure-code/jerasure/ErasureCodeJerasure.h:138-187; ISA-L kCauchy,
    src/erasure-code/isa/ErasureCodeIsa.h:37).
    """
    if k + m > GF_SIZE:
        raise ValueError(f"k+m={k + m} exceeds GF(2^8) point count")
    g = np.zeros((k + m, k), dtype=np.uint8)
    g[:k, :] = np.eye(k, dtype=np.uint8)
    for i in range(m):
        for j in range(k):
            g[k + i, j] = gf_inv((k + i) ^ j)
    return g


def recovery_matrix(matrix: np.ndarray, k: int, survivors, targets
                    ) -> np.ndarray:
    """(len(targets), k) GF(2^8) coefficients rebuilding `targets` shards
    from the k `survivors` rows of the systematic generator `matrix`
    ((k+m, k)).  Shared by the single-chip plugin decode plan and the
    mesh codec's distributed repair (reference ECUtil::decode inversion,
    src/osd/ECUtil.cc:9; ISA-L decode tables, ErasureCodeIsa.cc:385)."""
    inv = gf_invert_matrix(matrix[list(survivors), :])
    rows = []
    for t in targets:
        if t < k:
            rows.append(inv[t])
        else:
            rows.append(gf_matmul(matrix[t:t + 1], inv)[0])
    return np.stack(rows).astype(np.uint8)
