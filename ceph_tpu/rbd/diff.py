"""RBD incremental transport: export-diff / import-diff.

Re-expresses the reference's between-snap delta stream
(src/tools/rbd/action/Export.cc export-diff, Import.cc import-diff,
src/librbd/DeepCopyRequest.h role) over this framework's
rados-selfmanaged-snapshot images.

Stream format (own framing, documented here — the reference's v1/v2
banner format is byte-specific to its librbd types):

    magic line   b"ceph-tpu rbd diff v1\\n"
    'm' u32 len  JSON meta {image, from_snap, to_snap, size}
    'w' u64 off u64 len <len bytes>     changed data run
    'z' u64 off u64 len                 run that became zero
    'e'                                 end

Runs are sub-block tight: a changed block contributes only the
[first-diff, last-diff] byte span.  The walk is object-map-assisted on
the head: blocks the map knows were never written are skipped without
an OSD round-trip (they cannot differ — no discard op exists to
remove data that a snapshot still holds).

Deviation vs reference: change detection reads both snap contexts and
compares bytes (the reference consults the OSD's per-object snapset
clone intervals).  At this substrate's scale the read-compare is the
honest equivalent; the stream format is what matters for the backup
workflow.
"""

from __future__ import annotations

import errno
import json
import struct

from ..rados.client import RadosError

MAGIC = b"ceph-tpu rbd diff v1\n"
_U64x2 = struct.Struct("<QQ")


def _emit_span(fh, off: int, old: bytes, new: bytes) -> bool:
    """Write one 'w'/'z' record covering the differing span of a
    block pair; returns True if anything was emitted."""
    if old == new:
        return False
    lo = 0
    hi = max(len(old), len(new))
    o = old.ljust(hi, b"\0")
    n = new.ljust(hi, b"\0")
    while lo < hi and o[lo] == n[lo]:
        lo += 1
    while hi > lo and o[hi - 1] == n[hi - 1]:
        hi -= 1
    span = n[lo:hi]
    if span.count(0) == len(span):
        fh.write(b"z" + _U64x2.pack(off + lo, hi - lo))
    else:
        fh.write(b"w" + _U64x2.pack(off + lo, hi - lo))
        fh.write(span)
    return True


def export_diff(img, fh, from_snap: str | None = None,
                to_snap: str | None = None) -> int:
    """Write the delta stream between from_snap (None = empty image:
    a full export in diff clothing) and to_snap (None = head).
    Returns the number of records emitted."""
    hdr = img._header
    for s in (from_snap, to_snap):
        if s is not None and s not in hdr["snap_ids"]:
            raise RadosError(errno.ENOENT, f"no snap {s}")
    snap_sizes = hdr.get("snap_sizes", {})
    to_size = snap_sizes.get(to_snap, img.size()) if to_snap \
        else img.size()
    from_size = snap_sizes.get(from_snap, img.size()) if from_snap \
        else 0
    from_id = hdr["snap_ids"][from_snap] if from_snap else None
    to_id = hdr["snap_ids"][to_snap] if to_snap else 0
    fh.write(MAGIC)
    meta = json.dumps({"image": img.name, "from_snap": from_snap,
                       "to_snap": to_snap, "size": to_size}).encode()
    fh.write(b"m" + struct.pack("<I", len(meta)) + meta)
    bs = img.block_size
    # the diff's domain is [0, to_size): the import resizes the target
    # first, so content beyond to_size needs no records — emitting any
    # would make import write past the (shrunk) end
    nblocks = -(-to_size // bs)
    omap = img._live_omap()
    records = 0
    for b in range(nblocks):
        window = max(0, min(bs, to_size - b * bs))
        if window == 0:
            continue
        if from_id is None and omap is not None and \
                not omap.object_may_exist(b):
            # full-export mode (baseline = zeros): a block absent at
            # head reads zeros == baseline, nothing to emit.  The
            # skip is NOT sound for snap-to-snap diffs — a shrink +
            # regrow leaves the head block absent while the from-snap
            # clone still holds data (resize is a discard).
            continue
        new = img._read_block_at(b, to_id)[:window]
        if from_id is None:
            old = b"\0" * len(new)
        else:
            old = img._read_block_at(b, from_id)[:window]
        if _emit_span(fh, b * bs, old, new):
            records += 1
    fh.write(b"e")
    return records


def _read_exact(fh, n: int) -> bytes:
    buf = fh.read(n)
    if len(buf) != n:
        raise RadosError(errno.EINVAL, "truncated diff stream")
    return buf


def import_diff(img, fh) -> dict:
    """Apply a delta stream onto an image.  The image must already
    carry the stream's from_snap (same name — the reference checks
    the end-snap of the previous diff the same way); the stream's
    to_snap is created at the end, so chained diffs compose."""
    if _read_exact(fh, len(MAGIC)) != MAGIC:
        raise RadosError(errno.EINVAL, "not a ceph-tpu rbd diff stream")
    tag = _read_exact(fh, 1)
    if tag != b"m":
        raise RadosError(errno.EINVAL, f"expected meta, got {tag!r}")
    (mlen,) = struct.unpack("<I", _read_exact(fh, 4))
    meta = json.loads(_read_exact(fh, mlen).decode())
    from_snap = meta.get("from_snap")
    if from_snap is not None and \
            from_snap not in img._header["snap_ids"]:
        raise RadosError(
            errno.EINVAL,
            f"image {img.name} lacks base snap {from_snap!r} — "
            f"this diff does not apply here")
    if meta["size"] != img.size():
        img.resize(meta["size"])
    applied = {"w": 0, "z": 0, "bytes": 0}
    while True:
        tag = _read_exact(fh, 1)
        if tag == b"e":
            break
        if tag not in (b"w", b"z"):
            raise RadosError(errno.EINVAL, f"bad record tag {tag!r}")
        off, ln = _U64x2.unpack(_read_exact(fh, _U64x2.size))
        if tag == b"w":
            data = _read_exact(fh, ln)
            img.write(off, data)
            applied["w"] += 1
            applied["bytes"] += ln
        else:
            img.write(off, b"\0" * ln)
            applied["z"] += 1
    to_snap = meta.get("to_snap")
    if to_snap and to_snap not in img._header["snap_ids"]:
        img.snap_create(to_snap)
    return applied
