"""RBD: block device images over RADOS (reference src/librbd/)."""

from .image import RBD, Image

__all__ = ["RBD", "Image"]
