"""RBD: block device images over RADOS (reference src/librbd/)."""

from .journal import ImageReplayer, Journal
from .image import RBD, Image

__all__ = ["RBD", "Image", "Journal", "ImageReplayer"]
