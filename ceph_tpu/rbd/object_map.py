"""RBD object map: which data blocks exist, without probing the OSDs.

Re-expresses reference src/librbd/ObjectMap.h + object_map/ (state
bitmap per data object, maintained under the exclusive lock, consulted
by reads/copyup/diff and backing `rbd du`-style accounting).  The map
is one byte per block (OBJECT_NONEXISTENT / OBJECT_EXISTS) in a
`rbd_object_map.<image>` RADOS object; updates are one-byte
offset-writes, applied WRITE-AHEAD of the data op exactly like the
reference (a block is marked EXISTS before its first write, and
NONEXISTENT only after its object is removed, so a crash between the
two leaves the map conservative, never wrong).

Only an exclusive-lock owner maintains the map (reference gates the
object-map feature on the lock); lockless handles fall back to OSD
probes.
"""

from __future__ import annotations

import errno

from ..rados.client import RadosError

NONEXISTENT = 0
EXISTS = 1


def _map_oid(name: str) -> str:
    return f"rbd_object_map.{name}"


def _inval_oid(name: str) -> str:
    return f"rbd_object_map_inval.{name}"


def invalidate(io, name: str) -> None:
    """Flag the map untrustworthy (reference FLAG_OBJECT_MAP_INVALID):
    a sentinel object, NOT removal of the map — a live lock owner's
    one-byte updates would silently recreate a short, mostly-zero map
    object, which the next loader would wrongly trust."""
    io.write_full(_inval_oid(name), b"1")


class ObjectMap:
    def __init__(self, ioctx, image_name: str, nblocks: int):
        self.io = ioctx
        self.name = image_name
        self.nblocks = nblocks
        self.state = bytearray(nblocks)
        self._loaded = False

    # -- load / rebuild ------------------------------------------------------

    def load(self, probe_block) -> None:
        """Read the persisted map; rebuild by probing each block when
        it is absent (pre-object-map image), flagged invalid by a
        lockless writer, or its size disagrees with the image
        (reference rbd object-map rebuild + FLAG_OBJECT_MAP_INVALID)."""
        invalid = True
        try:
            self.io.read(_inval_oid(self.name), 1, snap=0)
        except RadosError as e:
            if e.errno != errno.ENOENT:
                raise
            invalid = False
        if not invalid:
            try:
                raw = bytes(self.io.read(_map_oid(self.name), 0, snap=0))
                if len(raw) == self.nblocks:
                    self.state = bytearray(raw)
                    self._loaded = True
                    return
                # size mismatch: stale map — rebuild everything (a
                # partially-trusted map can mark live data absent)
            except RadosError as e:
                if e.errno != errno.ENOENT:
                    raise
        for b in range(self.nblocks):
            self.state[b] = EXISTS if probe_block(b) else NONEXISTENT
        self.io.write_full(_map_oid(self.name), bytes(self.state))
        try:
            self.io.remove(_inval_oid(self.name))
        except RadosError:
            pass
        self._loaded = True

    # -- queries -------------------------------------------------------------

    def object_may_exist(self, block: int) -> bool:
        if not self._loaded or block >= self.nblocks:
            return True               # conservative without a map
        return self.state[block] == EXISTS

    def used_bytes(self, block_size: int) -> int:
        """rbd du role (fast-diff accounting): EXISTS blocks only."""
        return sum(1 for s in self.state if s == EXISTS) * block_size

    # -- write-ahead updates -------------------------------------------------

    def ensure_exists(self, block: int) -> None:
        """Mark EXISTS before the data write lands."""
        if not self._loaded or block >= self.nblocks:
            return
        if self.state[block] != EXISTS:
            self.io.write(_map_oid(self.name), bytes([EXISTS]),
                          offset=block)
            self.state[block] = EXISTS

    def mark_removed(self, block: int) -> None:
        """Mark NONEXISTENT after the data object is removed."""
        if not self._loaded or block >= self.nblocks:
            return
        if self.state[block] != NONEXISTENT:
            self.io.write(_map_oid(self.name), bytes([NONEXISTENT]),
                          offset=block)
            self.state[block] = NONEXISTENT

    def resize(self, nblocks: int, exists_hint: int = NONEXISTENT) -> None:
        if nblocks < len(self.state):
            del self.state[nblocks:]
        else:
            self.state.extend(bytes([exists_hint]) *
                              (nblocks - len(self.state)))
        self.nblocks = nblocks
        if self._loaded:
            self.io.write_full(_map_oid(self.name), bytes(self.state))

    def remove(self) -> None:
        try:
            self.io.remove(_map_oid(self.name))
        except RadosError:
            pass
