"""RBD images: virtual block devices striped over RADOS objects.

Re-expresses the core of reference src/librbd/ (ImageCtx + the
ImageRequest -> ObjectRequest dispatch in io/): an image is a header
object (`rbd_header.<name>`: JSON size/order) plus data objects
`rbd_data.<name>.<block#>`, each 2^order bytes; block I/O at arbitrary
offsets maps to per-object extents (reference Striper::file_to_extents
role).  Snapshots are full-copy (`rbd_data.<name>@<snap>.<block#>`) —
the layering/clone chain and journal-based mirroring of the reference
are roadmap items, recorded in docs/PARITY.md.
"""

from __future__ import annotations

import errno
import json

from ..rados.client import IoCtx, RadosError

DEFAULT_ORDER = 22  # 4 MiB objects, the reference default


class RBD:
    """Image management (reference librbd.h rbd_create/list/remove)."""

    def __init__(self, ioctx: IoCtx):
        self.io = ioctx

    def create(self, name: str, size: int,
               order: int = DEFAULT_ORDER) -> None:
        try:
            self.io.read(_header(name), 1)
            raise RadosError(errno.EEXIST, f"image {name} exists")
        except RadosError as e:
            if e.errno != errno.ENOENT:
                raise
        header = {"size": size, "order": order, "snaps": []}
        self.io.write_full(_header(name), json.dumps(header).encode())
        self._dir_add(name)

    def list(self) -> list[str]:
        # images register in a directory object (reference rbd_directory)
        try:
            raw = self.io.read("rbd_directory", 0)
            return sorted(json.loads(raw.decode()))
        except RadosError:
            return []

    def _dir_add(self, name: str) -> None:
        names = set(self.list())
        names.add(name)
        self.io.write_full("rbd_directory",
                           json.dumps(sorted(names)).encode())

    def _dir_rm(self, name: str) -> None:
        names = set(self.list())
        names.discard(name)
        self.io.write_full("rbd_directory",
                           json.dumps(sorted(names)).encode())

    def remove(self, name: str) -> None:
        img = Image(self.io, name)
        nblocks = -(-img.size() // img.block_size)
        for b in range(nblocks):
            try:
                self.io.remove(_data(name, b))
            except RadosError:
                pass
        self.io.remove(_header(name))
        self._dir_rm(name)


def _header(name: str) -> str:
    return f"rbd_header.{name}"


def _data(name: str, block: int, snap: str | None = None) -> str:
    base = f"rbd_data.{name}" + (f"@{snap}" if snap else "")
    return f"{base}.{block:016x}"


class Image:
    """Open image handle (reference ImageCtx + Image API)."""

    def __init__(self, ioctx: IoCtx, name: str):
        self.io = ioctx
        self.name = name
        self._header = json.loads(
            self.io.read(_header(name), 0).decode())

    @property
    def block_size(self) -> int:
        return 1 << self._header["order"]

    def size(self) -> int:
        return self._header["size"]

    def _save_header(self) -> None:
        self.io.write_full(_header(self.name),
                           json.dumps(self._header).encode())

    # -- block I/O ----------------------------------------------------------

    def write(self, offset: int, data: bytes) -> int:
        if offset + len(data) > self.size():
            raise RadosError(errno.EINVAL, "write past end of image")
        bs = self.block_size
        pos = 0
        while pos < len(data):
            block, boff = divmod(offset + pos, bs)
            run = min(bs - boff, len(data) - pos)
            self.io.write(_data(self.name, block),
                          data[pos:pos + run], offset=boff)
            pos += run
        return len(data)

    def read(self, offset: int, length: int) -> bytes:
        length = max(0, min(length, self.size() - offset))
        bs = self.block_size
        out = bytearray()
        pos = 0
        while pos < length:
            block, boff = divmod(offset + pos, bs)
            run = min(bs - boff, length - pos)
            try:
                piece = self.io.read(_data(self.name, block), run, boff)
            except RadosError as e:
                if e.errno == errno.ENOENT:
                    piece = b""
                else:
                    raise
            if len(piece) < run:                 # sparse: zero-fill
                piece = piece + b"\0" * (run - len(piece))
            out += piece
            pos += run
        return bytes(out)

    def resize(self, new_size: int) -> None:
        old_blocks = -(-self.size() // self.block_size)
        new_blocks = -(-new_size // self.block_size)
        for b in range(new_blocks, old_blocks):
            try:
                self.io.remove(_data(self.name, b))
            except RadosError:
                pass
        self._header["size"] = new_size
        self._save_header()

    # -- snapshots (full-copy) ----------------------------------------------

    def snap_create(self, snap: str) -> None:
        if snap in self._header["snaps"]:
            raise RadosError(errno.EEXIST, f"snap {snap} exists")
        nblocks = -(-self.size() // self.block_size)
        for b in range(nblocks):
            try:
                data = self.io.read(_data(self.name, b), 0)
            except RadosError:
                continue
            if data:
                self.io.write_full(_data(self.name, b, snap), data)
        self._header["snaps"].append(snap)
        self._save_header()

    def snap_list(self) -> list[str]:
        return list(self._header["snaps"])

    def snap_rollback(self, snap: str) -> None:
        if snap not in self._header["snaps"]:
            raise RadosError(errno.ENOENT, f"no snap {snap}")
        nblocks = -(-self.size() // self.block_size)
        for b in range(nblocks):
            try:
                data = self.io.read(_data(self.name, b, snap), 0)
            except RadosError:
                data = b""
            if data:
                self.io.write_full(_data(self.name, b), data)
            else:
                try:
                    self.io.remove(_data(self.name, b))
                except RadosError:
                    pass

    def snap_remove(self, snap: str) -> None:
        if snap not in self._header["snaps"]:
            raise RadosError(errno.ENOENT, f"no snap {snap}")
        nblocks = -(-self.size() // self.block_size)
        for b in range(nblocks):
            try:
                self.io.remove(_data(self.name, b, snap))
            except RadosError:
                pass
        self._header["snaps"].remove(snap)
        self._save_header()
