"""RBD images: virtual block devices striped over RADOS objects.

Re-expresses the core of reference src/librbd/ (ImageCtx + the
ImageRequest -> ObjectRequest dispatch in io/): an image is a header
object (`rbd_header.<name>`: JSON size/order/snaps/parent) plus data
objects `rbd_data.<name>.<block#>`, each 2^order bytes; block I/O at
arbitrary offsets maps to per-object extents (reference
Striper::file_to_extents role).

Snapshots are RADOS self-managed snapshots (reference librbd snapshots
over rados selfmanaged snap contexts): snap_create allocates a snap id
from the mon and subsequent writes carry the image's SnapContext, so
the OSD clones objects copy-on-write — no data is copied at snap time.
Clones are layered images (reference parent/child layering): a child
records (parent image, parent snap); reads fall through to the parent
at that snap for blocks the child has never written, and the first
child write to such a block pulls the parent content (COW pull,
reference CopyupRequest).
"""

from __future__ import annotations

import errno
import json

from ..rados.client import IoCtx, RadosError

DEFAULT_ORDER = 22  # 4 MiB objects, the reference default


class RBD:
    """Image management (reference librbd.h rbd_create/list/remove/
    clone)."""

    def __init__(self, ioctx: IoCtx):
        self.io = ioctx

    def create(self, name: str, size: int,
               order: int = DEFAULT_ORDER) -> None:
        try:
            self.io.read(_header(name), 1)
            raise RadosError(errno.EEXIST, f"image {name} exists")
        except RadosError as e:
            if e.errno != errno.ENOENT:
                raise
        header = {"size": size, "order": order, "snaps": [],
                  "snap_ids": {}, "parent": None}
        self.io.write_full(_header(name), json.dumps(header).encode())
        self._dir_add(name)

    def clone(self, parent: str, snap: str, child: str) -> None:
        """Layered clone from a parent snapshot (reference rbd clone;
        the snap plays the protected-snap role)."""
        pimg = Image(self.io, parent)
        if snap not in pimg._header.get("snap_ids", {}):
            raise RadosError(errno.ENOENT,
                             f"no snap {snap} on {parent}")
        try:
            self.io.read(_header(child), 1)
            raise RadosError(errno.EEXIST, f"image {child} exists")
        except RadosError as e:
            if e.errno != errno.ENOENT:
                raise
        header = {"size": pimg.size(), "order": pimg._header["order"],
                  "snaps": [], "snap_ids": {},
                  "parent": [parent, pimg._header["snap_ids"][snap]]}
        self.io.write_full(_header(child), json.dumps(header).encode())
        self._dir_add(child)

    def list(self) -> list[str]:
        # images register in a directory object (reference rbd_directory)
        try:
            raw = self.io.read("rbd_directory", 0)
            return sorted(json.loads(raw.decode()))
        except RadosError:
            return []

    def _dir_add(self, name: str) -> None:
        names = set(self.list())
        names.add(name)
        self.io.write_full("rbd_directory",
                           json.dumps(sorted(names)).encode())

    def _dir_rm(self, name: str) -> None:
        names = set(self.list())
        names.discard(name)
        self.io.write_full("rbd_directory",
                           json.dumps(sorted(names)).encode())

    def remove(self, name: str) -> None:
        img = Image(self.io, name)
        nblocks = img._nblocks()
        for b in range(nblocks):
            try:
                self.io.remove(_data(name, b))
            except RadosError:
                pass
        from .object_map import _inval_oid, _map_oid
        for aux in (_map_oid(name), _inval_oid(name)):
            try:
                self.io.remove(aux)
            except RadosError:
                pass
        self.io.remove(_header(name))
        self._dir_rm(name)


def _header(name: str) -> str:
    return f"rbd_header.{name}"


def _data(name: str, block: int) -> str:
    return f"rbd_data.{name}.{block:016x}"


def _legacy_snap_data(name: str, snap: str, block: int) -> str:
    """Pre-COW full-copy snapshot object naming (kept readable so
    images snapshotted before the COW scheme still work)."""
    return f"rbd_data.{name}@{snap}.{block:016x}"


class Image:
    """Open image handle (reference ImageCtx + Image API).

    exclusive=True acquires the RBD exclusive lock on open (reference
    librbd/ExclusiveLock.h over cls_lock) and maintains the object map
    (reference ObjectMap.h): required for safe concurrent access — two
    lockless writers on one image corrupt it, exactly like the
    reference with the exclusive-lock feature disabled.  steal=True
    fences a live previous owner (its handle raises ESHUTDOWN on every
    later mutation)."""

    def __init__(self, ioctx: IoCtx, name: str,
                 journaling: bool = False, exclusive: bool = False,
                 steal: bool = False):
        # private IoCtx: the image's SnapContext/read-snap must not
        # leak onto other users of the caller's ioctx
        self.io = IoCtx(ioctx.client, ioctx.pool_id, ioctx.pool_name)
        self.name = name
        self._want_journal = journaling
        self._header = json.loads(
            self.io.read(_header(name), 0).decode())
        self._header.setdefault("snap_ids", {})
        self._header.setdefault("parent", None)
        # snapshots taken under the pre-COW scheme (full-copy objects,
        # no rados snap id) remain usable through their own paths
        self._legacy_snaps = {s for s in self._header["snaps"]
                              if s not in self._header["snap_ids"]}
        self._apply_snapc()
        self._parent: Image | None = None
        self._read_snap_id = 0
        self._legacy_read: str | None = None
        self._present_blocks: set[int] = set()   # copyup probe cache
        # exclusive lock + object map ride a snapc-free ioctx (their
        # objects must not be COW-cloned by image snapshots; the
        # reference keeps per-snap object maps — head-only here)
        self._lock = None
        self._omap = None
        self._closed = False
        self._lockless_checked = False
        if exclusive:
            from .exclusive_lock import ExclusiveLock
            from .object_map import ObjectMap
            aux_io = IoCtx(ioctx.client, ioctx.pool_id, ioctx.pool_name)
            self._lock = ExclusiveLock(aux_io, _header(name), name)
            self._lock.acquire(steal=steal)
            self._omap = ObjectMap(aux_io, name, self._nblocks())
            self._omap.load(self._probe_block)
        # journaling image feature (reference librbd journaling):
        # mutations are recorded write-ahead for rbd-mirror replay.
        # The journal rides a snapc-FREE ioctx (its objects must not be
        # COW-cloned by the image's snapshots) and is only created once
        # the header read proved the image exists.
        self._journal = None
        if self._want_journal:
            from .journal import Journal
            self._journal = Journal(
                IoCtx(ioctx.client, ioctx.pool_id, ioctx.pool_name),
                name)

    def _nblocks(self) -> int:
        return -(-self.size() // self.block_size)

    def _probe_block(self, block: int) -> bool:
        try:
            self.io.read(_data(self.name, block), 1, snap=0)
            return True
        except RadosError as e:
            if e.errno != errno.ENOENT:
                raise
            return False

    def _live_omap(self):
        """The object map, but only while this handle legitimately
        owns it: a fenced handle consulting its stale map would
        fabricate zeros for blocks the new owner wrote."""
        if self._omap is None or self._lock is None:
            return None
        return self._omap if (self._lock.acquired and
                              not self._lock.lost) else None

    def _writable(self) -> None:
        """Mutation gate.  Exclusive handles: closed or fenced fail.
        Lockless handles (legacy clients): refused while a LIVE owner
        holds the lock (the reference blocks lockless writes when the
        exclusive-lock feature is on), and their first write flags the
        object map invalid so the next owner rebuilds instead of
        trusting stale state (reference FLAG_OBJECT_MAP_INVALID).
        The lock-presence probe runs once per handle — a lock taken
        AFTER this handle's first write is not seen, a documented gap
        vs the reference's dynamic lock acquisition."""
        if self._closed:
            raise RadosError(errno.EBADF,
                             f"image {self.name}: handle closed")
        if self._lock is not None:
            self._lock.check()
            if not self._lock.acquired:
                self._lock.acquire()
            return
        if self._lockless_checked:
            return
        from .exclusive_lock import ExclusiveLock
        from .object_map import invalidate
        aux = IoCtx(self.io.client, self.io.pool_id, self.io.pool_name)
        probe = ExclusiveLock(aux, _header(self.name), self.name)
        if probe.lockers() and aux.list_watchers(_header(self.name)):
            raise RadosError(
                errno.EBUSY,
                f"image {self.name} is exclusively locked; open with "
                f"exclusive=True")
        invalidate(aux, self.name)
        self._lockless_checked = True

    def close(self) -> None:
        self._closed = True
        if self._lock is not None:
            self._lock.release()

    def __enter__(self) -> "Image":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def du(self) -> int:
        """Bytes of allocated blocks (reference `rbd du` fast-diff):
        O(1) from the object map under the lock, probe otherwise."""
        omap = self._live_omap()
        if omap is not None:
            return omap.used_bytes(self.block_size)
        return sum(self.block_size for b in range(self._nblocks())
                   if self._probe_block(b))

    def lock_owners(self) -> list[str]:
        from .exclusive_lock import ExclusiveLock
        lk = self._lock
        if lk is None:
            aux = IoCtx(self.io.client, self.io.pool_id,
                        self.io.pool_name)
            lk = ExclusiveLock(aux, _header(self.name), self.name)
        return sorted(lk.lockers())

    @property
    def block_size(self) -> int:
        return 1 << self._header["order"]

    def size(self) -> int:
        return self._header["size"]

    def _save_header(self) -> None:
        self.io.write_full(_header(self.name),
                           json.dumps(self._header).encode())

    def _apply_snapc(self) -> None:
        ids = sorted(self._header["snap_ids"].values(), reverse=True)
        self.io.snapc = [ids[0], ids] if ids else None

    def _get_parent(self) -> "Image | None":
        if self._header["parent"] is None:
            return None
        if self._parent is None:
            pname, psnap = self._header["parent"]
            self._parent = Image(self.io, pname)
            self._parent._read_snap_id = psnap
        return self._parent

    def _read_block(self, block: int, boff: int, run: int) -> bytes:
        """One block's bytes at this image's read context, falling
        through to the parent for never-written clone blocks."""
        # head reads under the lock skip the OSD round-trip for blocks
        # the object map knows are absent (reference ObjectMap-aware
        # ObjectReadRequest)
        omap = self._live_omap()
        skip_probe = (omap is not None and self._read_snap_id == 0
                      and self._legacy_read is None and
                      not omap.object_may_exist(block))
        try:
            if skip_probe:
                raise RadosError(errno.ENOENT, "object map: absent")
            if self._legacy_read is not None:
                piece = self.io.read(
                    _legacy_snap_data(self.name, self._legacy_read,
                                      block), run, boff, snap=0)
            else:
                piece = self.io.read(_data(self.name, block), run, boff,
                                     snap=self._read_snap_id)
            return piece + b"\0" * (run - len(piece))
        except RadosError as e:
            if e.errno != errno.ENOENT:
                raise
        parent = self._get_parent()
        if parent is not None and \
                block * self.block_size < parent.size():
            return parent._read_block(block, boff, run)
        return b"\0" * run

    # -- block I/O ----------------------------------------------------------

    def write(self, offset: int, data: bytes) -> int:
        if offset + len(data) > self.size():
            raise RadosError(errno.EINVAL, "write past end of image")
        self._writable()
        if self._journal is not None:
            self._journal.append({"op": "write", "offset": offset},
                                 bytes(data))
        bs = self.block_size
        pos = 0
        while pos < len(data):
            block, boff = divmod(offset + pos, bs)
            run = min(bs - boff, len(data) - pos)
            if run < bs:
                self._copyup(block)
            if self._omap is not None:
                self._omap.ensure_exists(block)   # write-ahead
            self.io.write(_data(self.name, block),
                          data[pos:pos + run], offset=boff)
            pos += run
        return len(data)

    def _copyup(self, block: int) -> None:
        """First partial write to a clone block pulls the parent's
        content (reference CopyupRequest).  A per-handle presence cache
        keeps steady-state writes to one probe total per block."""
        parent = self._get_parent()
        if parent is None:
            return
        if block in self._present_blocks:
            return
        omap = self._live_omap()
        if omap is not None and not omap.object_may_exist(block):
            pass                        # map says absent: skip probe
        else:
            try:
                self.io.read(_data(self.name, block), 1)
                self._present_blocks.add(block)
                return                  # child block already exists
            except RadosError as e:
                if e.errno != errno.ENOENT:
                    raise
        content = parent._read_block(block, 0, self.block_size)
        if content.rstrip(b"\0"):
            if self._omap is not None:
                self._omap.ensure_exists(block)
            self.io.write_full(_data(self.name, block), content)
        self._present_blocks.add(block)

    def _read_block_at(self, block: int, snapid: int) -> bytes:
        """One whole block read at an explicit snap context (the
        export-diff walk reads both sides of a snap pair)."""
        save = self._read_snap_id
        self._read_snap_id = snapid or 0
        try:
            return self._read_block(block, 0, self.block_size)
        finally:
            self._read_snap_id = save

    def export_diff(self, fh, from_snap: str | None = None,
                    to_snap: str | None = None) -> int:
        """Between-snap delta stream (reference rbd export-diff)."""
        from .diff import export_diff
        return export_diff(self, fh, from_snap, to_snap)

    def import_diff(self, fh) -> dict:
        """Apply a delta stream (reference rbd import-diff)."""
        self._writable()
        from .diff import import_diff
        return import_diff(self, fh)

    def read(self, offset: int, length: int) -> bytes:
        length = max(0, min(length, self.size() - offset))
        bs = self.block_size
        out = bytearray()
        pos = 0
        while pos < length:
            block, boff = divmod(offset + pos, bs)
            run = min(bs - boff, length - pos)
            out += self._read_block(block, boff, run)
            pos += run
        return bytes(out)

    def resize(self, new_size: int) -> None:
        self._writable()
        if self._journal is not None:
            self._journal.append({"op": "resize", "size": new_size})
        old_blocks = self._nblocks()
        new_blocks = -(-new_size // self.block_size)
        for b in range(new_blocks, old_blocks):
            try:
                self.io.remove(_data(self.name, b))
            except RadosError:
                pass
            self._present_blocks.discard(b)
        self._header["size"] = new_size
        self._save_header()
        if self._omap is not None:
            self._omap.resize(new_blocks)

    # -- snapshots (rados selfmanaged COW) -----------------------------------

    def snap_create(self, snap: str) -> None:
        if snap in self._header["snaps"]:
            raise RadosError(errno.EEXIST, f"snap {snap} exists")
        self._writable()
        if self._journal is not None:
            self._journal.append({"op": "snap_create", "snap": snap})
        snapid = self.io.selfmanaged_snap_create()
        self._header["snaps"].append(snap)
        self._header["snap_ids"][snap] = snapid
        # size at snap time: export-diff must bound its walk by the
        # snapshot's extent, not the (possibly resized) head's
        self._header.setdefault("snap_sizes", {})[snap] = self.size()
        self._save_header()
        self._apply_snapc()   # later writes COW against this snap

    def snap_list(self) -> list[str]:
        return list(self._header["snaps"])

    def snap_set(self, snap: str | None) -> None:
        """Route reads to a snapshot (reference rbd_snap_set); None
        returns to the head."""
        if snap is None:
            self._read_snap_id = 0
            self._legacy_read = None
        elif snap in self._legacy_snaps:
            self._legacy_read = snap
            self._read_snap_id = 0
        else:
            if snap not in self._header["snap_ids"]:
                raise RadosError(errno.ENOENT, f"no snap {snap}")
            self._read_snap_id = self._header["snap_ids"][snap]
            self._legacy_read = None

    def snap_rollback(self, snap: str) -> None:
        self._writable()
        if snap in self._legacy_snaps:
            snapid = None
        elif snap in self._header["snap_ids"]:
            snapid = self._header["snap_ids"][snap]
        else:
            raise RadosError(errno.ENOENT, f"no snap {snap}")
        bs = self.block_size
        nblocks = self._nblocks()
        for b in range(nblocks):
            try:
                if snapid is None:
                    data = self.io.read(
                        _legacy_snap_data(self.name, snap, b), 0)
                else:
                    data = self.io.read(_data(self.name, b), 0,
                                        snap=snapid)
            except RadosError as e:
                if e.errno != errno.ENOENT:
                    raise
                data = b""
            if data.rstrip(b"\0"):
                if self._omap is not None:
                    self._omap.ensure_exists(b)
                self.io.write(_data(self.name, b),
                              data.ljust(bs, b"\0")[:bs], offset=0)
            else:
                try:
                    self.io.remove(_data(self.name, b))
                except RadosError:
                    pass
                if self._omap is not None:
                    self._omap.mark_removed(b)
                self._present_blocks.discard(b)

    def snap_remove(self, snap: str) -> None:
        self._writable()
        if self._journal is not None:
            self._journal.append({"op": "snap_remove", "snap": snap})
        if snap in self._legacy_snaps:
            nblocks = self._nblocks()
            for b in range(nblocks):
                try:
                    self.io.remove(_legacy_snap_data(self.name, snap, b))
                except RadosError:
                    pass
            self._legacy_snaps.discard(snap)
            self._header["snaps"].remove(snap)
            self._save_header()
            return
        if snap not in self._header["snap_ids"]:
            raise RadosError(errno.ENOENT, f"no snap {snap}")
        snapid = self._header["snap_ids"][snap]
        self._header["snaps"].remove(snap)
        del self._header["snap_ids"][snap]
        self._save_header()
        self._apply_snapc()
        # report deletion so the OSD snap trimmer reclaims the clones
        try:
            self.io.selfmanaged_snap_remove(snapid)
        except RadosError:
            pass   # advisory; trim just won't run for this id yet

    def flatten(self) -> None:
        """Detach from the parent by copying up every missing block
        (reference rbd flatten)."""
        parent = self._get_parent()
        if parent is None:
            return
        self._writable()
        for b in range(self._nblocks()):
            self._copyup(b)
        self._header["parent"] = None
        self._parent = None
        self._save_header()
