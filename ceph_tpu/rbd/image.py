"""RBD images: virtual block devices striped over RADOS objects.

Re-expresses the core of reference src/librbd/ (ImageCtx + the
ImageRequest -> ObjectRequest dispatch in io/): an image is a header
object (`rbd_header.<name>`: JSON size/order/snaps/parent) plus data
objects `rbd_data.<name>.<block#>`, each 2^order bytes; block I/O at
arbitrary offsets maps to per-object extents (reference
Striper::file_to_extents role).

Snapshots are RADOS self-managed snapshots (reference librbd snapshots
over rados selfmanaged snap contexts): snap_create allocates a snap id
from the mon and subsequent writes carry the image's SnapContext, so
the OSD clones objects copy-on-write — no data is copied at snap time.
Clones are layered images (reference parent/child layering): a child
records (parent image, parent snap); reads fall through to the parent
at that snap for blocks the child has never written, and the first
child write to such a block pulls the parent content (COW pull,
reference CopyupRequest).
"""

from __future__ import annotations

import errno
import json

from ..rados.client import IoCtx, RadosError

DEFAULT_ORDER = 22  # 4 MiB objects, the reference default


class RBD:
    """Image management (reference librbd.h rbd_create/list/remove/
    clone)."""

    def __init__(self, ioctx: IoCtx):
        self.io = ioctx

    def create(self, name: str, size: int,
               order: int = DEFAULT_ORDER) -> None:
        try:
            self.io.read(_header(name), 1)
            raise RadosError(errno.EEXIST, f"image {name} exists")
        except RadosError as e:
            if e.errno != errno.ENOENT:
                raise
        header = {"size": size, "order": order, "snaps": [],
                  "snap_ids": {}, "parent": None}
        self.io.write_full(_header(name), json.dumps(header).encode())
        self._dir_add(name)

    def clone(self, parent: str, snap: str, child: str) -> None:
        """Layered clone from a parent snapshot (reference rbd clone;
        the snap plays the protected-snap role)."""
        pimg = Image(self.io, parent)
        if snap not in pimg._header.get("snap_ids", {}):
            raise RadosError(errno.ENOENT,
                             f"no snap {snap} on {parent}")
        try:
            self.io.read(_header(child), 1)
            raise RadosError(errno.EEXIST, f"image {child} exists")
        except RadosError as e:
            if e.errno != errno.ENOENT:
                raise
        header = {"size": pimg.size(), "order": pimg._header["order"],
                  "snaps": [], "snap_ids": {},
                  "parent": [parent, pimg._header["snap_ids"][snap]]}
        self.io.write_full(_header(child), json.dumps(header).encode())
        self._dir_add(child)

    def list(self) -> list[str]:
        # images register in a directory object (reference rbd_directory)
        try:
            raw = self.io.read("rbd_directory", 0)
            return sorted(json.loads(raw.decode()))
        except RadosError:
            return []

    def _dir_add(self, name: str) -> None:
        names = set(self.list())
        names.add(name)
        self.io.write_full("rbd_directory",
                           json.dumps(sorted(names)).encode())

    def _dir_rm(self, name: str) -> None:
        names = set(self.list())
        names.discard(name)
        self.io.write_full("rbd_directory",
                           json.dumps(sorted(names)).encode())

    def remove(self, name: str) -> None:
        img = Image(self.io, name)
        nblocks = -(-img.size() // img.block_size)
        for b in range(nblocks):
            try:
                self.io.remove(_data(name, b))
            except RadosError:
                pass
        self.io.remove(_header(name))
        self._dir_rm(name)


def _header(name: str) -> str:
    return f"rbd_header.{name}"


def _data(name: str, block: int) -> str:
    return f"rbd_data.{name}.{block:016x}"


def _legacy_snap_data(name: str, snap: str, block: int) -> str:
    """Pre-COW full-copy snapshot object naming (kept readable so
    images snapshotted before the COW scheme still work)."""
    return f"rbd_data.{name}@{snap}.{block:016x}"


class Image:
    """Open image handle (reference ImageCtx + Image API)."""

    def __init__(self, ioctx: IoCtx, name: str,
                 journaling: bool = False):
        # private IoCtx: the image's SnapContext/read-snap must not
        # leak onto other users of the caller's ioctx
        self.io = IoCtx(ioctx.client, ioctx.pool_id, ioctx.pool_name)
        self.name = name
        self._want_journal = journaling
        self._header = json.loads(
            self.io.read(_header(name), 0).decode())
        self._header.setdefault("snap_ids", {})
        self._header.setdefault("parent", None)
        # snapshots taken under the pre-COW scheme (full-copy objects,
        # no rados snap id) remain usable through their own paths
        self._legacy_snaps = {s for s in self._header["snaps"]
                              if s not in self._header["snap_ids"]}
        self._apply_snapc()
        self._parent: Image | None = None
        self._read_snap_id = 0
        self._legacy_read: str | None = None
        self._present_blocks: set[int] = set()   # copyup probe cache
        # journaling image feature (reference librbd journaling):
        # mutations are recorded write-ahead for rbd-mirror replay.
        # The journal rides a snapc-FREE ioctx (its objects must not be
        # COW-cloned by the image's snapshots) and is only created once
        # the header read proved the image exists.
        self._journal = None
        if self._want_journal:
            from .journal import Journal
            self._journal = Journal(
                IoCtx(ioctx.client, ioctx.pool_id, ioctx.pool_name),
                name)

    @property
    def block_size(self) -> int:
        return 1 << self._header["order"]

    def size(self) -> int:
        return self._header["size"]

    def _save_header(self) -> None:
        self.io.write_full(_header(self.name),
                           json.dumps(self._header).encode())

    def _apply_snapc(self) -> None:
        ids = sorted(self._header["snap_ids"].values(), reverse=True)
        self.io.snapc = [ids[0], ids] if ids else None

    def _get_parent(self) -> "Image | None":
        if self._header["parent"] is None:
            return None
        if self._parent is None:
            pname, psnap = self._header["parent"]
            self._parent = Image(self.io, pname)
            self._parent._read_snap_id = psnap
        return self._parent

    def _read_block(self, block: int, boff: int, run: int) -> bytes:
        """One block's bytes at this image's read context, falling
        through to the parent for never-written clone blocks."""
        try:
            if self._legacy_read is not None:
                piece = self.io.read(
                    _legacy_snap_data(self.name, self._legacy_read,
                                      block), run, boff, snap=0)
            else:
                piece = self.io.read(_data(self.name, block), run, boff,
                                     snap=self._read_snap_id)
            return piece + b"\0" * (run - len(piece))
        except RadosError as e:
            if e.errno != errno.ENOENT:
                raise
        parent = self._get_parent()
        if parent is not None and \
                block * self.block_size < parent.size():
            return parent._read_block(block, boff, run)
        return b"\0" * run

    # -- block I/O ----------------------------------------------------------

    def write(self, offset: int, data: bytes) -> int:
        if offset + len(data) > self.size():
            raise RadosError(errno.EINVAL, "write past end of image")
        if self._journal is not None:
            self._journal.append({"op": "write", "offset": offset},
                                 bytes(data))
        bs = self.block_size
        pos = 0
        while pos < len(data):
            block, boff = divmod(offset + pos, bs)
            run = min(bs - boff, len(data) - pos)
            if run < bs:
                self._copyup(block)
            self.io.write(_data(self.name, block),
                          data[pos:pos + run], offset=boff)
            pos += run
        return len(data)

    def _copyup(self, block: int) -> None:
        """First partial write to a clone block pulls the parent's
        content (reference CopyupRequest).  A per-handle presence cache
        keeps steady-state writes to one probe total per block."""
        parent = self._get_parent()
        if parent is None:
            return
        if block in self._present_blocks:
            return
        try:
            self.io.read(_data(self.name, block), 1)
            self._present_blocks.add(block)
            return                      # child block already exists
        except RadosError as e:
            if e.errno != errno.ENOENT:
                raise
        content = parent._read_block(block, 0, self.block_size)
        if content.rstrip(b"\0"):
            self.io.write_full(_data(self.name, block), content)
        self._present_blocks.add(block)

    def read(self, offset: int, length: int) -> bytes:
        length = max(0, min(length, self.size() - offset))
        bs = self.block_size
        out = bytearray()
        pos = 0
        while pos < length:
            block, boff = divmod(offset + pos, bs)
            run = min(bs - boff, length - pos)
            out += self._read_block(block, boff, run)
            pos += run
        return bytes(out)

    def resize(self, new_size: int) -> None:
        if self._journal is not None:
            self._journal.append({"op": "resize", "size": new_size})
        old_blocks = -(-self.size() // self.block_size)
        new_blocks = -(-new_size // self.block_size)
        for b in range(new_blocks, old_blocks):
            try:
                self.io.remove(_data(self.name, b))
            except RadosError:
                pass
            self._present_blocks.discard(b)
        self._header["size"] = new_size
        self._save_header()

    # -- snapshots (rados selfmanaged COW) -----------------------------------

    def snap_create(self, snap: str) -> None:
        if snap in self._header["snaps"]:
            raise RadosError(errno.EEXIST, f"snap {snap} exists")
        if self._journal is not None:
            self._journal.append({"op": "snap_create", "snap": snap})
        snapid = self.io.selfmanaged_snap_create()
        self._header["snaps"].append(snap)
        self._header["snap_ids"][snap] = snapid
        self._save_header()
        self._apply_snapc()   # later writes COW against this snap

    def snap_list(self) -> list[str]:
        return list(self._header["snaps"])

    def snap_set(self, snap: str | None) -> None:
        """Route reads to a snapshot (reference rbd_snap_set); None
        returns to the head."""
        if snap is None:
            self._read_snap_id = 0
            self._legacy_read = None
        elif snap in self._legacy_snaps:
            self._legacy_read = snap
            self._read_snap_id = 0
        else:
            if snap not in self._header["snap_ids"]:
                raise RadosError(errno.ENOENT, f"no snap {snap}")
            self._read_snap_id = self._header["snap_ids"][snap]
            self._legacy_read = None

    def snap_rollback(self, snap: str) -> None:
        if snap in self._legacy_snaps:
            snapid = None
        elif snap in self._header["snap_ids"]:
            snapid = self._header["snap_ids"][snap]
        else:
            raise RadosError(errno.ENOENT, f"no snap {snap}")
        bs = self.block_size
        nblocks = -(-self.size() // bs)
        for b in range(nblocks):
            try:
                if snapid is None:
                    data = self.io.read(
                        _legacy_snap_data(self.name, snap, b), 0)
                else:
                    data = self.io.read(_data(self.name, b), 0,
                                        snap=snapid)
            except RadosError as e:
                if e.errno != errno.ENOENT:
                    raise
                data = b""
            if data.rstrip(b"\0"):
                self.io.write(_data(self.name, b),
                              data.ljust(bs, b"\0")[:bs], offset=0)
            else:
                try:
                    self.io.remove(_data(self.name, b))
                except RadosError:
                    pass
                self._present_blocks.discard(b)

    def snap_remove(self, snap: str) -> None:
        if self._journal is not None:
            self._journal.append({"op": "snap_remove", "snap": snap})
        if snap in self._legacy_snaps:
            nblocks = -(-self.size() // self.block_size)
            for b in range(nblocks):
                try:
                    self.io.remove(_legacy_snap_data(self.name, snap, b))
                except RadosError:
                    pass
            self._legacy_snaps.discard(snap)
            self._header["snaps"].remove(snap)
            self._save_header()
            return
        if snap not in self._header["snap_ids"]:
            raise RadosError(errno.ENOENT, f"no snap {snap}")
        snapid = self._header["snap_ids"][snap]
        self._header["snaps"].remove(snap)
        del self._header["snap_ids"][snap]
        self._save_header()
        self._apply_snapc()
        # report deletion so the OSD snap trimmer reclaims the clones
        try:
            self.io.selfmanaged_snap_remove(snapid)
        except RadosError:
            pass   # advisory; trim just won't run for this id yet

    def flatten(self) -> None:
        """Detach from the parent by copying up every missing block
        (reference rbd flatten)."""
        parent = self._get_parent()
        if parent is None:
            return
        nblocks = -(-self.size() // self.block_size)
        for b in range(nblocks):
            self._copyup(b)
        self._header["parent"] = None
        self._parent = None
        self._save_header()
