"""RBD exclusive lock: single-writer ownership of an image.

Re-expresses reference src/librbd/ExclusiveLock.h + ManagedLock.h over
the cls_lock object class (reference src/cls/lock/), the way the
reference does it:

- the lock lives on the image header object (cls lock "rbd_lock",
  exclusive, one owner id per open handle)
- the owner WATCHES the header object; liveness of a contender's
  counterpart is judged by the OSD's live-watcher list (reference
  list_watchers-based break_lock decision in ManagedLock) — a crashed
  owner has no watcher and its lock is broken automatically
- stealing notifies the header; the previous owner's watch callback
  marks its handle fenced, so every subsequent mutation through it
  raises ESHUTDOWN instead of corrupting the image (the role of the
  reference's watch-invalidation + osdmap blacklisting; the
  in-flight-op window the reference closes with an OSD-side blacklist
  is documented as out of scope here)

Cooperative handoff (reference request_lock notify) is intentionally
not implemented: a live owner either blocks the contender (EBUSY) or
is fenced by an explicit steal.
"""

from __future__ import annotations

import errno
import json
import os

from ..rados.client import RadosError

LOCK_NAME = "rbd_lock"


class LockLost(RadosError):
    """The handle's exclusive lock was stolen; the handle is fenced."""

    def __init__(self, image: str):
        super().__init__(errno.ESHUTDOWN,
                         f"exclusive lock on {image} was stolen")


class ExclusiveLock:
    def __init__(self, ioctx, header_oid: str, image_name: str):
        self.io = ioctx
        self.header_oid = header_oid
        self.image_name = image_name
        self.owner_id = f"client.{os.urandom(8).hex()}"
        self.acquired = False
        self.lost = False
        self._watch_cookie: int | None = None

    # -- owner side ----------------------------------------------------------

    def _on_notify(self, _oid: str, payload: bytes) -> None:
        try:
            msg = json.loads(payload.decode())
        except ValueError:
            return
        if msg.get("event") == "acquired" and \
                msg.get("owner") != self.owner_id:
            # someone stole the lock: fence this handle
            self.lost = True
            self.acquired = False

    def _cls(self, method: str, payload: dict) -> bytes:
        return self.io.execute(self.header_oid, "lock", method,
                               json.dumps(payload).encode())

    def acquire(self, steal: bool = False) -> None:
        """Take the exclusive lock; break a dead owner's lock
        automatically; EBUSY against a live owner unless steal."""
        if self.acquired:
            return
        if self.lost:
            raise LockLost(self.image_name)
        # watch first: our own liveness marker must be in place before
        # the lock record exists (a contender probing in between would
        # otherwise break our fresh lock)
        if self._watch_cookie is None:
            self._watch_cookie = self.io.watch(self.header_oid,
                                              self._on_notify)
        req = {"name": LOCK_NAME, "owner": self.owner_id,
               "type": "exclusive",
               "entity": self.io.client.objecter.messenger.entity}
        try:
            try:
                self._cls("lock", req)
            except RadosError as e:
                if e.errno != errno.EBUSY:
                    raise
                # EBUSY: is the current owner alive?  Watchers other
                # than our own cookie count as the owner's presence.
                watchers = set(self.io.list_watchers(self.header_oid))
                watchers.discard(self._watch_cookie)
                if watchers and not steal:
                    raise RadosError(
                        errno.EBUSY,
                        f"image {self.image_name} is locked by a live "
                        f"client (steal to take over)") from e
                self._blacklist_owners()
                self._cls("break_lock", {})
                self._cls("lock", req)
        except Exception:
            # failed acquire must not leave our watcher behind: a
            # contender would count it as a live owner forever
            self.release()
            raise
        self.acquired = True
        # fence any previous owner's handle
        self.io.notify(self.header_oid, json.dumps(
            {"event": "acquired", "owner": self.owner_id}).encode())

    def _blacklist_owners(self) -> None:
        """Fence the old owner(s) at the OSDs BEFORE breaking the
        lock (reference ManagedLock: blacklist-on-break-lock closes
        the window where the fenced owner's already-sent ops land
        after the steal).  Waits until this client observes the
        blacklisting osdmap epoch so the break doesn't race the map."""
        client = self.io.client
        my_entity = client.objecter.messenger.entity
        try:
            info = json.loads(self._cls("get_info", {}).decode())
        except RadosError:
            return
        epoch = 0
        for owner, rec in (info.get("lockers") or {}).items():
            ent = (rec or {}).get("entity")
            if not ent or ent == my_entity:
                continue
            r, out = client.mon_command({
                "prefix": "osd blacklist add", "entity": ent})
            if r == 0:
                epoch = max(epoch, out.get("epoch", 0))
        # map barrier (librados wait_for_latest_osdmap role)
        import time
        deadline = time.time() + 10
        while epoch and client.objecter.osdmap.epoch < epoch and \
                time.time() < deadline:
            client.objecter.refresh_map()

    def check(self) -> None:
        """Raise LockLost if this handle was fenced."""
        if self.lost:
            raise LockLost(self.image_name)

    def release(self) -> None:
        if self.acquired:
            try:
                self._cls("unlock", {"owner": self.owner_id})
            except RadosError:
                pass
            self.acquired = False
        if self._watch_cookie is not None:
            try:
                self.io.unwatch(self.header_oid, self._watch_cookie)
            except RadosError:
                pass
            self._watch_cookie = None

    def lockers(self) -> dict:
        return json.loads(self._cls("get_info", {}).decode())["lockers"]
