"""RBD image journal + mirror replay (rbd-mirror role).

Re-expresses the reference's journaling stack at reduced scope:
src/journal/ (an ordered, replayable event log in RADOS objects) +
librbd's journaling image feature (every mutation is recorded before
it is applied — write-ahead, src/librbd/journal/) + the rbd-mirror
daemon's replayer (src/tools/rbd_mirror/ImageReplayer: tail the
journal, apply events to a peer image, advance the commit position).

Layout: a journal header object ("rbd_journal.<image>") maintained by
the journal object class (cls/cls_journal.py — the same cls seam the
reference routes this through, src/cls/journal): atomic server-side
seq allocation, ordered listing, per-peer client commit positions, and
client-fenced trim.  Bulky write payloads live in per-entry data
objects so the header stays light.
"""

from __future__ import annotations

import errno
import json
import uuid

from ..rados.client import IoCtx, RadosError


def _journal_oid(image: str) -> str:
    return f"rbd_journal.{image}"


def _entry_data_oid(image: str, seq: int) -> str:
    return f"rbd_journal.{image}.{seq:016x}"


def _resolve_data_oid(image: str, event: dict, seq: int) -> str:
    """Payload object for an entry: the uuid oid recorded in the index
    row, or the legacy seq-derived name for pre-data_oid journals."""
    return event.get("data_oid") or _entry_data_oid(image, seq)


class Journal:
    """Ordered event log for one image (reference journal::Journaler)."""

    def __init__(self, ioctx: IoCtx, image: str):
        self.io = ioctx
        self.image = image
        self.oid = _journal_oid(image)
        self.io.execute(self.oid, "journal", "create", b"")
        self._registered: set[str] = set()   # client_register cache

    def _list(self, after_seq: int) -> list:
        """Full ordered [(seq, event)...] listing, following
        pagination."""
        out = []
        pos = after_seq
        while True:
            raw = self.io.execute(self.oid, "journal", "list",
                                  json.dumps({"after_seq": pos,
                                              "max": 4096}).encode())
            page = json.loads(raw.decode())
            out.extend(page["entries"])
            if not page["truncated"] or not page["entries"]:
                return out
            pos = page["entries"][-1][0]

    # -- recording (image side) ---------------------------------------------

    def append(self, event: dict, data: bytes = b"") -> int:
        """Record one event (write-ahead: call BEFORE applying).  The
        sequence number is allocated SERVER-SIDE in the same atomic
        class call that stores the index row, so concurrent journaling
        handles never collide.  The payload object is written FIRST,
        under a unique provisional oid carried in the index row's meta
        (``data_oid``): an index entry therefore always has its payload
        durable before it becomes visible to replayers.  A crash between
        the two writes leaves only an unreferenced data object (harmless
        orphan; the event was never recorded, and write-ahead means the
        image mutation never happened either)."""
        if not data:
            return int(self.io.execute(
                self.oid, "journal", "append",
                json.dumps({"entry": event}).encode()))
        doid = f"rbd_journal.{self.image}.data.{uuid.uuid4().hex}"
        self.io.write_full(doid, data)
        event = dict(event, data_len=len(data), data_oid=doid)
        try:
            return int(self.io.execute(
                self.oid, "journal", "append",
                json.dumps({"entry": event}).encode()))
        except Exception:
            # index write failed but we're still alive: reclaim the
            # would-be orphan (its random name is unreachable by trim)
            try:
                self.io.remove(doid)
            except RadosError:
                pass
            raise

    # -- replay (mirror side) -----------------------------------------------

    def get_position(self, peer: str) -> int:
        try:
            raw = self.io.execute(self.oid, "journal", "client_get",
                                  json.dumps({"id": peer}).encode())
        except RadosError:
            return -1
        return int(json.loads(raw.decode())["pos"])

    def set_position(self, peer: str, seq: int) -> None:
        if peer not in self._registered:     # idempotent; once per
            self.io.execute(                 # handle, not per event
                self.oid, "journal", "client_register",
                json.dumps({"id": peer, "pos": -1}).encode())
            self._registered.add(peer)
        self.io.execute(self.oid, "journal", "client_update",
                        json.dumps({"id": peer, "pos": seq}).encode())

    def entries_after(self, seq: int):
        """Yield (seq, event, data) in order for every entry > seq."""
        for eseq, event in self._list(after_seq=seq):
            data = b""
            if event.get("data_len"):
                doid = _resolve_data_oid(self.image, event, eseq)
                try:
                    data = self.io.read(doid, event["data_len"])
                except RadosError as e:
                    if e.errno != errno.ENOENT:
                        raise   # transient error: retry, don't skip
                    # Payload object GONE (not unreadable): only
                    # possible for an entry a concurrent trim is midway
                    # through removing, or a pre-fix journal that
                    # crashed in the old index-before-payload window.
                    # Either way the entry is not replayable and never
                    # will be — skip it rather than wedging every
                    # future replay at this seq.
                    continue
            yield eseq, event, data

    def trim_to(self, seq: int) -> None:
        """Drop entries every peer has replayed (reference journal
        trimming at the minimum commit position — and the class
        REFUSES a trim past the slowest registered client, so a lagging
        mirror can never lose unreplayed events).  The fenced cls trim
        runs FIRST; payload objects are deleted only after it succeeds
        (deleting them first would destroy data the fence just
        protected)."""
        doids = [(_resolve_data_oid(self.image, event, eseq))
                 for eseq, event in self._list(after_seq=-1)
                 if eseq <= seq and event.get("data_len")]
        self.io.execute(self.oid, "journal", "trim",
                        json.dumps({"to_seq": seq}).encode())
        for doid in doids:
            try:
                self.io.remove(doid)
            except RadosError:
                pass


class ImageReplayer:
    """rbd-mirror's per-image replayer: tail the source journal, apply
    events to the peer image, advance the commit position
    (reference tools/rbd_mirror/ImageReplayer.cc)."""

    def __init__(self, src_ioctx: IoCtx, image: str, dst_ioctx: IoCtx,
                 peer: str = "mirror"):
        from .image import RBD, Image
        self.journal = Journal(src_ioctx, image)
        self.peer = peer
        self.image = image
        rbd = RBD(dst_ioctx)
        try:
            self.dst = Image(dst_ioctx, image)
        except RadosError:
            src = Image(src_ioctx, image)
            rbd.create(image, src.size(),
                       order=src._header["order"])
            self.dst = Image(dst_ioctx, image)

    def replay(self) -> int:
        """Apply all new events; returns how many were replayed.  The
        commit position advances PER EVENT (reference commits per
        entry), so a mid-batch failure resumes exactly where it
        stopped instead of re-applying."""
        pos = self.journal.get_position(self.peer)
        applied = 0
        for seq, event, data in self.journal.entries_after(pos):
            self._apply(event, data)
            self.journal.set_position(self.peer, seq)
            applied += 1
        return applied

    def _apply(self, event: dict, data: bytes) -> None:
        import errno as _errno
        op = event["op"]
        if op == "write":
            self.dst.write(event["offset"], data)
        elif op == "resize":
            self.dst.resize(event["size"])
        elif op == "snap_create":
            try:
                self.dst.snap_create(event["snap"])
            except RadosError as e:
                if e.errno != _errno.EEXIST:   # idempotent re-apply
                    raise
        elif op == "snap_remove":
            try:
                self.dst.snap_remove(event["snap"])
            except RadosError as e:
                if e.errno != _errno.ENOENT:
                    raise
        else:
            raise RadosError(22, f"unknown journal op {op!r}")
