"""Per-host EC launch queue: cross-PG continuous batching.

The single-PG bench numbers (BENCH_r05: ~147 GB/s bare encode) come
from large, full-occupancy device launches; a loaded OSD host with
hundreds of post-split PGs issues hundreds of partial-occupancy
launches instead, because every ECBackend drains per-PG.  This module
is the fix ROADMAP item 2 names: one per-device launch queue per host,
owned by the same `MeshService` seam that already owns the device
plane (parallel/service.py) — every ECBackend on the host submits its
assemble-complete extent runs here instead of launching its own
`encode_extents_with_crc_submit`, and the queue coalesces runs from
DIFFERENT PGs into autotuned super-batches: the continuous-batching
move inference servers use to keep an accelerator at full occupancy
under many small request streams.

Why cross-PG concatenation is safe: the fused extents contract (PR 9,
ops/bitsliced.gf_encode_extents_with_crc_submit) pads every run to a
tile multiple (front-padded on the accumulator path), emits ONE
per-run L per shard, and parity is a columnwise-linear GF map — so a
super-batch is just a longer list of independent runs, and the
per-run results demultiplex exactly.  The plain (no-crc) chunk path
concatenates along the byte axis and demuxes by column for the same
reason.

Contract with the owning backends (docs/PIPELINE.md "Host launch
queue"):

* `submit_*` returns a `LaunchTicket` immediately — the submitting
  drain never blocks.  The queue launches a super-batch when the
  batching window (`osd_ec_host_batch_window_us`) expires, when the
  pending input bytes reach the super-batch cap
  (`osd_ec_host_batch_max_bytes`), or when any ticket's `result()` is
  called first (flush-on-demand: a lone PG with nothing behind it
  keeps the synchronous flush-on-idle semantics of the per-PG
  pipeline).
* Per-PG in-order completion is untouched: the queue only owns the
  LAUNCH; each backend still materializes its drains in submit order
  through its own `_complete_drain` / `_try_finish_rmw` path.
* Repair rides the same machinery (docs/REPAIR.md): `submit_decode`
  coalesces recovery / reconstruct-on-read `decode_chunks` runs across
  PGs per (codec, erasure pattern), and `submit_clay_repair` coalesces
  CLAY repair-plan applies per plan signature — an OSD-loss storm's
  decode launches share window/byte-cap/flush-on-demand semantics and
  occupancy accounting with the write path instead of issuing
  per-object launches beside it.
* Failure containment: submissions only coalesce when their codecs
  are provably identical (generator-matrix signature).  If a combined
  launch still fails, the queue retries each submission on its OWN
  plugin, so a poison run aborts only the owning PG's ops while
  co-batched PGs' runs launch and commit.  A finalize (device)
  failure fails every ticket of that batch — each backend aborts its
  own ops and the queue keeps serving (the mesh-failure analog).
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..common.util import next_pow2
from ..ops.profiler import device_profiler

# max summed input width of one coalesced decode launch.  Decode
# launches are pow2-padded (see _do_launch), so together with this cap
# the decode jit-bucket universe is {pow2 width <= cap} x {erasure
# cardinality <= m} — finite, and exactly enumerable by the boot
# prewarm (ops/prewarm.py).  A single submission wider than the cap
# still launches alone (a recovery group's chunk is atomic); its width
# follows the object geometry, which prewarm covers separately.
DECODE_MAX_LAUNCH_W = 65536


def _codec_label(plugin) -> str:
    """Short human codec tag for the flight recorder (the full
    codec_signature carries raw matrix bytes — ledger rows want
    'JaxCodec:k8m3', not a kilobyte of generator matrix)."""
    try:
        return (f"{type(plugin).__name__}:"
                f"k{plugin.get_data_chunk_count()}"
                f"m{plugin.get_coding_chunk_count()}")
    except Exception:  # noqa: BLE001 — plans/odd plugins
        return type(plugin).__name__


def _extents_bucket(handle) -> str:
    """Jit-bucket key of a fused-extents submit handle: the (path,
    padded width, bucketed run count) triple the pow2 launch-shape
    bucketing (ops/bitsliced.py) collapses XLA's cache key to.  Best
    effort — an opaque plugin handle degrades to its path alone."""
    if isinstance(handle, dict):
        if "split" in handle:
            return "+".join(_extents_bucket(h)
                            for _idx, h in handle["split"])
        w = handle.get("big_width")
        nr = next_pow2(max(1, len(handle.get("meta", ()))))
        return f"x:{handle.get('path')}:w{w}:r{nr}"
    return "x:opaque"


def codec_signature(plugin) -> tuple:
    """Coalescing key for a plugin instance: two submissions may share
    one launch only when this is equal — same geometry AND bit-equal
    generator matrix (cauchy parity is garbage to a reed_sol_van
    decode; an unproven match must never batch).  Plugins may provide
    their own `codec_signature()`; without a generator matrix the
    signature degrades to instance identity, so such plugins still
    batch with themselves but never across instances."""
    own = getattr(plugin, "codec_signature", None)
    if callable(own):
        return own()
    mat = getattr(plugin, "matrix", None)
    if mat is None or \
            not getattr(plugin, "matrix_determines_encode", False):
        # exposing a matrix is NOT proof the encode uses it (jerasure's
        # minimal-density techniques encode via bitmatrix packets) —
        # only plugins that explicitly declare matrix-determined
        # encode semantics may batch across instances on the matrix
        return ("instance", id(plugin))
    # plugin-typed: the super-batch launches and finalizes through the
    # FIRST submitter's plugin, so the capability set must be uniform
    # within a launch — two plugin classes with bit-equal matrices
    # must never co-batch on the matrix alone
    return (type(plugin).__name__,) + matrix_signature(
        mat, plugin.get_data_chunk_count(),
        plugin.get_coding_chunk_count())


def matrix_signature(matrix, k, m) -> tuple:
    """The geometry + bit-equal-generator-matrix fields every
    coalescing key shares (the fallback above and plugin
    `codec_signature()` implementations prepend their type tag).
    The RAW matrix bytes ride the key — a hash would make "provably
    identical" probabilistic, and a collision would silently encode
    one pool's runs with another pool's matrix; generator matrices
    are tiny and plugins cache the signature, so exact bytes cost
    nothing."""
    a = np.ascontiguousarray(np.asarray(matrix))
    return (int(k), int(m), a.shape, a.tobytes())


class LaunchQueueError(RuntimeError):
    """A ticket whose launch/finalize died; the owning backend aborts
    its drain's ops (never other PGs')."""


class _Sub:
    """One backend drain's submission (all its fused runs, its one
    concatenated plain chunk run, or one recovery decode / CLAY repair
    run).  `extra` carries kind-specific launch arguments (the decode
    erasure list)."""
    __slots__ = ("ticket", "plugin", "runs", "n_runs", "width",
                 "nbytes", "t_submit", "owner", "extra", "traces")

    def __init__(self, ticket, plugin, runs, owner, extra=None,
                 traces=()):
        self.ticket = ticket
        self.plugin = plugin
        self.runs = runs
        self.n_runs = len(runs)
        self.width = runs[0].shape[1]
        self.nbytes = sum(r.shape[0] * r.shape[1] for r in runs)
        self.t_submit = time.perf_counter()
        self.owner = owner
        self.extra = extra
        # trace ids of the ops whose bytes ride this submission
        # (PR 4 stitching: the flight recorder's LaunchRecord carries
        # them so a slow-op's blame can name its launch and vice versa)
        self.traces = traces


class _Batch:
    """One launched super-batch.  `combined` holds the shared handle
    (launched through the first submission's plugin) plus the demux
    order; `per_sub` is the containment fallback — each submission
    launched on its own plugin after a combined-launch failure."""

    def __init__(self, kind: str, subs: list[_Sub]):
        self.kind = kind
        self.subs = subs
        self.lock = threading.Lock()
        # set once _do_launch has issued (or containment-retried) the
        # device submit; finalizers wait on it, so a result() racing
        # the launching thread never sees a half-built batch
        self.launch_done = threading.Event()
        # one-shot claim on the device submit: the window worker
        # launches popped batches sequentially, so a finalizer whose
        # batch is still unclaimed steals the launch instead of
        # head-of-line-blocking behind another key's multi-second
        # compile (or a CPU plugin's synchronous encode)
        self._launch_claim = threading.Lock()
        self.finalized = False
        self.combined = None        # (plugin, handle)
        self.per_sub = None         # [(sub, handle | None)]
        self.path = None
        # flight-recorder state (ops/profiler.py): queue wait of the
        # oldest submission (set at pop) and the in-flight record the
        # finalizer closes with the device time
        self.queue_wait = 0.0
        self.prof_rec = None


class LaunchTicket:
    """What a backend drain holds instead of a plugin submit handle.
    `result()` blocks until the super-batch containing this
    submission has launched (forcing the launch if the window hasn't
    fired — flush-on-demand) and finalized, then returns this
    submission's demultiplexed share of the results."""

    is_launch_ticket = True

    def __init__(self, queue: "ECLaunchQueue", kind: str, key: tuple):
        self._queue = queue
        self.kind = kind
        self._key = key
        self._batch: _Batch | None = None
        self._result = None
        self._error: Exception | None = None
        self._done = False
        self.path: str | None = None
        self.cancelled = False
        # flight-recorder stitching (ops/profiler.py): filled at
        # launch so the owning backend can put the launch id (and a
        # first-compile blame event) on its ops' timelines
        self.launch_id: int | None = None
        self.bucket: str | None = None
        self.compiled = False
        self.compile_s = 0.0
        self.cache_hit = False

    @property
    def launched(self) -> bool:
        return self._batch is not None

    def cancel(self) -> None:
        """Withdraw a not-yet-launched submission (the owning drain
        died during its own submit half); post-launch this is a no-op
        and the results are simply never read."""
        self._queue._cancel(self)

    def result(self):
        if not self._done:
            if self._batch is None:
                self._queue.flush(self._key)
            batch = self._batch
            if batch is None:
                if self._error is None:
                    self._error = LaunchQueueError(
                        "launch ticket cancelled before launch")
            else:
                self._queue._finalize_batch(batch)
        if self._error is not None:
            raise self._error
        return self._result


def _build_queue_perf(name: str):
    from ..common.perf_counters import PerfCountersBuilder
    return (PerfCountersBuilder(name)
            .add_u64_counter("ec_host_launches",
                             "super-batch device launches issued")
            .add_u64_counter("ec_host_launch_runs",
                             "extent runs coalesced into launches")
            .add_u64_counter("ec_host_launch_bytes",
                             "input bytes coalesced into launches")
            .add_u64_counter("ec_host_launch_pg_mix",
                             "sum of distinct submitters per launch")
            .add_u64_counter("ec_host_cross_pg_launches",
                             "launches coalescing >1 PG's runs")
            .add_u64_counter("ec_host_launch_retries",
                             "combined launches retried per-submission "
                             "(containment)")
            .add_u64_counter("ec_host_launch_errors",
                             "submissions whose launch failed")
            .add_u64_counter("ec_host_decode_launches",
                             "recovery/reconstruct decode super-batch "
                             "launches")
            .add_u64_counter("ec_host_repair_launches",
                             "CLAY repair-plan super-batch launches")
            .add_gauge("ec_host_occupancy_pct",
                       "last launch bytes / max super-batch bytes")
            .add_histogram("lat_ec_batch_wait",
                           "submit -> launch batching wait")
            .create_perf_counters())


class ECLaunchQueue:
    """The per-host (per-process in the multi-process simulation,
    where each process stands in for a host — same topology rule as
    MeshService) EC launch queue."""

    # one queue per host: the MeshService seam hands this out
    _host: "ECLaunchQueue | None" = None
    _host_lock = threading.Lock()

    def __init__(self, window_us: float = 250.0,
                 max_bytes: int = 32 << 20, perf=None,
                 perf_name: str = "ec_host_queue"):
        self.window_us = float(window_us)
        self.max_bytes = max(1, int(max_bytes))
        self.perf = perf if perf is not None \
            else _build_queue_perf(perf_name)
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        # aggregates have their own leaf lock: launch/finalize threads
        # bump error counters while holding a batch lock, and must not
        # contend with (or deadlock against) the pending-queue lock
        self._stats_lock = threading.Lock()
        self._pending: dict[tuple, list[_Sub]] = {}
        self._pending_bytes: dict[tuple, int] = {}
        self._deadline: float | None = None
        self._worker: threading.Thread | None = None
        self._closed = False
        self.created_at = time.time()
        # aggregates for status()
        self.launches = 0
        self.launched_runs = 0
        self.launched_bytes = 0
        self.launched_subs = 0
        self.pg_mix_total = 0
        self.cross_pg_launches = 0
        self.launch_retries = 0
        self.launch_errors = 0
        self.decode_launches = 0
        self.repair_launches = 0
        self.last_launch: dict | None = None

    # -- host singleton (MeshService wiring rides this) ----------------------

    @classmethod
    def host_instance(cls, window_us: float | None = None,
                      max_bytes: int | None = None) -> "ECLaunchQueue":
        """The host's queue, built on first use (first caller's knobs
        win — one queue per host is the deployment contract, like the
        mesh shape)."""
        with cls._host_lock:
            if cls._host is None:
                kw = {}
                if window_us is not None:
                    kw["window_us"] = window_us
                if max_bytes is not None:
                    kw["max_bytes"] = max_bytes
                cls._host = cls(**kw)
            return cls._host

    @classmethod
    def host_get(cls) -> "ECLaunchQueue | None":
        return cls._host

    @classmethod
    def reset_host(cls) -> None:
        """Tests only (in-flight tickets of the old queue still
        resolve through their own references)."""
        with cls._host_lock:
            if cls._host is not None:
                cls._host.close()
            cls._host = None

    # -- submission ----------------------------------------------------------

    def submit_extents(self, plugin, runs: list[np.ndarray],
                       owner=None, traces=()) -> LaunchTicket:
        """Queue a drain's fused append runs (each (k, Wi) uint8) for
        a coalesced `encode_extents_with_crc_submit` launch;
        `result()` yields the per-run (parity, l, tail, body) tuples
        in this submission's run order.  traces: the contributing
        ops' trace ids (flight-recorder stitching)."""
        return self._submit("x", plugin, [
            np.ascontiguousarray(r, dtype=np.uint8) for r in runs],
            owner, traces=traces)

    def submit_chunks(self, plugin, chunks: np.ndarray,
                      owner=None, traces=()) -> LaunchTicket:
        """Queue a drain's concatenated plain (k, W) run for a
        coalesced parity-only launch; `result()` yields this
        submission's (m, W) parity columns."""
        return self._submit("c", plugin, [
            np.ascontiguousarray(chunks, dtype=np.uint8)], owner,
            traces=traces)

    def submit_decode(self, plugin, dense: np.ndarray, erasures,
                      owner=None, traces=()) -> LaunchTicket:
        """Queue one recovery/reconstruct decode: `dense` is the
        (k+m, W) array with zeros in the erased rows.  Submissions
        sharing (codec, erasure pattern) coalesce into one
        `decode_chunks` launch across PGs — repair rides the same
        launch-occupancy machinery as writes (ROADMAP item 2's named
        remainder); `result()` yields this submission's decoded
        (k+m, W) columns."""
        erasures = tuple(sorted(int(e) for e in erasures))
        return self._submit(
            "d", plugin,
            [np.ascontiguousarray(dense, dtype=np.uint8)], owner,
            key_suffix=(erasures,), extra=erasures, traces=traces)

    def submit_clay_repair(self, plan, rows: np.ndarray,
                           owner=None, traces=()) -> LaunchTicket:
        """Queue one CLAY repair-plan apply: `rows` are the stacked
        helper repair-plane symbols (d*P, W) of ONE object (or a
        backend's own concatenation of several).  Submissions sharing
        a plan signature — same (geometry, lost chunk, helper set) —
        coalesce into one batched GF matmul launch
        (parallel/mesh.ClayRepairPlan); `result()` yields this
        submission's (sub_chunks, W) rebuilt columns."""
        return self._submit(
            "r", plan, [np.ascontiguousarray(rows, dtype=np.uint8)],
            owner, key_suffix=(), traces=traces)

    def _submit(self, kind: str, plugin, runs, owner,
                key_suffix: tuple = (), extra=None,
                traces=()) -> LaunchTicket:
        if kind == "r":
            key = (kind,) + tuple(plugin.signature)
        else:
            key = (kind,) + codec_signature(plugin) + key_suffix
        ticket = LaunchTicket(self, kind, key)
        sub = _Sub(ticket, plugin, runs, owner, extra=extra,
                   traces=traces)
        batches: list[_Batch] = []
        with self._lock:
            self._pending.setdefault(key, []).append(sub)
            nb = self._pending_bytes.get(key, 0) + sub.nbytes
            self._pending_bytes[key] = nb
            if nb >= self.max_bytes or self.window_us <= 0:
                # occupancy cap reached (or batching disabled): launch
                # this key's super-batch immediately
                batches = self._pop_batches_locked(key)
            else:
                self._arm_window_locked()
        for batch in batches:
            self._do_launch(batch)
        return ticket

    def _cancel(self, ticket: LaunchTicket) -> None:
        with self._lock:
            subs = self._pending.get(ticket._key)
            if subs:
                for sub in subs:
                    if sub.ticket is ticket:
                        subs.remove(sub)
                        self._pending_bytes[ticket._key] -= sub.nbytes
                        if not subs:
                            del self._pending[ticket._key]
                            del self._pending_bytes[ticket._key]
                        if not self._pending:
                            self._deadline = None
                        break
        ticket.cancelled = True

    # -- window --------------------------------------------------------------

    def _arm_window_locked(self) -> None:
        """First pending submission of a window sets the deadline (a
        later submit never extends it) and wakes the single persistent
        window worker — NOT a fresh Timer thread per window, which at
        a 250 us default would be thousands of thread spawns per
        second on the write hot path."""
        if self._deadline is None:
            self._deadline = time.perf_counter() + self.window_us / 1e6
            self._cv.notify()
        if self._worker is None:
            self._worker = threading.Thread(
                target=self._window_loop, daemon=True,
                name="ec-launch-window")
            self._worker.start()

    def close(self) -> None:
        """Flush pending batches and retire the window worker.  For
        throwaway queues (benches, tests) — a host queue lives for
        the process.  Tickets submitted after close still launch via
        byte cap or flush-on-demand; only the window stops firing."""
        self.flush()
        with self._lock:
            self._closed = True
            self._cv.notify()

    def _window_loop(self) -> None:
        while True:
            with self._cv:
                if self._closed:
                    return
                if self._deadline is None:
                    self._cv.wait()
                    continue
                delay = self._deadline - time.perf_counter()
                if delay > 0:
                    self._cv.wait(delay)
                    continue
                batches = [b for k in list(self._pending)
                           if self._pending.get(k)
                           for b in self._pop_batches_locked(k)]
                self._deadline = None
            for batch in batches:
                self._do_launch(batch)

    def flush(self, key: tuple | None = None) -> None:
        """Launch pending super-batches now (all keys, or one):
        flush-on-demand for tickets finalized before the window
        fires, and the idle-flush hook."""
        with self._lock:
            keys = [key] if key is not None else list(self._pending)
            batches = [b for k in keys if self._pending.get(k)
                       for b in self._pop_batches_locked(k)]
        for batch in batches:
            self._do_launch(batch)

    # -- launch --------------------------------------------------------------

    def _pop_batches_locked(self, key: tuple) -> "list[_Batch]":
        """Under self._lock: claim a key's pending submissions as one
        or more batches, binding every ticket to one (so a racing
        result() waits on its batch instead of re-flushing an empty
        key).  Decode keys split at DECODE_MAX_LAUNCH_W of summed
        input width: with the pow2 padding in _do_launch this keeps
        every decode launch inside the prewarm-enumerable bucket set
        ({pow2 <= cap} x cardinality) no matter how many PGs' repair
        slices coalesce in one window.  The device submit itself
        happens OUTSIDE the queue lock in _do_launch — a multi-second
        first-bucket compile (or a CPU plugin's synchronous encode)
        must stall only its batch, not every PG's submit path."""
        subs = self._pending.pop(key)
        self._pending_bytes.pop(key, None)
        if not self._pending:
            self._deadline = None
        if key[0] != "d":
            groups = [subs]
        else:
            groups, cur, cur_w = [], [], 0
            for s in subs:
                w = int(s.runs[0].shape[1])
                if cur and cur_w + w > DECODE_MAX_LAUNCH_W:
                    groups.append(cur)
                    cur, cur_w = [], 0
                cur.append(s)
                cur_w += w
            if cur:
                groups.append(cur)
        return [self._make_batch_locked(key, g) for g in groups]

    def _make_batch_locked(self, key: tuple,
                           subs: "list[_Sub]") -> _Batch:
        batch = _Batch(key[0], subs)
        now = time.perf_counter()
        for s in subs:
            s.ticket._batch = batch
            if self.perf:
                self.perf.hinc("lat_ec_batch_wait", now - s.t_submit)
        # the launch ledger records the OLDEST submission's wait (the
        # batching cost an op actually paid, not the average)
        batch.queue_wait = now - min(s.t_submit for s in subs)
        nbytes = sum(s.nbytes for s in subs)
        nruns = sum(s.n_runs for s in subs)
        owners = {s.owner for s in subs}
        # a single submission larger than max_bytes launches alone and
        # oversizes the batch (the cap is checked after append); clamp
        # so the gauge stays a percentage
        occupancy = min(100.0, 100.0 * nbytes / self.max_bytes)
        with self._stats_lock:
            self.launches += 1
            self.launched_runs += nruns
            self.launched_bytes += nbytes
            self.launched_subs += len(subs)
            self.pg_mix_total += len(owners)
            if len(owners) > 1:
                self.cross_pg_launches += 1
            if batch.kind == "d":
                self.decode_launches += 1
            elif batch.kind == "r":
                self.repair_launches += 1
            self.last_launch = {"runs": nruns, "bytes": nbytes,
                                "submissions": len(subs),
                                "pg_mix": len(owners),
                                "occupancy_pct": round(occupancy, 2)}
        if self.perf:
            self.perf.inc("ec_host_launches")
            self.perf.inc("ec_host_launch_runs", nruns)
            self.perf.inc("ec_host_launch_bytes", nbytes)
            self.perf.inc("ec_host_launch_pg_mix", len(owners))
            if len(owners) > 1:
                self.perf.inc("ec_host_cross_pg_launches")
            if batch.kind == "d":
                self.perf.inc("ec_host_decode_launches")
            elif batch.kind == "r":
                self.perf.inc("ec_host_repair_launches")
            self.perf.set("ec_host_occupancy_pct", round(occupancy, 2))
        return batch

    def _note_launch_error(self) -> None:
        with self._stats_lock:
            self.launch_errors += 1
        if self.perf:
            self.perf.inc("ec_host_launch_errors")

    def _do_launch(self, batch: _Batch) -> None:
        if not batch._launch_claim.acquire(blocking=False):
            # another thread owns the submit (a finalizer stole its
            # batch's launch, or vice versa); it sets launch_done
            return
        subs = batch.subs
        kind = batch.kind
        # flight recorder (ops/profiler.py): one LaunchRecord per
        # super-batch, begun before the device submit so its clock
        # covers the dispatch (and a first-bucket compile)
        prof = device_profiler()
        rec = prof.begin(
            {"x": "fused_encode", "c": "plain_encode",
             "d": "decode", "r": "clay_repair"}.get(kind, kind),
            codec=_codec_label(subs[0].plugin),
            runs=sum(s.n_runs for s in subs),
            nbytes=sum(s.nbytes for s in subs),
            pg_mix=len({s.owner for s in subs}),
            traces=[t for s in subs for t in s.traces],
            queue_wait_s=batch.queue_wait)
        bucket = None
        try:
            plugin = subs[0].plugin
            if kind == "x":
                all_runs = [r for s in subs for r in s.runs]
                handle = plugin.encode_extents_with_crc_submit(all_runs)
                batch.path = handle.get("path") \
                    if isinstance(handle, dict) else None
                # plugins that know their real jit-key axes (the jax
                # plugin's autotuned operating point) refine the bucket
                bucket = plugin.launch_bucket(handle) \
                    if hasattr(plugin, "launch_bucket") \
                    else _extents_bucket(handle)
            elif kind == "r":
                # CLAY repair plan: one batched GF matmul for every
                # co-submitted object (plugin slot holds the shared
                # ClayRepairPlan — signatures matched, so it IS shared)
                bigs = [s.runs[0] for s in subs]
                big = np.concatenate(bigs, axis=1) if len(bigs) > 1 \
                    else bigs[0]
                sig = abs(hash(tuple(plugin.signature))) & 0xFFFFFF
                bucket = f"r:{sig:x}:w{big.shape[1]}"
                handle = ("np", np.asarray(plugin.apply(big)))
            elif kind == "d":
                # recovery/reconstruct decode: erasure patterns match
                # within a key, so the concatenated dense array decodes
                # in one launch; zero pad columns (launch-shape
                # bucketing, like the plain path) decode to zeros the
                # demux never reads
                bigs = [s.runs[0] for s in subs]
                big = np.concatenate(bigs, axis=1) if len(bigs) > 1 \
                    else bigs[0]
                # launch-shape bucketing, UNCONDITIONAL: a solo sub
                # can carry an arbitrary width (a recovery group's
                # concatenated chunks, a non-pow2 chunk_len), and an
                # unpadded width mints a fresh jit bucket no boot
                # prewarm can enumerate.  Pow2 padding bounds the
                # decode bucket universe; the finalize demux slices
                # each sub's real width, so pad columns are never read.
                w = big.shape[1]
                w2 = next_pow2(w)
                if w2 != w:
                    big = np.concatenate(
                        [big, np.zeros((big.shape[0], w2 - w),
                                       dtype=np.uint8)], axis=1)
                era = "".join(str(e) for e in subs[0].extra)
                bucket = f"d:e{era}:w{big.shape[1]}"
                handle = ("np", np.asarray(plugin.decode_chunks(
                    big, list(subs[0].extra))))
            else:
                bigs = [s.runs[0] for s in subs]
                big = np.concatenate(bigs, axis=1) if len(bigs) > 1 \
                    else bigs[0]
                if hasattr(plugin, "encode_chunks_submit"):
                    if len(bigs) > 1:
                        # launch-shape bucketing (see bitsliced.py):
                        # a jit'd plugin would recompile per distinct
                        # super-batch width — pad coalesced launches
                        # to the next power of two (zero columns
                        # encode to zero parity; the column demux
                        # never reads them)
                        w = big.shape[1]
                        w2 = next_pow2(w)
                        if w2 != w:
                            big = np.concatenate(
                                [big, np.zeros((big.shape[0], w2 - w),
                                               dtype=np.uint8)],
                                axis=1)
                    handle = ("h", plugin.encode_chunks_submit(big))
                else:
                    # host-synchronous CPU plugins: ONE concatenated
                    # encode for the whole super-batch (fewer, larger
                    # host matmuls — the CPU analog of occupancy)
                    handle = ("np", np.asarray(plugin.encode_chunks(big)))
                bucket = f"c:{handle[0]}:w{big.shape[1]}"
            batch.combined = (plugin, handle)
            # host-synchronous launches (pure-CPU plugin encode/
            # decode: handle kind "np" on a plugin without a jitted
            # backend) carry no compiled program — their submit wall
            # must not enter the compile ledger (jit=False); the jax
            # plugin and ClayRepairPlan declare jit_backed, and a
            # device submit handle ("h") is jitted by construction
            jit = (kind == "x"
                   or (isinstance(handle, tuple) and handle[0] == "h")
                   or getattr(plugin, "jit_backed", False))
            prof.submitted(rec, bucket, path=batch.path or
                           (handle[0] if isinstance(handle, tuple)
                            else None), jit=jit)
            batch.prof_rec = rec
            if rec is not None:
                # stitching: the owning backends put these on their
                # ops' timelines (launch id event + first-compile
                # blame) at completion
                for s in subs:
                    t = s.ticket
                    t.launch_id = rec.launch_id
                    t.bucket = rec.bucket
                    t.compiled = rec.compiled
                    t.compile_s = rec.compile_s
                    t.cache_hit = rec.cache_hit
        except Exception:  # noqa: BLE001 — containment retry
            # a poison submission must fail only its owner: launch
            # each submission on its OWN plugin, recording per-ticket
            # errors instead of failing the super-batch wholesale
            with self._stats_lock:
                self.launch_retries += 1
            if self.perf:
                self.perf.inc("ec_host_launch_retries")
            batch.per_sub = []
            for s in subs:
                try:
                    if kind == "x":
                        h = s.plugin.encode_extents_with_crc_submit(
                            s.runs)
                    elif kind == "r":
                        h = ("np", np.asarray(
                            s.plugin.apply(s.runs[0])))
                    elif kind == "d":
                        h = ("np", np.asarray(s.plugin.decode_chunks(
                            s.runs[0], list(s.extra))))
                    elif hasattr(s.plugin, "encode_chunks_submit"):
                        h = ("h", s.plugin.encode_chunks_submit(
                            s.runs[0]))
                    else:
                        h = ("np", np.asarray(
                            s.plugin.encode_chunks(s.runs[0])))
                    batch.per_sub.append((s, h))
                except Exception as e:  # noqa: BLE001 — the poison sub
                    self._note_launch_error()
                    s.ticket._error = LaunchQueueError(
                        f"launch failed for this submission: {e!r}")
                    s.ticket._error.__cause__ = e
                    s.ticket._done = True
                    batch.per_sub.append((s, None))
        finally:
            for s in subs:
                s.runs = None   # the launch holds the staged arrays now
            batch.launch_done.set()

    # -- finalize ------------------------------------------------------------

    def _finalize_batch(self, batch: _Batch) -> None:
        """Materialize one super-batch ONCE and demultiplex each
        submission's share onto its ticket; errors are memoized so
        every co-batched ticket sees the same outcome.  Runs on the
        first finalizing backend's thread (completion stays in each
        PG's own submit order — the queue imposes no ordering across
        PGs)."""
        if not batch.launch_done.is_set():
            # steal the launch if the window worker hasn't started it
            # yet — a bound ticket must not wait behind other keys'
            # batches in the worker's sequential loop
            self._do_launch(batch)
        batch.launch_done.wait()
        with batch.lock:
            if batch.finalized:
                return
            t_mat = time.perf_counter()
            try:
                if batch.per_sub is not None:
                    for sub, handle in batch.per_sub:
                        if handle is None:
                            continue        # launch already failed
                        try:
                            self._finalize_sub(batch.kind, sub, handle)
                        except Exception as e:  # noqa: BLE001
                            self._note_launch_error()
                            sub.ticket._error = e
                            sub.ticket._done = True
                else:
                    plugin, handle = batch.combined
                    if batch.kind == "x":
                        res = plugin.encode_extents_with_crc_finalize(
                            handle)
                        pos = 0
                        for sub in batch.subs:
                            sub.ticket._result = \
                                res[pos:pos + sub.n_runs]
                            sub.ticket.path = batch.path
                            sub.ticket._done = True
                            pos += sub.n_runs
                    else:
                        kind_h, h = handle
                        par = plugin.encode_chunks_finalize(h) \
                            if kind_h == "h" else h
                        col = 0
                        for sub in batch.subs:
                            sub.ticket._result = \
                                par[:, col:col + sub.width]
                            sub.ticket._done = True
                            col += sub.width
            except Exception as e:  # noqa: BLE001 — device finalize
                # died: every ticket of the batch carries the error;
                # each backend aborts ITS ops and the queue lives on
                for sub in batch.subs:
                    if not sub.ticket._done:
                        self._note_launch_error()
                        sub.ticket._error = e
                        sub.ticket._done = True
            finally:
                batch.finalized = True
                # ledger: submit -> materialize is the device time
                # (the first finalizer blocks on the futures here)
                device_profiler().materialized(
                    batch.prof_rec, time.perf_counter() - t_mat)

    def _finalize_sub(self, kind: str, sub: _Sub, handle) -> None:
        if kind == "x":
            sub.ticket._result = \
                sub.plugin.encode_extents_with_crc_finalize(handle)
            sub.ticket.path = handle.get("path") \
                if isinstance(handle, dict) else None
        else:
            kind_h, h = handle
            sub.ticket._result = sub.plugin.encode_chunks_finalize(h) \
                if kind_h == "h" else h
        sub.ticket._done = True

    # -- observability -------------------------------------------------------

    def status(self) -> dict:
        """The `launch queue status` asok payload: batching knobs,
        launch/coalescing/occupancy aggregates, pending backlog."""
        with self._lock:
            pending_subs = sum(len(v) for v in self._pending.values())
            pending_bytes = sum(self._pending_bytes.values())
        with self._stats_lock:
            launches = self.launches
            return {
                "window_us": self.window_us,
                "max_super_batch_bytes": self.max_bytes,
                "launches": launches,
                "coalesced_runs": self.launched_runs,
                "coalesced_bytes": self.launched_bytes,
                "submissions": self.launched_subs,
                "avg_runs_per_launch": round(
                    self.launched_runs / launches, 2)
                if launches else 0.0,
                "occupancy_pct_avg": round(min(
                    100.0, 100.0 * self.launched_bytes
                    / (launches * self.max_bytes)), 2)
                if launches else 0.0,
                "cross_pg_launches": self.cross_pg_launches,
                "pg_mix_avg": round(
                    self.pg_mix_total / launches, 2)
                if launches else 0.0,
                "launch_retries": self.launch_retries,
                "launch_errors": self.launch_errors,
                "decode_launches": self.decode_launches,
                "repair_launches": self.repair_launches,
                "last_launch": self.last_launch,
                "pending_submissions": pending_subs,
                "pending_bytes": pending_bytes,
                "uptime_s": round(time.time() - self.created_at, 1),
            }
