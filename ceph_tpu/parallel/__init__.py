"""Device-mesh parallelism for the EC data plane."""

from .mesh import DistributedStripeCodec, make_mesh

__all__ = ["DistributedStripeCodec", "make_mesh"]
