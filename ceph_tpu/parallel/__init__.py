"""Device-mesh parallelism for the EC data plane."""

from .mesh import DistributedStripeCodec, make_mesh
from .service import MeshError, MeshService

__all__ = ["DistributedStripeCodec", "make_mesh",
           "MeshError", "MeshService"]
