"""MeshService: the per-host owner of the multichip EC data plane.

Deployment model (docs/MULTICHIP.md): a host that drives a multichip
accelerator runs ONE process-wide MeshService owning the
('shard', 'data') `jax.sharding.Mesh`; every OSD daemon on the host
(and every EC PG backend inside each daemon) acquires its
`DistributedStripeCodec` handle from the service instead of building a
private mesh — one compiled collective program per EC geometry, shared
launch queue, shared failure accounting.  This is the mesh analog of
the reference scaling writes with CRUSH fan-out over OSD hosts
(ECBackend.cc MOSDECSubOpWrite): where the reference's unit of scale-
out is a host on the network, ours is a chip on the ICI mesh, and the
service is the host-side broker that hands the chips out.

Acquisition is geometry-checked: the codec's k must divide over the
mesh's 'shard' axis and, when the caller supplies its plugin's
generator matrix, the mesh codec's matrix must be bit-identical
(cauchy parity written by the mesh is garbage to a reed_sol_van
decode).  Violations raise MeshError — callers (ECBackend, the OSD)
treat that as a surfaced config error and fall back to the single-chip
plane rather than crashing the daemon.

The service also keeps the containment ledger: when a mesh launch
fails mid-pipeline the owning ECBackend aborts the op, permanently
falls back to the single-chip plane for that PG, and reports the
failure here so `mesh status` (asok) shows a cluster operator exactly
which plane is serving and why.
"""

from __future__ import annotations

import threading
import time


class MeshError(RuntimeError):
    """Mesh configuration/geometry error: the caller must fall back to
    the single-chip plane (never fatal to a daemon)."""


def parse_mesh_shape(spec: str | None, have: int) -> tuple[int, int]:
    """'SxD' -> (S, D); a bare count (or empty = all `have` devices)
    gets the dryrun heuristic: the largest of 4/2/1 dividing the count
    becomes the 'shard' axis (k=8 work shards 4-ways; odd meshes
    degrade to data-parallel only)."""
    spec = (spec or "").strip().lower()
    if "x" in spec:
        s, _, d = spec.partition("x")
        try:
            shape = (int(s), int(d))
        except ValueError as e:
            raise MeshError(f"bad mesh_devices spec {spec!r}: {e}") from e
        if shape[0] < 1 or shape[1] < 1:
            raise MeshError(f"bad mesh_devices spec {spec!r}")
        return shape
    try:
        n = int(spec) if spec else have
    except ValueError as e:
        raise MeshError(f"bad mesh_devices spec {spec!r}: {e}") from e
    if n < 1:
        raise MeshError(f"bad mesh_devices count {n}")
    n_shard = 4 if n % 4 == 0 else (2 if n % 2 == 0 else 1)
    return n_shard, n // n_shard


class MeshService:
    """Process-wide (= per-host in the thread topology; per-daemon in
    the multi-process simulation, where each process stands in for a
    host) broker of the device mesh."""

    _instance: "MeshService | None" = None
    _lock = threading.Lock()

    def __init__(self, mesh, spec: str):
        from .launch_queue import ECLaunchQueue
        from .mesh import DistributedStripeCodec  # noqa: F401 (doc link)
        self.mesh = mesh
        self.spec = spec
        self.n_shard = mesh.shape["shard"]
        self.n_data = mesh.shape["data"]
        self._codecs: dict[tuple, object] = {}
        self._codec_lock = threading.Lock()
        self.created_at = time.time()
        self.failures = 0
        self.last_error: str | None = None
        # the host's EC launch queue (cross-PG continuous batching,
        # launch_queue.py) when one has been wired; the service owns
        # the device plane, so it also brokers the launch queue —
        # codec-owner AND launch-queue-owner (ROADMAP item 2)
        self.launch_queue = ECLaunchQueue.host_get()

    # -- lifecycle ----------------------------------------------------------

    @classmethod
    def configure(cls, devices: str | int | None = None,
                  ) -> "MeshService":
        """Build (or return) the host's mesh service.  First
        configuration wins: one mesh per host is the deployment
        contract — a second configure with a conflicting shape raises
        MeshError instead of silently rebuilding compiled programs
        under live backends."""
        spec = "" if devices is None else str(devices)
        with cls._lock:
            if cls._instance is not None:
                inst = cls._instance
                if spec and spec != inst.spec:
                    # resolve count specs through the same parser a
                    # fresh configure would use — a silently-ignored
                    # conflicting count would leave `mesh status`
                    # contradicting the conf the operator set
                    import jax
                    want = parse_mesh_shape(spec, len(jax.devices()))
                    if want != (inst.n_shard, inst.n_data):
                        raise MeshError(
                            f"mesh already configured as "
                            f"{inst.n_shard}x{inst.n_data} "
                            f"(requested {spec!r} = "
                            f"{want[0]}x{want[1]})")
                return inst
            import jax

            from .mesh import make_mesh
            have = len(jax.devices())
            n_shard, n_data = parse_mesh_shape(spec, have)
            if n_shard * n_data > have:
                raise MeshError(
                    f"mesh {n_shard}x{n_data} needs "
                    f"{n_shard * n_data} devices, have {have} "
                    f"(pre-set XLA_FLAGS="
                    f"--xla_force_host_platform_device_count=N for "
                    f"CPU meshes)")
            cls._instance = cls(make_mesh(n_shard, n_data), spec)
            return cls._instance

    @classmethod
    def get(cls) -> "MeshService | None":
        """The configured instance, or None (mesh mode off)."""
        return cls._instance

    @classmethod
    def get_or_configure(cls, devices: str | int | None = None
                         ) -> "MeshService":
        return cls.configure(devices)

    @classmethod
    def reset(cls) -> None:
        """Drop the singleton (tests only: compiled programs cache per
        geometry, so production never resets a live service)."""
        with cls._lock:
            cls._instance = None

    # -- launch queue (cross-PG continuous batching) ------------------------

    @classmethod
    def host_launch_queue(cls, window_us: float | None = None,
                          max_bytes: int | None = None):
        """The host's EC launch queue (launch_queue.ECLaunchQueue),
        built on first use — the service seam hands it out exactly
        like codec handles, and it works with OR without a configured
        mesh (single-chip hosts batch across PGs too).  First caller's
        knobs win, like the mesh shape."""
        from .launch_queue import ECLaunchQueue
        queue = ECLaunchQueue.host_instance(window_us=window_us,
                                            max_bytes=max_bytes)
        inst = cls.get()
        if inst is not None:
            inst.launch_queue = queue
        return queue

    # -- acquisition --------------------------------------------------------

    def acquire(self, k: int, m: int, technique: str = "cauchy",
                matrix=None):
        """Geometry-checked DistributedStripeCodec handle, cached per
        (k, m, technique) so every PG of every OSD on the host shares
        one compiled collective program per EC profile.

        matrix: the acquiring plugin's generator matrix when it has
        one — must match the mesh codec's bit for bit (parity written
        on the mesh plane must decode on the single-chip plane and
        vice versa)."""
        import numpy as np

        from .mesh import DistributedStripeCodec
        if k % self.n_shard:
            raise MeshError(
                f"EC k={k} not divisible by mesh shard axis "
                f"{self.n_shard} (mesh {self.n_shard}x{self.n_data})")
        tech = "cauchy" if technique in ("cauchy", "cauchy_good") \
            else "reed_sol_van"
        key = (k, m, tech)
        with self._codec_lock:
            codec = self._codecs.get(key)
            if codec is None:
                try:
                    codec = DistributedStripeCodec(
                        k, m, self.mesh,
                        technique="cauchy" if tech == "cauchy"
                        else "vandermonde")
                except Exception as e:  # noqa: BLE001 — geometry/build
                    raise MeshError(f"mesh codec build failed: {e}") \
                        from e
                self._codecs[key] = codec
        if matrix is not None and \
                not np.array_equal(np.asarray(matrix), codec.matrix):
            raise MeshError(
                f"plugin generator matrix (technique={technique!r}) "
                f"does not match the mesh codec's {tech} matrix — "
                f"mesh parity would not decode on the plugin plane")
        return codec

    # -- containment ledger -------------------------------------------------

    def note_failure(self, err: BaseException | str) -> None:
        """Record a mesh launch failure (the owning backend has
        already aborted the op and fallen back to the single-chip
        plane); surfaced via status() / the `mesh status` asok."""
        self.failures += 1
        self.last_error = repr(err) if isinstance(err, BaseException) \
            else str(err)

    # -- observability ------------------------------------------------------

    def status(self) -> dict:
        import jax
        return {
            "shape": {"shard": self.n_shard, "data": self.n_data},
            "n_devices": self.n_shard * self.n_data,
            "devices_visible": len(jax.devices()),
            "backend": jax.default_backend(),
            "codecs": sorted(
                f"k={k} m={m} {t}" for (k, m, t) in self._codecs),
            "failures": self.failures,
            "last_error": self.last_error,
            "launch_queue": (self.launch_queue.status()
                             if self.launch_queue is not None else None),
            "uptime_s": round(time.time() - self.created_at, 1),
        }
