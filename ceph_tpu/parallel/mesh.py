"""Multi-chip sharding of the erasure-code data plane.

Where the reference scales with CRUSH placement over OSD hosts and ships
shard writes over its async messenger (reference src/osd/ECBackend.cc:2074
MOSDECSubOpWrite fan-out; recovery fan-in :570), the TPU-native data
plane scales over a `jax.sharding.Mesh` with XLA collectives riding ICI:

  axis 'shard' — tensor-parallel over the k data chunks.  Each device
      holds a slice of the chunk rows and the matching *columns* of the
      generator bit-matrix, runs the SAME fused Pallas kernel the
      single-chip path uses on its slice, and the cross-device GF(2)
      fan-in is an `all_gather` + XOR fold of the packed partial
      parities (mod-2 commutes with the sum, so per-device parities XOR
      to the total — the parity fan-in a messenger would carry becomes
      one collective of exactly the parity bytes).
  axis 'data' — data-parallel over the byte/stripe axis, no
      communication: stripes are independent, like separate PGs.

Round 1 shipped a psum-of-unpacked-bitplanes fan-in; that moves 32x the
parity bytes over ICI (8 bit-planes x int32) and forces the pack out of
the kernel.  The XOR-of-packed fold moves (n_shard-1) x m x W bytes and
lets each device run the full w32 Pallas kernel locally — both encode
and decode ride the headline kernel now.

Decode/repair is the same contraction with the inverted matrix: the k
survivor rows shard over 'shard', each device applies its column slice
of the (targets x k) recovery matrix, XOR fold completes the rebuild
(reference ECBackend recovery reads k shards to the primary and decodes
locally; here the gather IS the collective).

Everything is shape-static and jit-clean: one compiled program per
(r, geometry), cached; `jax.jit` re-specializes per byte-width.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..common.util import concat_columns, split_columns
from ..ec import gf
from ..ops import bitsliced
from ..ops.profiler import device_profiler

LANE = bitsliced.LANE

# jax.shard_map (with check_vma) landed after 0.4.x; older runtimes
# expose it as jax.experimental.shard_map with the check_rep kwarg.
# Same semantics for this module's use (the replication checker can't
# statically infer the XOR-of-all_gather fold either way).
if hasattr(jax, "shard_map"):
    _shard_map = jax.shard_map
    _SM_NOCHECK = {"check_vma": False}
else:  # pragma: no cover - exercised on older jax runtimes
    from jax.experimental.shard_map import shard_map as _shard_map
    _SM_NOCHECK = {"check_rep": False}


def make_mesh(n_shard: int, n_data: int, devices=None) -> Mesh:
    """Build a ('shard', 'data') mesh from the first n_shard*n_data devices."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    need = n_shard * n_data
    if devices.size < need:
        raise ValueError(f"need {need} devices, have {devices.size}")
    return Mesh(devices[:need].reshape(n_shard, n_data), ("shard", "data"))


class DistributedStripeCodec:
    """Sharded batched RS encode/decode over a device mesh.

    The flagship distributed computation.  Two entry families:

      encode_flat / decode_flat — (k, W) chunk rows, the OSD pipeline's
          native drain layout (ECBackend concatenates every extent of
          every in-flight transaction along the byte axis);
      encode — (B, k, C) stripe batches (benchmarks, tests).

    `use_w32` selects the word-packed Pallas kernel (the single-chip
    headline path) inside each device's shard of the contraction; the
    byte/XLA formulation remains for CPU meshes (the driver's virtual
    8-device dry run) and as the oracle.  `interpret=True` runs the w32
    Pallas kernel in interpret mode so the word-packed mesh path is
    exercised on CPU CI too.
    """

    def __init__(self, k: int, m: int, mesh: Mesh,
                 technique: str = "cauchy",
                 use_w32: bool | None = None,
                 interpret: bool | None = None):
        self.k, self.m, self.mesh = k, m, mesh
        on_cpu = jax.default_backend() == "cpu"
        self.use_w32 = use_w32 if use_w32 is not None else not on_cpu
        self.interpret = interpret if interpret is not None else on_cpu
        self.n_shard = mesh.shape["shard"]
        self.n_data = mesh.shape["data"]
        if k % self.n_shard:
            raise ValueError(
                f"k={k} not divisible by shard axis {self.n_shard}")
        self.k_local = k // self.n_shard
        self.matrix = (gf.cauchy_rs_matrix(k, m) if technique == "cauchy"
                       else gf.vandermonde_rs_matrix(k, m))
        self.enc_bitmats = self._column_bitmats(self.matrix[k:])
        self._apply_cache: dict[int, object] = {}
        self._decode_plans: dict[tuple, object] = {}
        self._clay_plans: dict[tuple, object] = {}

    # -- bitmatrix plumbing -------------------------------------------------

    def _column_bitmats(self, coeff: np.ndarray,
                        cols_per_shard: int | None = None):
        """(r, j) GF(2^8) matrix -> device-put stack of per-shard column
        slices in the kernel's layout: device s gets the columns for its
        cols_per_shard input rows ((n_shard, 32r, 32c) w32 or
        (n_shard, 8r, 8c) byte), 'shard'-sharded on dim 0.  Defaults to
        the k_local encode/decode split; the CLAY repair lowering passes
        its own (padded) split."""
        cps = self.k_local if cols_per_shard is None else cols_per_shard
        build = bitsliced._w32_bitmat if self.use_w32 \
            else bitsliced.interleave_bitmatrix
        mats = [build(np.ascontiguousarray(
                    coeff[:, s * cps:(s + 1) * cps]))
                for s in range(self.n_shard)]
        stacked = np.stack(mats).astype(np.int8)
        return jax.device_put(
            stacked, NamedSharding(self.mesh, P("shard", None, None)))

    def _sharded_apply(self, r: int):
        """shard_map'd contraction for r output rows: local kernel on
        each device's (k_local, W_local) slice, all_gather + XOR fold
        over 'shard'.  Cached per r; jit respecializes per width."""
        fn = self._apply_cache.get(r)
        if fn is not None:
            return fn
        n_shard = self.n_shard
        use_w32, interpret = self.use_w32, self.interpret

        def local(bitmat, x):
            # bitmat (1, R, C); x (k_local, W_local)
            if use_w32:
                part = bitsliced.gf_bitmatmul_pallas_w32(
                    bitmat[0], x, r,
                    tile=4 * bitsliced._pick_wt(x.shape[1]),
                    interpret=interpret)
            else:
                part = bitsliced.gf_bitmatmul_xla(bitmat[0], x, r)
            gath = jax.lax.all_gather(part, "shard")   # (n_shard, r, W)
            return functools.reduce(
                jnp.bitwise_xor, [gath[i] for i in range(n_shard)])

        # no-check: the checker can't statically infer that the
        # XOR fold of an all_gather over 'shard' is 'shard'-replicated
        # (it is: every member folds the same gathered operands)
        fn = jax.jit(_shard_map(
            local, mesh=self.mesh,
            in_specs=(P("shard", None, None), P("shard", "data")),
            out_specs=P(None, "data"), **_SM_NOCHECK))
        self._apply_cache[r] = fn
        return fn

    def _quantum(self) -> int:
        """Byte-axis pad quantum: every device slice must be a LANE
        multiple (words for w32, bytes otherwise)."""
        per_dev = LANE * 4 if self.use_w32 else LANE
        return self.n_data * per_dev

    def _apply_flat_submit(self, bitmats, rows: np.ndarray, r: int):
        """Dispatch half of _apply_flat: stages rows onto the mesh and
        launches the sharded contraction, returning a handle of the
        device future + layout metadata — no host sync (the OSD's
        dispatch-ahead drains materialize in a later completion
        stage)."""
        rows = np.ascontiguousarray(rows, dtype=np.uint8)
        j, w = rows.shape
        pad = -w % self._quantum()
        if pad:
            rows = np.pad(rows, ((0, 0), (0, pad)))
        if self.use_w32:
            x = rows.view("<u4").view(np.int32)
        else:
            x = rows
        x = jax.device_put(
            jnp.asarray(x), NamedSharding(self.mesh, P("shard", "data")))
        return {"dev": self._sharded_apply(r)(bitmats, x),
                "r": r, "w": w, "pad": pad}

    def _apply_flat_finalize(self, handle) -> np.ndarray:
        out = np.asarray(handle["dev"])
        r, w, pad = handle["r"], handle["w"], handle["pad"]
        if self.use_w32:
            out = out.view("<u4").view(np.uint8).reshape(r, w + pad)
        return out[:, :w] if pad else out

    def _apply_flat(self, bitmats, rows: np.ndarray, r: int) -> np.ndarray:
        """rows (j, W) uint8 (j = k data rows or k survivor rows) ->
        (r, W) uint8 via the sharded contraction."""
        return self._apply_flat_finalize(
            self._apply_flat_submit(bitmats, rows, r))

    # -- device-resident entry (no host round-trip) -------------------------

    def apply_words(self, bitmats, words, r: int):
        """Fully device-resident contraction for callers that keep the
        data plane on device (benchmarks, chained pipelines): `words`
        (k, W) i32, already 'shard'x'data'-sharded or not (jit will
        reshard), W divisible by the device quantum.  Returns the
        (r, W) i32 result as a device array — zero host traffic.
        w32 codecs only (the device layout IS the word layout)."""
        if not self.use_w32:
            raise RuntimeError("apply_words requires a w32 mesh codec")
        assert words.shape[1] % (self.n_data * LANE) == 0
        return self._sharded_apply(r)(bitmats, words)

    def encode_words(self, words):
        """Device-resident sharded encode: (k, W) i32 -> (m, W) i32."""
        return self.apply_words(self.enc_bitmats, words, self.m)

    # -- encode (host byte API: the OSD pipeline entry) ---------------------

    def encode_flat(self, chunks: np.ndarray) -> np.ndarray:
        """(k, W) uint8 data rows -> (m, W) parity.  The OSD pipeline
        entry: ECBackend hands the whole batched drain here when a mesh
        is configured (reference analog: the per-shard MOSDECSubOpWrite
        fan-out, ECBackend.cc:2074, as one collective program)."""
        assert chunks.shape[0] == self.k
        return self._apply_flat(self.enc_bitmats, chunks, self.m)

    def encode_flat_submit(self, chunks: np.ndarray):
        """Dispatch half of encode_flat (no host sync); materialize
        with encode_flat_finalize.  The ECBackend dispatch-ahead drain
        entry for mesh-configured pools."""
        assert chunks.shape[0] == self.k
        return self._apply_flat_submit(self.enc_bitmats, chunks, self.m)

    def encode_flat_finalize(self, handle) -> np.ndarray:
        return self._apply_flat_finalize(handle)

    def encode(self, stripes):
        """stripes (B, k, C) uint8 -> parity (B, m, C): batch and byte
        axes ride 'data' together via the flat layout."""
        stripes = np.ascontiguousarray(stripes, dtype=np.uint8)
        b, k, c = stripes.shape
        assert k == self.k
        flat = stripes.transpose(1, 0, 2).reshape(k, b * c)
        par = self.encode_flat(flat)
        return par.reshape(self.m, b, c).transpose(1, 0, 2)

    # -- decode / repair ----------------------------------------------------

    def _decode_bitmats(self, survivors: tuple[int, ...],
                        targets: tuple[int, ...]):
        """Column-sharded bitmats of the (targets x survivors) recovery
        matrix (reference ECUtil::decode inversion, ECUtil.cc:9; the
        ISA-L table-cache role for the mesh)."""
        key = (survivors, targets)
        hit = self._decode_plans.get(key)
        if hit is not None:
            return hit
        coeff = gf.recovery_matrix(self.matrix, self.k, survivors, targets)
        mats = self._column_bitmats(coeff)
        self._decode_plans[key] = mats
        return mats

    def decode_flat(self, avail: np.ndarray, survivors, targets
                    ) -> np.ndarray:
        """Distributed reconstruct: `avail` (k, W) holds the survivor
        shards' bytes in `survivors` order; returns the rebuilt `targets`
        shards (len(targets), W).  Survivor rows shard over 'shard', so
        repair reads stay distributed end to end (reference
        continue_recovery_op gathers k shards to one node instead)."""
        survivors = tuple(survivors)
        targets = tuple(targets)
        if len(survivors) != self.k:
            raise ValueError(f"need exactly k={self.k} survivors")
        mats = self._decode_bitmats(survivors, targets)
        return self._apply_flat(mats, avail, len(targets))

    def decode_flat_batch(self, avail_list, survivors, targets
                          ) -> list[np.ndarray]:
        """Batched distributed repair: MANY objects' survivor rows
        (same survivor/target pattern — the common case in an OSD-loss
        storm, where every object of a PG misses the same shards) ride
        ONE sharded contraction.  avail_list: [(k, W_i) uint8] in
        `survivors` order; returns the rebuilt targets per object.
        The byte axes concatenate (stripes are independent), so a
        recovery queue of N objects costs one launch instead of N —
        the reference's per-object continue_recovery_op decode loop
        collapsed into a single collective program."""
        if not avail_list:
            return []
        widths = [a.shape[1] for a in avail_list]
        big = np.concatenate(avail_list, axis=1) \
            if len(avail_list) > 1 else avail_list[0]
        survivors = tuple(survivors)
        targets = tuple(targets)
        if len(survivors) != self.k:
            raise ValueError(f"need exactly k={self.k} survivors")
        # flight recorder (ops/profiler.py): one record per batched
        # repair collective, submit/finalize split preserved
        import time as _time
        prof = device_profiler()
        rec = prof.begin("mesh_decode",
                         codec=f"mesh:k{self.k}m{self.m}",
                         runs=len(avail_list), nbytes=int(big.size))
        mats = self._decode_bitmats(survivors, targets)
        handle = self._apply_flat_submit(mats, big, len(targets))
        tgt = "".join(str(t) for t in targets)
        prof.submitted(rec, f"mesh:d{tgt}:w{big.shape[1]}",
                       path="mesh")
        t0 = _time.perf_counter()
        out = self._apply_flat_finalize(handle)
        prof.materialized(rec, _time.perf_counter() - t0)
        res = []
        col = 0
        for w in widths:
            res.append(out[:, col:col + w])
            col += w
        return res

    # -- CLAY repair (docs/REPAIR.md) ---------------------------------------

    def clay_repair_batch(self, plan: "ClayRepairPlan",
                          rows_list) -> list[np.ndarray]:
        """Batched distributed CLAY repair: MANY objects lost the same
        chunk to the same helper set (the storm case), each object's
        stacked helper repair-plane rows (d*P, S_i) riding ONE sharded
        GF contraction — the coupled-layer host plane-solver collapsed
        to the same collective program shape as decode_flat_batch
        (input rows shard over 'shard', byte axes concatenate over
        'data').  The repair matrix's input rows pad with zero rows
        (and zero matrix columns) to divide over the shard axis; zero
        rows XOR-fold to nothing."""
        if not rows_list:
            return []
        j = plan.in_rows
        pad = -j % self.n_shard
        mats = self._clay_plans.get(plan.signature)
        if mats is None:
            coeff = plan.matrix
            if pad:
                coeff = np.concatenate(
                    [coeff, np.zeros((plan.out_rows, pad),
                                     dtype=np.uint8)], axis=1)
            mats = self._column_bitmats(
                coeff, cols_per_shard=(j + pad) // self.n_shard)
            self._clay_plans[plan.signature] = mats
        big, widths = concat_columns(rows_list)
        if pad:
            big = np.concatenate(
                [big, np.zeros((pad, big.shape[1]), dtype=np.uint8)],
                axis=0)
        import time as _time
        prof = device_profiler()
        rec = prof.begin("mesh_clay_repair",
                         codec=f"mesh:k{self.k}m{self.m}",
                         runs=len(rows_list), nbytes=int(big.size))
        handle = self._apply_flat_submit(mats, big, plan.out_rows)
        sig = abs(hash(plan.signature)) & 0xFFFFFF
        prof.submitted(rec, f"mesh:r{sig:x}:w{big.shape[1]}",
                       path="mesh")
        t0 = _time.perf_counter()
        out = self._apply_flat_finalize(handle)
        prof.materialized(rec, _time.perf_counter() - t0)
        return split_columns(out, widths)

    def decode(self, stripes_avail, survivors, targets):
        """(B, k, C) survivor stripes -> (B, len(targets), C)."""
        a = np.ascontiguousarray(stripes_avail, dtype=np.uint8)
        b, k, c = a.shape
        flat = a.transpose(1, 0, 2).reshape(k, b * c)
        out = self.decode_flat(flat, survivors, targets)
        return out.reshape(len(tuple(targets)), b, c).transpose(1, 0, 2)

    # -- oracle -------------------------------------------------------------

    def encode_reference(self, stripes) -> np.ndarray:
        """Single-host oracle for tests."""
        out = []
        coding = self.matrix[self.k:]
        for s in np.asarray(stripes, dtype=np.uint8):
            out.append(gf.gf_matvec(coding, s))
        return np.stack(out)


# ----------------------------------------------------------------------------
# CLAY repair on the device plane (docs/REPAIR.md)
# ----------------------------------------------------------------------------
#
# ec/plugins/ec_clay.py's repair() is GF(2^8)-linear in the helper
# symbols, so the whole coupled-layer contraction — pairwise decouple
# transforms, per-plane parity-check solves in score order, final
# re-coupling — collapses to ONE (sub_chunks x d*P) matrix per
# (lost chunk, helper set), extracted host-side by an identity probe
# (ErasureCodeClay.repair_matrix) and applied here as a batched GF
# matmul: the same bit-sliced contraction the encode/decode paths ride,
# on a single device (apply_device) or sharded over the mesh
# (DistributedStripeCodec.clay_repair_batch).  What used to be a
# per-object, per-plane host crawl during the exact storm CLAY was
# built for becomes a handful of device launches.


class ClayRepairPlan:
    """One (lost, helpers) repair lowering: the GF(2^8) matrix plus its
    lazily-built device bitmatrix.  Shareable across PGs/backends of
    the same geometry (the signature is the coalescing key the launch
    queue batches on)."""

    def __init__(self, matrix: np.ndarray, signature: tuple,
                 lost_chunk: int, helper_ids: tuple[int, ...]):
        self.matrix = np.ascontiguousarray(matrix, dtype=np.uint8)
        self.out_rows, self.in_rows = self.matrix.shape
        self.signature = signature
        self.lost_chunk = lost_chunk
        self.helper_ids = tuple(helper_ids)
        self._bitmat = None

    @classmethod
    def build(cls, plugin, lost_chunk: int,
              helper_ids=None) -> "ClayRepairPlan":
        """Lower one single-failure repair of a sub-chunked plugin
        (ErasureCodeClay.repair_matrix) into a plan."""
        helpers = plugin.repair_helper_order(lost_chunk, helper_ids)
        return cls(plugin.repair_matrix(lost_chunk, helpers),
                   plugin.repair_signature(lost_chunk, helpers),
                   lost_chunk, helpers)

    # -- host oracle ---------------------------------------------------------

    # flight-recorder hint (ops/profiler.py): apply() runs the jitted
    # XLA bitmatmul, so a first-seen width IS a compile
    jit_backed = True

    def apply_host(self, rows: np.ndarray) -> np.ndarray:
        """(in_rows, W) helper rows -> (out_rows, W) rebuilt sub-chunk
        rows via the host GF matvec (the fallback/oracle path)."""
        return gf.gf_matvec(self.matrix, rows)

    # -- single-device path (the launch-queue / smoke configuration) --------

    def apply_device(self, rows: np.ndarray) -> np.ndarray:
        """Same contraction through the jitted XLA bit-sliced matmul
        on the default jax device — the batched path a host without a
        configured mesh serves repair from (one launch for every
        object of a (lost, helpers) group, byte axes concatenated)."""
        if self._bitmat is None:
            self._bitmat = jnp.asarray(
                bitsliced.interleave_bitmatrix(self.matrix),
                dtype=jnp.int8)
        rows = np.ascontiguousarray(rows, dtype=np.uint8)
        return np.asarray(bitsliced.gf_bitmatmul_xla(
            self._bitmat, jnp.asarray(rows), self.out_rows))

    def apply(self, rows: np.ndarray) -> np.ndarray:
        """Device contraction with host fallback (a dead/absent
        accelerator must never fail a repair)."""
        try:
            return self.apply_device(rows)
        except Exception:  # noqa: BLE001 — device unavailable
            return self.apply_host(rows)

    def apply_batch(self, rows_list) -> list[np.ndarray]:
        """Batched single-device apply: objects' byte axes concatenate
        into one launch, results demux per object (the non-mesh analog
        of clay_repair_batch)."""
        if not rows_list:
            return []
        big, widths = concat_columns(rows_list)
        return split_columns(self.apply(big), widths)
