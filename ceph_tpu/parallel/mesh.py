"""Multi-chip sharding of the erasure-code data plane.

Where the reference scales with CRUSH placement over OSD hosts and ships
shard writes over its async messenger (reference src/osd/ECBackend.cc:2074
MOSDECSubOpWrite fan-out), the TPU-native data plane scales over a
`jax.sharding.Mesh` with XLA collectives riding ICI:

  axis 'shard' — tensor-parallel over the k data chunks.  Each device
      holds a slice of the data chunks and the matching columns of the
      generator bit-matrix, computes a *partial* bit-product, and a
      `psum` over 'shard' followed by mod-2 completes the GF(2) sum —
      XOR-reduction expressed as an integer all-reduce, which is exactly
      how a parity fan-in over the messenger becomes a collective.
  axis 'data' — data-parallel over the stripe batch (and the byte axis),
      no communication: stripes are independent, like separate PGs.

This module is deliberately shape-static and jit-clean: one compiled
program per (k, m, batch-geometry), reused across the write pipeline.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ec import gf
from ..ops import bitsliced


def make_mesh(n_shard: int, n_data: int, devices=None) -> Mesh:
    """Build a ('shard', 'data') mesh from the first n_shard*n_data devices."""
    devices = np.asarray(devices if devices is not None else jax.devices())
    need = n_shard * n_data
    if devices.size < need:
        raise ValueError(f"need {need} devices, have {devices.size}")
    return Mesh(devices[:need].reshape(n_shard, n_data), ("shard", "data"))


class DistributedStripeCodec:
    """Sharded batched RS encode/decode over a device mesh.

    The flagship distributed computation: stripes (B, k, C) arrive
    sharded B-over-'data'; data chunks are split k-over-'shard'; parity
    comes back sharded like the batch and replicated over 'shard'.
    """

    def __init__(self, k: int, m: int, mesh: Mesh,
                 technique: str = "cauchy"):
        self.k, self.m, self.mesh = k, m, mesh
        n_shard = mesh.shape["shard"]
        if k % n_shard:
            raise ValueError(f"k={k} not divisible by shard axis {n_shard}")
        self.k_local = k // n_shard
        self.matrix = (gf.cauchy_rs_matrix(k, m) if technique == "cauchy"
                       else gf.vandermonde_rs_matrix(k, m))
        coding = self.matrix[k:]
        # Per-device interleaved bitmatrix: device s gets the columns for
        # its k_local chunks, stacked on a leading 'shard'-sharded axis.
        mats = [bitsliced.interleave_bitmatrix(
                    np.ascontiguousarray(
                        coding[:, s * self.k_local:(s + 1) * self.k_local]))
                for s in range(n_shard)]
        stacked = np.stack(mats).astype(np.int8)   # (n_shard, 8m, 8k_local)
        self.bitmats = jax.device_put(
            stacked, NamedSharding(mesh, P("shard", None, None)))
        self._encode = self._build_encode()

    def _build_encode(self):
        m = self.m
        k_local = self.k_local
        mesh = self.mesh

        def local_encode(bitmat, chunks):
            # bitmat (1, 8m, 8k_local); chunks (k_local, b_local, C)
            kl, b, c = chunks.shape
            flat = chunks.reshape(kl, b * c)
            bits = bitsliced._unpack_bits(flat)          # (8k_local, b*C)
            partial = jax.lax.dot_general(
                bitmat[0], bits,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32,
            )
            total = jax.lax.psum(partial, "shard") & 1   # GF(2) fan-in
            parity = bitsliced._pack_bits(total, m)      # (m, b*C)
            return parity.reshape(m, b, c).transpose(1, 0, 2)

        shard_fn = jax.shard_map(
            local_encode, mesh=mesh,
            in_specs=(P("shard", None, None), P("shard", "data", None)),
            out_specs=P("data", None, None),
        )
        return jax.jit(shard_fn)

    def encode(self, stripes):
        """stripes (B, k, C) uint8 (any sharding) -> parity (B, m, C).

        Input is laid out (k, B, C) internally so the chunk axis shards
        over 'shard'; callers holding already-sharded device arrays skip
        the relayout.
        """
        stripes = jnp.asarray(stripes, dtype=jnp.uint8)
        n_data = self.mesh.shape["data"]
        if stripes.shape[0] % n_data:
            raise ValueError(
                f"stripe batch {stripes.shape[0]} not divisible by 'data' "
                f"mesh axis {n_data}")
        chunks_first = jnp.transpose(stripes, (1, 0, 2))
        chunks_first = jax.device_put(
            chunks_first,
            NamedSharding(self.mesh, P("shard", "data", None)))
        return self._encode(self.bitmats, chunks_first)

    def encode_reference(self, stripes) -> np.ndarray:
        """Single-host oracle for tests."""
        out = []
        coding = self.matrix[self.k:]
        for s in np.asarray(stripes, dtype=np.uint8):
            out.append(gf.gf_matvec(coding, s))
        return np.stack(out)
