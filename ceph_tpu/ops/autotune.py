"""Cached autotuner for the fused parity+crc kernel's operating point.

The fused kernel has four knobs with hardware-dependent optima:

  * `tile` — bytes per grid step (DMA granularity vs VMEM pressure);
  * `wb` — crc sub-block words (the crc matmul's M dimension is
    (k+m) * tile/4/wb, so wb trades MXU row utilization against matrix
    VMEM, and with the in-kernel combine also the accumulator size);
  * `extract` — the crc bit-extraction variant: "planar" (32
    single-bit passes, lowers everywhere), "packed" (4 bits per masked
    pass) or "wide" (mask-free shift-only passes, mod-2 junk
    cancellation) — the non-planar variants use a strided sublane
    slice that only lowers on some Mosaic generations;
  * `combine` — the L combine depth: "xla" streams per-grid-step
    sub-block L-blocks to HBM and log-folds them in XLA (parallel grid
    semantics), "kernel" folds them into a VMEM-resident per-run
    accumulator inside the kernel (sequential grid, no HBM round-trip
    or relayout).  Which wins depends on how the generation prices
    sequential-grid pipelining vs the XLA epilogue.

tools/fused_tile_sweep.py used to sweep tile/wb by hand and the
winners were frozen into bitsliced.FUSED_TILE_HIER / FUSED_WB; this
module replaces the hardcoded constants with a measured, per-device
choice:

  * the sweep runs at plugin init (first fused encode) on accelerator
    backends only — CPU/interpret callers get the static defaults;
  * every candidate is first VALIDATED bit-exactly against the host
    crc32c and parity oracles, so a variant that miscompiles or
    misbehaves on this Mosaic generation is skipped, never shipped;
  * results persist in a JSON cache keyed by (platform, device_kind,
    k, m), so only the first init on a given device pays the sweep;
  * a wall-clock budget (CEPH_TPU_AUTOTUNE_BUDGET_S, default 75 s)
    bounds init latency — candidates are ordered best-guess-first
    (the cached winner of the nearest (platform, device_kind) key
    when this exact (k, m) is cold, then the static default) and the
    sweep keeps the best fully-measured point when time runs out.

Env knobs: CEPH_TPU_AUTOTUNE=0 disables sweeping (cache hits are still
honored); CEPH_TPU_AUTOTUNE_CACHE overrides the cache path.
"""

from __future__ import annotations

import json
import os
import threading
import time
from pathlib import Path

import numpy as np

# candidate space: tiles around the headline kernel's W32_TILE, wb
# spanning crc-matmul M from ~(k+m)*32 to ~(k+m)*256
SWEEP_TILES = (32768, 65536, 131072, 262144)
SWEEP_WBS = (256, 512, 1024)
SWEEP_EXTRACTS = ("planar", "packed", "wide")
SWEEP_COMBINES = ("xla", "kernel")

# measurement input: bytes per shard (multiple of every sweep tile)
MEASURE_BYTES = 1 << 21
MEASURE_ITERS = (5, 15)
ROOFLINE_BPS = 1e12           # same elision gate as bench.py

# the cache's kernel-generation tag: bumped when the kernel family
# changes shape (r2 = the overlapped/accumulator kernel), so winners
# measured under an older kernel never satisfy a lookup — they remain
# visible to the nearest-key SEEDING below, which only affects sweep
# ordering, never skips validation
KERNEL_GEN = "fused_w32r2"

_lock = threading.Lock()


def default_point() -> dict:
    """The static fallback point: the frozen tile/wb with the planar
    extraction and XLA combine — the only variant shipped without a
    per-device validation run (it is the one that lowers everywhere)."""
    from . import bitsliced as bs
    return {"tile": bs.FUSED_TILE_HIER, "wb": bs.FUSED_WB,
            "extract": "planar", "combine": "xla"}


def _cache_path() -> Path:
    env = os.environ.get("CEPH_TPU_AUTOTUNE_CACHE")
    if env:
        return Path(env)
    return Path.home() / ".cache" / "ceph_tpu" / "autotune.json"


def _migrate_v1_entry(ent: dict) -> dict | None:
    """v1 cache rows ({tile, wb, packed}) become v2 rows so they can
    still SEED candidate ordering; their keys carry the old kernel
    generation, so they never satisfy a lookup directly."""
    if "tile" not in ent or "wb" not in ent:
        return None
    return {"tile": ent["tile"], "wb": ent["wb"],
            "extract": "packed" if ent.get("packed") else "planar",
            "combine": "xla", "gbps": ent.get("gbps", 0.0),
            "when": ent.get("when", "")}


def _load_cache() -> dict:
    try:
        data = json.loads(_cache_path().read_text())
    except (OSError, ValueError):
        return {"version": 2, "entries": {}}
    if data.get("version") == 2:
        return data
    if data.get("version") == 1:
        entries = {}
        for key, ent in data.get("entries", {}).items():
            migrated = _migrate_v1_entry(ent)
            if migrated is not None:
                entries[key] = migrated
        return {"version": 2, "entries": entries}
    return {"version": 2, "entries": {}}


def _save_cache(data: dict) -> None:
    """Atomic, best-effort: a read-only home dir must not break init."""
    try:
        path = _cache_path()
        path.parent.mkdir(parents=True, exist_ok=True)
        tmp = path.with_suffix(".tmp")
        tmp.write_text(json.dumps(data, indent=1, sort_keys=True))
        os.replace(tmp, path)
    except OSError:
        pass


def _device_prefix() -> str:
    import jax
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", "?")
    return f"{dev.platform}/{kind}/"


def _device_key(k: int, m: int) -> str:
    import jax
    # the jax/jaxlib version is part of the key: the packed/wide
    # variants' lowering is Mosaic-generation-dependent, so a point
    # validated on one runtime must NOT be trusted (unvalidated) on
    # another — an upgrade simply re-sweeps
    return (f"{_device_prefix()}jax{jax.__version__}"
            f"/{KERNEL_GEN}/k{k}m{m}")


def _nearest_point(cache: dict, k: int, m: int) -> dict | None:
    """Seed for a cold (k, m): the cached winner whose key shares this
    device's (platform, device_kind) prefix — any geometry, jax
    version or kernel generation.  A cold k=4,m=2 plugin init on a
    device that already swept k=8,m=3 starts from that winner's
    neighborhood instead of the static best-guess order, so a
    budget-capped sweep measures the likely-best region first.  Seeds
    only ORDER candidates; every candidate still validates."""
    import jax
    prefix = _device_prefix()
    ver_tag = f"/jax{jax.__version__}/"
    best, best_rank = None, None
    for key, ent in cache.get("entries", {}).items():
        if not key.startswith(prefix):
            continue
        point = {kk: ent.get(kk) for kk in
                 ("tile", "wb", "extract", "combine")}
        if point["tile"] is None or point["wb"] is None:
            continue
        # prefer: same jax version, then same kernel generation, then
        # the fastest measured winner (gbps 0.0 = failure sentinel)
        rank = (ver_tag not in key, f"/{KERNEL_GEN}/" not in key,
                -float(ent.get("gbps") or 0.0))
        if best_rank is None or rank < best_rank:
            best, best_rank = point, rank
    return best


def candidates(k: int, m: int, tiles=None, wbs=None,
               seed: dict | None = None) -> list[dict]:
    """Legal (tile, wb, extract, combine) points, best-guess-first:
    the `seed` point (a cached neighbor's winner) leads when given,
    then the frozen default, then the seed's (tile, wb) neighborhood —
    so a budget-capped sweep still measures a meaningful baseline."""
    r = k + m
    out = []
    for tile in tiles or SWEEP_TILES:
        wt = tile // 4
        for wb in wbs or SWEEP_WBS:
            if wt % wb:
                continue
            s = wt // wb
            if (r * s) % 8:      # lsub/lacc out-block sublane alignment
                continue
            for combine in SWEEP_COMBINES:
                for extract in SWEEP_EXTRACTS:
                    out.append({"tile": tile, "wb": wb,
                                "extract": extract, "combine": combine})
    dflt = default_point()

    def _match(c: dict, p: dict | None) -> bool:
        return p is not None and \
            all(c[kk] == p.get(kk) for kk in c)

    out.sort(key=lambda c: (
        not _match(c, seed),
        not _match(c, dflt),
        seed is None or c["tile"] != seed.get("tile"),
        seed is None or c["wb"] != seed.get("wb"),
        c["tile"] != dflt["tile"], c["wb"] != dflt["wb"],
        c["extract"] != "planar", c["combine"] != "xla"))
    return out


def _validate(mat: np.ndarray, bitmat32, cand: dict,
              interpret: bool = False) -> bool:
    """Bit-exactness gate: one small fused launch (TWO grid steps, so
    the accumulator's cross-step advance fold is exercised) vs the
    host parity and crc32c oracles.  A candidate that fails to
    compile, lower, or match (e.g. the packed/wide extraction's
    strided slice on an older Mosaic, or the accumulator kernel's
    scalar-prefetch grid) is rejected here — never silently shipped.
    `interpret` runs the same check through the Pallas interpreter
    (the CPU tier-1 gate, fused_tile_sweep --validate-only)."""
    import jax.numpy as jnp

    from ..common import crc32c as _crc
    from ..ec import gf
    from . import bitsliced as bs
    from . import crc32c_linear as cl
    m_, k = mat.shape
    tile, wb = cand["tile"], cand["wb"]
    rng = np.random.default_rng(0xC5C)
    chunks = rng.integers(0, 256, (k, 2 * tile), dtype=np.uint8)
    words = jnp.asarray(chunks.view("<u4").view(np.int32))
    cmat_sub = jnp.asarray(cl.crc_tile_matrix_w32(wb))
    try:
        par_w, lbits = bs.gf_encode_with_crc_w32_fold(
            bitmat32, cmat_sub, words, m_, tile=tile, wb=wb,
            interpret=interpret, extract=cand["extract"],
            combine=cand["combine"])
        parity = np.asarray(par_w).view("<u4").view(np.uint8) \
            .reshape(m_, 2 * tile)
        ls = cl.bits_to_u32(np.asarray(lbits))
    except Exception:  # noqa: BLE001 — any lowering/compile failure
        return False
    if not np.array_equal(parity, gf.gf_matvec(mat, chunks)):
        return False
    allsh = np.concatenate([chunks, parity], axis=0)
    return all(
        cl.fold_run_crc(int(ls[s]), 2 * tile, 0xFFFFFFFF)
        == _crc.crc32c(allsh[s].tobytes(), 0xFFFFFFFF)
        for s in range(k + m_))


def _measure(bitmat32, k: int, m: int, cand: dict) -> float:
    """Short chained-fori slope timing (bench.py's anti-elision method,
    scaled down): returns input bytes/sec, 0.0 on a gated sample."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from . import bitsliced as bs
    from . import crc32c_linear as cl
    tile, wb = cand["tile"], cand["wb"]
    rng = np.random.default_rng(0x7E5)
    flat = rng.integers(0, 256, (k, MEASURE_BYTES), dtype=np.uint8)
    x0 = jnp.asarray(flat.view(np.int32))
    cmat_sub = jnp.asarray(cl.crc_tile_matrix_w32(wb))

    def step(x):
        par, lbits = bs.gf_encode_with_crc_w32_fold(
            bitmat32, cmat_sub, x, m, tile=tile, wb=wb,
            extract=cand["extract"], combine=cand["combine"])
        return par ^ jnp.sum(lbits)      # crc feeds the chain: no DCE

    def make(iters):
        @jax.jit
        def f(x):
            def body(i, x):
                return x.at[:m, :].set(x[:m, :] ^ step(x))
            return lax.fori_loop(0, iters, body, x)
        return f

    lo_i, hi_i = MEASURE_ITERS
    f_lo, f_hi = make(lo_i), make(hi_i)
    jax.block_until_ready(f_lo(x0))
    jax.block_until_ready(f_hi(x0))
    best = []
    for rep in range(2):
        v = jax.block_until_ready(x0 ^ (rep + 1))
        t0 = time.perf_counter()
        jax.block_until_ready(f_lo(v))
        lo = time.perf_counter() - t0
        t0 = time.perf_counter()
        jax.block_until_ready(f_hi(v))
        hi = time.perf_counter() - t0
        dt = (hi - lo) / (hi_i - lo_i)
        if dt > 0 and k * MEASURE_BYTES / dt < ROOFLINE_BPS:
            best.append(k * MEASURE_BYTES / dt)
    best.sort()
    return best[len(best) // 2] if best else 0.0


def fused_operating_point(k: int, m: int, mat: np.ndarray | None = None,
                          bitmat32=None, tiles=None, wbs=None,
                          force: bool = False,
                          report: list | None = None,
                          interpret: bool = False) -> dict:
    """The (tile, wb, extract, combine) point the fused encode+crc
    path should run at on THIS device, sweeping and caching on first
    use.

    `mat` (m, k) GF(2^8) generator rows and `bitmat32` (its
    _w32_bitmat device array) enable the sweep; without them (or on
    CPU, or with CEPH_TPU_AUTOTUNE=0) the cached or default point is
    returned as-is.  `report`, when given, collects per-candidate
    (cand, gbps|None) tuples for the sweep CLI; `interpret` runs
    candidate validation through the Pallas interpreter and waives
    the accelerator-backend requirement (tests and the CPU validate
    gate — measurement still runs on whatever backend is live)."""
    import jax
    if jax.default_backend() == "cpu" and not interpret:
        return default_point()
    with _lock:
        key = _device_key(k, m)
        cache = _load_cache()
        hit = cache["entries"].get(key)
        if hit is not None and not force:
            return {kk: hit[kk]
                    for kk in ("tile", "wb", "extract", "combine")}
        if os.environ.get("CEPH_TPU_AUTOTUNE", "1") == "0" or \
                mat is None or bitmat32 is None:
            return default_point()
        budget = float(os.environ.get("CEPH_TPU_AUTOTUNE_BUDGET_S", "75"))
        seed = _nearest_point(cache, k, m)
        t0 = time.perf_counter()
        best, best_rate = None, 0.0
        tried = 0
        for cand in candidates(k, m, tiles, wbs, seed=seed):
            # honor the budget once ANY candidate has been attempted —
            # even if every sample so far was roofline-gated to 0.0 —
            # so a noisy/elision-prone runtime cannot turn plugin init
            # into an unbounded 72-candidate sweep
            if tried and time.perf_counter() - t0 > budget:
                break
            tried += 1
            if not _validate(mat, bitmat32, cand, interpret=interpret):
                if report is not None:
                    report.append((cand, None))
                continue
            try:
                rate = _measure(bitmat32, k, m, cand)
            except Exception:  # noqa: BLE001 — e.g. interpret-mode
                # validation on a CPU backend, where the compiled
                # measurement kernel cannot lower: a candidate that
                # validates but cannot be timed scores 0.0 instead of
                # crashing the sweep out of plugin init
                rate = 0.0
            if report is not None:
                report.append((cand, rate))
            if rate > best_rate:
                best, best_rate = cand, rate
        if best is None:
            # nothing validated/measured: cache the DEFAULT as this
            # device's point so every later init doesn't re-pay the
            # full failed sweep ("only the first init pays" must hold
            # exactly where the sweep is most expensive); gbps 0.0
            # marks it as a failure sentinel, and --force re-sweeps
            best, best_rate = default_point(), 0.0
        cache["entries"][key] = {**best,
                                 "gbps": round(best_rate / 1e9, 3),
                                 "when": time.strftime(
                                     "%Y-%m-%dT%H:%M:%S")}
        _save_cache(cache)
        return best
