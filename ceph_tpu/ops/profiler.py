"""Device-plane flight recorder: launch ledger + compile attribution.

Every layer above the device has attribution — PR 4's OpTracker tells
you which op stalled at which stage, PR 7's percentile pipeline tells
you which stage's tail moved — but the device plane itself has only
counters: a launch happens, bytes move, and when a first-seen jit
bucket pays a multi-second XLA/Mosaic compile the only evidence is
folklore ("compile stalls flap OSDs", PR 8's heartbeat note; "the
64pg frac gate wanders", PR 12/14's retry notes).  This module is the
recorder that turns those into data, the same shape a training or
inference serving stack keeps for its accelerators:

* **Launch ledger** — every device launch (fused encode, plain
  encode, recovery decode, CLAY repair, mesh batch, deep-scrub CRC)
  gets a monotonic launch id and a `LaunchRecord`: kind, codec label,
  jit-bucket key, runs, input bytes, queue wait, submit wall time,
  submit->materialize device time, PG mix, and the trace ids of the
  contributing ops (PR 4 stitching).  Completed records live in a
  bounded ring (`launch profile` asok); `lat_launch_submit` /
  `lat_launch_device` / `lat_launch_queue_wait` histograms share
  DEFAULT_LAT_BUCKETS with the tracing stages, so `dump_latencies`,
  the exporter's percentile gauges and the load harness's per-stage
  blame all pick them up unchanged.

* **Compile attribution** — the first submit of a jit bucket (a
  distinct (kind, path, padded-shape) key — exactly what XLA keys its
  cache on after PR 12's pow2 bucketing) carries the compile.  The
  recorder detects first-seen buckets and times them: submit-side
  wall clock on the first hit vs the bucket's steady-state minimum
  afterwards; the difference is the compile estimate.  The per-host
  compile ledger (`compile ledger` asok) lists every bucket with
  count / first_s / steady_s / compile_s; first hits over
  `stall_s` (conf osd_ec_compile_stall_s) count in the
  `ec_compile_stalls` counter and enter a bounded window of compile
  events that the OSD ships monward for the COMPILE_STORM health
  warning (mon/monitor.py) — the known "compile stall flaps OSDs"
  failure mode made visible instead of folklore.

* **Always on, null when off** — the profiler is enabled by default
  (conf osd_ec_profiler); disabled, `begin()` returns None after one
  attribute check and every other entry point no-ops on a None
  record, so the off path allocates nothing (the NULL_TRACKED rule).
  The on-path cost is one record per LAUNCH (not per op) and is gated
  ≤2% in bench.py --smoke like PR 4's tracking overhead.

`inject_stall_s` (conf osd_ec_inject_compile_stall) is the fault
injection the gates use: a positive value sleeps that long inside the
submit of every FIRST-seen bucket — a real compile stall's exact
shape (it delays only that batch, blocks its finalizers, and trips
the slow-op / tick-lag / COMPILE_STORM detectors honestly).
"""

from __future__ import annotations

import collections
import threading
import time

from .compile_cache import hit_count as _cc_hits
from .compile_cache import status as _cc_status


def _build_prof_perf(name: str = "device_profiler"):
    from ..common.perf_counters import PerfCountersBuilder
    return (PerfCountersBuilder(name)
            .add_u64_counter("ec_launches",
                             "device launches recorded in the ledger")
            .add_u64_counter("ec_launch_runs",
                             "runs carried by recorded launches")
            .add_u64_counter("ec_launch_bytes",
                             "input bytes carried by recorded launches")
            .add_u64_counter("ec_compile_stalls",
                             "first-seen jit buckets whose submit "
                             "exceeded osd_ec_compile_stall_s "
                             "(persistent-cache hits excluded)")
            .add_u64_counter("ec_compile_cache_hits",
                             "first-seen jit buckets served from the "
                             "persistent compile cache at runtime")
            .add_u64_counter("ec_prewarm_compiles",
                             "jit buckets compiled by the boot-time "
                             "prewarm plan (cold cache)")
            .add_u64_counter("ec_prewarm_cache_hits",
                             "prewarm-plan buckets served from the "
                             "persistent compile cache")
            .add_histogram("lat_launch_submit",
                           "launch dispatch wall time (includes the "
                           "compile on a bucket's first hit)")
            .add_histogram("lat_launch_device",
                           "submit -> materialize device time")
            .add_histogram("lat_launch_queue_wait",
                           "host-queue batching wait before launch")
            .add_histogram("lat_prewarm",
                           "per-bucket boot-time prewarm compile wall")
            .create_perf_counters())


class LaunchRecord:
    """One device launch's ledger entry (ring + stitching payload)."""

    __slots__ = ("launch_id", "kind", "codec", "bucket", "path",
                 "runs", "nbytes", "pg_mix", "traces", "queue_wait_s",
                 "submit_s", "device_s", "compiled", "compile_s",
                 "cache_hit", "ts", "_t0", "_cc0")

    def __init__(self, launch_id: int, kind: str, codec: str,
                 runs: int, nbytes: int, pg_mix: int, traces,
                 queue_wait_s: float):
        self.launch_id = launch_id
        self.kind = kind
        self.codec = codec
        self.bucket: str | None = None
        self.path: str | None = None
        self.runs = runs
        self.nbytes = nbytes
        self.pg_mix = pg_mix
        self.traces = tuple(traces)[:8]   # bounded: a 64-op super-
        #                                   batch must not drag 64 ids
        self.queue_wait_s = queue_wait_s
        self.submit_s = 0.0
        self.device_s = 0.0
        self.compiled = False
        self.compile_s = 0.0
        # a FIRST launch of this bucket whose compile was served by
        # the persistent compile cache (or whose bucket was prewarmed
        # at boot): fast by construction, excluded from stall counting
        self.cache_hit = False
        self.ts = time.time()
        self._t0 = time.perf_counter()
        # persistent-cache hit counter at record start: submitted()
        # deltas it to attribute a disk-served compile to THIS launch
        self._cc0 = _cc_hits()

    def to_dict(self) -> dict:
        return {
            "launch_id": self.launch_id,
            "kind": self.kind,
            "codec": self.codec,
            "bucket": self.bucket,
            "path": self.path,
            "runs": self.runs,
            "bytes": self.nbytes,
            "pg_mix": self.pg_mix,
            "traces": list(self.traces),
            "queue_wait_ms": round(self.queue_wait_s * 1e3, 3),
            "submit_ms": round(self.submit_s * 1e3, 3),
            "device_ms": round(self.device_s * 1e3, 3),
            "compiled": self.compiled,
            "compile_s": round(self.compile_s, 4),
            "cache_hit": self.cache_hit,
            "ts": self.ts,
        }


class DeviceProfiler:
    """Per-host (process-wide, like ECLaunchQueue/MeshService) launch
    ledger + compile ledger."""

    _host: "DeviceProfiler | None" = None
    _host_lock = threading.Lock()

    def __init__(self, ring_size: int = 256, stall_s: float = 0.25,
                 storm_window_s: float = 60.0, perf=None,
                 enabled: bool = True):
        self.enabled = enabled
        self.stall_s = float(stall_s)
        self.storm_window_s = float(storm_window_s)
        # fault injection (conf osd_ec_inject_compile_stall): sleep
        # inside the submit of every first-seen bucket — the shape of
        # a real compile stall, for the smoke/health gates
        self.inject_stall_s = 0.0
        self.perf = perf if perf is not None else _build_prof_perf()
        self._lock = threading.Lock()
        self._next_id = 1
        self._ring: collections.deque[LaunchRecord] = \
            collections.deque(maxlen=max(1, int(ring_size)))
        # bucket key -> {count, first_s, steady_min_s, first_ts}
        self._buckets: dict[str, dict] = {}
        # recent first-compile events (ts, bucket, seconds): the
        # COMPILE_STORM window source; bounded — steady state sees no
        # first-compiles, so this never grows past distinct buckets
        self._compile_events: collections.deque = \
            collections.deque(maxlen=512)
        self.launches = 0
        self.launched_runs = 0
        self.launched_bytes = 0
        self.compile_stalls = 0
        # first-seen buckets whose compile came off the persistent
        # compile cache at runtime (the revive-storm success metric)
        self.cache_hits = 0
        # boot-time prewarm tallies (ops/prewarm.py feeds these through
        # note_prewarm; the `prewarm status` asok reads them back)
        self.prewarm_compiles = 0
        self.prewarm_cache_hits = 0
        self.prewarm_s = 0.0
        self.created_at = time.time()

    # -- host singleton ------------------------------------------------------

    @classmethod
    def host_instance(cls) -> "DeviceProfiler":
        with cls._host_lock:
            if cls._host is None:
                cls._host = cls()
            return cls._host

    @classmethod
    def reset_host(cls) -> None:
        """Tests/benches only: drop the singleton (records of the old
        one stay readable through any direct references)."""
        with cls._host_lock:
            cls._host = None

    def set_ring_size(self, n: int) -> None:
        """Resize the completed-launch ring (startup conf
        osd_ec_profiler_ring; existing records carry over, oldest
        dropped)."""
        with self._lock:
            self._ring = collections.deque(self._ring,
                                           maxlen=max(1, int(n)))

    # -- recording -----------------------------------------------------------

    def begin(self, kind: str, codec: str = "", runs: int = 1,
              nbytes: int = 0, pg_mix: int = 1, traces=(),
              queue_wait_s: float = 0.0) -> LaunchRecord | None:
        """Start a launch record (call IMMEDIATELY before the device
        submit — the record's t0 anchors the submit wall clock).
        Returns None when profiling is off: the null fast path is one
        attribute check, no allocation."""
        if not self.enabled:
            return None
        with self._lock:
            lid = self._next_id
            self._next_id += 1
        return LaunchRecord(lid, kind, codec, runs, nbytes, pg_mix,
                            traces, queue_wait_s)

    def submitted(self, rec: LaunchRecord | None, bucket: str,
                  path: str | None = None, jit: bool = True) -> None:
        """The device submit returned: close the submit clock, detect
        a first-seen jit bucket, and feed the compile ledger.  No-op
        on a None record.

        jit=False marks a host-synchronous launch with NO compiled
        program behind it (pure-CPU plugin encode/decode, the np
        containment paths): its submit wall still lands in the
        histograms and the ring, but it must never enter the compile
        ledger — a 100 ms host matmul counted as a "compile" would
        raise false COMPILE_STORMs and grow the bucket table by one
        entry per distinct raw width."""
        if rec is None:
            return
        if not jit:
            rec.submit_s = time.perf_counter() - rec._t0
            rec.bucket = bucket
            rec.path = path
            if self.perf:
                self.perf.hinc("lat_launch_submit", rec.submit_s)
                self.perf.hinc("lat_launch_queue_wait",
                               rec.queue_wait_s)
            return
        with self._lock:
            first = bucket not in self._buckets
        injected = False
        if first and self.inject_stall_s > 0:
            time.sleep(self.inject_stall_s)
            injected = True
        now = time.perf_counter()
        rec.submit_s = now - rec._t0
        rec.bucket = bucket
        rec.path = path
        # persistent compile cache (ops/compile_cache.py): the hit
        # counter advancing during THIS submit means the first-seen
        # compile was served from disk — a fast first launch, never a
        # stall.  Best-effort under concurrency (a racing launch's hit
        # could land in this window), but misattribution only ever
        # downgrades a stall into a hit on a host where the cache IS
        # serving compiles — the semantics the ledger wants.  An ARMED
        # injection overrides the downgrade: the launch really did
        # sleep, and letting a warm disk cache reclassify the simulated
        # stall as a hit silently greens the storm/blame smokes on any
        # host that has ever compiled these buckets before.
        cache_hit = (not injected) and _cc_hits() > rec._cc0
        stalled = False
        hit = False
        with self._lock:
            ent = self._buckets.get(bucket)
            if ent is None:
                self._buckets[bucket] = {
                    "count": 1, "first_s": rec.submit_s,
                    "steady_min_s": None, "first_ts": rec.ts,
                    "cache_hit": cache_hit}
                rec.compiled = True
                # upper-bound estimate until a warm relaunch
                # establishes the bucket's steady state (the ledger
                # dump refines it; the record keeps the first-hit view)
                rec.compile_s = rec.submit_s
                rec.cache_hit = cache_hit
                if cache_hit:
                    # excluded from the stall counter AND the
                    # COMPILE_STORM window: a disk-served compile is
                    # the fix working, not a storm brewing
                    self.cache_hits += 1
                    hit = True
                else:
                    self._compile_events.append(
                        (time.time(), bucket, rec.submit_s))
                    if rec.submit_s >= self.stall_s:
                        self.compile_stalls += 1
                        stalled = True
            else:
                ent["count"] += 1
                if ent.get("prewarmed") and ent["count"] == 1:
                    # first RUNTIME launch of a boot-prewarmed bucket:
                    # the ledger shows it as a cache hit, not a compile
                    rec.cache_hit = True
                sm = ent["steady_min_s"]
                ent["steady_min_s"] = rec.submit_s if sm is None \
                    else min(sm, rec.submit_s)
        if self.perf:
            if stalled:
                self.perf.inc("ec_compile_stalls")
            if hit:
                self.perf.inc("ec_compile_cache_hits")
            self.perf.hinc("lat_launch_submit", rec.submit_s)
            self.perf.hinc("lat_launch_queue_wait", rec.queue_wait_s)

    def materialized(self, rec: LaunchRecord | None,
                     device_s: float) -> None:
        """The launch's results materialized: close the record into
        the ring.  No-op on a None record."""
        if rec is None:
            return
        rec.device_s = device_s
        with self._lock:
            self._ring.append(rec)
            self.launches += 1
            self.launched_runs += rec.runs
            self.launched_bytes += rec.nbytes
        if self.perf:
            self.perf.inc("ec_launches")
            self.perf.inc("ec_launch_runs", rec.runs)
            self.perf.inc("ec_launch_bytes", rec.nbytes)
            self.perf.hinc("lat_launch_device", device_s)

    def note_prewarm(self, bucket: str, warm_s: float,
                     cache_hit: bool) -> None:
        """Record one boot-time prewarm compile (ops/prewarm.py): the
        bucket enters the ledger PRE-SEEDED — the first runtime launch
        of a prewarmed bucket is not first-seen, so it pays no compile,
        trips no stall/injection, and records as a cache hit.  Prewarm
        compiles never enter the COMPILE_STORM window: they happen
        before the daemon reports up, by design."""
        with self._lock:
            if bucket not in self._buckets:
                self._buckets[bucket] = {
                    "count": 0, "first_s": warm_s,
                    "steady_min_s": None, "first_ts": time.time(),
                    "prewarmed": True, "cache_hit": cache_hit}
            if cache_hit:
                self.prewarm_cache_hits += 1
            else:
                self.prewarm_compiles += 1
            self.prewarm_s += warm_s
        if self.perf:
            self.perf.inc("ec_prewarm_cache_hits" if cache_hit
                          else "ec_prewarm_compiles")
            self.perf.hinc("lat_prewarm", warm_s)

    def prewarm_summary(self) -> dict:
        """The prewarm tallies block (`prewarm status` asok /
        compile-ledger provenance)."""
        with self._lock:
            prewarmed = sum(1 for e in self._buckets.values()
                            if e.get("prewarmed"))
            return {
                "compiles": self.prewarm_compiles,
                "cache_hits": self.prewarm_cache_hits,
                "buckets": prewarmed,
                "total_s": round(self.prewarm_s, 3),
            }

    # -- compile ledger ------------------------------------------------------

    def _bucket_rows(self) -> list[dict]:
        with self._lock:
            items = [(b, dict(e)) for b, e in self._buckets.items()]
        rows = []
        for bucket, e in items:
            steady = e["steady_min_s"]
            compile_s = e["first_s"] if steady is None \
                else max(0.0, e["first_s"] - steady)
            rows.append({
                "bucket": bucket,
                "count": e["count"],
                "first_s": round(e["first_s"], 4),
                "steady_s": round(steady, 6)
                if steady is not None else None,
                "compile_s": round(compile_s, 4),
                "first_ts": e["first_ts"],
                "prewarmed": bool(e.get("prewarmed")),
                "cache_hit": bool(e.get("cache_hit")),
            })
        rows.sort(key=lambda r: -r["compile_s"])
        return rows

    def compile_ledger(self) -> dict:
        """The `compile ledger` asok payload: every jit bucket this
        host ever compiled, worst first."""
        rows = self._bucket_rows()
        return {
            "enabled": self.enabled,
            "stall_threshold_s": self.stall_s,
            "buckets": rows,
            "distinct_buckets": len(rows),
            "total_compile_s": round(
                sum(r["compile_s"] for r in rows), 4),
            "max_compile_s": round(
                max((r["compile_s"] for r in rows), default=0.0), 4),
            "compile_stalls": self.compile_stalls,
            "compile_cache_hits": self.cache_hits,
            "prewarm": self.prewarm_summary(),
            "persistent_cache": _cc_status(),
            "window": self.compile_report(),
        }

    def compile_report(self, window_s: float | None = None) -> dict:
        """Windowed compile summary (the OSD ships this monward on
        MPGStats; mon/monitor.py turns budget overruns into the
        COMPILE_STORM health warning)."""
        window_s = self.storm_window_s if window_s is None \
            else float(window_s)
        cutoff = time.time() - window_s
        with self._lock:
            recent = [(b, s) for ts, b, s in self._compile_events
                      if ts >= cutoff]
        total = sum(s for _b, s in recent)
        worst = max(recent, key=lambda e: e[1], default=None)
        return {
            "window_s": window_s,
            "compile_s": round(total, 3),
            "events": len(recent),
            # IN-WINDOW stalls (against the current threshold): a
            # stall from hours ago must not read as current activity
            # nor keep the monward report shipping forever; the
            # lifetime counter stays on ec_compile_stalls / the ledger
            "stalls": sum(1 for _b, s in recent if s >= self.stall_s),
            "stalls_total": self.compile_stalls,
            "worst_bucket": worst[0] if worst else None,
            "worst_s": round(worst[1], 3) if worst else 0.0,
        }

    # -- dumps ---------------------------------------------------------------

    def profile(self, last: int | None = None) -> dict:
        """The `launch profile` asok payload: ledger aggregates +
        the (bounded) ring of recent launches, newest last."""
        with self._lock:
            ring = list(self._ring)
            launches = self.launches
        if last is not None:
            n = max(0, int(last))
            ring = ring[-n:] if n else []
        lat = self.perf.dump_latencies() if self.perf else {}
        return {
            "enabled": self.enabled,
            "launches": launches,
            "runs": self.launched_runs,
            "bytes": self.launched_bytes,
            "runs_per_launch": round(self.launched_runs / launches, 2)
            if launches else 0.0,
            "ring_size": self._ring.maxlen,
            "latencies": lat,
            "recent": [r.to_dict() for r in ring],
            "uptime_s": round(time.time() - self.created_at, 1),
        }

    def bench_summary(self) -> dict:
        """The bench-row provenance block (`launch_ledger` in
        bench.py / cluster_bench rows): enough for a BENCH_r* reader
        to see what the device plane actually did — and on which
        jax/device — without the asok."""
        def q(key, quant):
            est = self.perf.quantile(key, quant) if self.perf else None
            return round(est[0] * 1e3, 3) if est else None
        with self._lock:
            launches = self.launches
        rows = self._bucket_rows()
        out = {
            "launches": launches,
            "runs_per_launch": round(self.launched_runs / launches, 2)
            if launches else 0.0,
            "bytes": self.launched_bytes,
            "compile_buckets": len(rows),
            "compile_s_total": round(
                sum(r["compile_s"] for r in rows), 3),
            "compile_stalls": self.compile_stalls,
            "compile_cache_hits": self.cache_hits,
            "prewarm_compiles": self.prewarm_compiles,
            "prewarm_cache_hits": self.prewarm_cache_hits,
            "device_ms_p50": q("lat_launch_device", 0.5),
            "device_ms_p99": q("lat_launch_device", 0.99),
            "queue_wait_ms_p50": q("lat_launch_queue_wait", 0.5),
            "queue_wait_ms_p99": q("lat_launch_queue_wait", 0.99),
        }
        try:
            import jax
            import jaxlib
            out["jax"] = jax.__version__
            out["jaxlib"] = jaxlib.__version__
            out["device_kind"] = jax.devices()[0].device_kind
            out["backend"] = jax.default_backend()
        except Exception:  # noqa: BLE001 — provenance must not fail a row
            pass
        return out

    def reset(self) -> None:
        """Clear ledger state (benches isolating a phase; the perf
        histograms are monotonic by design and stay)."""
        with self._lock:
            self._ring.clear()
            self._buckets.clear()
            self._compile_events.clear()
            self.launches = 0
            self.launched_runs = 0
            self.launched_bytes = 0
            self.compile_stalls = 0
            self.cache_hits = 0
            self.prewarm_compiles = 0
            self.prewarm_cache_hits = 0
            self.prewarm_s = 0.0


def device_profiler() -> DeviceProfiler:
    """The host's flight recorder (built on first use, enabled)."""
    return DeviceProfiler.host_instance()
