"""crc32c as GF(2) linear algebra — the fused-checksum half of the
north star (BASELINE.json: "crc32c for the same shards is fused into the
stripe kernel so checksum and parity come out of one launch").

Why this works: the crc32c byte-update  crc' = (crc >> 8) ^ T[(crc ^ b)
& 0xff]  is GF(2)-linear in (crc, b).  Hence for an N-byte block B,

    crc(B, seed) = A_N . seed  (+)  L(B)

where A_N is the 32x32 zero-advance matrix (ceph_tpu.common.crc32c
crc32c_zeros computes A_N . s) and the *linear part* L(B) = crc(B, 0) is
a GF(2)-linear map of B's bits: L(B) = C_T @ bits(B) mod 2 for a fixed
(32, 8T) 0/1 matrix per tile size T.  So the same bit-planes the GF(2^8)
encode kernel already holds in VMEM feed a second small matmul that
yields each shard's per-tile L-vector; tiles then fold on the host in
O(ntiles) 32-bit combines:  L(B1||B2) = A_{|B2|} L(B1) + L(B2).

Matches `bufferlist::crc32c` exactly (Castagnoli, caller seed, no final
xor) — verified against ceph_tpu.common.crc32c in tests.

Layout note: the encode kernel's bit rows are bit-major interleaved
(row i*r + s = bit i of shard s), so the tile matrix is exposed as
stacked C_i^T slices, shape (8T, 32), rows [i*T:(i+1)*T] = C_i^T:
L_shard = sum_i bits_i(shard) @ C_i^T.
"""

from __future__ import annotations

import functools

import numpy as np

from ..common import crc32c as _crc
from ..common.util import next_pow2


@functools.lru_cache(maxsize=8)
def crc_tile_matrix(tile: int) -> np.ndarray:
    """(8*tile, 32) int8: row [i*tile + t] = bits of L(block with only
    bit i of byte t set).  Flat 2-D so Pallas/Mosaic never sees a
    rank-3 operand."""
    out = np.zeros((8, tile, 32), dtype=np.int8)
    # contribution of byte v at position t in a T-byte block:
    # A_{T-1-t} . L1(v), with L1(v) = crc of the single byte from state 0
    l1 = np.zeros((8, 32), dtype=np.int8)
    for i in range(8):
        v = _crc.crc32c(bytes([1 << i]), 0)
        l1[i] = [(v >> j) & 1 for j in range(32)]
    # walk positions from the last byte backwards, advancing by one byte
    cur = l1.copy()           # A_0 . L1
    for t in range(tile - 1, -1, -1):
        out[:, t, :] = cur
        if t > 0:
            for i in range(8):
                val = sum(int(cur[i, j]) << j for j in range(32))
                adv = _crc.crc32c_zeros(val, 1)
                cur[i] = [(adv >> j) & 1 for j in range(32)]
    return out.reshape(8 * tile, 32)


@functools.lru_cache(maxsize=8)
def crc_tile_matrix_w32(wt: int) -> np.ndarray:
    """(32*wt, 32) int8 for the word-packed kernel: rows [i*wt + t] =
    L-contribution of word-bit i at word position t.  Word bit i of a
    little-endian i32 word is bit (i%8) of the byte at tile position
    4t + i//8, so this is a re-indexing of crc_tile_matrix(4*wt)."""
    base = crc_tile_matrix(4 * wt).reshape(8, 4 * wt, 32)
    out = np.zeros((32, wt, 32), dtype=np.int8)
    for i in range(32):
        out[i] = base[i % 8, (i // 8)::4, :]
    return out.reshape(32 * wt, 32)


def tile_crc_bits_w32(words, cmat32):
    """words: (r, Wt) i32 packed bytes; cmat32: (32*Wt, 32) from
    crc_tile_matrix_w32 -> (r, 32) int32 0/1 L-bit matrix per shard.
    i32 shifts legalize in Mosaic (i8 shifts don't), so the 32
    bit-plane extractions stay word-wide."""
    import jax
    import jax.numpy as jnp
    r, wt = words.shape
    acc = jnp.zeros((r, 32), dtype=jnp.float32)
    for i in range(32):
        plane = ((words >> i) & 1).astype(jnp.float32)   # (r, Wt)
        acc = acc + jax.lax.dot_general(
            plane, cmat32[i * wt:(i + 1) * wt].astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    return acc.astype(jnp.int32) & 1


@functools.lru_cache(maxsize=16)
def crc_advance_matrix(nbytes: int) -> np.ndarray:
    """(32, 32) int8: row j = bits of A_{nbytes} e_j, so advancing an
    L-vector over `nbytes` zero bytes is `lbits @ this` (mod 2) — the
    per-grid-step fold matrix of the in-kernel L accumulator
    (bitsliced._make_gf_crc_kernel_w32_hier_acc): a (rows, 32) x
    (32, 32) int8 matmul whose sublane layout never changes, so it
    lowers in Mosaic where the pairwise combine's sublane-to-lane
    relayout does not."""
    out = np.zeros((32, 32), dtype=np.int8)
    for j in range(32):
        v = _crc.crc32c_zeros(1 << j, nbytes)
        out[j] = [(v >> b) & 1 for b in range(32)]
    return out


@functools.lru_cache(maxsize=8)
def crc_combine_matrix(s: int, block_bytes: int) -> np.ndarray:
    """(s*32, 32) int8 level-2 matrix: row [si*32 + j] = bits of
    A^{block_bytes*(s-1-si)} e_j, so  L(B_0||...||B_{s-1}) =
    [L(B_0)..L(B_{s-1})] (flattened, 32 bits each) @ this matrix.

    This is the GF(2)-matrix form of the host fold (fold_tile_crcs):
    L(B1||B2) = A_{|B2|} L(B1) ^ L(B2), unrolled over s equal blocks."""
    out = np.zeros((s, 32, 32), dtype=np.int8)
    for si in range(s):
        nzeros = block_bytes * (s - 1 - si)
        for j in range(32):
            v = _crc.crc32c_zeros(1 << j, nzeros)
            out[si, j] = [(v >> b) & 1 for b in range(32)]
    return out.reshape(s * 32, 32)


def combine_crcs_pow2(lbits, block_bytes: int):
    """Log-depth GF(2) combine of per-block L-vectors into one L per
    shard — the device-side replacement for the host fold_tile_crcs
    loop (each launch returns ONE 32-bit L per shard; the host pays a
    single seed-advance per extent).

    lbits: (r, T, 32) int32 0/1, block t of shard r' in time order;
    block_bytes: bytes per block.  Returns (r, 32) int32 0/1 =
    L(B_0||...||B_{T-1}) per shard.

    Each level pairs adjacent equal-size blocks with ONE int8 matmul
    against crc_combine_matrix(2, bytes) — L(B1||B2) = A_{|B2|} L(B1)
    ^ L(B2) — then doubles the block size, so depth is ceil(log2 T)
    and total work is ~2T tiny (., 64)x(64, 32) MACs.  An odd level is
    evened by PREPENDING a virtual zero block: L(0^n) = 0 and
    L(0^n || B) = A_{|B|}·0 ^ L(B) = L(B), so a zero PREFIX never
    changes the combined L (a zero suffix would).  Runs as plain XLA
    (inside the launch's jit, outside the Pallas kernel: the
    (r*T, 32) -> (r, T*32) sublane-to-lane relayouts a log-depth
    combine needs do not lower in Mosaic, and at 32 bits per block the
    extra HBM round-trip is noise)."""
    import jax
    import jax.numpy as jnp
    r, t, _ = lbits.shape
    if t == 0:
        return jnp.zeros((r, 32), dtype=jnp.int32)
    lbits = lbits.astype(jnp.int8)
    bb = block_bytes
    while t > 1:
        if t % 2:
            lbits = jnp.concatenate(
                [jnp.zeros((r, 1, 32), dtype=lbits.dtype), lbits], axis=1)
            t += 1
        pairs = jnp.concatenate(
            [lbits[:, 0::2], lbits[:, 1::2]], axis=2)     # (r, t/2, 64)
        mat = jnp.asarray(crc_combine_matrix(2, bb), dtype=jnp.int8)
        prod = jax.lax.dot_general(
            pairs.reshape(r * (t // 2), 64), mat,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32) & 1
        lbits = prod.reshape(r, t // 2, 32).astype(jnp.int8)
        t //= 2
        bb *= 2
    return lbits[:, 0].astype(jnp.int32)


def fold_run_crc(lbody: int, body_bytes: int, seed: int,
                 tail: bytes = b"") -> int:
    """O(1) host fold of one run: the device-combined body L plus an
    optional sub-block tail, re-seeded.  crc = A_{n}(seed) ^
    (A_{|tail|}(L_body) ^ L(tail)) — one seed-advance per extent,
    replacing the per-tile fold_tile_crcs Python loop."""
    acc = int(lbody) & 0xFFFFFFFF
    n = body_bytes
    if tail:
        acc = _crc.crc32c_zeros(acc, len(tail)) ^ _crc.crc32c(tail, 0)
        n += len(tail)
    return _crc.crc32c_zeros(seed & 0xFFFFFFFF, n) ^ acc


SCRUB_BLOCK = 2048       # bytes per L-block of the scrub rows path
SCRUB_WB = SCRUB_BLOCK // 4


def _rows_l(words, cmat_sub, wb: int):
    """(R, Wt) i32 word rows -> (R, 32) 0/1 L-bits per row: per-sub-
    block L matmuls + the log-depth device combine.  Pure jnp (no
    Pallas), so it runs on CPU XLA too — the deep-scrub verify core."""
    r, wt = words.shape
    s = wt // wb
    lsub = subblock_crc_bits_w32(words, cmat_sub, wb)     # (R*S, 32)
    return combine_crcs_pow2(lsub.reshape(r, s, 32), 4 * wb)


_rows_l_jit = None          # lazily-built jit (jax imported on demand)


def crc32c_rows_device(row_list, seeds,
                       block_bytes: int = SCRUB_BLOCK) -> list[int]:
    """crc32c of many independent byte rows in ONE device launch — the
    deep-scrub verify path (every shard of a scrub chunk hashed by one
    kernel dispatch instead of per-object host crc32c).

    Rows may have different lengths.  Each row splits into body (full
    `block_bytes` blocks) + tail; bodies are FRONT-padded with zeros to
    their power-of-two size bucket (L(0^n || B) = L(B), so prefix
    zeros are free AND the pow2 rounding bounds the jit-cache key
    space), one launch per bucket emits one L per row, and the host
    pays one seed-advance + tail fold per row (fold_run_crc)."""
    import jax
    import jax.numpy as jnp
    global _rows_l_jit
    import functools as _ft
    if _rows_l_jit is None:
        _rows_l_jit = _ft.partial(jax.jit,
                                  static_argnames=("wb",))(_rows_l)
    wb = block_bytes // 4
    rows = [np.ascontiguousarray(r, dtype=np.uint8).ravel()
            for r in row_list]
    bodies = [r.size - r.size % block_bytes for r in rows]
    ls = np.zeros(len(rows), dtype=np.uint64)
    # bucket rows by their pow2-padded width: padding every row to the
    # GLOBAL max would cost rows x max_width memory (one large object
    # in a chunk of small ones multiplies the footprint thousands of
    # times); per-bucket matrices keep the pad overhead < 2x per row
    # while still batching each size class into one launch
    buckets: dict[int, list[int]] = {}
    for i, b in enumerate(bodies):
        if b:
            nb = b // block_bytes
            buckets.setdefault(next_pow2(nb), []).append(i)
    for nb2, idxs in sorted(buckets.items()):
        w = block_bytes * nb2
        mat = np.zeros((len(idxs), w), dtype=np.uint8)
        for j, i in enumerate(idxs):
            mat[j, w - bodies[i]:] = rows[i][:bodies[i]]
        words = mat.view("<u4").view(np.int32)
        cmat_sub = jnp.asarray(crc_tile_matrix_w32(wb))
        lbits = _rows_l_jit(jnp.asarray(words), cmat_sub, wb)
        ls[idxs] = bits_to_u32(np.asarray(lbits))
    return [fold_run_crc(int(ls[i]), bodies[i], int(seeds[i]),
                         rows[i][bodies[i]:].tobytes())
            for i in range(len(rows))]


def subblock_crc_bits_w32(words, cmat_sub, wb: int):
    """Level 1 of the hierarchical tile crc, MXU-friendly.

    words: (r, Wt) i32; cmat_sub: (32*wb, 32) from crc_tile_matrix_w32(wb).
    Returns (r*S, 32) int32 0/1: row r'*S + si = L-bits of shard r''s
    si-th wb-word sub-block.

    Why hierarchical: the flat formulation is a (r, 32*Wt) x (32*Wt, 32)
    matmul — M=r~11, N=32, huge K — a degenerate MXU shape (~2%
    utilization, measured 14-17 GB/s fused vs 159 bare encode), and its
    cmat needs 1 KiB of VMEM per tile byte, capping the fused tile at
    2 KiB.  Splitting the tile into S = Wt/wb sub-blocks makes level 1 a
    (r*S, wb) x (wb, 32) matmul per bit-plane — M grows with the tile —
    and shrinks the matrix VMEM to ~0.5 MiB regardless of tile,
    unlocking the headline kernel's 128 KiB tile.  Operands are int8
    with int32 accumulate (0/1 sums stay tiny), riding the MXU's int
    path like the parity matmul.  The tiny
    level-2 advance-combine (combine_subblock_crcs) runs OUTSIDE the
    kernel: its (r*S, 32) -> (r, S*32) sublane-to-lane reshape does not
    lower in Mosaic, and at 128 B of L-vectors per 128 KiB tile the
    extra HBM round-trip is ~0.1%."""
    import jax
    import jax.numpy as jnp
    r, wt = words.shape
    s = wt // wb
    w2 = words.reshape(r * s, wb)            # row = r'*s + si
    # 4 bit-planes per matmul, concatenated along the contraction axis
    # (cmat_sub is plane-major so the matching rows are contiguous);
    # int8 operands with int32 accumulate ride the MXU's int path like
    # the parity matmul (2x the bf16 rate; 0/1 sums stay tiny)
    acc = jnp.zeros((r * s, 32), dtype=jnp.int32)
    for g in range(8):
        cat = jnp.concatenate(
            [((w2 >> i) & 1).astype(jnp.int8)
             for i in range(4 * g, 4 * g + 4)], axis=1)   # (r*s, 4wb)
        acc = acc + jax.lax.dot_general(
            cat, cmat_sub[4 * g * wb:(4 * g + 4) * wb],
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
    return acc & 1


def subblock_crc_bits_w32_packed(words, cmat_sub, wb: int,
                                 interpret: bool = False):
    """Packed-extraction twin of subblock_crc_bits_w32: same output,
    1/4 the VPU bit-extraction work.

    The planar variant extracts the 32 word-bits one at a time (32
    shift+mask passes over the full (r*S, wb) block).  Here the crc
    reuses the parity path's packed-mask trick: `(w >> i) & 0x01010101`
    pulls bit i of all FOUR bytes per word in one pass, and the free
    Mosaic sublane bitcast exposes them as byte rows — 8 passes total.
    The bitcast row 4q+b holds bit i of byte b of sub-block q, i.e.
    word-bit 8b+i, whose crc contribution at word position t is
    cmat_sub row (8b+i)*wb + t: de-interleaving the byte offset with a
    strided sublane slice and re-stacking the four slices along the
    contraction axis makes the matmul shapes identical to the planar
    variant ((r*S, 4wb) x (4wb, 32) per bit-of-byte i).

    The strided sublane slice is the lowering risk (Mosaic support for
    stride-4 second-minor slices varies by generation), so this
    variant is only selected by the autotuner after a bit-exactness
    check against the host crc on real hardware."""
    import jax
    import jax.numpy as jnp
    from .bitsliced import _words_to_bytes
    r, wt = words.shape
    s = wt // wb
    w2 = words.reshape(r * s, wb)
    mask = jnp.int32(0x01010101)
    acc = jnp.zeros((r * s, 32), dtype=jnp.int32)
    for i in range(8):
        plane = _words_to_bytes((w2 >> i) & mask, interpret)  # (4rS, wb)
        cat = jnp.concatenate(
            [plane[b::4] for b in range(4)], axis=1)          # (rS, 4wb)
        ccat = jnp.concatenate(
            [cmat_sub[(8 * b + i) * wb:(8 * b + i + 1) * wb]
             for b in range(4)], axis=0)                      # (4wb, 32)
        acc = acc + jax.lax.dot_general(
            cat, ccat,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
    return acc & 1


def subblock_crc_bits_w32_wide(words, cmat_sub, wb: int,
                               interpret: bool = False):
    """Widest extraction variant: the mask drops out entirely — 8
    shift-only passes (pass 0 is the raw words), half the packed
    variant's VPU work and a quarter of planar's.

    Why no mask is needed: the matmul already reduces mod 2 (`& 1`
    after int32 accumulation), and every non-LSB bit of an operand
    byte contributes an EVEN multiple (bit p of a byte weighs 2^p in
    the int8 product), so it self-cancels.  Byte b of `(w >> i)` holds
    word-bit 8b+i in its LSB plus junk above it; matching it against
    cmat_sub's rows for bit 8b+i therefore yields exactly that
    bit-plane's contribution mod 2.  Signed int8 wrap (bytes >= 0x80
    read as v-256) is a multiple of 256 — also even — and the int32
    accumulator cannot overflow (|sum| <= 128 * 4wb * 8 passes << 2^31).

    Same matmul shapes and strided sublane slice as the packed
    variant, so it carries the same Mosaic-generation risk and ships
    only through the autotuner's bit-exactness gate."""
    import jax
    import jax.numpy as jnp
    from .bitsliced import _words_to_bytes
    r, wt = words.shape
    s = wt // wb
    w2 = words.reshape(r * s, wb)
    acc = jnp.zeros((r * s, 32), dtype=jnp.int32)
    for i in range(8):
        plane = _words_to_bytes(w2 >> i if i else w2, interpret)  # (4rS, wb)
        cat = jnp.concatenate(
            [plane[b::4] for b in range(4)], axis=1)              # (rS, 4wb)
        ccat = jnp.concatenate(
            [cmat_sub[(8 * b + i) * wb:(8 * b + i + 1) * wb]
             for b in range(4)], axis=0)                          # (4wb, 32)
        acc = acc + jax.lax.dot_general(
            cat, ccat,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)
    return acc & 1


def subblock_crc_bits_w32_extract(words, cmat_sub, wb: int, extract: str,
                                  interpret: bool = False):
    """Single dispatch point for the level-1 crc extraction variants
    (the autotuner's `extract` axis): "planar" (32 single-bit passes,
    lowers everywhere), "packed" (4 bits per masked pass), "wide"
    (mask-free, mod-2 junk cancellation).  Looked up at call time so
    tests can substitute a deliberately-miscompiling variant."""
    if extract == "packed":
        return subblock_crc_bits_w32_packed(words, cmat_sub, wb, interpret)
    if extract == "wide":
        return subblock_crc_bits_w32_wide(words, cmat_sub, wb, interpret)
    if extract != "planar":
        raise ValueError(f"unknown crc extraction variant {extract!r}")
    return subblock_crc_bits_w32(words, cmat_sub, wb)


def combine_subblock_crcs(lsub, combine, r: int, s: int):
    """Level 2: fold per-sub-block L-vectors into per-tile L-vectors.

    lsub: (ntiles*r*s, 32) 0/1 i32 from subblock_crc_bits_w32 (row-major
    [tile, shard, sub-block]); combine: (s*32, 32) from
    crc_combine_matrix(s, sub_block_bytes).  Returns (ntiles, r, 32)
    0/1 i32.  Plain XLA (outside any kernel): a few MFLOP per MiB."""
    import jax
    import jax.numpy as jnp
    ntiles = lsub.shape[0] // (r * s)
    l2 = lsub.reshape(ntiles * r, s * 32).astype(jnp.bfloat16)
    out = jax.lax.dot_general(
        l2, combine.astype(jnp.bfloat16),
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (out.astype(jnp.int32) & 1).reshape(ntiles, r, 32)


def bits_to_u32(bits: np.ndarray) -> np.ndarray:
    """(..., 32) 0/1 -> (...,) uint32, bit j = lsb weight 2^j."""
    weights = (1 << np.arange(32, dtype=np.uint64))
    return (bits.astype(np.uint64) @ weights).astype(np.uint32)


def fold_tile_crcs(tile_ls: np.ndarray, tile: int, seed: int,
                   tail: bytes = b"") -> int:
    """Fold per-tile L-vectors (ntiles, uint32) + optional tail bytes
    into the final crc with `seed`."""
    acc = 0
    for lv in tile_ls:
        acc = _crc.crc32c_zeros(acc, tile) ^ int(lv)
    n_bytes = len(tile_ls) * tile
    if tail:
        acc = _crc.crc32c_zeros(acc, len(tail)) ^ _crc.crc32c(tail, 0)
        n_bytes += len(tail)
    return _crc.crc32c_zeros(seed & 0xFFFFFFFF, n_bytes) ^ acc


# ----------------------------------------------------------------------------
# device-side tile CRC (jnp; callable inside the Pallas kernel too)
# ----------------------------------------------------------------------------

def tile_crc_bits_tiled(bits, cmat, tile: int):
    """Batched tile_crc_bits over EVERY tile of a launch in one rank-3
    dot per bit plane: bits (8r, ntiles*T) -> (ntiles, r, 32).  The
    per-tile Python loop this replaces unrolled O(ntiles) matmuls into
    the traced program, so XLA compile time scaled with the launch
    width — fatal once the per-host launch queue started bucketing
    cross-PG super-batches (one multi-minute compile per bucket);
    here the program size is width-independent."""
    import jax
    import jax.numpy as jnp
    r8, n = bits.shape
    r = r8 // 8
    nt = n // tile
    acc = jnp.zeros((nt, r, 32), dtype=jnp.float32)
    for i in range(8):
        plane = (bits[i * r:(i + 1) * r].astype(jnp.float32)
                 .reshape(r, nt, tile).transpose(1, 0, 2))
        acc = acc + jax.lax.dot_general(
            plane, cmat[i * tile:(i + 1) * tile].astype(jnp.float32),
            dimension_numbers=(((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    return acc.astype(jnp.int32) & 1


def tile_crc_bits(bits, cmat):
    """bits: (8r, T) int8 bit-major rows; cmat: (8T, 32) with rows
    [i*T:(i+1)*T] = C_i^T -> (r, 32) int32 0/1 L-bit matrix for each of
    the r shards of this tile.  Rank-2 only (Mosaic-lowerable)."""
    import jax
    import jax.numpy as jnp
    r8, t = bits.shape
    r = r8 // 8
    # sum_i (r, T) @ (T, 32); f32 keeps 0/1 sums exact up to 2^24
    acc = jnp.zeros((r, 32), dtype=jnp.float32)
    for i in range(8):
        acc = acc + jax.lax.dot_general(
            bits[i * r:(i + 1) * r].astype(jnp.float32),
            cmat[i * t:(i + 1) * t].astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
    return acc.astype(jnp.int32) & 1
