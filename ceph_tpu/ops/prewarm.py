"""Boot-time jit-bucket prewarm: compile before reporting `up`.

The pow2 launch-shape bucketing (ops/bitsliced.py) already collapses
the jit key space to ~log2 shapes per kernel path — small enough to
enumerate and compile at OSD boot, BEFORE the daemon sends MOSDBoot.
With the persistent compile cache (ops/compile_cache.py) a prewarm
pass is compiles on the host's first boot ever and millisecond disk
reads on every boot after, so the runtime write path never sees a
first-seen bucket at all: no compile stalls, no COMPILE_STORM, no
heartbeat flaps on revive.

Exactness guarantee: the plan does NOT predict bucket strings — it
EXECUTES the same plugin entry points the launch queue and the direct
backend paths call (`encode_extents_with_crc_submit`,
`encode_chunks_submit`, `decode_chunks`), with synthetic zero runs of
the planned geometry, and reads the bucket back through the same
`launch_bucket()` the queue uses.  A prewarmed bucket therefore
matches the runtime bucket by construction, not by parallel
arithmetic.  Each executed entry also registers the AOT executable
(plugin `aot_compile_*` hooks -> ops/bitsliced.aot_compile) so the
covered shapes dispatch compiled code with zero trace-time at runtime.

Every warmed bucket is pre-seeded into the flight recorder
(DeviceProfiler.note_prewarm), so the first RUNTIME launch of a
prewarmed bucket is not first-seen: it pays no compile, trips no
stall injection, and records as a cache hit in the launch ledger.

Bounded: `budget_s` (conf osd_ec_prewarm_budget_s) caps the wall the
boot may spend here; a cutoff marks the plan truncated and the daemon
boots with whatever was warmed — prewarm is an optimization, never a
boot dependency.  Entries run cheapest-first so a tight budget still
covers the hottest small-write buckets.
"""

from __future__ import annotations

import threading
import time

import numpy as np

from ..common.util import next_pow2
from . import compile_cache

# one prewarm per process: in-process clusters (tools/vstart.py) boot
# many OSDs into one interpreter, but the jit caches being warmed are
# process-global — the first booting daemon warms for all
_guard_lock = threading.Lock()
_ran = False
_last_status: dict | None = None
# buckets whose XLA program has already been built in THIS process:
# jax's in-memory executable cache emits no persistent-cache hit
# event on reuse, so without this ledger an entry sharing an
# already-built program (same pow2 bucket from another entry, or a
# warm in-process re-run) would count as a compile it never paid
_warmed_buckets: set[str] = set()


class PrewarmPlan:
    """Ordered prewarm entries for one codec.

    widths: total fused-drain byte widths (pow2 multiples of the flat
    fused tile); for each, every pow2 run count r with r <= W/tile is
    an entry (r runs of W/r bytes — the depth-r pipelined write-storm
    shape).  plain_widths: plain (no-crc) encode widths.
    decode_widths x decode_erasures: recovery/reconstruct shapes.
    """

    def __init__(self, plugin, widths=None, run_counts=None,
                 plain_widths=None, decode_widths=None,
                 decode_erasures=None, budget_s: float = 8.0,
                 profiler=None):
        from .bitsliced import FUSED_TILE
        self.plugin = plugin
        self.budget_s = float(budget_s)
        self.profiler = profiler
        tile = FUSED_TILE
        if widths is None:
            widths = [tile << j for j in range(5)]     # 2K..32K
        if run_counts is None:
            run_counts = [1, 2, 4]
        if plain_widths is None:
            plain_widths = [2048 << j for j in range(4)]
        if decode_widths is None:
            # up to osd/ec_backend.ECBackend.DECODE_MAX_LAUNCH_W: the
            # grouped recovery decode caps its concatenated launch
            # width there and the launch queue pow2-pads every decode,
            # so {pow2 <= cap} IS the full runtime decode width set
            # (single chunks wider than the cap excepted)
            decode_widths = [2048 << j for j in range(6)]   # 2K..64K
        if decode_erasures is None:
            # one representative per erasure CARDINALITY, for every
            # cardinality up to m: decode jits on the bitmat shape,
            # which depends only on how many shards are missing —
            # every same-cardinality pattern shares the program, so
            # _buckets_of seeds the whole combination class from one
            # execution.  Multi-loss cardinalities matter even under
            # single-OSD churn (a remapped acting set can leave a read
            # missing two shards at once), and a kill/revive storm's
            # recovery pass can decode with up to m shards missing.
            m = (plugin.get_chunk_count()
                 - plugin.get_data_chunk_count())
            decode_erasures = [tuple(range(c))
                               for c in range(1, max(1, m) + 1)]
        # entries: ("x", run_widths) | ("c", width) | ("d", width, erasures)
        entries: list[tuple] = []
        for w in sorted(set(plain_widths)):
            entries.append(("c", int(w)))
        for w, era in [(w, e) for w in sorted(set(decode_widths))
                       for e in decode_erasures]:
            entries.append(("d", int(w), tuple(era)))
        for w in sorted(set(widths)):
            for r in sorted(set(run_counts)):
                if r >= 1 and w % r == 0 and w // r >= tile:
                    entries.append(("x", (int(w // r),) * int(r)))
                elif r == 1:
                    entries.append(("x", (int(w),)))
        self.entries = entries
        self.status: dict = {
            "planned": len(entries), "done": 0, "skipped": 0,
            "truncated": False, "total_s": 0.0, "budget_s": self.budget_s,
            "compiles": 0, "cache_hits": 0, "buckets": [],
        }

    # -- plan prediction (for tests / status, no execution) -------------

    def planned_buckets(self) -> list[str]:
        """Bucket strings this plan will seed, computed WITHOUT
        compiling: submit-handle geometry is reproduced from the entry
        shapes.  Used by tests to compare against runtime buckets."""
        out = []
        for e in self.entries:
            out.extend(self._buckets_of(e, None))
        return out

    def _buckets_of(self, entry, handle) -> list[str]:
        """Bucket spellings one entry covers.  With a live submit
        handle the fused bucket comes from plugin.launch_bucket (the
        queue's own refinement); without one it is predicted from the
        entry geometry via the same pow2 arithmetic."""
        plugin = self.plugin
        kind = entry[0]
        if kind == "x":
            if handle is not None and hasattr(plugin, "launch_bucket"):
                return [plugin.launch_bucket(handle)]
            from ..parallel.launch_queue import _extents_bucket
            if handle is not None:
                return [_extents_bucket(handle)]
            from .bitsliced import FUSED_TILE
            run_ws = entry[1]
            tile = FUSED_TILE
            nt = next_pow2(sum(-(-w // tile) for w in run_ws))
            base = (f"x:xla:w{nt * tile}"
                    f":r{next_pow2(max(1, len(run_ws)))}")
            point = getattr(plugin, "_fused_point", None)
            if point and getattr(plugin, "_use_w32", False):
                base += (f":t{point.get('tile')}:wb{point.get('wb')}"
                         f":{point.get('extract')}.{point.get('combine')}")
            return [base]
        if kind == "c":
            w = entry[1]
            if hasattr(plugin, "encode_chunks_submit"):
                if handle is not None:
                    sub_kind = handle[0]
                else:
                    sub_kind = "w32" if getattr(plugin, "_use_w32",
                                                False) else "bytes"
                # both spellings: the direct backend path keys on the
                # plugin handle kind, the launch queue on its own
                # ("h", ...) wrapper
                return [f"c:{sub_kind}:w{w}", f"c:h:w{w}"]
            return [f"c:np:w{w}"]
        w, era = entry[1], entry[2]
        # the executed pattern stands in for its whole cardinality
        # class (same bitmat shape -> same jit program): seed every
        # pattern string of that cardinality
        from itertools import combinations
        n = plugin.get_chunk_count()
        return [f"d:e{''.join(str(i) for i in c)}:w{w}"
                for c in combinations(range(n), len(era))]

    # -- execution ------------------------------------------------------

    def _run_entry(self, entry):
        """Execute one entry's real plugin calls (blocking on the
        device result so the compile definitely finished) and return
        the live submit handle (fused) or None."""
        plugin = self.plugin
        k = plugin.get_data_chunk_count()
        kind = entry[0]
        if kind == "x" and hasattr(plugin,
                                   "encode_extents_with_crc_submit"):
            run_ws = entry[1]
            if hasattr(plugin, "aot_compile_fused"):
                plugin.aot_compile_fused(list(run_ws))
            runs = [np.zeros((k, w), dtype=np.uint8) for w in run_ws]
            handle = plugin.encode_extents_with_crc_submit(runs)
            plugin.encode_extents_with_crc_finalize(handle)
            return handle
        if kind == "c":
            w = entry[1]
            if hasattr(plugin, "aot_compile_encode"):
                plugin.aot_compile_encode(w)
            chunks = np.zeros((k, w), dtype=np.uint8)
            if hasattr(plugin, "encode_chunks_submit"):
                h = plugin.encode_chunks_submit(chunks)
                plugin.encode_chunks_finalize(h)
                return h
            plugin.encode_chunks(chunks)
            return None
        if kind == "d":
            w, era = entry[1], entry[2]
            if hasattr(plugin, "aot_compile_decode"):
                plugin.aot_compile_decode(w, len(era))
            n = plugin.get_chunk_count()
            dense = np.zeros((n, w), dtype=np.uint8)
            plugin.decode_chunks(dense, list(era))
        return None

    def run(self) -> dict:
        """Execute the plan within budget; returns (and stores) the
        `prewarm status` dict.  Failures of individual entries are
        counted and skipped — prewarm must never fail a boot."""
        t0 = time.perf_counter()
        st = self.status
        for entry in self.entries:
            spent = time.perf_counter() - t0
            if spent >= self.budget_s:
                st["truncated"] = True
                st["skipped"] = st["planned"] - st["done"]
                break
            hits0 = compile_cache.hit_count()
            te = time.perf_counter()
            try:
                handle = self._run_entry(entry)
            except Exception:  # noqa: BLE001 — warm what we can
                st["skipped"] += 1
                continue
            warm_s = time.perf_counter() - te
            buckets = self._buckets_of(entry, handle)
            # a disk-cache hit event OR every covered bucket already
            # built in-process means no XLA compile happened — the
            # in-memory program reuse path emits no event, so it must
            # be inferred from the warmed-bucket ledger or `compiles`
            # over-reports on warm boots
            cache_hit = compile_cache.hit_count() > hits0 or (
                bool(buckets) and
                all(b in _warmed_buckets for b in buckets))
            for b in buckets:
                if self.profiler is not None:
                    self.profiler.note_prewarm(b, warm_s, cache_hit)
                st["buckets"].append(b)
                _warmed_buckets.add(b)
            st["done"] += 1
            if cache_hit:
                st["cache_hits"] += 1
            else:
                st["compiles"] += 1
        st["total_s"] = round(time.perf_counter() - t0, 3)
        st["persistent_cache"] = compile_cache.status()
        return st


def run_once(plugin, profiler=None, budget_s: float = 8.0,
             **plan_kwargs) -> dict:
    """Process-level prewarm entry (OSD boot): the first caller runs
    the plan, later callers (more in-process daemons) get the stored
    status back — the warmed caches are process-global."""
    global _ran, _last_status
    with _guard_lock:
        if _ran:
            return dict(_last_status or {}, reused=True)
        _ran = True
    plan = PrewarmPlan(plugin, budget_s=budget_s, profiler=profiler,
                       **plan_kwargs)
    status = plan.run()
    with _guard_lock:
        _last_status = status
    return status


def last_status() -> dict | None:
    return _last_status


def reset_for_tests() -> None:
    """Tests only: allow another run_once (paired with
    compile_cache.reset_for_tests + jax.clear_caches when simulating a
    daemon restart)."""
    global _ran, _last_status
    with _guard_lock:
        _ran = False
        _last_status = None
        # a simulated restart clears jax's in-memory executables, so
        # the in-process warmed ledger must reset with it
        _warmed_buckets.clear()
