"""Persistent XLA compile cache: one compile per host LIFETIME.

PR 15's flight recorder made compile stalls visible; this module (with
ops/prewarm.py) removes them.  JAX's persistent compilation cache
serializes every compiled executable to disk keyed by the (HLO,
compile options, backend) fingerprint, so a RESTARTED daemon re-traces
its jit buckets but never re-compiles them — the multi-second XLA/
Mosaic compile that used to flap heartbeats on every revive becomes a
millisecond disk read.  The cache directory sits alongside the
autotune v2 cache under ~/.cache/ceph_tpu/ and is configured through
`osd_ec_compile_cache_dir` (conf, not env-only; the CEPH_TPU_* env
layer of common/options.py reaches it anyway).

Hit/miss attribution rides jax.monitoring: the backend records a
'/jax/compilation_cache/cache_hits' event every time a compile is
served from disk.  This module keeps a process-global hit counter;
the flight recorder (ops/profiler.py) snapshots it around each
first-seen submit, so a persistent-cache hit records as a fast
first-launch with `cache_hit: true` in the launch ledger — NOT as a
compile stall (before this PR the two were indistinguishable).

Everything degrades gracefully: a jax without the persistent cache
knobs, an unwritable directory, or a backend that never emits the
monitoring events leaves the module disabled and every query cheap
(`enabled()` one bool, `hit_count()` one int).
"""

from __future__ import annotations

import os
import threading
from pathlib import Path

_lock = threading.Lock()
_enabled = False
_dir: str | None = None
_error: str | None = None
_listener_ok = False
# process-global persistent-cache hit counter (bumped by the
# jax.monitoring listener; int reads are atomic under the GIL, so the
# profiler's per-launch snapshots never take _lock)
_hits = 0


def default_cache_dir() -> Path:
    """~/.cache/ceph_tpu/xla — beside the autotune v2 cache
    (ops/autotune._cache_path), honoring the same style of env
    override for hermetic CI."""
    env = os.environ.get("CEPH_TPU_COMPILE_CACHE")
    if env:
        return Path(env)
    return Path.home() / ".cache" / "ceph_tpu" / "xla"


def _on_event(event: str, **kw) -> None:
    global _hits
    if "compilation_cache" in event and "hit" in event:
        _hits += 1


def enable(cache_dir: str | os.PathLike | None = None) -> bool:
    """Point jax's persistent compilation cache at `cache_dir`
    (default: default_cache_dir()) and register the hit listener.
    Idempotent per process — the first caller's directory wins (one
    cache per host, like the mesh shape); returns whether the cache is
    live.  Must run before the first jit COMPILE to cover it, but is
    safe (and still effective for later compiles) at any point."""
    global _enabled, _dir, _error, _listener_ok
    with _lock:
        if _enabled:
            return True
        path = Path(cache_dir) if cache_dir else default_cache_dir()
        try:
            path.mkdir(parents=True, exist_ok=True)
        except OSError as e:
            _error = f"mkdir {path}: {e}"
            return False
        try:
            import jax
            jax.config.update("jax_compilation_cache_dir", str(path))
            # jax memoizes "is the cache in use" at the process's FIRST
            # compile (compilation_cache._cache_checked); if anything
            # compiled before enable() — a test, an import-time trace —
            # that latch reads "disabled" forever.  Drop it so the next
            # compile re-evaluates against the directory just set.
            try:
                from jax._src import compilation_cache as _jcc
                _jcc.reset_cache()
            except Exception:  # noqa: BLE001 — private API; best-effort
                pass
            # daemon workloads are many SMALL programs: cache every
            # compile regardless of size or compile time (the defaults
            # skip sub-second compiles — exactly the ones whose sum
            # makes a revive storm)
            jax.config.update(
                "jax_persistent_cache_min_entry_size_bytes", -1)
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
        except Exception as e:  # noqa: BLE001 — old jax / no knob
            _error = f"jax persistent cache unavailable: {e!r}"
            return False
        try:
            from jax._src import monitoring
            monitoring.register_event_listener(_on_event)
            _listener_ok = True
        except Exception:  # noqa: BLE001 — hit attribution degrades,
            _listener_ok = False       # the cache itself still works
        _enabled = True
        _dir = str(path)
        _error = None
        return True


def enabled() -> bool:
    return _enabled


def cache_dir() -> str | None:
    return _dir


def hit_count() -> int:
    """Process-global persistent-cache hits (monotonic; the profiler
    deltas it around each submit for per-launch attribution)."""
    return _hits


def status() -> dict:
    """The `prewarm status` / `compile ledger` asok block."""
    out = {
        "enabled": _enabled,
        "dir": _dir,
        "hits": _hits,
        "hit_listener": _listener_ok,
    }
    if _error:
        out["error"] = _error
    if _enabled and _dir:
        try:
            files = [f for f in Path(_dir).iterdir() if f.is_file()]
            out["entries"] = len(files)
            out["bytes"] = sum(f.stat().st_size for f in files)
        except OSError:
            pass
    return out


def reset_for_tests() -> None:
    """Tests only: forget the enabled state so a test can re-point the
    cache at its own tmpdir.  jax's own config keeps the LAST enabled
    directory until the next enable() — callers pair this with
    jax.clear_caches() when simulating a daemon restart."""
    global _enabled, _dir, _error
    with _lock:
        _enabled = False
        _dir = None
        _error = None
