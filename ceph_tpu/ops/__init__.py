"""TPU kernels: bit-sliced GF(2^8) matmul, crc32c, packing utilities."""
