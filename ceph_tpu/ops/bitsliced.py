"""Bit-sliced GF(2^8) linear algebra on TPU.

The hot loop of the whole framework.  The reference computes erasure-code
parity with per-coefficient Galois region ops (jerasure schedules /
ISA-L `ec_encode_data`, reference src/erasure-code/isa/ErasureCodeIsa.cc:129)
— a CPU-SIMD formulation.  TPU-first, the same math is one matmul:

  * multiply-by-constant in GF(2^8) is GF(2)-linear on the 8 bits, so a
    (r, k) coefficient matrix over GF(2^8) expands to an (8r, 8k) 0/1
    matrix (ceph_tpu/ec/gf.py expand_to_bitmatrix);
  * a chunk of N bytes unpacks to 8 bit-planes; stacking k chunks gives
    a (8k, N) 0/1 operand;
  * parity bits = bitmatrix @ bits mod 2 — an int8 matmul on the MXU
    with int32 accumulation (inner dim 8k <= 256 so sums stay tiny),
    followed by `& 1` and a pack on the VPU.

Layout: *bit-major interleaved*.  Row index bit*n + chunk (not
chunk*8+bit) so the in-kernel unpack `(block >> i) & 1` needs no
transpose: shifting a (k, T) byte tile by i in [0, 8) and stacking gives
exactly rows [i*k + j].  `interleave_bitmatrix` converts the math-layout
matrix from gf.expand_to_bitmatrix into this kernel layout.

Everything here is shape-static and jit-compatible; the Pallas kernel
tiles the byte axis and keeps unpack -> matmul -> pack fused in VMEM so
HBM traffic is just bytes-in + parity-out (the reason this beats an XLA
fallback, which materializes the 8x unpacked bit-planes in HBM).
"""

from __future__ import annotations

import functools
import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu imports fail off-TPU for some symbols; guard for CPU tests
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from ..common.util import next_pow2
from ..ec import gf

LANE = 128           # TPU lane width: byte-axis tiles must be multiples
DEFAULT_TILE = 8192  # bytes of each chunk processed per grid step


def _parallel_grid(n_dims: int, interpret: bool):
    """compiler_params marking every grid axis parallel: byte-axis grid
    steps are independent, and telling Mosaic so lets it double-buffer
    across steps (measured: up to ~1.7x encode on v5e vs the default
    sequential assumption; see BASELINE.md round-3 notes)."""
    if interpret or pltpu is None:
        return {}
    return {"compiler_params": pltpu.CompilerParams(
        dimension_semantics=("parallel",) * n_dims)}


def interleave_bitmatrix(mat: np.ndarray) -> np.ndarray:
    """(r, k) GF(2^8) matrix -> (8r, 8k) 0/1 matrix in bit-major layout.

    out[i*r + ri, j*k + cj] = bit (i, j) of the 8x8 bit-matrix of
    mat[ri, cj]; i.e. rows grouped by output bit, columns by input bit.
    """
    r, k = mat.shape
    math_layout = gf.expand_to_bitmatrix(mat)          # (8r, 8k) chunk-major
    # pure index shuffle, vectorized: the CLAY repair lowering feeds
    # matrices of hundreds of rows/columns through here (81 x 272 at
    # k=8,m=3 vs the (m, k) encode matrices), where the elementwise
    # python loop costs seconds per plan build
    return np.ascontiguousarray(
        math_layout.reshape(r, 8, k, 8)
        .transpose(1, 0, 3, 2).reshape(8 * r, 8 * k))


def _unpack_bits(block: jnp.ndarray) -> jnp.ndarray:
    """(k, T) uint8 -> (8k, T) int8 bit-planes, bit-major rows.

    Strictly rank-2 (concat of shifted tiles): Mosaic on real TPUs
    cannot lower rank-3 reshapes with tiny leading dims.
    """
    # mask+compare stays in i8 end to end (4 bytes/lane-slot on the
    # VPU); i8 vector shifts don't legalize in Mosaic, and an i32
    # upcast would quadruple the elementwise work in the hot unpack
    rows = [(block & jnp.uint8(1 << i)).astype(jnp.bool_).astype(jnp.int8)
            for i in range(8)]
    return jnp.concatenate(rows, axis=0)


def _pack_bits(bits: jnp.ndarray, r: int) -> jnp.ndarray:
    """(8r, T) int32 0/1 bit-major rows -> (r, T) uint8 bytes."""
    out = bits[0:r]
    for i in range(1, 8):
        out = out + (bits[i * r:(i + 1) * r] << i)
    return out.astype(jnp.uint8)


# ----------------------------------------------------------------------------
# XLA (non-Pallas) path: correct everywhere, used on CPU and as the oracle
# ----------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("r",))
def gf_bitmatmul_xla(bitmat: jnp.ndarray, chunks: jnp.ndarray, r: int
                     ) -> jnp.ndarray:
    """Apply an interleaved (8r, 8k) bitmatrix to (k, N) uint8 chunks."""
    bits = _unpack_bits(chunks)
    prod = jax.lax.dot_general(
        bitmat.astype(jnp.int8), bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ) & 1
    return _pack_bits(prod, r)


# ----------------------------------------------------------------------------
# Pallas kernel: fused unpack -> MXU matmul -> mod2 -> pack
# ----------------------------------------------------------------------------

def _gf_kernel(bitmat_ref, in_ref, out_ref):
    r8 = bitmat_ref.shape[0]
    bits = _unpack_bits(in_ref[:])
    prod = jax.lax.dot_general(
        bitmat_ref[:], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ) & 1
    out_ref[:] = _pack_bits(prod, r8 // 8)


@functools.partial(jax.jit, static_argnames=("r", "tile"))
def gf_bitmatmul_pallas(bitmat: jnp.ndarray, chunks: jnp.ndarray, r: int,
                        tile: int = DEFAULT_TILE) -> jnp.ndarray:
    """Pallas path of gf_bitmatmul.  chunks (k, N) with N % tile == 0."""
    k, n = chunks.shape
    assert n % tile == 0, (n, tile)
    grid = (n // tile,)
    return pl.pallas_call(
        _gf_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * r, 8 * k), lambda t: (0, 0)),
            pl.BlockSpec((k, tile), lambda t: (0, t)),
        ],
        out_specs=pl.BlockSpec((r, tile), lambda t: (0, t)),
        out_shape=jax.ShapeDtypeStruct((r, n), jnp.uint8),
        **_parallel_grid(1, False),
    )(bitmat.astype(jnp.int8), chunks)


# ----------------------------------------------------------------------------
# Word-packed Pallas kernel: 4 bytes per VPU op in the unpack/pack
# ----------------------------------------------------------------------------
#
# The plain kernel above is VPU-bound in the bit unpack (8 shift+mask
# passes over every byte).  Packing 4 bytes into an i32 word makes one
# `(w >> i) & 0x01010101` extract bit i of four bytes at once, and
# `pltpu.bitcast` reinterprets the result as byte sublanes for the MXU
# (measured ~3x on v5e).  Sublane layout of the bitcast (probed on
# hardware): i32 (r, W) <-> u8 (4r, W) with u8 row 4r+b = byte b
# (little-endian) of word row r, so the generator matrix is expanded
# block-diagonally over the byte offset b (`_w32_bitmat`).

def _w32_bitmat(mat: np.ndarray) -> np.ndarray:
    """(r, k) GF(2^8) matrix -> (32r, 32k) 0/1 matrix for the w32 kernel.

    out[i*4r + 4ri + b, j*4k + 4cj + b] = bit (i, j) of mat[ri, cj];
    zero for mismatched byte offsets b (bytes never mix positions in a
    linear code over byte streams).
    """
    r, k = mat.shape
    m8 = interleave_bitmatrix(mat)                     # (8r, 8k)
    out = np.zeros((32 * r, 32 * k), dtype=m8.dtype)
    # vectorized block-diagonal expansion (see interleave_bitmatrix on
    # why the elementwise loop can't serve the big repair matrices):
    # view as [i, ri, b_r, j, cj, b_c] and fill the b_r == b_c diagonal
    o6 = out.reshape(8, r, 4, 8, k, 4)
    m4 = m8.reshape(8, r, 8, k)
    for b in range(4):
        o6[:, :, b, :, :, b] = m4
    return out


def _words_to_bytes(x: jnp.ndarray, interpret: bool) -> jnp.ndarray:
    """(r, W) i32 -> (4r, W) i8 with row 4r+b = byte b (little-endian)
    of word row r.  On hardware this is the free Mosaic sublane
    reinterpret (pltpu.bitcast); in interpret mode (CPU tests of the w32
    kernels — the ADVICE round-1 gap) an equivalent lax bitcast +
    transpose reproduces the same layout."""
    if not interpret:
        return pltpu.bitcast(x, jnp.int8)
    r, w = x.shape
    b = jax.lax.bitcast_convert_type(x, jnp.int8)      # (r, W, 4)
    return b.transpose(0, 2, 1).reshape(4 * r, w)


def _bytes_to_words(x: jnp.ndarray, interpret: bool) -> jnp.ndarray:
    """(4r, W) u8 -> (r, W) i32, inverse of _words_to_bytes."""
    if not interpret:
        return pltpu.bitcast(x, jnp.int32)
    r4, w = x.shape
    b = x.reshape(r4 // 4, 4, w).transpose(0, 2, 1)    # (r, W, 4)
    return jax.lax.bitcast_convert_type(b, jnp.int32)


def _w32_parity_words(bitmat, w, interpret: bool) -> jnp.ndarray:
    """Shared core of the w32 kernels: (k, W) i32 words -> (m, W) i32
    parity words via word-unpack, bitplane matmul, shift-accumulate."""
    m = bitmat.shape[0] // 32
    mask = jnp.int32(0x01010101)
    planes = [_words_to_bytes((w >> i) & mask, interpret)
              for i in range(8)]
    bits = jnp.concatenate(planes, axis=0)             # (32k, W) i8
    prod = jax.lax.dot_general(
        bitmat, bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ) & 1                                              # (32m, W)
    acc = prod[0:4 * m]
    for i in range(1, 8):
        acc = acc + (prod[i * 4 * m:(i + 1) * 4 * m] << i)
    return _bytes_to_words(acc.astype(jnp.uint8), interpret)


def _make_gf_kernel_w32(interpret: bool):
    def _gf_kernel_w32(bitmat_ref, in_ref, out_ref):
        out_ref[:] = _w32_parity_words(bitmat_ref[:], in_ref[:], interpret)
    return _gf_kernel_w32


def _stream_group(k: int) -> int:
    """Bit-planes per streaming grid step: as many as fit a 128-lane
    matrix block (the Pallas TPU block divisibility rule AND the MXU's
    native contraction depth).  0 = streaming unsupported for this k
    (non-power-of-two chunk rows; use the all-planes kernel)."""
    if 128 % (4 * k) == 0:
        g = min(8, 128 // (4 * k))
        if 8 % g == 0:
            return g
    return 0


def _make_gf_kernel_w32_stream(interpret: bool, k: int, g: int):
    """Streaming kernel: the bit-plane GROUP index is the INNERMOST
    grid axis, so each grid step extracts g planes (g*4k = 128 rows —
    one MXU-native block), runs one matmul, and XOR-folds the mod-2
    partial into a persistent VMEM scratch accumulator ((a+b)&1 ==
    (a&1)^(b&1) over GF(2), so the accumulator is i8).  Neither the
    full concatenated (32k, W) plane buffer (8x the input tile) nor
    more than one group's matmul product is ever live — the VMEM cut
    the BASELINE.md tile-sweep finding calls for.  (An unrolled
    in-kernel sum chain OOMs VMEM — every partial stays allocated on
    the kernel stack — and lax.dynamic_slice on the matrix doesn't
    lower in Pallas TPU, so the grid axis IS the plane loop.)"""
    ngroups = 8 // g

    def _kern(bitmat_ref, in_ref, out_ref, acc_ref):
        gi = pl.program_id(1)
        m = out_ref.shape[0]
        mask = jnp.int32(0x01010101)

        @pl.when(gi == 0)
        def _init():
            acc_ref[:] = jnp.zeros_like(acc_ref)

        j0 = gi * g
        w = in_ref[:]
        planes = jnp.concatenate(
            [_words_to_bytes((w >> (j0 + jj)) & mask, interpret)
             for jj in range(g)], axis=0)               # (g*4k, W) i8
        part = jax.lax.dot_general(
            bitmat_ref[:], planes,
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32,
        )                                               # (32m, W)
        acc_ref[:] = acc_ref[:] ^ (part & 1).astype(jnp.int8)

        @pl.when(gi == ngroups - 1)
        def _emit():
            prod = acc_ref[:]
            out = prod[0:4 * m].astype(jnp.int32)
            for i in range(1, 8):
                out = out + (prod[i * 4 * m:(i + 1) * 4 * m]
                             .astype(jnp.int32) << i)
            out_ref[:] = _bytes_to_words(out.astype(jnp.uint8),
                                         interpret)
    return _kern


@functools.partial(jax.jit,
                   static_argnames=("r", "tile", "interpret", "stream"))
def gf_bitmatmul_pallas_w32(bitmat32: jnp.ndarray, words: jnp.ndarray,
                            r: int, tile: int = DEFAULT_TILE,
                            interpret: bool = False,
                            stream: bool = False) -> jnp.ndarray:
    """Word-packed path: operates on i32 words end to end so no device
    relayout is ever paid (a host numpy `.view('<u4')` is free; an XLA
    u8<->i32 bitcast on TPU is a physical retiling copy that costs more
    than the whole encode).  words (k, W) int32 = little-endian packed
    chunk bytes, W % tile_words == 0; bitmat32 from _w32_bitmat.
    Returns (r, W) int32 parity words."""
    k, w = words.shape
    wt = tile // 4                                     # lane words per step
    assert w % wt == 0, (w, wt)
    if not stream:
        return pl.pallas_call(
            _make_gf_kernel_w32(interpret),
            grid=(w // wt,),
            in_specs=[
                pl.BlockSpec((32 * r, 32 * k), lambda t: (0, 0)),
                pl.BlockSpec((k, wt), lambda t: (0, t)),
            ],
            out_specs=pl.BlockSpec((r, wt), lambda t: (0, t)),
            out_shape=jax.ShapeDtypeStruct((r, w), jnp.int32),
            interpret=interpret,
            **_parallel_grid(1, interpret),
        )(bitmat32.astype(jnp.int8), words)
    # streaming: plane-group index is the innermost grid axis; group
    # gi's matrix block is bitmat32's contiguous column range for
    # planes [gi*g, (gi+1)*g) — the w32 layout is plane-major, so the
    # BlockSpec index is just (0, gi)
    g = _stream_group(k)
    if g == 0:
        raise ValueError(
            f"streaming w32 kernel needs 128 %% (4k) == 0 (k={k}); "
            "use stream=False")
    if pltpu is None:
        raise ValueError("streaming w32 kernel unavailable: "
                         "pallas tpu module not importable")
    scratch = pltpu.VMEM((32 * r, wt), jnp.int8)
    return pl.pallas_call(
        _make_gf_kernel_w32_stream(interpret, k, g),
        grid=(w // wt, 8 // g),
        in_specs=[
            pl.BlockSpec((32 * r, g * 4 * k), lambda t, gi: (0, gi)),
            pl.BlockSpec((k, wt), lambda t, gi: (0, t)),
        ],
        out_specs=pl.BlockSpec((r, wt), lambda t, gi: (0, t)),
        out_shape=jax.ShapeDtypeStruct((r, w), jnp.int32),
        scratch_shapes=[scratch],
        interpret=interpret,
    )(bitmat32.astype(jnp.int8), words)


W32_TILE = 131072  # bytes per grid step for the w32 kernel (VMEM-bound)


def _pick_wt(w: int) -> int:
    """Lane-words per grid step: divides w, multiple of LANE."""
    assert w % LANE == 0, w  # the max() clamp below relies on it
    wt = min(W32_TILE // 4, w)
    while w % wt:
        wt //= 2
    return max(wt, LANE)


def gf_bitmatmul_w32(bitmat32: jnp.ndarray, words: jnp.ndarray, r: int
                     ) -> jnp.ndarray:
    """Padding wrapper over gf_bitmatmul_pallas_w32: accepts any W,
    pads the word axis to a lane multiple (zero words make zero parity
    in a linear code), strips it after."""
    k, w = words.shape
    wpad = -w % LANE
    if wpad:
        words = jnp.pad(words, ((0, 0), (0, wpad)))
    out = _aot_dispatch("mm_w32", gf_bitmatmul_pallas_w32,
                        (bitmat32, words),
                        {"r": r, "tile": 4 * _pick_wt(w + wpad)})
    return out[:, :w] if wpad else out


FUSED_TILE = 2048  # fused parity+crc kernel tile (cmat VMEM footprint)


def _crc_rows(n_shards: int) -> int:
    """Per-tile rows of the fused kernel's flat crc output: n_shards
    sublane-padded to a multiple of 8.  Single source of truth for the
    producer (out_spec/padding in the kernel) and the consumer (the
    de-interleaving reshape in gf_encode_with_crc)."""
    return -(-n_shards // 8) * 8


def _gf_crc_kernel(bitmat_ref, cmat_ref, in_ref, par_ref, crc_ref):
    """Fused: parity tile + per-tile crc32c L-bits for every shard, one
    launch (the north-star fusion: checksum and parity from the same
    VMEM-resident bit-planes)."""
    from . import crc32c_linear as cl
    r8 = bitmat_ref.shape[0]
    m = r8 // 8
    bits = _unpack_bits(in_ref[:])                    # (8k, T)
    prod = jax.lax.dot_general(
        bitmat_ref[:], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ) & 1
    par_ref[:] = _pack_bits(prod, m)
    data_crc = cl.tile_crc_bits(bits, cmat_ref[:])            # (k, 32)
    par_crc = cl.tile_crc_bits(prod.astype(jnp.int8),
                               cmat_ref[:])                   # (m, 32)
    crc = jnp.concatenate([data_crc, par_crc], axis=0)
    pad = crc_ref.shape[0] - crc.shape[0]   # sublane-align to 8 rows
    if pad:
        crc = jnp.concatenate(
            [crc, jnp.zeros((pad, 32), dtype=crc.dtype)], axis=0)
    crc_ref[:] = crc


@functools.partial(jax.jit, static_argnames=("m", "tile"))
def gf_encode_with_crc_pallas(bitmat, cmat, chunks, m: int,
                              tile: int = FUSED_TILE):
    k, n = chunks.shape
    assert n % tile == 0, (n, tile)
    grid = (n // tile,)
    rows = _crc_rows(k + m)
    return pl.pallas_call(
        _gf_crc_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * m, 8 * k), lambda t: (0, 0)),
            pl.BlockSpec((8 * tile, 32), lambda t: (0, 0)),
            pl.BlockSpec((k, tile), lambda t: (0, t)),
        ],
        out_specs=[
            pl.BlockSpec((m, tile), lambda t: (0, t)),
            pl.BlockSpec((rows, 32), lambda t: (t, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), jnp.uint8),
            jax.ShapeDtypeStruct(((n // tile) * rows, 32), jnp.int32),
        ],
        **_parallel_grid(1, False),
    )(bitmat.astype(jnp.int8), cmat, chunks)


def _make_gf_crc_kernel_w32(interpret: bool):
    def _gf_crc_kernel_w32(bitmat_ref, cmat_ref, in_ref, par_ref, crc_ref):
        """w32 twin of _gf_crc_kernel: word-packed unpack feeds the MXU
        parity matmul AND the crc32c L-vector matmul from the same VMEM
        residency — the north-star fusion at the headline kernel's
        speed (the byte-path fused kernel runs ~4x slower, VERDICT
        round-1 Weak #1)."""
        from . import crc32c_linear as cl
        w = in_ref[:]                                  # (k, Wt) i32
        par_words = _w32_parity_words(bitmat_ref[:], w, interpret)
        par_ref[:] = par_words
        allw = jnp.concatenate([w, par_words], axis=0)  # (k+m, Wt)
        crc = cl.tile_crc_bits_w32(allw, cmat_ref[:])   # (k+m, 32)
        pad = crc_ref.shape[0] - crc.shape[0]   # sublane-align to 8 rows
        if pad:
            crc = jnp.concatenate(
                [crc, jnp.zeros((pad, 32), dtype=crc.dtype)], axis=0)
        crc_ref[:] = crc
    return _gf_crc_kernel_w32


@functools.partial(jax.jit, static_argnames=("m", "tile", "interpret"))
def gf_encode_with_crc_pallas_w32(bitmat32, cmat32, words, m: int,
                                  tile: int = FUSED_TILE,
                                  interpret: bool = False):
    """Fused parity+crc over word-packed input.  words (k, W) i32,
    tile in BYTES (W words per grid step = tile/4); cmat32 from
    crc32c_linear.crc_tile_matrix_w32(tile//4).  Returns
    (parity (m, W) i32 words, crc L-bits (ntiles*rows, 32) i32)."""
    k, wtot = words.shape
    wt = tile // 4
    assert wtot % wt == 0, (wtot, wt)
    grid = (wtot // wt,)
    rows = _crc_rows(k + m)
    return pl.pallas_call(
        _make_gf_crc_kernel_w32(interpret),
        grid=grid,
        in_specs=[
            pl.BlockSpec((32 * m, 32 * k), lambda t: (0, 0)),
            pl.BlockSpec((32 * wt, 32), lambda t: (0, 0)),
            pl.BlockSpec((k, wt), lambda t: (0, t)),
        ],
        out_specs=[
            pl.BlockSpec((m, wt), lambda t: (0, t)),
            pl.BlockSpec((rows, 32), lambda t: (t, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, wtot), jnp.int32),
            jax.ShapeDtypeStruct(((wtot // wt) * rows, 32), jnp.int32),
        ],
        interpret=interpret,
        **_parallel_grid(1, interpret),
    )(bitmat32.astype(jnp.int8), cmat32, words)


FUSED_WB = 512       # hier-crc sub-block, words (2 KiB); lane multiple
FUSED_TILE_HIER = W32_TILE   # hier matrices are tile-size-independent


def _hier_crc_step(bitmat_ref, cmat_sub_ref, in_ref, par_ref, wb: int,
                   extract: str, interpret: bool):
    """Shared per-grid-step body of the hier fused kernels: parity +
    per-sub-block L-bits, with the crc extraction OVERLAPPED against
    the parity MXU work instead of run as a tail.

    The old kernel concatenated data and parity words before the crc
    extraction, which made even the data shards' VPU shift/mask passes
    data-dependent on the parity matmul — the whole crc half
    serialized behind the MXU.  Split per shard class, the data-shard
    extraction+matmuls depend only on the input block, so Mosaic is
    free to interleave them with the parity matmul (VPU and MXU run
    concurrently) and with the next block's HBM->VMEM DMA; only the
    parity-shard crc (m of k+m rows, ~27% of the crc work at k=8,m=3)
    still waits on the parity output.  Row order of the concatenated
    result is unchanged (shard*S + si, data shards first)."""
    from . import crc32c_linear as cl
    w = in_ref[:]                                      # (k, Wt) i32
    lsub_data = cl.subblock_crc_bits_w32_extract(
        w, cmat_sub_ref[:], wb, extract, interpret)    # (k*S, 32)
    par_words = _w32_parity_words(bitmat_ref[:], w, interpret)
    par_ref[:] = par_words
    lsub_par = cl.subblock_crc_bits_w32_extract(
        par_words, cmat_sub_ref[:], wb, extract, interpret)  # (m*S, 32)
    return jnp.concatenate([lsub_data, lsub_par], axis=0)


def _make_gf_crc_kernel_w32_hier(interpret: bool, wb: int,
                                 extract: str = "planar"):
    def _kern(bitmat_ref, cmat_sub_ref, in_ref, par_ref, lsub_ref):
        """Fused parity + level-1 hierarchical crc at the headline
        kernel's tile: the same VMEM-resident words feed the MXU parity
        matmul and the sub-block crc matmuls (see
        crc32c_linear.subblock_crc_bits_w32 for why the flat crc matmul
        capped the fused tile at 2 KiB).  `extract` selects the crc
        bit-extraction variant (planar / packed / wide) — non-planar
        variants are autotune-gated, as their strided sublane slice is
        generation-dependent in Mosaic."""
        lsub_ref[:] = _hier_crc_step(bitmat_ref, cmat_sub_ref, in_ref,
                                     par_ref, wb, extract, interpret)
    return _kern


def _make_gf_crc_kernel_w32_hier_acc(interpret: bool, wb: int,
                                     extract: str):
    """The VMEM-resident L accumulator kernel (the tentpole of the
    overlapped fused path): instead of writing every grid step's
    (r*S, 32) sub-block L-block to HBM and re-laying it out in XLA
    (combine_crcs_pow2's transpose + log-depth folds), the kernel
    folds each step's L-bits into a REVISITED output block that Mosaic
    keeps resident in VMEM for the whole run:

        acc[shard, si] <- A_tile . acc[shard, si]  ^  L(B_{t,si})

    — one (r*S, 32) x (32, 32) int8 matmul per step against the
    constant `tile`-byte advance matrix (crc_advance_matrix; advance
    powers commute, so per-si streams fold independently and the
    si-position advance is applied ONCE per run by the tiny XLA
    combine_subblock_crcs epilogue).  Each launch therefore writes one
    (r*S, 32) block per RUN, not per grid step, and the epilogue's
    input no longer scales with extent length.

    Run boundaries ride scalar prefetch: `run_map[t]` indexes the
    output block (monotonic, so Mosaic flushes an accumulator block
    exactly when its run's last step retires) and `first_map[t]` marks
    each run's first step (accumulator init).  The grid is sequential
    (no `parallel` dimension semantics — cross-step accumulation
    orders the steps), which trades the reorder freedom for the HBM
    round-trip; the autotuner's `combine` axis decides per device
    whether that trade wins."""
    def _kern(run_ref, first_ref, bitmat_ref, cmat_sub_ref, adv_ref,
              in_ref, par_ref, lacc_ref):
        t = pl.program_id(0)
        lsub = _hier_crc_step(bitmat_ref, cmat_sub_ref, in_ref,
                              par_ref, wb, extract, interpret)

        @pl.when(first_ref[t] == 1)
        def _init():
            lacc_ref[:] = lsub

        @pl.when(first_ref[t] == 0)
        def _fold():
            adv = jax.lax.dot_general(
                lacc_ref[:].astype(jnp.int8), adv_ref[:],
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=jnp.int32) & 1
            lacc_ref[:] = adv ^ lsub
    return _kern


def _fused_hier_call(bitmat32, cmat_sub, words, m: int, tile: int,
                     wb: int, interpret: bool, extract: str = "planar"):
    """Raw pallas_call of the hier fused kernel over a byte-axis grid
    with double-buffered input blocks (the `parallel` dimension
    semantics let Mosaic overlap each block's HBM->VMEM DMA with the
    previous block's MXU work — the launch is a pipeline, not one
    VMEM-resident tile).  Returns (parity (m, W) i32, lsub
    ((W*4//tile) * (k+m) * S, 32) i32 per-SUB-BLOCK L-bits, row-major
    [tile, shard, sub]) — callers choose the combine (per-tile level-2
    for the legacy contract, whole-extent log-fold for the device-side
    combine path)."""
    k, wtot = words.shape
    wt = tile // 4
    assert wtot % wt == 0, (wtot, wt)
    assert wt % wb == 0, (wt, wb)
    s = wt // wb
    r = k + m
    assert (r * s) % 8 == 0, (r, s)     # lsub out-block sublane align
    grid = (wtot // wt,)
    return pl.pallas_call(
        _make_gf_crc_kernel_w32_hier(interpret, wb, extract),
        grid=grid,
        in_specs=[
            pl.BlockSpec((32 * m, 32 * k), lambda t: (0, 0)),
            pl.BlockSpec((32 * wb, 32), lambda t: (0, 0)),
            pl.BlockSpec((k, wt), lambda t: (0, t)),
        ],
        out_specs=[
            pl.BlockSpec((m, wt), lambda t: (0, t)),
            pl.BlockSpec((r * s, 32), lambda t: (t, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, wtot), jnp.int32),
            jax.ShapeDtypeStruct(((wtot // wt) * r * s, 32), jnp.int32),
        ],
        interpret=interpret,
        **_parallel_grid(1, interpret),
    )(bitmat32.astype(jnp.int8), cmat_sub, words)


def _fused_hier_acc_call(bitmat32, cmat_sub, adv, run_map, first_map,
                         words, m: int, tile: int, wb: int, nruns: int,
                         interpret: bool, extract: str):
    """Raw pallas_call of the accumulator hier kernel: sequential
    byte-axis grid, per-run VMEM-resident L accumulation (see
    _make_gf_crc_kernel_w32_hier_acc).  run_map/first_map are (ntiles,)
    i32 scalar-prefetch arrays (run index per grid step, monotonic;
    1 at each run's first step).  Returns (parity (m, W) i32, lacc
    (nruns * (k+m) * S, 32) i32 — ONE accumulator block per run,
    row-major [run, shard, sub])."""
    k, wtot = words.shape
    wt = tile // 4
    assert wtot % wt == 0, (wtot, wt)
    assert wt % wb == 0, (wt, wb)
    s = wt // wb
    r = k + m
    assert (r * s) % 8 == 0, (r, s)     # lacc out-block sublane align
    if pltpu is None:
        raise ValueError("accumulator hier kernel unavailable: "
                         "pallas tpu module not importable")
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(wtot // wt,),
        in_specs=[
            pl.BlockSpec((32 * m, 32 * k), lambda t, rm, fm: (0, 0)),
            pl.BlockSpec((32 * wb, 32), lambda t, rm, fm: (0, 0)),
            pl.BlockSpec((32, 32), lambda t, rm, fm: (0, 0)),
            pl.BlockSpec((k, wt), lambda t, rm, fm: (0, t)),
        ],
        out_specs=[
            pl.BlockSpec((m, wt), lambda t, rm, fm: (0, t)),
            pl.BlockSpec((r * s, 32), lambda t, rm, fm: (rm[t], 0)),
        ],
    )
    return pl.pallas_call(
        _make_gf_crc_kernel_w32_hier_acc(interpret, wb, extract),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((m, wtot), jnp.int32),
            jax.ShapeDtypeStruct((nruns * r * s, 32), jnp.int32),
        ],
        interpret=interpret,
    )(run_map, first_map, bitmat32.astype(jnp.int8), cmat_sub, adv,
      words)


def _acc_launch_args(ntiles_run, tile: int, wb: int):
    """Scalar-prefetch maps + fold matrices for one accumulator
    launch: run_map (run index per grid step, monotonic), first_map
    (1 at each run's first step), the per-step tile advance matrix and
    the per-run si-position combine matrix.  Single source of truth
    for the single-extent fold entry and the extents path — the two
    must never diverge on the accumulator contract."""
    from . import crc32c_linear as cl
    counts = np.asarray(list(ntiles_run), dtype=np.int64)
    run_map = np.repeat(np.arange(len(counts), dtype=np.int32), counts)
    first_map = np.zeros(len(run_map), dtype=np.int32)
    starts = np.concatenate(([0], np.cumsum(counts)[:-1]))
    # zero-tile filler runs (launch-shape bucketing) have no first
    # step; their start index aliases the next run's (or falls off the
    # end) and must not set a flag
    first_map[starts[counts > 0]] = 1
    adv = jnp.asarray(cl.crc_advance_matrix(tile), dtype=jnp.int8)
    comb = jnp.asarray(
        cl.crc_combine_matrix((tile // 4) // wb, 4 * wb),
        dtype=jnp.int8)
    return jnp.asarray(run_map), jnp.asarray(first_map), adv, comb


def _hier_acc_core(bitmat32, cmat_sub, adv, combine, run_map, first_map,
                   words, m: int, tile: int, wb: int, nruns: int,
                   interpret: bool, extract: str):
    """Accumulator launch + the per-run si-position fold: returns
    (parity (m, W) i32, L-bits (nruns, k+m, 32) i32 — one combined L
    per shard per run, covering every byte of the run including the
    sub-block tail).  The epilogue is ONE tiny combine_subblock_crcs
    matmul over (nruns * (k+m) * S, 32) — independent of extent
    length, vs the old per-step lsub round-trip + log-depth
    combine_crcs_pow2 chain."""
    from . import crc32c_linear as cl
    k = words.shape[0]
    s = (tile // 4) // wb
    parity, lacc = _fused_hier_acc_call(
        bitmat32, cmat_sub, adv, run_map, first_map, words, m, tile,
        wb, nruns, interpret, extract)
    return parity, cl.combine_subblock_crcs(lacc, combine, k + m, s)


_hier_acc = functools.partial(jax.jit, static_argnames=(
    "m", "tile", "wb", "nruns", "interpret", "extract"))(_hier_acc_core)

# donated twin (see _fused_hier_lsub_donate): the staged drain words
# are single-use, so real accelerators may reuse their HBM for parity
_hier_acc_donate = functools.partial(jax.jit, static_argnames=(
    "m", "tile", "wb", "nruns", "interpret", "extract"),
    donate_argnums=(6,))(_hier_acc_core)


@functools.partial(jax.jit, static_argnames=("m", "tile", "wb",
                                             "interpret"))
def gf_encode_with_crc_pallas_w32_hier(bitmat32, cmat_sub, combine,
                                       words, m: int,
                                       tile: int = FUSED_TILE_HIER,
                                       wb: int = FUSED_WB,
                                       interpret: bool = False):
    """Hier-crc twin of gf_encode_with_crc_pallas_w32.  words (k, W)
    i32, tile in BYTES; cmat_sub from crc_tile_matrix_w32(wb), combine
    from crc_combine_matrix(tile//(4*wb), 4*wb).  Returns (parity (m, W)
    i32, crc L-bits (ntiles*rows, 32) i32) — same contract as the flat
    kernel, one L-row block per tile.  The kernel emits per-sub-block
    L-vectors (~0.1% of input bytes); the level-2 advance-combine runs
    as plain XLA here, inside the same jit."""
    from . import crc32c_linear as cl
    k, wtot = words.shape
    wt = tile // 4
    s = wt // wb
    r = k + m
    rows = _crc_rows(r)
    parity, lsub = _fused_hier_call(bitmat32, cmat_sub, words, m,
                                    tile, wb, interpret)
    crc = cl.combine_subblock_crcs(lsub, combine, r, s)  # (nt, r, 32)
    pad = rows - r
    if pad:
        crc = jnp.pad(crc, ((0, 0), (0, pad), (0, 0)))
    return parity, crc.reshape(-1, 32)


@functools.partial(jax.jit, static_argnames=("m", "tile", "wb",
                                             "interpret", "extract",
                                             "combine"))
def gf_encode_with_crc_w32_fold(bitmat32, cmat_sub, words, m: int,
                                tile: int = FUSED_TILE_HIER,
                                wb: int = FUSED_WB,
                                interpret: bool = False,
                                extract: str = "planar",
                                combine: str = "xla"):
    """The device-side-combine fused launch: parity AND one 32-bit
    crc32c L-vector per shard from a single dispatch.

    words (k, W) i32, W bytes a `tile` multiple; cmat_sub from
    crc_tile_matrix_w32(wb).  Returns (parity (m, W) i32, L-bits
    (k+m, 32) i32).  `extract` picks the crc bit-extraction variant
    (planar/packed/wide) and `combine` the combine depth — both
    autotuner axes:

      * combine="kernel": the accumulator kernel folds per-tile Ls in
        VMEM across grid steps (A_tile advance matmul per step, see
        _make_gf_crc_kernel_w32_hier_acc); the only epilogue is the
        tiny si-position fold.
      * combine="xla": the legacy shape — the kernel streams per-step
        (r*S, 32) L-blocks to HBM (parallel grid semantics) and the
        log-depth combine_crcs_pow2 runs as XLA inside this jit.

    Either way the host sees ONE L per shard and pays a single
    seed-advance per extent (fold_run_crc), never a per-tile loop."""
    from . import crc32c_linear as cl
    if combine == "kernel":
        wtot = words.shape[1]
        run_map, first_map, adv, comb = _acc_launch_args(
            [wtot // (tile // 4)], tile, wb)
        parity, lb = _hier_acc_core(
            bitmat32, cmat_sub, adv, comb, run_map, first_map, words,
            m, tile, wb, 1, interpret, extract)
        return parity, lb[0]
    if combine != "xla":
        raise ValueError(f"unknown combine depth {combine!r}")
    parity, lb = _hier_lsub_core(bitmat32, cmat_sub, words, m,
                                 tile, wb, interpret, extract)
    # fold the whole extent's sub-block Ls in log2(nsub) matmuls
    return parity, cl.combine_crcs_pow2(lb, 4 * wb)


@functools.partial(jax.jit, static_argnames=("block_bytes",))
def _combine_run(lbits, block_bytes: int):
    """jit shell over combine_crcs_pow2 for the per-run folds of the
    extents path (cached per (shape, block_bytes))."""
    from . import crc32c_linear as cl
    return cl.combine_crcs_pow2(lbits, block_bytes)


@functools.partial(jax.jit, static_argnames=("m", "tile"))
def gf_encode_with_crc_xla(bitmat, cmat, chunks, m: int,
                           tile: int = FUSED_TILE):
    """XLA twin of the fused kernel (CPU tests / fallback)."""
    from . import crc32c_linear as cl
    k, n = chunks.shape
    ntiles = n // tile
    bits = _unpack_bits(chunks)                       # (8k, N)
    prod = jax.lax.dot_general(
        bitmat.astype(jnp.int8), bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ) & 1
    parity = _pack_bits(prod, m)
    # one batched crc contraction over every tile (program size
    # independent of ntiles — the old per-tile loop unrolled into the
    # program and made compile time scale with launch width)
    d = cl.tile_crc_bits_tiled(bits, cmat, tile)             # (nt,k,32)
    p = cl.tile_crc_bits_tiled(prod.astype(jnp.int8), cmat,
                               tile)                         # (nt,m,32)
    return parity, jnp.concatenate([d, p], axis=1)


def _hier_lsub_core(bitmat32, cmat_sub, words, m: int, tile: int,
                    wb: int, interpret: bool, extract: str):
    """Hier launch + re-layout: (parity, per-sub-block L-bits reordered
    [tile, shard, sub] -> (k+m, total_sub_blocks, 32) stream order).
    Shared by the single-extent fold entry and the extents path."""
    k, wtot = words.shape
    wt = tile // 4
    s = wt // wb
    r = k + m
    nt = wtot // wt
    parity, lsub = _fused_hier_call(bitmat32, cmat_sub, words, m,
                                    tile, wb, interpret, extract)
    lb = lsub.reshape(nt, r, s, 32).transpose(1, 0, 2, 3) \
        .reshape(r, nt * s, 32)
    return parity, lb


_fused_hier_lsub = functools.partial(jax.jit, static_argnames=(
    "m", "tile", "wb", "interpret", "extract"))(_hier_lsub_core)

# donated twin for the dispatch-ahead pipeline: the staged device input
# words are single-use (one drain's concatenated runs), so XLA may
# reuse their HBM for the parity output instead of allocating fresh —
# only selected on real accelerators (CPU ignores donation and warns)
_fused_hier_lsub_donate = functools.partial(jax.jit, static_argnames=(
    "m", "tile", "wb", "interpret", "extract"),
    donate_argnums=(2,))(_hier_lsub_core)


def gf_encode_extents_with_crc(bitmat, bitmat32, runs, m: int,
                               use_w32: bool | None = None,
                               force_xla: bool | None = None,
                               interpret: bool = False,
                               tile: int | None = None,
                               wb: int | None = None,
                               extract: str = "planar",
                               combine: str = "xla"):
    """Multi-extent fused launch: parity + ONE device-combined crc
    L-vector per shard per run, for a whole pipeline drain in one
    kernel call (lifting the round-1 restriction that only a single-op
    drain could fuse).

    Each run (k, Wi) uint8 is zero-padded to a tile multiple and the
    padded runs concatenate along the byte axis, so every run starts
    tile-aligned.  The launch emits per-block L-vectors (hier kernel:
    2 KiB sub-blocks; flat/XLA: 2 KiB tiles); each run's full blocks
    fold ON DEVICE (combine_crcs_pow2, log-depth int8 matmuls) into a
    single L per shard, and only the sub-BLOCK tail (data rows from the
    input, parity rows from the launch output) reaches the host.  Zero
    padding is benign for parity (linear code) and the padded block's
    L-row is simply unused.

    `tile`/`wb`/`extract`/`combine` override the hier kernel's
    operating point (fed by ops/autotune via the plugin); defaults
    keep the static FUSED_TILE_HIER/FUSED_WB constants with the
    planar/xla variants.

    Returns a list of (parity (m, Wi) uint8, l (k+m,) uint32 over the
    run's body, tail_bytes (k+m, tail_len) uint8, body_bytes) per run —
    fold with crc32c_linear.fold_run_crc seeded per shard: O(1) host
    combines per extent, no per-tile Python loop.  On the accumulator
    path (combine="kernel") the kernel's L covers the run's every byte,
    so tail_bytes is empty and body_bytes == Wi.
    """
    return gf_encode_extents_with_crc_finalize(
        gf_encode_extents_with_crc_submit(
            bitmat, bitmat32, runs, m, use_w32=use_w32,
            force_xla=force_xla, interpret=interpret, tile=tile,
            wb=wb, extract=extract, combine=combine))


def gf_encode_extents_with_crc_submit(bitmat, bitmat32, runs, m: int,
                                      use_w32: bool | None = None,
                                      force_xla: bool | None = None,
                                      interpret: bool = False,
                                      tile: int | None = None,
                                      wb: int | None = None,
                                      extract: str = "planar",
                                      combine: str = "xla",
                                      donate: bool | None = None):
    """Dispatch half of gf_encode_extents_with_crc: stages the drain's
    runs, launches parity + the per-run device L folds, and returns an
    opaque handle holding ONLY device arrays (futures) plus host
    metadata — no np.asarray anywhere, so the caller never blocks on
    the device.  `donate=True` (resolved to the backend: real
    accelerators only) hands the staged input words' HBM to XLA for
    reuse.  The handle records the kernel `path` that served the drain
    ("hier_acc" / "hier_lsub" / "w32_flat" / "bytes" / "xla") so bench
    and the backend can attribute a perf move to kernel vs dispatch
    changes.  Pair with gf_encode_extents_with_crc_finalize."""
    from . import crc32c_linear as cl
    if combine not in ("xla", "kernel"):
        # reject up front like the words-path twin — a malformed cache
        # entry must not silently demote to the legacy lsub path while
        # the backend still counts the drain as kernel-served
        raise ValueError(f"unknown combine depth {combine!r}")
    if force_xla is None:
        force_xla = jax.default_backend() == "cpu"
    if use_w32 is None:
        use_w32 = not force_xla
    if donate is None:
        donate = jax.default_backend() != "cpu"
    runs = [np.ascontiguousarray(r, dtype=np.uint8) for r in runs]
    k = runs[0].shape[0]
    assert all(r.shape[0] == k for r in runs), \
        "all runs of one launch must share k (one codec per batch)"
    r_tot = k + m
    tile_hier = tile or FUSED_TILE_HIER
    wb = wb or FUSED_WB
    # Mixed-width batches (a cross-PG super-batch mixing big
    # sequential appends with small writes) must not demote EVERY run
    # off the hier kernel just because one run is under the hier tile:
    # split into a hier-eligible launch and a flat-tile launch, demuxed
    # back to the caller's run order at finalize.  Two launches instead
    # of one, but the big runs keep the headline kernel — the
    # occupancy-preserving trade continuous batching needs.
    if use_w32 and not force_xla:
        big_idx = [i for i, r in enumerate(runs)
                   if r.shape[1] >= tile_hier]
        if 0 < len(big_idx) < len(runs):
            small_idx = [i for i, r in enumerate(runs)
                         if r.shape[1] < tile_hier]
            parts = []
            for idxs in (big_idx, small_idx):
                parts.append((idxs, gf_encode_extents_with_crc_submit(
                    bitmat, bitmat32, [runs[i] for i in idxs], m,
                    use_w32=use_w32, force_xla=force_xla,
                    interpret=interpret, tile=tile, wb=wb,
                    extract=extract, combine=combine, donate=donate)))
            return {"split": parts, "n_runs": len(runs),
                    "path": "+".join(h["path"] for _, h in parts)}
    # operating point: big sequential drains ride the hier-crc kernel at
    # the autotuned tile; small/mixed drains keep the flat 2 KiB tile
    # where padding waste would dominate
    tile = FUSED_TILE
    hier = False
    if use_w32 and not force_xla and \
            min(r.shape[1] for r in runs) >= tile_hier:
        tile = tile_hier
        hier = True
    acc = hier and combine == "kernel"
    meta = []           # width per run
    pads = []           # front pad per run (accumulator path only)
    padded = []
    for r in runs:
        w = r.shape[1]
        pad = -w % tile
        meta.append(w)
        # accumulator path: pad each run at the FRONT — a zero prefix
        # is free for the crc (L(0^n || B) = L(B)), so the in-kernel
        # per-run accumulator covers the run's every byte (no host
        # tail fold at all); the legacy paths keep the back pad and
        # drop the padded tail blocks' L rows on the host instead
        pads.append(pad if acc else 0)
        if pad:
            padded.append(np.pad(r, ((0, 0), (pad, 0)) if acc
                          else ((0, 0), (0, pad))))
        else:
            padded.append(r)
    big = np.concatenate(padded, axis=1)               # (k, ntiles*tile)
    ntiles_total = big.shape[1] // tile
    # Launch-shape bucketing: continuous batching (the per-host launch
    # queue) makes every super-batch a different total width, and every
    # distinct width is a fresh XLA/Mosaic compile — seconds each,
    # paid per launch instead of once.  Zero-pad the concatenated
    # launch to the next power-of-two tile count so the jit key space
    # collapses to ~log2 shapes per path; the pad tiles sit AFTER
    # every real run (per-run demux never reaches them) and zero bytes
    # encode to zero parity, so the bucket is free for correctness.
    ntiles2 = next_pow2(ntiles_total)
    pad_tiles = ntiles2 - ntiles_total
    if pad_tiles:
        big = np.concatenate(
            [big, np.zeros((k, pad_tiles * tile), dtype=np.uint8)],
            axis=1)
        ntiles_total = ntiles2
    rows = _crc_rows(r_tot)
    w32_out = False
    lbits_devs = None
    if force_xla:
        cmat = jnp.asarray(cl.crc_tile_matrix(tile))
        parity_dev, crc_bits = _aot_dispatch(
            "fused_xla", gf_encode_with_crc_xla,
            (bitmat, cmat, jnp.asarray(big)), {"m": m, "tile": tile})
        lb_all = jnp.transpose(crc_bits, (1, 0, 2))    # (r, ntiles, 32)
        block_bytes = tile
        path = "xla"
    elif not use_w32:
        # byte-path Pallas kernel (TPU without the w32 layout): per-tile
        # L rows, device-combined per run below like the flat w32 path
        cmat = jnp.asarray(cl.crc_tile_matrix(tile))
        parity_dev, crc_flat = gf_encode_with_crc_pallas(
            bitmat, cmat, jnp.asarray(big), m)
        lb_all = jnp.transpose(
            crc_flat.reshape(ntiles_total, rows, 32)[:, :r_tot],
            (1, 0, 2))                                 # (r, ntiles, 32)
        block_bytes = tile
        path = "bytes"
    elif acc:
        # the overlapped accumulator kernel: one L block per RUN from
        # the launch itself — no per-step lsub round-trip, no per-run
        # combine dispatches, no sub-block host tail
        cmat_sub = jnp.asarray(cl.crc_tile_matrix_w32(wb))
        words = big.view("<u4").view(np.int32)
        # the L out-block is keyed by run count: bucket it to a power
        # of two as well (pad tiles ride a dummy trailing run, empty
        # filler runs contribute no grid steps), so (tiles, runs) jit
        # keys stay ~log2 x log2 under cross-PG batching
        ntiles_run = [p.shape[1] // tile for p in padded]
        if pad_tiles:
            ntiles_run.append(pad_tiles)
        nruns_acc = next_pow2(len(ntiles_run))
        ntiles_run += [0] * (nruns_acc - len(ntiles_run))
        run_map, first_map, adv, comb = _acc_launch_args(
            ntiles_run, tile, wb)
        acc_fn = _hier_acc_donate if donate else _hier_acc
        parity_dev, lb = _aot_dispatch(
            "hier_acc_donate" if donate else "hier_acc", acc_fn,
            (bitmat32, cmat_sub, adv, comb, run_map, first_map,
             jnp.asarray(words)),
            {"m": m, "tile": tile, "wb": wb, "nruns": nruns_acc,
             "interpret": interpret,
             "extract": extract})                      # (nruns, r, 32)
        lbits_devs = [lb[i] for i in range(len(runs))]
        block_bytes = 4 * wb
        w32_out = True
        path = "hier_acc"
    elif hier:
        cmat_sub = jnp.asarray(cl.crc_tile_matrix_w32(wb))
        words = big.view("<u4").view(np.int32)
        hier_fn = _fused_hier_lsub_donate if donate else _fused_hier_lsub
        parity_dev, lb_all = _aot_dispatch(
            "hier_lsub_donate" if donate else "hier_lsub", hier_fn,
            (bitmat32, cmat_sub, jnp.asarray(words)),
            {"m": m, "tile": tile, "wb": wb, "interpret": interpret,
             "extract": extract})                      # (r, nsub, 32)
        block_bytes = 4 * wb
        w32_out = True
        path = "hier_lsub"
    else:
        wt = tile // 4
        cmat32 = jnp.asarray(cl.crc_tile_matrix_w32(wt))
        words = big.view("<u4").view(np.int32)
        parity_dev, crc_flat = _aot_dispatch(
            "fused_w32", gf_encode_with_crc_pallas_w32,
            (bitmat32, cmat32, jnp.asarray(words)),
            {"m": m, "interpret": interpret})
        lb_all = jnp.transpose(
            crc_flat.reshape(ntiles_total, rows, 32)[:, :r_tot],
            (1, 0, 2))                                 # (r, ntiles, 32)
        block_bytes = tile
        w32_out = True
        path = "w32_flat"
    if lbits_devs is None:
        # per-run device combines dispatched NOW (still no host sync):
        # each run's full blocks fold to one L per shard on device
        lbits_devs = []
        coff = 0
        for w, pr in zip(meta, padded):
            nb = w // block_bytes
            if nb:
                boff = coff // block_bytes
                lb_run = lb_all[:, boff:boff + nb]
                # zero-PREFIX pad to the next power of two before the
                # jitted combine: L(0^n || B) = L(B), so the pad is
                # free, and it collapses the jit-cache key space from
                # "every distinct extent length" to ~log2 shapes (a
                # drain of varied object sizes must not recompile per
                # length)
                nb2 = next_pow2(nb)
                if nb2 != nb:
                    lb_run = jnp.pad(lb_run, ((0, 0), (nb2 - nb, 0),
                                              (0, 0)))
                lbits_devs.append(_combine_run(lb_run, block_bytes))
            else:
                lbits_devs.append(None)
            coff += pr.shape[1]
    return {"meta": meta, "padded": padded, "pads": pads,
            "parity_dev": parity_dev, "lbits_devs": lbits_devs,
            "block_bytes": block_bytes, "r_tot": r_tot, "m": m,
            "w32_out": w32_out, "big_width": big.shape[1],
            "path": path, "acc": acc}


def gf_encode_extents_with_crc_finalize(handle):
    """Completion half: blocks on the device results of one submit
    handle and materializes the per-run
    (parity, l, tail_bytes, body_bytes) tuples (the contract of
    gf_encode_extents_with_crc).  Accumulator-path handles
    (path "hier_acc") carry per-run Ls covering EVERY run byte, so
    body == run width and tail_bytes is empty — the host pays one
    seed-advance per extent and never touches a byte."""
    from . import crc32c_linear as cl
    if "split" in handle:
        # mixed-width batch: finalize both sub-launches and restore
        # the caller's run order
        out = [None] * handle["n_runs"]
        for idxs, sub in handle["split"]:
            for i, res in zip(idxs,
                              gf_encode_extents_with_crc_finalize(sub)):
                out[i] = res
        return out
    meta, padded = handle["meta"], handle["padded"]
    pads = handle.get("pads") or [0] * len(meta)
    r_tot = handle["r_tot"]
    block_bytes = handle["block_bytes"]
    acc = handle.get("acc", False)
    parity_big = np.asarray(handle["parity_dev"])
    if handle["w32_out"]:
        parity_big = parity_big.view("<u4").view(np.uint8) \
            .reshape(handle["m"], handle["big_width"])
    out = []
    coff = 0
    for w, pr, pad, lbits in zip(meta, padded, pads,
                                 handle["lbits_devs"]):
        par = parity_big[:, coff + pad:coff + pad + w]
        if acc:
            body = w                     # kernel L covers the full run
        else:
            nb = w // block_bytes        # full blocks = run body
            body = nb * block_bytes
        if lbits is not None:
            l = cl.bits_to_u32(np.asarray(lbits))      # (k+m,) u32
        else:
            l = np.zeros(r_tot, dtype=np.uint32)
        tail_data = pr[:, pad + body:pad + w]
        tail_par = par[:, body:w]
        tail_bytes = np.concatenate([tail_data, tail_par], axis=0) \
            if w > body else np.zeros((r_tot, 0), dtype=np.uint8)
        out.append((par, l, tail_bytes, body))
        coff += pr.shape[1]
    return out


def _pick_tile(n: int) -> int:
    assert n % LANE == 0, n  # the max() clamp below relies on it
    tile = min(DEFAULT_TILE, n)
    while n % tile:
        tile //= 2
    return max(tile, LANE)


def gf_bitmatmul(bitmat: jnp.ndarray, chunks: jnp.ndarray, r: int,
                 force_xla: bool | None = None) -> jnp.ndarray:
    """Dispatch: Pallas on TPU, XLA elsewhere.  Pads N up to a lane/tile
    multiple and strips the pad (zero bytes encode to zero parity, so
    padding is benign for linear codes)."""
    k, n = chunks.shape
    use_xla = force_xla if force_xla is not None \
        else jax.default_backend() == "cpu"
    npad = -n % LANE
    if npad:
        chunks = jnp.pad(chunks, ((0, 0), (0, npad)))
    if use_xla:
        out = _aot_dispatch("mm_xla", gf_bitmatmul_xla,
                            (bitmat, chunks), {"r": r})
    else:
        out = gf_bitmatmul_pallas(bitmat, chunks, r,
                                  tile=_pick_tile(n + npad))
    return out[:, :n] if npad else out


# ----------------------------------------------------------------------------
# AOT lowering: headline kernels compiled ahead of time
# ----------------------------------------------------------------------------
# The compile-stall fix's third leg (with the persistent compile cache
# and the boot-time prewarm plan): the headline entry points — the
# fused hier-acc encode+crc point, the plain/flat encode, and the flat
# decode — get jax.jit(...).lower().compile() executables built BEFORE
# any data exists, keyed by (entry name, input avals, static args).
# The dispatch sites below consult this registry first, so a
# steady-state launch of an AOT-covered shape calls the compiled
# executable directly and never touches jit dispatch (no trace-time,
# ever); uncovered shapes fall through to the jitted path unchanged.
# With the persistent cache enabled, an AOT lower+compile also lands
# the executable on disk — a restarted daemon's aot_compile() of the
# same shape is a cache read, not a compile.

_AOT_LOCK = threading.Lock()
_AOT: dict[tuple, object] = {}
_AOT_STATS = {"compiles": 0, "calls": 0, "errors": 0, "compile_s": 0.0}


def _aot_key(name: str, args, statics: dict) -> tuple:
    return (name,
            tuple((tuple(a.shape), str(np.dtype(a.dtype)))
                  for a in args),
            tuple(sorted(statics.items())))


def aot_compile(name: str, jitted, args, statics: dict) -> bool:
    """Lower+compile one jitted entry at the given arg shapes (arrays
    or ShapeDtypeStructs — only shape/dtype are read) and register the
    executable under (name, avals, statics).  Idempotent; returns
    whether the executable is (now) registered.  Failures degrade to
    the jitted path and are counted, never raised — AOT is an
    optimization, not a correctness dependency."""
    key = _aot_key(name, args, statics)
    with _AOT_LOCK:
        if key in _AOT:
            return True
    import time as _time
    avals = tuple(jax.ShapeDtypeStruct(tuple(a.shape),
                                       np.dtype(a.dtype))
                  for a in args)
    t0 = _time.perf_counter()
    try:
        exe = jitted.lower(*avals, **statics).compile()
    except Exception:  # noqa: BLE001 — unsupported backend/shape
        _AOT_STATS["errors"] += 1
        return False
    with _AOT_LOCK:
        _AOT.setdefault(key, exe)
        _AOT_STATS["compiles"] += 1
        _AOT_STATS["compile_s"] += _time.perf_counter() - t0
    return True


def _aot_dispatch(name: str, jitted, args, statics: dict):
    """Call the AOT executable registered for (name, arg shapes,
    statics) when one exists, else the jitted path.  A call-time
    mismatch (dtype drift, backend change) drops the stale executable
    and falls back — one failed call, never a wedged path."""
    exe = _AOT.get(_aot_key(name, args, statics))
    if exe is not None:
        try:
            out = exe(*args)
            _AOT_STATS["calls"] += 1
            return out
        except Exception:  # noqa: BLE001 — stale/mismatched executable
            _AOT_STATS["errors"] += 1
            with _AOT_LOCK:
                _AOT.pop(_aot_key(name, args, statics), None)
    return jitted(*args, **statics)


def aot_stats() -> dict:
    with _AOT_LOCK:
        out = dict(_AOT_STATS)
        out["executables"] = len(_AOT)
    return out


def aot_reset_for_tests() -> None:
    with _AOT_LOCK:
        _AOT.clear()
        _AOT_STATS.update(
            {"compiles": 0, "calls": 0, "errors": 0, "compile_s": 0.0})
