"""Bit-sliced GF(2^8) linear algebra on TPU.

The hot loop of the whole framework.  The reference computes erasure-code
parity with per-coefficient Galois region ops (jerasure schedules /
ISA-L `ec_encode_data`, reference src/erasure-code/isa/ErasureCodeIsa.cc:129)
— a CPU-SIMD formulation.  TPU-first, the same math is one matmul:

  * multiply-by-constant in GF(2^8) is GF(2)-linear on the 8 bits, so a
    (r, k) coefficient matrix over GF(2^8) expands to an (8r, 8k) 0/1
    matrix (ceph_tpu/ec/gf.py expand_to_bitmatrix);
  * a chunk of N bytes unpacks to 8 bit-planes; stacking k chunks gives
    a (8k, N) 0/1 operand;
  * parity bits = bitmatrix @ bits mod 2 — an int8 matmul on the MXU
    with int32 accumulation (inner dim 8k <= 256 so sums stay tiny),
    followed by `& 1` and a pack on the VPU.

Layout: *bit-major interleaved*.  Row index bit*n + chunk (not
chunk*8+bit) so the in-kernel unpack `(block >> i) & 1` needs no
transpose: shifting a (k, T) byte tile by i in [0, 8) and stacking gives
exactly rows [i*k + j].  `interleave_bitmatrix` converts the math-layout
matrix from gf.expand_to_bitmatrix into this kernel layout.

Everything here is shape-static and jit-compatible; the Pallas kernel
tiles the byte axis and keeps unpack -> matmul -> pack fused in VMEM so
HBM traffic is just bytes-in + parity-out (the reason this beats an XLA
fallback, which materializes the 8x unpacked bit-planes in HBM).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

try:  # pltpu imports fail off-TPU for some symbols; guard for CPU tests
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from ..ec import gf

LANE = 128           # TPU lane width: byte-axis tiles must be multiples
DEFAULT_TILE = 8192  # bytes of each chunk processed per grid step


def interleave_bitmatrix(mat: np.ndarray) -> np.ndarray:
    """(r, k) GF(2^8) matrix -> (8r, 8k) 0/1 matrix in bit-major layout.

    out[i*r + ri, j*k + cj] = bit (i, j) of the 8x8 bit-matrix of
    mat[ri, cj]; i.e. rows grouped by output bit, columns by input bit.
    """
    r, k = mat.shape
    math_layout = gf.expand_to_bitmatrix(mat)          # (8r, 8k) chunk-major
    out = np.zeros_like(math_layout)
    for ri in range(r):
        for i in range(8):
            for cj in range(k):
                for j in range(8):
                    out[i * r + ri, j * k + cj] = \
                        math_layout[ri * 8 + i, cj * 8 + j]
    return out


def _unpack_bits(block: jnp.ndarray) -> jnp.ndarray:
    """(k, T) uint8 -> (8k, T) int8 bit-planes, bit-major rows."""
    k, t = block.shape
    shifts = jnp.arange(8, dtype=jnp.uint8)[:, None, None]
    bits = (block[None, :, :] >> shifts) & jnp.uint8(1)   # (8, k, T)
    return bits.reshape(8 * k, t).astype(jnp.int8)


def _pack_bits(bits: jnp.ndarray, r: int) -> jnp.ndarray:
    """(8r, T) int32 0/1 bit-major rows -> (r, T) uint8 bytes."""
    t = bits.shape[1]
    weights = (jnp.int32(1) << jnp.arange(8, dtype=jnp.int32))[:, None, None]
    return jnp.sum(bits.reshape(8, r, t) * weights, axis=0).astype(jnp.uint8)


# ----------------------------------------------------------------------------
# XLA (non-Pallas) path: correct everywhere, used on CPU and as the oracle
# ----------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("r",))
def gf_bitmatmul_xla(bitmat: jnp.ndarray, chunks: jnp.ndarray, r: int
                     ) -> jnp.ndarray:
    """Apply an interleaved (8r, 8k) bitmatrix to (k, N) uint8 chunks."""
    bits = _unpack_bits(chunks)
    prod = jax.lax.dot_general(
        bitmat.astype(jnp.int8), bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ) & 1
    return _pack_bits(prod, r)


# ----------------------------------------------------------------------------
# Pallas kernel: fused unpack -> MXU matmul -> mod2 -> pack
# ----------------------------------------------------------------------------

def _gf_kernel(bitmat_ref, in_ref, out_ref):
    r8 = bitmat_ref.shape[0]
    bits = _unpack_bits(in_ref[:])
    prod = jax.lax.dot_general(
        bitmat_ref[:], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ) & 1
    out_ref[:] = _pack_bits(prod, r8 // 8)


@functools.partial(jax.jit, static_argnames=("r", "tile"))
def gf_bitmatmul_pallas(bitmat: jnp.ndarray, chunks: jnp.ndarray, r: int,
                        tile: int = DEFAULT_TILE) -> jnp.ndarray:
    """Pallas path of gf_bitmatmul.  chunks (k, N) with N % tile == 0."""
    k, n = chunks.shape
    assert n % tile == 0, (n, tile)
    grid = (n // tile,)
    return pl.pallas_call(
        _gf_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * r, 8 * k), lambda t: (0, 0)),
            pl.BlockSpec((k, tile), lambda t: (0, t)),
        ],
        out_specs=pl.BlockSpec((r, tile), lambda t: (0, t)),
        out_shape=jax.ShapeDtypeStruct((r, n), jnp.uint8),
    )(bitmat.astype(jnp.int8), chunks)


FUSED_TILE = 2048  # fused parity+crc kernel tile (cmat VMEM footprint)


def _gf_crc_kernel(bitmat_ref, cmat_ref, in_ref, par_ref, crc_ref):
    """Fused: parity tile + per-tile crc32c L-bits for every shard, one
    launch (the north-star fusion: checksum and parity from the same
    VMEM-resident bit-planes)."""
    from . import crc32c_linear as cl
    r8 = bitmat_ref.shape[0]
    m = r8 // 8
    bits = _unpack_bits(in_ref[:])                    # (8k, T)
    prod = jax.lax.dot_general(
        bitmat_ref[:], bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ) & 1
    par_ref[:] = _pack_bits(prod, m)
    data_crc = cl.tile_crc_bits(bits, cmat_ref[:])            # (k, 32)
    par_crc = cl.tile_crc_bits(prod.astype(jnp.int8),
                               cmat_ref[:])                   # (m, 32)
    crc_ref[:] = jnp.concatenate([data_crc, par_crc],
                                 axis=0)[None, :, :]


@functools.partial(jax.jit, static_argnames=("m", "tile"))
def gf_encode_with_crc_pallas(bitmat, cmat, chunks, m: int,
                              tile: int = FUSED_TILE):
    k, n = chunks.shape
    assert n % tile == 0, (n, tile)
    grid = (n // tile,)
    return pl.pallas_call(
        _gf_crc_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((8 * m, 8 * k), lambda t: (0, 0)),
            pl.BlockSpec((8, tile, 32), lambda t: (0, 0, 0)),
            pl.BlockSpec((k, tile), lambda t: (0, t)),
        ],
        out_specs=[
            pl.BlockSpec((m, tile), lambda t: (0, t)),
            pl.BlockSpec((1, k + m, 32), lambda t: (t, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((m, n), jnp.uint8),
            jax.ShapeDtypeStruct((n // tile, k + m, 32), jnp.int32),
        ],
    )(bitmat.astype(jnp.int8), cmat, chunks)


@functools.partial(jax.jit, static_argnames=("m", "tile"))
def gf_encode_with_crc_xla(bitmat, cmat, chunks, m: int,
                           tile: int = FUSED_TILE):
    """XLA twin of the fused kernel (CPU tests / fallback)."""
    from . import crc32c_linear as cl
    k, n = chunks.shape
    ntiles = n // tile
    bits = _unpack_bits(chunks)                       # (8k, N)
    prod = jax.lax.dot_general(
        bitmat.astype(jnp.int8), bits,
        dimension_numbers=(((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    ) & 1
    parity = _pack_bits(prod, m)
    crcs = []
    for t in range(ntiles):
        sl = slice(t * tile, (t + 1) * tile)
        d = cl.tile_crc_bits(bits[:, sl], cmat)
        p = cl.tile_crc_bits(prod[:, sl].astype(jnp.int8), cmat)
        crcs.append(jnp.concatenate([d, p], axis=0))
    return parity, jnp.stack(crcs)


def gf_encode_with_crc(bitmat, chunks, m: int,
                       force_xla: bool | None = None):
    """Encode + per-shard crc32c L-values in one fused launch.

    chunks (k, N) uint8.  Returns (parity (m, N) uint8,
    tile_ls (n_shards, ntiles) uint32, tail bytes per shard start) —
    callers fold with crc32c_linear.fold_tile_crcs.  N's remainder
    beyond the tile grid is returned as `tail` for host folding.
    """
    from . import crc32c_linear as cl
    k, n = chunks.shape
    tile = FUSED_TILE
    use_xla = force_xla if force_xla is not None \
        else jax.default_backend() == "cpu"
    body = (n // tile) * tile
    cmat = jnp.asarray(cl.crc_tile_matrix(tile))
    if body:
        fn = gf_encode_with_crc_xla if use_xla else gf_encode_with_crc_pallas
        parity_body, crc_bits = fn(bitmat, cmat, chunks[:, :body], m)
        crc_bits = np.asarray(crc_bits)               # (ntiles, n_sh, 32)
        tile_ls = cl.bits_to_u32(crc_bits).T          # (n_sh, ntiles)
    else:
        parity_body = jnp.zeros((m, 0), dtype=jnp.uint8)
        tile_ls = np.zeros((k + m, 0), dtype=np.uint32)
    tail = chunks[:, body:]
    if tail.shape[1]:
        parity_tail = gf_bitmatmul(bitmat, tail, m, force_xla=force_xla)
        parity = jnp.concatenate([parity_body, parity_tail], axis=1)
        tail_bytes = np.concatenate(
            [np.asarray(tail), np.asarray(parity_tail)], axis=0)
    else:
        parity = parity_body
        tail_bytes = np.zeros((k + m, 0), dtype=np.uint8)
    return parity, tile_ls, tail_bytes, tile


def _pick_tile(n: int) -> int:
    tile = min(DEFAULT_TILE, n)
    while n % tile:
        tile //= 2
    return max(tile, LANE)


def gf_bitmatmul(bitmat: jnp.ndarray, chunks: jnp.ndarray, r: int,
                 force_xla: bool | None = None) -> jnp.ndarray:
    """Dispatch: Pallas on TPU, XLA elsewhere.  Pads N up to a lane/tile
    multiple and strips the pad (zero bytes encode to zero parity, so
    padding is benign for linear codes)."""
    k, n = chunks.shape
    use_xla = force_xla if force_xla is not None \
        else jax.default_backend() == "cpu"
    npad = -n % LANE
    if npad:
        chunks = jnp.pad(chunks, ((0, 0), (0, npad)))
    if use_xla:
        out = gf_bitmatmul_xla(bitmat, chunks, r)
    else:
        out = gf_bitmatmul_pallas(bitmat, chunks, r,
                                  tile=_pick_tile(n + npad))
    return out[:, :n] if npad else out
