"""Async messenger: reactor, sessions, connections, dispatch.

Re-expresses the reference's AsyncMessenger stack (src/msg/async/
AsyncMessenger.cc, AsyncConnection.cc, Stack.h Worker reactors) and
ProtocolV2's lossless session semantics (src/msg/async/ProtocolV2.cc:
out_seq/in_seq, ack frames, session resume + replay on reconnect):

- Every connection opens with a HELLO frame carrying a stable entity
  identity and the receiver's highest-delivered seq; the server binds
  the TCP stream to a per-entity Session that survives reconnects.
- Senders keep unacked frames; receivers ack delivered seqs; acks trim
  the replay window.  On reconnect the peer's HELLO tells the sender
  what arrived, so replay starts exactly after it and the receive path
  drops any already-seen seq — exactly-once delivery per session.
- The Session owns the live TCP stream; Connections are facades over it,
  so a server reply issued after the client reconnected rides the new
  stream (the reference rebinds AsyncConnection to the existing session
  the same way on reconnect_ok).
- Lossy connections (heartbeats may opt in) skip retention and resume.

Fault injection (reference ms_inject_socket_failures / ms_inject_delay_*
in src/common/options.cc:1071-1092): per-messenger knobs that randomly
reset sockets or delay frame writes, used by the thrasher tests.

Idiomatic shift: a small POOL of asyncio event loops (each in its own
thread) replaces N epoll worker threads — every Messenger instance is
pinned to one loop of the pool at creation (reference AsyncMessenger
worker assignment).  A single shared loop was measured to serialize
the EC read fan-out: 8 concurrent 128 KiB sub-read replies took 4.2 ms
through one reactor vs 0.57 ms for one reply, because every frame's
encode + crc + retention copy runs on the loop thread.  Sessions,
sockets, and locks are all per-messenger, so loops never share
connection state.  The public surface (Messenger/Connection/
Dispatcher) keeps the reference's shape so daemon code reads the same.
"""

from __future__ import annotations

import asyncio
import collections
import json
import random
import struct
import threading
import time
import uuid
from concurrent.futures import TimeoutError as _FuturesTimeout
from typing import Callable

from .message import (CTRL_ACK, CTRL_COMP, CTRL_ENC, CTRL_HELLO, Message,
                      encode_frame)
from .msgr_ledger import MsgrLedger, msgr_ledger

Dispatcher = Callable[["Connection", Message], None]


def _grow_socket_buffers(writer: asyncio.StreamWriter,
                         size: int = 4 << 20) -> None:
    """MiB-scale frames on default (~64-208 KiB) kernel buffers cost
    several epoll write/read cycles each; grow both directions."""
    import socket as _socket
    sock = writer.get_extra_info("socket")
    if sock is None:
        return
    try:
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_SNDBUF, size)
        sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_RCVBUF, size)
    except OSError:
        pass

# A lossless peer that stops acking cannot hold frames forever: past this
# many retained frames the session is torn down (abnormal reset, like the
# reference's session reset after policy limits) rather than leaking.
UNACKED_HARD_CAP = 65536


def _parse_raw(raw: bytes) -> tuple[int, int, bytes, bytes, int]:
    """Split one frame already in memory (the unwrapped payload of an
    ENC/COMP envelope) into (tid, seq, meta_raw, data, pcrc).  A short
    or mangled buffer raises ValueError so the read loop's corruption
    path (session-preserving wire reset) handles it — struct.error
    would kill the loop."""
    import struct as _struct
    try:
        tid, seq, meta_len, data_len = \
            Message.parse_header(raw[:Message.HEADER_SIZE])
    except (_struct.error, ValueError) as e:
        raise ValueError(f"bad inner frame: {e}") from e
    off = Message.HEADER_SIZE
    if len(raw) < off + meta_len + data_len + 4:
        raise ValueError("truncated inner frame")
    meta_raw = raw[off:off + meta_len]
    data = raw[off + meta_len:off + meta_len + data_len]
    pcrc = int.from_bytes(raw[-4:], "little")
    return tid, seq, meta_raw, data, pcrc


async def read_frame(reader: asyncio.StreamReader
                     ) -> tuple[int, int, bytes, bytes, int]:
    """Read one wire frame -> (tid, seq, meta_raw, data, pcrc); raises
    ValueError on corruption (bad magic / header crc).  Two reads per
    frame (header, then body in one readexactly + slice) — each await
    is a potential reactor suspension, and the EC fan-out pays it per
    shard reply."""
    head = await reader.readexactly(Message.HEADER_SIZE)
    tid, seq, meta_len, data_len = Message.parse_header(head)
    body = await reader.readexactly(meta_len + data_len + 4)
    meta_raw = body[:meta_len]
    data = body[meta_len:meta_len + data_len]
    (pcrc,) = struct.unpack("<I", body[-4:])
    return tid, seq, meta_raw, data, pcrc


class Session:
    """Per-peer-entity delivery state + the live wire; survives TCP
    reconnects (reference ProtocolV2 session: out_seq/in_seq/out_queue
    replay, rebound to a new AsyncConnection on resume)."""

    def __init__(self, lossless: bool = True, nonce: str | None = None):
        self.lossless = lossless
        # Distinguishes incarnations: a client that abandons a session
        # (unacked overflow) starts a new nonce, telling the server to
        # discard its old seq window instead of dedup-dropping the fresh
        # one (reference ProtocolV2 client_cookie semantics).
        self.nonce = nonce or uuid.uuid4().hex[:12]
        # Epoch cookies (reference ProtocolV2 client_cookie/server_cookie):
        # local_cookie identifies THIS session object; peer_cookie is the
        # last cookie seen from the peer.  A seq number is only meaningful
        # within the epoch whose cookie it was learned under — trusting a
        # stale in_seq would trim undelivered frames from the peer's
        # replay window (observed as lost replies across server restarts).
        self.local_cookie = uuid.uuid4().hex[:12]
        self.peer_cookie: str | None = None
        self.out_seq = 0          # last seq assigned to an outgoing frame
        self.in_seq = 0           # highest seq delivered to the dispatcher
        self.unacked: collections.deque[tuple[int, bytes]] = \
            collections.deque()
        self.reader: asyncio.StreamReader | None = None
        self.writer: asyncio.StreamWriter | None = None
        self.send_lock = asyncio.Lock()
        self.broken = False
        self.down_since: float | None = None
        self.last_acked = 0       # highest seq we have acked to the peer
        # auth state (per wire epoch; re-derived on every HELLO):
        # conn_key signs/encrypts this connection, auth_identity is the
        # verified peer {entity, caps} (reference CephXAuthorizer
        # session_key + secure-mode keys from crypto_onwire.cc)
        self.conn_key: bytes | None = None
        self.secure = False
        self.auth_identity: dict | None = None
        self._enc_ctr = 0
        self._enc_dir = b"\x01"   # \x01 = connector, \x02 = acceptor
        self._aead = None         # cached AESGCM (one key schedule)
        # on-wire compression (reference msgr2.1 compression feature):
        # negotiated at HELLO; frames >= comp_min wrap in CTRL_COMP
        # before (optional) encryption
        self.comp = None          # Compressor | None
        self.comp_min = 4096
        self.compressed_out = 0
        self._decomp_cache: dict = {}

    def wire_prepare(self, raw: bytes) -> bytes:
        """Outbound frame pipeline: compress-then-encrypt."""
        if self.comp is not None and len(raw) >= self.comp_min:
            raw = encode_frame(CTRL_COMP, 0, {"a": self.comp.name},
                               self.comp.compress(raw))
            self.compressed_out += 1
        if self.secure and self.conn_key:
            raw = self.wire_encrypt(raw)
        return raw

    def wire_decompress(self, algo: str, data: bytes) -> bytes:
        from ..compressor import CompressorError, create
        c = self._decomp_cache.get(algo)
        if c is None:
            try:
                c = self._decomp_cache[algo] = create(algo)
            except CompressorError as e:
                raise ValueError(f"bad compression algo: {e}") from e
        try:
            return c.decompress(data)
        except CompressorError as e:
            raise ValueError(f"corrupt compressed frame: {e}") from e

    def set_conn_key(self, key: bytes | None, direction: bytes) -> None:
        """Install the per-wire-epoch key; the counter reset is safe
        because every HELLO derives a fresh key from a fresh nonce."""
        self.conn_key = key
        self._enc_ctr = 0
        self._dec_ctr = 0
        self._enc_dir = direction
        if key is not None:
            from cryptography.hazmat.primitives.ciphers.aead import AESGCM
            self._aead = AESGCM(key)
        else:
            self._aead = None

    def wire_encrypt(self, raw: bytes) -> bytes:
        """AES-GCM-wrap one plaintext frame for the wire (secure mode,
        reference msg/async/crypto_onwire.cc rx/tx handlers)."""
        self._enc_ctr += 1
        nonce = self._enc_dir * 4 + self._enc_ctr.to_bytes(8, "little")
        ct = self._aead.encrypt(nonce, raw, b"")
        return encode_frame(CTRL_ENC, self._enc_ctr, {}, nonce + ct)

    def wire_decrypt(self, data: bytes) -> bytes:
        # The nonce is implicit state, not attacker-controlled input: it
        # must be exactly (peer direction byte, rx_counter+1).  Checking
        # the frame's claimed nonce against our own counter rejects
        # replayed or reordered ciphertext that would otherwise pass
        # AEAD and poison the seq window (reference crypto_onwire.cc
        # uses a strictly-incrementing implicit nonce for the same
        # reason).
        peer_dir = b"\x02" if self._enc_dir == b"\x01" else b"\x01"
        expect = peer_dir * 4 + (self._dec_ctr + 1).to_bytes(8, "little")
        if data[:12] != expect:
            raise ValueError(
                "secure frame rejected: nonce out of sequence "
                "(replayed or reordered ciphertext)")
        try:
            pt = self._aead.decrypt(data[:12], data[12:], b"")
        except Exception as e:  # noqa: BLE001 - InvalidTag et al
            # surfaces as a session-preserving wire reset (same path as
            # a crc failure in plain mode)
            raise ValueError(f"secure frame rejected: {e}") from e
        self._dec_ctr += 1
        return pt

    def reset_epoch(self) -> None:
        """Abandon this session's delivery state and start a fresh epoch
        in place: new nonce (receiver will not dedup against the old seq
        space) and new cookie (peer resets its dedup window).  Used to
        self-heal after an unacked-window overflow so callers holding a
        cached Connection keep working (at-least-once across the reset;
        the overflow already lost the old window)."""
        self.nonce = uuid.uuid4().hex[:12]
        self.local_cookie = uuid.uuid4().hex[:12]
        self.peer_cookie = None
        self.out_seq = 0
        self.in_seq = 0
        self.last_acked = 0
        self.unacked.clear()
        self.broken = False
        self.drop_wire()

    def record_out(self, seq: int, raw: bytes) -> None:
        if self.lossless:
            self.unacked.append((seq, raw))
            if len(self.unacked) > UNACKED_HARD_CAP:
                # peer has not acked for 64k frames: abnormal reset
                self.unacked.clear()
                self.broken = True
                self.drop_wire()

    def trim_acked(self, upto: int) -> None:
        while self.unacked and self.unacked[0][0] <= upto:
            self.unacked.popleft()

    def replay_frames(self, peer_in_seq: int) -> list[bytes]:
        self.trim_acked(peer_in_seq)
        # retention holds parts-tuples (zero-concat send path); join
        # only here, on the rare replay
        return [raw if isinstance(raw, bytes) else b"".join(raw)
                for _, raw in self.unacked]

    def drop_wire(self) -> None:
        import time
        self.down_since = time.monotonic()
        w, self.writer, self.reader = self.writer, None, None
        if w is not None:
            try:
                w.transport.abort()
            except Exception:  # noqa: BLE001
                pass


class Connection:
    """One peer endpoint (reference AsyncConnection).  Client connections
    own their Session and reconnect on failure; accepted connections bind
    to a server-side Session resumed via HELLO and never dial out —
    frames they queue while the wire is down are replayed when the peer
    reconnects."""

    def __init__(self, messenger: "Messenger",
                 peer_addr: tuple[str, int] | None,
                 lossless: bool = True,
                 session: Session | None = None,
                 can_reconnect: bool = True):
        self.messenger = messenger
        self.peer_addr = peer_addr
        self.lossless = lossless
        self.session = session or Session(lossless)
        self.can_reconnect = can_reconnect
        self._closed = False
        self.last_error: str | None = None
        self.peer_entity: str | None = None
        self._label: str | None = None   # cached ledger peer label

    def is_connected(self) -> bool:
        return self.session.writer is not None and not self._closed

    def _peer_label(self) -> str:
        """Short peer name for ledger rows / trace events: the peer
        entity with its per-process uuid dropped ('osd.3'), else
        ip:port.  Cached once the entity is known (it never changes
        afterwards)."""
        lab = self._label
        if lab is None:
            ent = self.peer_entity
            if ent:
                lab = ent.rsplit(".", 1)[0] or ent
                self._label = lab
            elif self.peer_addr:
                lab = f"{self.peer_addr[0]}:{self.peer_addr[1]}"
            else:
                lab = "?"
        return lab

    # -- sending (thread-safe entry) ---------------------------------------

    def send_message(self, msg: Message) -> None:
        self.messenger._run_soon(self._send(msg))

    async def _send(self, msg: Message) -> None:
        sess = self.session
        m = self.messenger
        async with sess.send_lock:
            if sess.broken:
                if not self.can_reconnect:
                    # accepted side cannot dial; the peer's next
                    # reconnect gets a fresh session (see _on_accept)
                    return
                sess.reset_epoch()
            sess.out_seq += 1
            raw = msg.encode_parts(sess.out_seq)
            sess.record_out(sess.out_seq, raw)
            if sess.broken:       # overflow tripped by this very frame
                if not self.can_reconnect:
                    return
                sess.reset_epoch()          # carry this frame into the
                sess.out_seq = 1            # fresh epoch
                raw = msg.encode_parts(1)
                sess.record_out(1, raw)
            if m.inject_dispatch_stall > 0:
                # fault injection (conf ms_inject_dispatch_stall): the
                # assembled frame sits in the send queue while the
                # reactor "works" — a stalled dispatch's exact shape;
                # the late msgr_send(peer) stamp inherits the blame
                await asyncio.sleep(m.inject_dispatch_stall)
            try:
                if sess.writer is None:
                    if not self.can_reconnect:
                        return  # replayed when the peer reconnects
                    await self._connect()
                    if self.lossless:
                        # _connect's replay already carried raw
                        self._note_sent(msg, raw)
                        return
                await self._write_raw(raw)
                self._note_sent(msg, raw)
            except (ConnectionError, OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError, ValueError) as e:
                # IncompleteReadError (EOF mid-HELLO) and ValueError
                # (corrupt HELLO reply) must not escape: an unhandled
                # reactor-task exception would strand the frame in
                # sess.unacked with no reconnect scheduled
                self.last_error = str(e)
                await self._reconnect()

    def _note_sent(self, msg: Message, raw) -> None:
        """Wire-plane ledger + trace stitch for one sent frame; one
        attribute check when the ledger is off."""
        m = self.messenger
        if not m.ledger.enabled:
            return
        parts = raw if isinstance(raw, tuple) else (raw,)
        nbytes = 0
        for p in parts:
            nbytes += len(p)
        peer = self._peer_label()
        m.stats.note_send(peer, type(msg).__name__, nbytes,
                          len(self.session.unacked))
        top = getattr(msg, "_top", None)
        if top is not None and getattr(top, "is_tracked", False):
            # the interval ENDING here (send-queue + wire write) lands
            # on the op timeline named by peer, so slow-op blame can
            # say "5.1 s in the send queue to osd.7"
            top.mark_event(f"msgr_send({peer})")

    async def _write_raw(self, raw: bytes) -> None:
        """Single choke point for outgoing bytes: fault injection hooks
        live here (reference ms_inject_socket_failures / ms_inject_delay
        applied in AsyncConnection::write)."""
        m = self.messenger
        if m.inject_delay_prob > 0 and \
                m._inject_rng.random() < m.inject_delay_prob:
            await asyncio.sleep(m._inject_rng.random() * m.inject_delay_max)
        if m.inject_socket_failures > 0 and \
                m._inject_rng.randrange(m.inject_socket_failures) == 0:
            m.injected_failures += 1
            self.session.drop_wire()
            raise ConnectionResetError("injected socket failure")
        writer = self.session.writer
        if writer is None:
            # wire dropped while we slept in the injected delay (the
            # accepted-conn read loop nulls it without the send lock)
            raise ConnectionResetError("wire dropped during delayed write")
        sess = self.session
        parts = raw if isinstance(raw, tuple) else (raw,)
        if sess.comp is not None or (sess.secure and sess.conn_key):
            # compression/encryption wrap the whole frame: join first
            joined = b"".join(parts)
            wired = sess.wire_prepare(joined)
            if m.ledger.enabled:
                m.stats.note_wrapped(
                    self._peer_label(), len(wired),
                    compressed=sess.comp is not None and
                    len(joined) >= sess.comp_min,
                    encrypted=bool(sess.secure and sess.conn_key))
            writer.write(wired)
        else:
            # writev-style: payload buffers go to the transport as-is,
            # never copied into one frame buffer
            for p in parts:
                writer.write(p)
        await writer.drain()

    async def _connect(self) -> None:
        """Open the TCP stream and run the HELLO exchange: send our
        entity + in_seq (+ authorizer), read the peer's (+ mutual auth
        proof), trim + replay unacked."""
        assert self.peer_addr is not None
        # 4 MiB stream buffer: the default 64 KiB limit makes every
        # 128 KiB shard reply / 1 MiB op reply ping-pong through flow
        # control pauses (resume_reading wakeups) several times per
        # frame
        reader, writer = await asyncio.open_connection(
            *self.peer_addr, limit=4 << 20)
        _grow_socket_buffers(writer)
        sess = self.session
        m = self.messenger
        hello_meta = {
            "entity": m.entity,
            "session": sess.nonce,
            "in_seq": sess.in_seq,
            "peer_cookie": sess.peer_cookie,
            "lossless": self.lossless,
            "secure": m.secure,
            "compress": [m.compress_algo] if m.compress_algo else [],
        }
        authorizer = None
        if m.auth is not None:
            authorizer = m.auth.build_authorizer(secure=m.secure)
            hello_meta["auth"] = authorizer
        writer.write(encode_frame(CTRL_HELLO, 0, hello_meta))
        await writer.drain()
        tid, _seq, meta_raw, _data, _pcrc = await asyncio.wait_for(
            read_frame(reader), timeout=5.0)
        if tid != CTRL_HELLO:
            writer.close()
            raise ConnectionError(f"expected HELLO, got frame type {tid:#x}")
        meta = json.loads(meta_raw.decode())
        if meta.get("auth_error"):
            # bad credentials are fatal, not retryable
            writer.close()
            self._closed = True
            raise ConnectionError(f"auth rejected: {meta['auth_error']}")
        if authorizer is not None:
            from ..auth.cephx import AuthError
            try:
                key = m.auth.check_reply(
                    authorizer, meta.get("auth_reply"))
            except AuthError as e:
                writer.close()
                self._closed = True
                raise ConnectionError(str(e)) from e
            sess.set_conn_key(key, b"\x01")
            # the secure decision was authenticated by check_reply
            # (mismatch already raised); m.secure == the agreed mode
            sess.secure = m.secure
            # mutual proof: whoever answered holds cluster-side
            # credentials (service key, keyring, or our ticket's
            # session key — all daemon-resident), so frames arriving
            # on this outbound session are from a cluster daemon
            sess.auth_identity = {"entity": meta.get("entity"),
                                  "kind": "service", "caps": ""}
        # compression: the server echoes the chosen algo — accept it
        # only if it is exactly what we offered (a bogus echo must not
        # crash the connect path or select an algo we lack)
        chosen = meta.get("compress")
        if chosen and chosen == m.compress_algo:
            from ..compressor import create
            sess.comp = create(chosen)
            sess.comp_min = m.compress_min
        else:
            sess.comp = None
        self.peer_entity = meta.get("entity")
        cookie = meta.get("cookie")
        if self.lossless and cookie != sess.peer_cookie:
            # New server-side session epoch (restart, prune, or we never
            # saw this session's first reply): its out_seq space starts
            # over at 0, so our dedup window must too, or we would
            # silently drop its first in_seq frames as replays.
            sess.in_seq = 0
            sess.last_acked = 0
            sess.peer_cookie = cookie
        sess.reader, sess.writer = reader, writer
        frames = sess.replay_frames(int(meta.get("in_seq", 0)))
        if frames and m.ledger.enabled:
            m.stats.note_replay(self._peer_label(), len(frames))
        for raw in frames:
            writer.write(sess.wire_prepare(raw))
        await writer.drain()
        self.messenger._spawn_read_loop(self)

    async def _reconnect(self) -> None:
        """Lossless policy: reconnect; the HELLO exchange replays exactly
        the frames the peer is missing (reference session reset/replay)."""
        if not self.lossless or not self.can_reconnect or \
                self.peer_addr is None or self._closed:
            return
        m = self.messenger
        if m.ledger.enabled:
            m.stats.note_reconnect(self._peer_label())
        for attempt in range(5):
            try:
                await asyncio.sleep(0.05 * (attempt + 1))
                self.session.drop_wire()
                await self._connect()
                return
            except (ConnectionError, OSError, asyncio.TimeoutError,
                    asyncio.IncompleteReadError, ValueError) as e:
                self.last_error = str(e)
        self._closed = True

    async def _send_ack(self) -> None:
        sess = self.session
        writer = sess.writer
        if writer is None:
            return
        try:
            sess.last_acked = sess.in_seq
            writer.write(sess.wire_prepare(
                encode_frame(CTRL_ACK, sess.in_seq, {})))
        except (ConnectionError, OSError):
            pass  # peer will learn our in_seq from the next HELLO

    async def _close(self) -> None:
        self._closed = True
        sess = self.session
        if sess.writer is not None:
            try:
                sess.writer.close()
            except Exception:  # noqa: BLE001
                pass
            sess.writer = None
            sess.reader = None

    def close(self) -> None:
        self.messenger._run_soon(self._close())


class Messenger:
    """Owns the reactor; binds servers; creates client connections
    (reference Messenger::create + bind + add_dispatcher_head)."""

    _loops: list[asyncio.AbstractEventLoop] = []
    _loop_threads: list[threading.Thread] = []
    _executor = None
    _next_loop = 0
    _loop_lock = threading.Lock()
    # pool size (reference ms_async_op_threads): loops beyond the core
    # count only add context switches — measured on a 1-core host,
    # 4 loops made the 8-way 128 KiB fan-out *slower* (4.8 vs 4.2 ms).
    # The auto default; conf ms_async_op_threads overrides it through
    # configure_pool() BEFORE the first messenger exists.
    import os as _os
    REACTORS = max(1, min(4, _os.cpu_count() or 1))

    @classmethod
    def configure_pool(cls, reactors) -> None:
        """Startup sizing of the reactor pool (conf
        ms_async_op_threads): applies to the NEXT pool creation — an
        already-running pool keeps its size (pinned loops cannot be
        resized live; the reference reads ms_async_op_threads once at
        start too).  0/None keeps the cpu-count auto size."""
        if reactors:
            n = int(reactors)
            if n > 0:
                cls.REACTORS = n

    def __init__(self, name: str = "client", auth=None,
                 secure: bool = False):
        self.name = name
        # Stable per-instance identity; the session key (reference
        # entity_name_t + nonce in the ProtocolV2 banner).
        self.entity = f"{name}.{uuid.uuid4().hex[:12]}"
        # auth context (auth.CephxAuth) — when set, every accepted
        # connection must present a verifiable authorizer and every
        # outgoing HELLO carries one; secure=True additionally AES-GCM
        # encrypts all frames under the per-connection key
        self.auth = auth
        self.secure = secure
        # on-wire compression opt-in (reference ms_osd_compress_mode);
        # effective only when both endpoints enable it
        self.compress_algo: str | None = None
        self.compress_min = 4096
        self.dispatcher: Dispatcher | None = None
        # fast dispatch (reference ms_fast_dispatch): a predicate
        # selecting messages whose handler is guaranteed non-blocking
        # (no nested synchronous RPC, no long store I/O waits).  Those
        # run INLINE on the reactor, skipping the executor's two
        # context switches per message — the dominant cost of the EC
        # sub-read fan-out on few-core hosts.
        self.fast_dispatch: Callable[[Message], bool] | None = None
        # test hook: drop received messages matching a predicate
        # (message-loss partitions without killing processes)
        self.recv_filter = None
        self.my_addr: tuple[str, int] | None = None
        self._server: asyncio.AbstractServer | None = None
        self._conns: dict[tuple[str, int], Connection] = {}
        self._accepted: list[Connection] = []
        self._sessions: dict[str, Session] = {}
        # fault injection (reference ms_inject_* dev options)
        self.inject_socket_failures = 0   # ~1/N frames resets the socket
        self.inject_delay_prob = 0.0
        self.inject_delay_max = 0.0
        self.injected_failures = 0
        self._inject_rng = random.Random(0xC3B7)
        # conf ms_inject_dispatch_stall: sleep this long in the send
        # path before the wire write (a stalled dispatch for the
        # slow-op blame gates)
        self.inject_dispatch_stall = 0.0
        # blocking-bridge deadline (conf ms_sync_timeout; was a
        # hardcoded 30 s) — expiries count in msgr_sync_timeouts
        self.sync_timeout = 30.0
        # wire-plane flight recorder (msg/msgr_ledger.py): the
        # process ledger plus this messenger's own counter slice
        self.ledger = MsgrLedger.host_instance()
        self.stats = self.ledger.register_messenger(self.entity)
        # pin this messenger to one loop of the pool for its lifetime
        self._loop = self._pick_loop()

    # -- reactor pool -------------------------------------------------------

    @classmethod
    def _ensure_pool(cls) -> list[asyncio.AbstractEventLoop]:
        with cls._loop_lock:
            if not cls._loops or \
                    not all(t.is_alive() for t in cls._loop_threads):
                cls._loops, cls._loop_threads = [], []
                # Wide dispatcher pool, SHARED across loops: handlers may
                # block on nested RPC round-trips (shard stat/attr fetches
                # inside a client-op handler), so the pool must exceed the
                # plausible nesting across all in-process daemons
                # (single-host test clusters share this pool).
                from concurrent.futures import ThreadPoolExecutor
                cls._executor = ThreadPoolExecutor(
                    max_workers=96, thread_name_prefix="msgr-dispatch")
                for i in range(cls.REACTORS):
                    loop = asyncio.new_event_loop()
                    loop.set_default_executor(cls._executor)

                    def run(loop=loop):
                        asyncio.set_event_loop(loop)
                        loop.run_forever()

                    t = threading.Thread(target=run,
                                         name=f"msgr-reactor-{i}",
                                         daemon=True)
                    t.start()
                    cls._loops.append(loop)
                    cls._loop_threads.append(t)
                # arm the per-reactor loop-lag probe on the fresh pool
                # (wire-plane flight recorder, msg/msgr_ledger.py)
                msgr_ledger().attach_reactors(cls._loops)
            return cls._loops

    @classmethod
    def _pick_loop(cls) -> asyncio.AbstractEventLoop:
        loops = cls._ensure_pool()
        with cls._loop_lock:
            cls._next_loop += 1
            return loops[cls._next_loop % len(loops)]

    @classmethod
    def dispatch_executor(cls):
        """The shared dispatcher thread pool — for handlers that must
        hand work OFF the reactor (blocking pipeline continuations)."""
        cls._ensure_pool()
        return cls._executor

    @classmethod
    def submit_dispatch(cls, fn, *args) -> None:
        """dispatch_executor().submit with the exception fence the
        bare Future lacks: a pipeline continuation that raises must
        surface a traceback, not die unobserved in the Future.  Queue
        wait and run time land in the wire-plane ledger's
        lat_msgr_qwait / lat_msgr_dispatch histograms."""
        led = msgr_ledger()
        t_sub = led.dispatch_submit() if led.enabled else None

        def run():
            t_run = led.dispatch_run(t_sub) if t_sub is not None \
                else None
            try:
                fn(*args)
            except Exception:  # noqa: BLE001
                import traceback
                traceback.print_exc()
            finally:
                if t_run is not None:
                    led.dispatch_done(t_run)

        cls.dispatch_executor().submit(run)

    def _run_soon(self, coro) -> None:
        # self._loop is pinned for the messenger's lifetime: pool
        # loops never stop while healthy, and run_coroutine_threadsafe
        # queues correctly even on a loop that has not entered
        # run_forever yet — re-picking here could split one session's
        # coroutines (and its asyncio.Lock) across two loops
        asyncio.run_coroutine_threadsafe(coro, self._loop)

    def send_batch(self, pairs) -> None:
        """Send [(conn, msg), ...] with ONE loop signal for the whole
        batch — a k-way shard fan-out otherwise pays a task creation +
        loop wakeup per message.  Each CONNECTION still gets its own
        task (messages to one peer stay ordered, but a dead/
        unreachable peer must not head-of-line-block the other
        shards' sends behind its reconnect timeouts)."""

        async def _send_group(conn, msgs):
            for m in msgs:
                try:
                    await conn._send(m)
                except Exception:  # noqa: BLE001 - per-conn isolation
                    import traceback
                    traceback.print_exc()

        async def _all():
            groups: dict[int, tuple] = {}
            for conn, msg in pairs:
                groups.setdefault(id(conn), (conn, []))[1].append(msg)
            for conn, msgs in groups.values():
                asyncio.ensure_future(_send_group(conn, msgs))

        self._run_soon(_all())

    def _run_sync(self, coro, timeout: float | None = None):
        """Blocking bridge into the reactor.  The default deadline is
        conf ms_sync_timeout (was a hardcoded 30 s); an expiry counts
        in the ledger (msgr_sync_timeouts) before surfacing — the
        caller still needs the exception, but the event is no longer
        invisible."""
        fut = asyncio.run_coroutine_threadsafe(coro, self._loop)
        try:
            return fut.result(self.sync_timeout if timeout is None
                              else timeout)
        except _FuturesTimeout:
            if self.ledger.enabled:
                self.stats.note_sync_timeout()
            raise

    # -- server side --------------------------------------------------------

    def add_dispatcher(self, dispatcher: Dispatcher) -> None:
        self.dispatcher = dispatcher

    def bind(self, addr: tuple[str, int]) -> tuple[str, int]:
        """Bind and start accepting; port 0 picks a free port."""

        async def _bind():
            server = await asyncio.start_server(
                self._on_accept, addr[0], addr[1], limit=4 << 20)
            return server

        self._server = self._run_sync(_bind())
        sock = self._server.sockets[0]
        self.my_addr = sock.getsockname()[:2]
        return self.my_addr

    async def _on_accept(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        """Accept = read the peer's HELLO, bind/resume its Session, reply
        with our in_seq, replay anything it is missing."""
        try:
            tid, _seq, meta_raw, _data, _pcrc = await asyncio.wait_for(
                read_frame(reader), timeout=10.0)
            if tid != CTRL_HELLO:
                writer.close()
                return
            meta = json.loads(meta_raw.decode())
        except (asyncio.IncompleteReadError, asyncio.TimeoutError,
                ConnectionError, OSError, ValueError):
            writer.close()
            return
        entity = str(meta.get("entity", ""))
        lossless = bool(meta.get("lossless", True))
        nonce = str(meta.get("session", ""))
        claimed_entity = entity
        # authorizer gate (reference AuthAuthorizeHandler at accept):
        # with an auth context, no verifiable authorizer -> no session
        auth_identity = None
        conn_key = None
        auth_reply = None
        if self.auth is not None:
            from ..auth.cephx import AuthError
            try:
                auth_identity, conn_key, auth_reply = \
                    self.auth.verify_authorizer(meta.get("auth"),
                                                server_secure=self.secure)
            except AuthError as e:
                try:
                    writer.write(encode_frame(CTRL_HELLO, 0, {
                        "entity": self.entity, "auth_error": str(e)}))
                    await writer.drain()
                except (ConnectionError, OSError):
                    pass
                writer.close()
                return
            # Session resumption is a capability of the AUTHENTICATED
            # identity: a peer holding different credentials must not
            # resume (and thereby hijack + drain the replay window of)
            # another daemon's session just by claiming its entity
            # string from a sniffed HELLO.
            entity = f"{auth_identity['entity']}/{claimed_entity}"
        self._prune_sessions()
        if lossless:
            sess = self._sessions.get(entity)
            # a broken session (unacked overflow) must not be resumed:
            # its _send path drops frames, so hand out a fresh one — the
            # new cookie makes the client reset its dedup window
            if sess is None or sess.nonce != nonce or sess.broken:
                sess = Session(lossless=True, nonce=nonce)
                self._sessions[entity] = sess
        else:
            sess = Session(lossless=False, nonce=nonce)
        sess.drop_wire()          # supersede any stale stream
        _grow_socket_buffers(writer)
        sess.reader, sess.writer = reader, writer
        sess.auth_identity = auth_identity
        sess.set_conn_key(conn_key, b"\x02")
        sess.secure = bool(auth_identity and
                           auth_identity.get("secure"))
        # compression: accept the client's offer when we opt in too
        offered = meta.get("compress") or []
        chosen = None
        if self.compress_algo and offered:
            from ..compressor import available, create
            for algo in offered:
                if algo in available():
                    chosen = algo
                    sess.comp = create(algo)
                    sess.comp_min = self.compress_min
                    break
        if chosen is None:
            sess.comp = None
        conn = Connection(self, None, lossless=lossless, session=sess,
                          can_reconnect=False)
        conn.peer_entity = claimed_entity
        peer = writer.get_extra_info("peername")
        conn.peer_addr = peer[:2] if peer else None
        # one facade per session: drop superseded ones from the registry
        self._accepted = [c for c in self._accepted
                          if c.session is not sess]
        self._accepted.append(conn)
        try:
            reply_meta = {"entity": self.entity, "in_seq": sess.in_seq,
                          "cookie": sess.local_cookie,
                          "secure": sess.secure,
                          "compress": chosen}
            if auth_reply is not None:
                reply_meta["auth_reply"] = auth_reply
            writer.write(encode_frame(CTRL_HELLO, 0, reply_meta))
            # The client's in_seq only counts frames of THIS session
            # epoch if it has seen our cookie; a stale epoch's in_seq
            # must trim nothing or undelivered replies would be lost.
            peer_in = int(meta.get("in_seq", 0)) \
                if meta.get("peer_cookie") == sess.local_cookie else 0
            frames = sess.replay_frames(peer_in)
            if frames and self.ledger.enabled:
                self.stats.note_replay(conn._peer_label(), len(frames))
            for raw in frames:
                writer.write(sess.wire_prepare(raw))
            await writer.drain()
        except (ConnectionError, OSError):
            writer.close()
            return
        self._spawn_read_loop(conn)

    def _prune_sessions(self, max_down: float = 600.0) -> None:
        """Reap server-side sessions whose wire has been down for a long
        time (their entities are per-process uuids, so a dead peer never
        comes back) and accepted-conn facades whose wire was superseded."""
        import time
        now = time.monotonic()
        for entity, sess in list(self._sessions.items()):
            if sess.writer is None and sess.down_since is not None and \
                    now - sess.down_since > max_down:
                del self._sessions[entity]
        self._accepted = [c for c in self._accepted
                          if c.session.reader is not None]

    # -- client side --------------------------------------------------------

    def connect(self, addr: tuple[str, int],
                lossless: bool = True) -> Connection:
        """Get-or-create the client connection for addr.  Lossless and
        lossy conns are separate sessions (the reference runs heartbeats
        on dedicated lossy messengers for the same reason: ping retention
        and replay make no sense)."""
        key = (addr[0], addr[1], lossless)
        conn = self._conns.get(key)
        if conn is None or conn._closed:
            # Carry the old session into the replacement connection: the
            # server resumes sessions by entity, so a fresh seq space
            # would collide with its dedup window (frames silently
            # dropped as "already seen").  A broken session (unacked
            # overflow) starts over with a new nonce.
            old = conn
            sess = None
            if old is not None and not old.session.broken:
                sess = old.session
            conn = Connection(self, (addr[0], addr[1]), lossless=lossless,
                              session=sess)
            self._conns[key] = conn
        return conn

    # -- read loop ----------------------------------------------------------

    def _spawn_read_loop(self, conn: Connection) -> None:
        self._run_soon(self._read_loop(conn, conn.session.reader))

    async def _read_loop(self, conn: Connection,
                         reader: asyncio.StreamReader) -> None:
        sess = conn.session
        try:
            while not conn._closed and reader is sess.reader:
                tid, seq, meta_raw, data, pcrc = await read_frame(reader)
                if reader is not sess.reader:
                    # epoch reset while we were blocked in read_frame: a
                    # buffered old-epoch frame must not touch the fresh
                    # epoch's seq window (in_seq poisoning)
                    break
                if tid == CTRL_ENC:
                    if sess.conn_key is None:
                        raise ValueError("encrypted frame on plain session")
                    inner = sess.wire_decrypt(data)  # raises on tamper
                    tid, seq, meta_raw, data, pcrc = _parse_raw(inner)
                elif sess.secure and sess.conn_key is not None and \
                        tid != CTRL_HELLO:
                    # plaintext data frame on a secure session: reject
                    raise ValueError("plaintext frame on secure session")
                if tid == CTRL_COMP:
                    algo = json.loads(meta_raw.decode()).get("a", "")
                    inner = sess.wire_decompress(algo, data)
                    tid, seq, meta_raw, data, pcrc = _parse_raw(inner)
                if tid == CTRL_ACK:
                    sess.trim_acked(seq)
                    continue
                if tid == CTRL_HELLO:
                    continue  # late/duplicate hello: ignore
                if conn.lossless and seq <= sess.in_seq:
                    # replayed frame we already delivered: re-ack, drop
                    # (reference ProtocolV2 in_seq dedup on session resume)
                    await conn._send_ack()
                    continue
                msg = Message.decode(tid, seq, meta_raw, data, pcrc)
                # ingest stamp for op tracking (reference
                # Message::recv_stamp set by the messenger): dispatch
                # latency is attributable even when the executor queues
                msg.recv_stamp = time.time()
                if self.ledger.enabled:
                    self.stats.note_recv(
                        conn._peer_label(), type(msg).__name__,
                        Message.HEADER_SIZE + len(meta_raw) +
                        len(data) + 4)
                sess.in_seq = seq
                if self.recv_filter is not None and \
                        self.recv_filter(msg):
                    # injected receive-side loss (partition testing):
                    # the frame is consumed and acked but never reaches
                    # the dispatcher — indistinguishable, to the
                    # protocol above, from a network that ate it
                    continue
                if self.dispatcher is not None:
                    if self.fast_dispatch is not None and \
                            self.fast_dispatch(msg):
                        # inline on the reactor (handler is declared
                        # non-blocking); fence exceptions so a handler
                        # bug cannot kill the read loop
                        try:
                            self.dispatcher(conn, msg)
                        except Exception:  # noqa: BLE001
                            import traceback
                            traceback.print_exc()
                    else:
                        # dispatch off-reactor so handlers may send
                        # synchronously / block on nested RPCs; the
                        # ledger times queue wait + handler run so
                        # "dispatcher slow" is attributable
                        led = self.ledger
                        if led.enabled:
                            t_sub = led.dispatch_submit()

                            def _timed(d=self.dispatcher, c=conn,
                                       mm=msg, t=t_sub):
                                t_run = led.dispatch_run(t)
                                try:
                                    d(c, mm)
                                finally:
                                    led.dispatch_done(t_run)

                            await asyncio.get_event_loop() \
                                .run_in_executor(None, _timed)
                        else:
                            await asyncio.get_event_loop() \
                                .run_in_executor(None, self.dispatcher,
                                                 conn, msg)
                # Batch acks: piggyback-style — ack when the pipe goes
                # idle or every 64 frames, not per message (reference
                # ProtocolV2 acks lazily from the write path too).
                buffered = getattr(reader, "_buffer", None)
                if (buffered is not None and len(buffered) == 0) or \
                        sess.in_seq - sess.last_acked >= 64:
                    await conn._send_ack()
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            # Wire died under us.  Mark the wire down (starts the prune
            # clock for accepted sessions); client conns re-dial so
            # pending server replies (in the peer's unacked window) flow.
            if not conn.can_reconnect:
                if sess.reader is reader:
                    sess.drop_wire()
            elif not conn._closed:
                async with sess.send_lock:
                    if sess.reader is reader or sess.reader is None:
                        sess.drop_wire()
                        await conn._reconnect()
        except ValueError as e:
            # crc/corruption: abort this wire; the session (seq window)
            # survives, so a reconnect replays cleanly (reference
            # ProtocolV2 treats a bad crc as a session-preserving reset)
            conn.last_error = str(e)
            if sess.reader is reader:
                sess.drop_wire()
            if conn.can_reconnect and not conn._closed:
                async with sess.send_lock:
                    if sess.writer is None:
                        await conn._reconnect()

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self) -> None:
        async def _stop():
            if self._server is not None:
                self._server.close()
            for c in list(self._conns.values()) + self._accepted:
                await c._close()
            self._sessions.clear()
            self._accepted.clear()
            self._conns.clear()
        try:
            self._run_sync(_stop(), timeout=5)
        except Exception:  # noqa: BLE001
            pass
