"""Async messenger: reactor, connections, dispatch.

Re-expresses the reference's AsyncMessenger stack (src/msg/async/
AsyncMessenger.cc, AsyncConnection.cc, Stack.h Worker reactors): an
event loop owns all sockets; daemons bind an address and register a
dispatcher; clients connect lazily and get ordered, crc-verified message
delivery with automatic reconnect + resend for lossless policies
(reference Policy.h lossless_peer; ProtocolV2 session replay is
approximated by a bounded unacked-resend queue).

Idiomatic shift: one asyncio event loop in a dedicated thread replaces
N epoll worker threads — Python's reactor economics differ from C++'s,
and the data plane's heavy bytes ride numpy buffers either way.  The
public surface (Messenger/Connection/Dispatcher) keeps the reference's
shape so daemon code reads the same.
"""

from __future__ import annotations

import asyncio
import struct
import threading
from typing import Callable

from .message import Message

Dispatcher = Callable[["Connection", Message], None]


class Connection:
    """One peer session (reference AsyncConnection)."""

    def __init__(self, messenger: "Messenger",
                 peer_addr: tuple[str, int] | None,
                 reader: asyncio.StreamReader | None = None,
                 writer: asyncio.StreamWriter | None = None,
                 lossless: bool = True):
        self.messenger = messenger
        self.peer_addr = peer_addr
        self._reader = reader
        self._writer = writer
        self.lossless = lossless
        self._out_seq = 0
        self._unacked: list[tuple[int, bytes]] = []
        self._send_lock = asyncio.Lock()
        self._closed = False
        self.last_error: str | None = None

    def is_connected(self) -> bool:
        return self._writer is not None and not self._closed

    # -- sending (thread-safe entry) ---------------------------------------

    def send_message(self, msg: Message) -> None:
        self.messenger._run_soon(self._send(msg))

    async def _send(self, msg: Message) -> None:
        async with self._send_lock:
            self._out_seq += 1
            raw = msg.encode(self._out_seq)
            if self.lossless:
                self._unacked.append((self._out_seq, raw))
                if len(self._unacked) > 4096:
                    self._unacked.pop(0)
            try:
                if self._writer is None:
                    await self._connect()
                self._writer.write(raw)
                await self._writer.drain()
            except (ConnectionError, OSError) as e:
                self.last_error = str(e)
                await self._reconnect_and_replay()

    async def _connect(self) -> None:
        assert self.peer_addr is not None
        self._reader, self._writer = await asyncio.open_connection(
            *self.peer_addr)
        self.messenger._spawn_read_loop(self)

    async def _reconnect_and_replay(self) -> None:
        """Lossless policy: reconnect and resend unacked messages
        (reference session reset/replay)."""
        if not self.lossless or self.peer_addr is None or self._closed:
            return
        for attempt in range(5):
            try:
                await asyncio.sleep(0.05 * (attempt + 1))
                self._reader = self._writer = None
                await self._connect()
                for _, raw in self._unacked:
                    self._writer.write(raw)
                await self._writer.drain()
                return
            except (ConnectionError, OSError) as e:
                self.last_error = str(e)
        self._closed = True

    async def _close(self) -> None:
        self._closed = True
        if self._writer is not None:
            try:
                self._writer.close()
            except Exception:  # noqa: BLE001
                pass
            self._writer = None

    def close(self) -> None:
        self.messenger._run_soon(self._close())


class Messenger:
    """Owns the reactor; binds servers; creates client connections
    (reference Messenger::create + bind + add_dispatcher_head)."""

    _loop: asyncio.AbstractEventLoop | None = None
    _loop_thread: threading.Thread | None = None
    _loop_lock = threading.Lock()

    def __init__(self, name: str = "client"):
        self.name = name
        self.dispatcher: Dispatcher | None = None
        self.my_addr: tuple[str, int] | None = None
        self._server: asyncio.AbstractServer | None = None
        self._conns: dict[tuple[str, int], Connection] = {}
        self._accepted: list[Connection] = []
        self._ensure_loop()

    # -- shared reactor -----------------------------------------------------

    @classmethod
    def _ensure_loop(cls) -> asyncio.AbstractEventLoop:
        with cls._loop_lock:
            if cls._loop is None or not cls._loop_thread.is_alive():
                loop = asyncio.new_event_loop()
                # Wide dispatcher pool: handlers may block on nested RPC
                # round-trips (shard stat/attr fetches inside a client-op
                # handler), so the pool must exceed the plausible nesting
                # across all in-process daemons (single-host test clusters
                # share this reactor).
                from concurrent.futures import ThreadPoolExecutor
                loop.set_default_executor(
                    ThreadPoolExecutor(max_workers=64,
                                       thread_name_prefix="msgr-dispatch"))

                def run():
                    asyncio.set_event_loop(loop)
                    loop.run_forever()

                t = threading.Thread(target=run, name="msgr-reactor",
                                     daemon=True)
                t.start()
                cls._loop = loop
                cls._loop_thread = t
            return cls._loop

    def _run_soon(self, coro) -> None:
        asyncio.run_coroutine_threadsafe(coro, self._ensure_loop())

    def _run_sync(self, coro, timeout: float = 30.0):
        fut = asyncio.run_coroutine_threadsafe(coro, self._ensure_loop())
        return fut.result(timeout)

    # -- server side --------------------------------------------------------

    def add_dispatcher(self, dispatcher: Dispatcher) -> None:
        self.dispatcher = dispatcher

    def bind(self, addr: tuple[str, int]) -> tuple[str, int]:
        """Bind and start accepting; port 0 picks a free port."""

        async def _bind():
            server = await asyncio.start_server(
                self._on_accept, addr[0], addr[1])
            return server

        self._server = self._run_sync(_bind())
        sock = self._server.sockets[0]
        self.my_addr = sock.getsockname()[:2]
        return self.my_addr

    async def _on_accept(self, reader: asyncio.StreamReader,
                         writer: asyncio.StreamWriter) -> None:
        conn = Connection(self, None, reader, writer)
        peer = writer.get_extra_info("peername")
        conn.peer_addr = peer[:2] if peer else None
        self._accepted.append(conn)
        self._spawn_read_loop(conn)

    # -- client side --------------------------------------------------------

    def connect(self, addr: tuple[str, int],
                lossless: bool = True) -> Connection:
        addr = (addr[0], addr[1])
        conn = self._conns.get(addr)
        if conn is None or conn._closed:
            conn = Connection(self, addr, lossless=lossless)
            self._conns[addr] = conn
        return conn

    # -- read loop ----------------------------------------------------------

    def _spawn_read_loop(self, conn: Connection) -> None:
        self._run_soon(self._read_loop(conn))

    async def _read_loop(self, conn: Connection) -> None:
        reader = conn._reader
        try:
            while not conn._closed:
                head = await reader.readexactly(Message.HEADER_SIZE)
                tid, seq, meta_len, data_len = Message.parse_header(head)
                meta_raw = await reader.readexactly(meta_len)
                data = await reader.readexactly(data_len)
                (pcrc,) = struct.unpack("<I", await reader.readexactly(4))
                msg = Message.decode(tid, seq, meta_raw, data, pcrc)
                if self.dispatcher is not None:
                    # dispatch off-reactor so handlers may send synchronously
                    await asyncio.get_event_loop().run_in_executor(
                        None, self.dispatcher, conn, msg)
        except (asyncio.IncompleteReadError, ConnectionError, OSError):
            pass
        except ValueError as e:  # crc/corruption: drop session
            conn.last_error = str(e)

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self) -> None:
        async def _stop():
            if self._server is not None:
                self._server.close()
            for c in list(self._conns.values()) + self._accepted:
                await c._close()
        try:
            self._run_sync(_stop(), timeout=5)
        except Exception:  # noqa: BLE001
            pass
