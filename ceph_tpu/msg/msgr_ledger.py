"""Wire-plane flight recorder (docs/TRACING.md "Wire plane").

The device plane attributes every launch (ops/profiler.py), the
control plane every PG transition (osd/pg_ledger.py); this is the
same discipline applied to the layer that connects them — the async
messenger.  PR 14's notes report the shared reactor pool's RT
intermittently exceeding 10 s right after boot with no counter that
names why; ROADMAP item 4 (recovery fan-out at 128-256 OSDs) needs
per-peer wire accounting to be diagnosable at all.  The reference
instruments exactly this layer (AsyncMessenger worker + DispatchQueue
perf counters, Throttle accounting); this module re-expresses that
surface on the asyncio reactor pool:

* **Per-connection ledger** — every frame sent/received lands in a
  bounded per-peer table (oldest peer evicted, ring-style): msgs and
  bytes in/out by message TYPE (bounded by-type dicts, overflow under
  "other"), send-queue depth high-water (len(sess.unacked) at send),
  reconnects, replayed frames, compressed/encrypted wire bytes.
  Surfaced by the `messenger status` / `conn profile` asoks on every
  daemon (tools/ceph_cli.py daemon mode).

* **Reactor health** — a per-reactor loop-lag probe: a callback
  rescheduling itself every ms_reactor_lag_interval seconds measures
  scheduled-vs-actual fire time (the OSD heartbeat tick-lag detector's
  rule: the gauge moves every tick, an EVENT counts only when the
  probe fired a FULL extra interval late).  Lag samples feed
  `lat_msgr_reactor_lag`; events enter a bounded window that ships
  monward.  The dispatch executor is timed the same way: submit->run
  wait in `lat_msgr_qwait`, handler run in `lat_msgr_dispatch`, both
  on the shared DEFAULT_LAT_BUCKETS axis so `dump_latencies`, the
  exporter's percentile gauges and the load harness pick them up
  unchanged — "reactor starved" vs "dispatcher slow" vs "peer slow"
  becomes attributable.

* **Trace stitching** — the send path stamps `msgr_send(peer)` onto
  tracked ops riding a frame (msg._top), and the OSD ingest path
  stamps `msgr_recv_lag`, so slow-op blame can say "5.1 s in the send
  queue to osd.7" the way it already says "waited on first-compile of
  bucket X" (Dapper-style stitching, Sigelman et al. 2010; tail
  blame, Dean & Barroso 2013).

* **Aggregation upward** — pgstats_block() rides MPGStats to the mon
  (MSGR_REACTOR_LAG health warning naming the worst daemon/reactor),
  bench_summary() embeds in cluster_bench --scale rows beside
  recovery_blame, and the per-messenger counter set registers into
  each daemon's perf collection for ceph_tpu_msgr_* exporter gauges.

* **Always on, null when off** — enabled by default (conf ms_ledger);
  disabled, every entry point returns after ONE attribute check and
  allocates nothing (the NULL_TRACKED rule).  On-path overhead is
  gated <= 2% in bench.py --smoke like the device/control planes.

Perf-owner rule: the process-wide ledger's perf set (reactor lag +
dispatch histograms — the reactors and executor are shared by every
in-process daemon) registers into exactly ONE daemon's collection via
the `_perf_registered` attribute check (the DeviceProfiler pattern);
that daemon ships the monward block.  Each Messenger's OWN counter
set (MsgrStats) is per-instance, so every daemon exports its own wire
totals without n_daemons-fold inflation.
"""

from __future__ import annotations

import collections
import threading
import time

from ..common.perf_counters import PerfCountersBuilder

# per-peer by-type maps are bounded: past this many distinct message
# type names, further types count under "other" (a fuzzer or a newer
# peer's unknown types must not grow the table)
TYPE_CAP = 32
OTHER_TYPE = "other"


def _build_ledger_perf(name: str = "msgr_ledger"):
    """The process-shared set: reactor + dispatch-executor health
    (registered into ONE daemon per process — see module doc)."""
    return (PerfCountersBuilder(name)
            .add_u64_counter("msgr_dispatches",
                             "handler runs completed through the "
                             "shared dispatch executor")
            .add_u64_counter("msgr_reactor_lag_events",
                             "reactor lag probes that fired a FULL "
                             "extra interval late (the tick-lag rule)")
            .add_gauge("msgr_dispatch_queued",
                       "dispatch-executor submissions currently "
                       "queued or running")
            .add_gauge("msgr_dispatch_queued_hwm",
                       "high-water of msgr_dispatch_queued")
            .add_gauge("msgr_reactor_lag_worst",
                       "worst last-probe loop lag across reactors "
                       "(seconds)")
            .add_histogram("lat_msgr_reactor_lag",
                           "per-probe reactor loop lag "
                           "(scheduled vs actual fire time)")
            .add_histogram("lat_msgr_qwait",
                           "dispatch-executor queue wait "
                           "(submit -> handler start)")
            .add_histogram("lat_msgr_dispatch",
                           "dispatch handler run time")
            .create_perf_counters())


def _build_msgr_perf(name: str = "msgr"):
    """One Messenger instance's counter set — registered into ITS
    daemon's collection (per-daemon ceph_tpu_msgr_* exporter gauges)."""
    return (PerfCountersBuilder(name)
            .add_u64_counter("msgr_msgs_out", "messages sent")
            .add_u64_counter("msgr_msgs_in", "messages received")
            .add_u64_counter("msgr_bytes_out", "frame bytes sent")
            .add_u64_counter("msgr_bytes_in", "frame bytes received")
            .add_u64_counter("msgr_reconnects",
                             "reconnect rounds entered after a wire "
                             "fault")
            .add_u64_counter("msgr_replay_frames",
                             "retained frames replayed to a resumed "
                             "session")
            .add_u64_counter("msgr_sync_timeouts",
                             "_run_sync bridge calls that expired "
                             "(conf ms_sync_timeout)")
            .add_u64_counter("msgr_compress_bytes",
                             "wire bytes written through the "
                             "compression wrap")
            .add_u64_counter("msgr_encrypt_bytes",
                             "wire bytes written through the AES-GCM "
                             "wrap")
            .add_gauge("msgr_sendq_hwm",
                       "send-queue (unacked window) depth high-water "
                       "across peers")
            .create_perf_counters())


def _type_inc(table: dict, mtype: str, by: int = 1) -> None:
    n = table.get(mtype)
    if n is None and len(table) >= TYPE_CAP:
        mtype = OTHER_TYPE
        n = table.get(mtype)
    table[mtype] = (n or 0) + by


class ConnStats:
    """One peer's wire accounting (bounded table entry, see module
    doc).  Mutated with plain attribute updates under the GIL, like
    perf counters — the hot-path writers are single updates."""

    __slots__ = ("peer", "msgs_out", "msgs_in", "bytes_out", "bytes_in",
                 "out_types", "in_types", "sendq_hwm", "reconnects",
                 "replay_frames", "compress_bytes", "encrypt_bytes",
                 "first_ts", "last_ts")

    def __init__(self, peer: str):
        self.peer = peer
        self.msgs_out = 0
        self.msgs_in = 0
        self.bytes_out = 0
        self.bytes_in = 0
        self.out_types: dict[str, int] = {}
        self.in_types: dict[str, int] = {}
        self.sendq_hwm = 0
        self.reconnects = 0
        self.replay_frames = 0
        self.compress_bytes = 0
        self.encrypt_bytes = 0
        self.first_ts = time.time()
        self.last_ts = self.first_ts

    def to_dict(self) -> dict:
        return {
            "peer": self.peer,
            "msgs_out": self.msgs_out,
            "msgs_in": self.msgs_in,
            "bytes_out": self.bytes_out,
            "bytes_in": self.bytes_in,
            "out_types": dict(self.out_types),
            "in_types": dict(self.in_types),
            "sendq_hwm": self.sendq_hwm,
            "reconnects": self.reconnects,
            "replay_frames": self.replay_frames,
            "compress_bytes": self.compress_bytes,
            "encrypt_bytes": self.encrypt_bytes,
            "first_ts": round(self.first_ts, 3),
            "last_ts": round(self.last_ts, 3),
        }


class MsgrStats:
    """One Messenger's ledger slice: its own perf set plus the bounded
    per-peer table.  Every entry point is called BEHIND the ledger's
    enabled check (the messenger hooks gate on it), so there is no
    second gate here."""

    def __init__(self, name: str, ledger: "MsgrLedger", perf=None,
                 peer_cap: int = 256):
        self.name = name
        self.ledger = ledger
        self.perf = perf if perf is not None else _build_msgr_perf()
        self.peer_cap = max(1, int(peer_cap))
        self._lock = threading.Lock()
        # insertion-ordered, oldest evicted past peer_cap: the bounded
        # per-peer "ring" (a churny client swarm must not grow it)
        self._peers: collections.OrderedDict[str, ConnStats] = \
            collections.OrderedDict()
        self.sendq_hwm = 0
        self.sync_timeouts = 0

    def _peer(self, key: str) -> ConnStats:
        p = self._peers.get(key)
        if p is None:
            with self._lock:
                p = self._peers.get(key)
                if p is None:
                    p = ConnStats(key)
                    self._peers[key] = p
                    while len(self._peers) > self.peer_cap:
                        self._peers.popitem(last=False)
        return p

    # -- hot-path entry points ----------------------------------------------

    def note_send(self, peer: str, mtype: str, nbytes: int,
                  sendq_depth: int) -> None:
        p = self._peer(peer)
        p.msgs_out += 1
        p.bytes_out += nbytes
        _type_inc(p.out_types, mtype)
        p.last_ts = time.time()
        if sendq_depth > p.sendq_hwm:
            p.sendq_hwm = sendq_depth
            if sendq_depth > self.sendq_hwm:
                self.sendq_hwm = sendq_depth
                self.perf.set("msgr_sendq_hwm", sendq_depth)
        self.perf.inc("msgr_msgs_out")
        self.perf.inc("msgr_bytes_out", nbytes)

    def note_recv(self, peer: str, mtype: str, nbytes: int) -> None:
        p = self._peer(peer)
        p.msgs_in += 1
        p.bytes_in += nbytes
        _type_inc(p.in_types, mtype)
        p.last_ts = time.time()
        self.perf.inc("msgr_msgs_in")
        self.perf.inc("msgr_bytes_in", nbytes)

    def note_wrapped(self, peer: str, nbytes: int, compressed: bool,
                     encrypted: bool) -> None:
        p = self._peer(peer)
        if compressed:
            p.compress_bytes += nbytes
            self.perf.inc("msgr_compress_bytes", nbytes)
        if encrypted:
            p.encrypt_bytes += nbytes
            self.perf.inc("msgr_encrypt_bytes", nbytes)

    def note_reconnect(self, peer: str) -> None:
        p = self._peer(peer)
        p.reconnects += 1
        p.last_ts = time.time()
        self.perf.inc("msgr_reconnects")

    def note_replay(self, peer: str, frames: int) -> None:
        p = self._peer(peer)
        p.replay_frames += frames
        p.last_ts = time.time()
        self.perf.inc("msgr_replay_frames", frames)

    def note_sync_timeout(self) -> None:
        self.sync_timeouts += 1
        self.perf.inc("msgr_sync_timeouts")

    # -- surfaces ------------------------------------------------------------

    def totals(self) -> dict:
        d = self.perf.dump()
        return {
            "msgs_out": d["msgr_msgs_out"],
            "msgs_in": d["msgr_msgs_in"],
            "bytes_out": d["msgr_bytes_out"],
            "bytes_in": d["msgr_bytes_in"],
            "reconnects": d["msgr_reconnects"],
            "replay_frames": d["msgr_replay_frames"],
            "sync_timeouts": d["msgr_sync_timeouts"],
            "compress_bytes": d["msgr_compress_bytes"],
            "encrypt_bytes": d["msgr_encrypt_bytes"],
            "sendq_hwm": self.sendq_hwm,
            "peers": len(self._peers),
        }

    def conn_rows(self) -> list[dict]:
        """Per-peer rows, busiest (bytes out+in) first."""
        with self._lock:
            peers = list(self._peers.values())
        rows = [p.to_dict() for p in peers]
        rows.sort(key=lambda r: -(r["bytes_out"] + r["bytes_in"]))
        return rows

    def set_peer_cap(self, cap: int) -> None:
        self.peer_cap = max(1, int(cap))
        with self._lock:
            while len(self._peers) > self.peer_cap:
                self._peers.popitem(last=False)


class MsgrLedger:
    """Per-process wire-plane ledger (module doc): owns the shared
    reactor/dispatch health state and the registry of per-messenger
    MsgrStats slices."""

    _host: "MsgrLedger | None" = None
    _host_lock = threading.Lock()
    # registered messengers kept (short-lived CLI clients churn; the
    # eviction only drops the LEDGER's reference — the messenger keeps
    # its own stats object working)
    MESSENGER_CAP = 128

    def __init__(self, perf=None, enabled: bool = True,
                 peer_cap: int = 256, probe_interval: float = 0.25,
                 warn_s: float = 1.0, window_s: float = 60.0):
        self.enabled = enabled
        self.peer_cap = max(1, int(peer_cap))
        self.probe_interval = float(probe_interval)
        # monward threshold (conf ms_reactor_lag_warn_s) rides the
        # report so the mon needs no config (the COMPILE_STORM rule)
        self.warn_s = float(warn_s)
        self.window_s = float(window_s)
        self.perf = perf if perf is not None else _build_ledger_perf()
        self._lock = threading.Lock()
        self._messengers: collections.OrderedDict[str, MsgrStats] = \
            collections.OrderedDict()
        # reactor probe state: idx -> (wall ts, last lag); lag events
        # (ts, reactor, lag) in a bounded window deque
        self._reactor_lag: dict[int, tuple[float, float]] = {}
        self._lag_events: collections.deque = \
            collections.deque(maxlen=512)
        self.lag_events_total = 0
        # per-loop probe ownership tokens: re-attaching to a loop (or a
        # recreated pool) replaces the token, so the superseded probe
        # chain dies on its next fire instead of double-counting
        self._probe_tokens: dict[int, object] = {}
        self._dispatch_pending = 0
        self._dispatch_hwm = 0
        self.dispatches_total = 0
        self.created_at = time.time()

    # -- host singleton ------------------------------------------------------

    @classmethod
    def host_instance(cls) -> "MsgrLedger":
        with cls._host_lock:
            if cls._host is None:
                cls._host = cls()
            return cls._host

    @classmethod
    def reset_host(cls) -> None:
        """Tests/benches only: drop the singleton (stats of the old one
        stay readable through any direct references)."""
        with cls._host_lock:
            cls._host = None

    # -- messenger registry --------------------------------------------------

    def register_messenger(self, entity: str,
                           perf=None) -> MsgrStats:
        """A Messenger is born: hand it its ledger slice.  Keyed by
        entity (unique per instance); the registry is bounded."""
        st = MsgrStats(entity, self, perf=perf, peer_cap=self.peer_cap)
        with self._lock:
            self._messengers[entity] = st
            while len(self._messengers) > self.MESSENGER_CAP:
                self._messengers.popitem(last=False)
        return st

    def set_peer_cap(self, cap: int) -> None:
        """conf ms_ledger_peers: applies to registered slices and
        future ones."""
        self.peer_cap = max(1, int(cap))
        with self._lock:
            stats = list(self._messengers.values())
        for st in stats:
            st.set_peer_cap(self.peer_cap)

    # -- dispatch-executor timing (called behind the enabled gate) -----------

    def dispatch_submit(self) -> float:
        """A handler was queued on the shared executor; returns the
        submit stamp the run-side calls thread through."""
        n = self._dispatch_pending + 1
        self._dispatch_pending = n
        self.perf.set("msgr_dispatch_queued", n)
        if n > self._dispatch_hwm:
            self._dispatch_hwm = n
            self.perf.set("msgr_dispatch_queued_hwm", n)
        return time.perf_counter()

    def dispatch_run(self, t_submit: float) -> float:
        """The handler started running: close the queue-wait clock."""
        now = time.perf_counter()
        self.perf.hinc("lat_msgr_qwait", max(0.0, now - t_submit))
        return now

    def dispatch_done(self, t_start: float) -> None:
        self.perf.hinc("lat_msgr_dispatch",
                       max(0.0, time.perf_counter() - t_start))
        self.dispatches_total += 1
        self.perf.inc("msgr_dispatches")
        n = self._dispatch_pending - 1
        self._dispatch_pending = n if n > 0 else 0
        self.perf.set("msgr_dispatch_queued", self._dispatch_pending)

    # -- reactor lag probe ---------------------------------------------------

    def attach_reactors(self, loops, interval: float | None = None
                        ) -> None:
        """Arm the self-rescheduling lag probe on each reactor loop
        (messenger._ensure_pool calls this right after pool creation).
        Probes keep firing while the ledger is disabled — the off-path
        cost is one attribute check per interval — so re-enabling
        needs no re-arm."""
        if interval is not None:
            self.probe_interval = float(interval)
        for idx, loop in enumerate(loops):
            token = object()
            self._probe_tokens[id(loop)] = token
            try:
                loop.call_soon_threadsafe(
                    self._arm_probe, loop, idx, token)
            except RuntimeError:
                pass          # loop already closed (teardown race)

    def _arm_probe(self, loop, idx: int, token) -> None:
        interval = max(0.01, float(self.probe_interval))
        expected = loop.time() + interval
        loop.call_later(interval, self._probe_fire, loop, idx, token,
                        expected, interval)

    def _probe_fire(self, loop, idx: int, token, expected: float,
                    interval: float) -> None:
        if self._probe_tokens.get(id(loop)) is not token:
            return            # superseded (pool recreated / re-attach)
        if self.enabled:
            self.note_reactor_lag(idx, loop.time() - expected,
                                  interval)
        self._arm_probe(loop, idx, token)

    def note_reactor_lag(self, reactor: int, lag: float,
                         interval: float | None = None) -> None:
        """One probe observation.  The histogram/gauge move every
        probe; an EVENT (counter + monward window) only when the probe
        fired a FULL extra interval late — the heartbeat tick-lag
        detector's rule, so a loaded-but-healthy reactor does not
        page."""
        if not self.enabled:
            return
        lag = max(0.0, lag)
        now = time.time()
        self._reactor_lag[reactor] = (now, lag)
        self.perf.hinc("lat_msgr_reactor_lag", lag)
        worst = max((l for _, l in self._reactor_lag.values()),
                    default=0.0)
        self.perf.set("msgr_reactor_lag_worst", worst)
        if interval is None:
            interval = self.probe_interval
        if lag >= interval:
            self.lag_events_total += 1
            self.perf.inc("msgr_reactor_lag_events")
            self._lag_events.append((now, reactor, lag))

    # -- aggregation surfaces ------------------------------------------------

    def _window_events(self) -> list[tuple[float, int, float]]:
        cutoff = time.time() - self.window_s
        return [(ts, r, l) for ts, r, l in list(self._lag_events)
                if ts >= cutoff]

    def pgstats_block(self) -> dict | None:
        """The MPGStats "msgr" block: None unless the lag-event window
        is non-empty, and coarsely rounded, so a healthy daemon's
        report stays bit-identical and the keepalive dedup
        (_pgstats_should_send) keeps working."""
        if not self.enabled:
            return None
        events = self._window_events()
        if not events:
            return None
        worst = max(events, key=lambda e: e[2])
        return {
            "window_s": self.window_s,
            "lag_events": len(events),
            "worst_lag_s": round(worst[2], 2),
            "worst_reactor": worst[1],
            "warn_s": float(self.warn_s),
        }

    def status(self) -> dict:
        """The `messenger status` asok payload."""
        with self._lock:
            msgrs = list(self._messengers.items())
        return {
            "enabled": self.enabled,
            "uptime_s": round(time.time() - self.created_at, 3),
            "reactors": {
                "count": len(self._reactor_lag),
                "probe_interval_s": self.probe_interval,
                "last_lag_s": {str(i): round(lag, 6)
                               for i, (_ts, lag)
                               in sorted(self._reactor_lag.items())},
                "lag_events": self.lag_events_total,
            },
            "dispatch": {
                "pending": self._dispatch_pending,
                "hwm": self._dispatch_hwm,
                "total": self.dispatches_total,
            },
            "latencies": self.perf.dump_latencies(),
            "messengers": {name: st.totals() for name, st in msgrs},
            "window": self.pgstats_block(),
        }

    def conn_profile(self, last: int | None = None) -> dict:
        """The `conn profile` asok payload: per-peer rows per
        messenger, busiest first (`last` caps rows per messenger)."""
        with self._lock:
            msgrs = list(self._messengers.items())
        out = {}
        for name, st in msgrs:
            rows = st.conn_rows()
            if last is not None:
                rows = rows[:max(0, int(last))]
            out[name] = rows
        return {"enabled": self.enabled, "messengers": out}

    def bench_summary(self) -> dict:
        """The bench-row provenance block (`msgr_ledger` in
        cluster_bench --scale rows, beside recovery_blame): reactor
        lag + dispatch percentiles, wire totals, top peers."""
        def q(key, quant):
            est = self.perf.quantile(key, quant)
            return round(est[0] * 1e3, 3) if est else None
        with self._lock:
            msgrs = list(self._messengers.values())
        totals = {"msgs_out": 0, "msgs_in": 0, "bytes_out": 0,
                  "bytes_in": 0, "reconnects": 0, "replay_frames": 0,
                  "sync_timeouts": 0}
        peer_bytes: dict[str, int] = {}
        for st in msgrs:
            t = st.totals()
            for k in totals:
                totals[k] += t[k]
            for row in st.conn_rows():
                peer_bytes[row["peer"]] = \
                    peer_bytes.get(row["peer"], 0) + \
                    row["bytes_out"] + row["bytes_in"]
        top_peers = dict(sorted(peer_bytes.items(),
                                key=lambda kv: -kv[1])[:8])
        out = {
            "reactor_lag_ms_p50": q("lat_msgr_reactor_lag", 0.5),
            "reactor_lag_ms_p99": q("lat_msgr_reactor_lag", 0.99),
            "qwait_ms_p50": q("lat_msgr_qwait", 0.5),
            "qwait_ms_p99": q("lat_msgr_qwait", 0.99),
            "dispatch_ms_p50": q("lat_msgr_dispatch", 0.5),
            "dispatch_ms_p99": q("lat_msgr_dispatch", 0.99),
            "lag_events": self.lag_events_total,
            "dispatch_hwm": self._dispatch_hwm,
            "dispatches": self.dispatches_total,
            "peer_bytes": top_peers,
        }
        out.update(totals)
        return out

    def reset(self) -> None:
        """Clear window/table state (benches isolating a phase; the
        perf histograms are monotonic by design and stay)."""
        with self._lock:
            self._messengers.clear()
        self._reactor_lag.clear()
        self._lag_events.clear()
        self.lag_events_total = 0
        self._dispatch_pending = 0
        self._dispatch_hwm = 0
        self.dispatches_total = 0
        self.created_at = time.time()


def msgr_ledger() -> MsgrLedger:
    """The process's wire-plane recorder (built on first use,
    enabled); the common fast path skips the singleton lock."""
    led = MsgrLedger._host
    return led if led is not None else MsgrLedger.host_instance()
