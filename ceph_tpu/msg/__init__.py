"""Async messenger: the cluster communication backend (reference src/msg/)."""

from .message import Message, register_message
from .messenger import Connection, Messenger

__all__ = ["Message", "register_message", "Messenger", "Connection"]
