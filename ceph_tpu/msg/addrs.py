"""Monitor address plumbing shared by every mon client (OSD daemons,
Objecter, CLIs) — the monmap-list normalization the reference keeps in
MonMap/MonClient (src/mon/MonMap.h)."""

from __future__ import annotations


def normalize_mon_addrs(mon_addr) -> list[tuple[str, int]]:
    """Accept one ("host", port) pair or an iterable of them; return
    the monmap as a list of tuples (rank order preserved)."""
    if (isinstance(mon_addr, (tuple, list)) and len(mon_addr) == 2
            and isinstance(mon_addr[0], str)):
        return [tuple(mon_addr)]
    return [tuple(a) for a in mon_addr]
