"""Typed messages + wire envelope.

Re-expresses the reference's Message model (src/msg/Message.h; 163 typed
headers in src/messages/) and ProtocolV2's crc-protected framing
(src/msg/async/ProtocolV2.cc:728 frame assembly, frames_v2.h): every
message travels as

  magic(4) | type(u16) | seq(u64) | meta_len(u32) | data_len(u64)
  | header_crc(u32) || meta(json) || data(raw) || payload_crc(u32)

meta is a small JSON control dict (the reference's encoded header
fields); data is the raw byte segment (bufferlist payload) so the data
plane never round-trips through JSON.  Both are covered by crc32c like
ProtocolV2's crc mode.  (Secure/AES-GCM mode is a hook, not implemented;
auth layer gates connections instead.)

Messages self-describe via a type registry keyed by `type_id`, the
analog of decode_message()'s switch over CEPH_MSG_* constants.
"""

from __future__ import annotations

import json
import struct

from ..common import crc32c as _crc

MAGIC = b"CTPU"
_HEADER = struct.Struct("<4sHxxQIQI")  # magic, type, seq, meta_len, data_len, hcrc

# Control frames handled by the messenger itself, below the typed-message
# registry (the analog of ProtocolV2's HELLO/ACK tag frames,
# reference src/msg/async/frames_v2.h Tag::HELLO / Tag::ACK).
CTRL_HELLO = 0xFFF0   # session open/resume: meta = {entity, in_seq, lossless}
CTRL_ACK = 0xFFF1     # seq field = highest contiguously-received seq
CTRL_ENC = 0xFFF2     # secure mode: data = 12-byte nonce + AESGCM(frame)
CTRL_COMP = 0xFFF3    # compressed: meta={"a": algo}, data = comp(frame)

_REGISTRY: dict[int, type["Message"]] = {}


def encode_frame(tid: int, seq: int, meta: dict, data: bytes = b"") -> bytes:
    """Assemble one crc-protected wire frame (shared by typed messages
    and the messenger's control frames)."""
    meta_raw = json.dumps(meta, separators=(",", ":")).encode()
    head = _HEADER.pack(MAGIC, tid, seq, len(meta_raw), len(data), 0)
    hcrc = _crc.crc32c(head[:-4], 0xFFFFFFFF)
    head = head[:-4] + struct.pack("<I", hcrc)
    pcrc = _crc.crc32c(data, _crc.crc32c(meta_raw, 0xFFFFFFFF))
    return head + meta_raw + data + struct.pack("<I", pcrc)


def register_message(cls: type["Message"]) -> type["Message"]:
    tid = cls.type_id
    assert tid not in _REGISTRY, f"duplicate message type {tid}"
    _REGISTRY[tid] = cls
    return cls


class Message:
    """Base message: subclasses set type_id and implement meta/data."""

    type_id: int = 0

    def __init__(self) -> None:
        self.seq = 0

    # -- subclass surface ---------------------------------------------------

    def to_meta(self) -> dict:
        return {}

    def data_segment(self) -> bytes:
        return b""

    @classmethod
    def from_wire(cls, meta: dict, data: bytes) -> "Message":
        msg = cls.__new__(cls)
        Message.__init__(msg)
        msg.decode_wire(meta, data)
        return msg

    def decode_wire(self, meta: dict, data: bytes) -> None:
        pass

    def data_parts(self) -> list[bytes]:
        """The data segment as a list of buffers.  Payload-heavy
        messages override this so the wire path never concatenates
        their bytes (writev-style framing); data_segment() stays the
        joined view for decode symmetry."""
        d = self.data_segment()
        return [d] if d else []

    # -- envelope -----------------------------------------------------------

    def encode(self, seq: int = 0) -> bytes:
        return encode_frame(self.type_id, seq, self.to_meta(),
                            self.data_segment())

    def encode_parts(self, seq: int = 0) -> tuple[bytes, ...]:
        """Zero-concat frame: (head+meta, *data_parts, pcrc).  Joining
        the parts yields exactly encode(seq) — retention stores the
        tuple and only joins on (rare) replay; the writer writes each
        part, so a 1 MiB payload is never copied into a frame buffer."""
        meta_raw = json.dumps(self.to_meta(),
                              separators=(",", ":")).encode()
        parts = self.data_parts()
        dlen = sum(len(p) for p in parts)
        head = _HEADER.pack(MAGIC, self.type_id, seq, len(meta_raw),
                            dlen, 0)
        hcrc = _crc.crc32c(head[:-4], 0xFFFFFFFF)
        head = head[:-4] + struct.pack("<I", hcrc)
        c = _crc.crc32c(meta_raw, 0xFFFFFFFF)
        for p in parts:
            c = _crc.crc32c(p, c)
        return (head + meta_raw, *parts, struct.pack("<I", c))

    HEADER_SIZE = _HEADER.size

    @staticmethod
    def parse_header(raw: bytes) -> tuple[int, int, int, int]:
        """-> (type_id, seq, meta_len, data_len); raises on corruption."""
        magic, tid, seq, meta_len, data_len, hcrc = _HEADER.unpack(raw)
        if magic != MAGIC:
            raise ValueError(f"bad magic {magic!r}")
        want = _crc.crc32c(raw[:-4], 0xFFFFFFFF)
        if want != hcrc:
            raise ValueError(f"header crc mismatch {want:#x} != {hcrc:#x}")
        return tid, seq, meta_len, data_len

    @staticmethod
    def decode(tid: int, seq: int, meta_raw: bytes, data: bytes,
               pcrc: int) -> "Message":
        want = _crc.crc32c(data, _crc.crc32c(meta_raw, 0xFFFFFFFF))
        if want != pcrc:
            raise ValueError(f"payload crc mismatch {want:#x} != {pcrc:#x}")
        cls = _REGISTRY.get(tid)
        if cls is None:
            raise ValueError(f"unknown message type {tid}")
        msg = cls.from_wire(json.loads(meta_raw.decode()), data)
        msg.seq = seq
        return msg
